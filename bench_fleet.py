"""Fleet-simulation micro-benchmark: jobs-steps/sec of the multi-job
trace walk on the reference 512-chip trace (no TPU required — the
workload is the cross-job replay amortization itself, docs/fleet.md).

Measures the ISSUE-15 perf stack end to end: ONE replay context per
template serving every job instantiated from it (healthy-step DES,
recorded streams, snapshot ladders, canonical step cache shared
across the whole trace), against the **naive baseline** — the same
scheduler walk costing every job with a fresh replay context per
``predict_goodput`` call, which re-pays the healthy-step DES run and
all replay state per job (``fleet/sim.py`` ``naive=True``).

Prints exactly ONE JSON line::

    {"metric": "fleet_jobs_steps_per_sec", "value": ..., "unit":
     "jobs-steps/s", "n_jobs": ..., "templates": ..., "world": ...,
     "total_steps": ..., "elapsed_s": ..., "costings": ...,
     "sims": ..., "step_cache_hit_rate": ...,
     "naive_elapsed_s": ..., "naive_jobs_steps_per_sec": ...,
     "speedup": ..., "bit_identical": true, ...}

``value`` counts trace job-steps per second of the *shared* walk;
``speedup`` is the same-run, same-machine ratio against the naive
loop, and ``bit_identical`` asserts the two fleet reports compare
equal with elastic reshaping disabled — the correctness oracle of the
gate. ``--jobs N`` additionally runs the pooled walk and asserts
``parallel_identical`` (serial == parallel byte-equality).

Usage::

    python bench_fleet.py                        # shared + naive
    python bench_fleet.py --jobs 2               # + parallel oracle
    python bench_fleet.py --skip-naive           # shared only
    python bench_fleet.py --elastic-demo         # + elastic timing
    python bench_fleet.py \
        --baseline results/bench_fleet_baseline.json \
        --max-regression 0.7 --min-speedup 6 \
        --min-naive-speedup 10   # gates (exit 1 on breach)

The recorded baseline carries ``naive_jobs_steps_per_sec`` — the
naive loop measured on the recording machine. ``--min-naive-speedup``
gates the shared walk's throughput against that recorded number times
the shared wide CI margin, so a revert to per-job replay-state
rebuilds fails the build even on a slower runner (the ISSUE-15 10x
acceptance gate).
"""

import argparse
import copy
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

try:
    from tools.bench_history import record_safely
except ImportError:  # script copied out of the repo: no trajectory
    def record_safely(result):
        return None

import warnings

warnings.filterwarnings("ignore")

from simumax_tpu.fleet import FleetSimulator
from simumax_tpu.fleet.trace import FleetTrace
from simumax_tpu.simulator.faults import ReplayOptions

DEFAULT_TRACE = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "configs", "fleet", "v5p512_reference.json",
)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--trace", default=DEFAULT_TRACE,
                    metavar="TRACE.json",
                    help="fleet trace to walk (default: the reference "
                         "512-chip trace)")
    ap.add_argument("--reps", type=int, default=2, metavar="N",
                    help="shared-walk repetitions; the fastest is "
                         "recorded (machine-noise control, the "
                         "bench_simulate min-of-N idiom; default 2)")
    ap.add_argument("--jobs", type=int, default=0, metavar="N",
                    help="also run the pooled walk with N workers and "
                         "assert serial == parallel byte-equality")
    ap.add_argument("--skip-naive", action="store_true",
                    help="skip the naive reference walk (no "
                         "bit-identity check, no measured speedup)")
    ap.add_argument("--elastic-demo", action="store_true",
                    help="also time the elastic walk (trace scheduler "
                         "settings; informational, never gated)")
    ap.add_argument(
        "--baseline", metavar="JSON",
        help="previously saved bench JSON line to gate against",
    )
    ap.add_argument(
        "--max-regression", type=float, default=0.15, metavar="FRAC",
        help="fail (exit 1) when jobs-steps/s drops more than this "
             "fraction below the baseline (default 0.15)",
    )
    ap.add_argument(
        "--min-speedup", type=float, default=0.0, metavar="X",
        help="fail when the measured same-run naive/shared speedup "
             "is below X (0 disables)",
    )
    ap.add_argument(
        "--min-naive-speedup", type=float, default=0.0, metavar="X",
        help="with --baseline: fail when jobs-steps/s is below X "
             "times the baseline's recorded "
             "naive_jobs_steps_per_sec, after the --max-regression "
             "margin (0 disables) — the ISSUE-15 10x acceptance gate",
    )
    ap.add_argument(
        "--replay-backend", default="auto",
        choices=("numpy", "jax", "auto"),
        help="miss-replay backend of the shared walk (ISSUE-17 "
             "batched replay; the naive loop always walks the scalar "
             "engine, so bit_identical doubles as the backend oracle)",
    )
    ap.add_argument(
        "--max-fallback-rate", type=float, default=0.0, metavar="FRAC",
        help="fail when more than this fraction of batched-eligible "
             "miss replays fell back to the scalar engine "
             "(0 disables; counted per reason in the JSON line)",
    )
    ap.add_argument(
        "--explain", action="store_true",
        help="also time an --explain walk (fleet forensics, "
             "observe/fleetledger.py) and gate it: base payload "
             "byte-identical to the plain walk, per-job + fleet "
             "attribution buckets sum to wall within 1e-6, the fleet "
             "Chrome trace passes the test_trace_validity checks, "
             "and the attribution overhead stays bounded",
    )
    ap.add_argument(
        "--max-explain-overhead", type=float, default=0.15,
        metavar="FRAC",
        help="with --explain: fail when the explain walk takes more "
             "than this fraction longer than the plain walk "
             "(default 0.15, the PR-7 observability discipline)",
    )
    args = ap.parse_args(argv)
    options = ReplayOptions(replay_backend=args.replay_backend)

    trace = FleetTrace.load(args.trace).to_dict()
    total_steps = sum(j["horizon_steps"] for j in trace["jobs"])

    # estimates are built untimed on BOTH modes (they share them);
    # the timed region isolates the replay-state differential. The
    # fastest of --reps walks is recorded (every rep is a FRESH
    # simulator: replay state is rebuilt, nothing leaks between reps)
    elapsed = None
    report = shared = None
    for _ in range(max(1, args.reps)):
        sim = FleetSimulator(copy.deepcopy(trace), elastic=False,
                             options=options)
        sim.prepare()
        t0 = time.perf_counter()
        rep = sim.run()
        dt = time.perf_counter() - t0
        if report is not None and rep != report:
            # determinism oracle across repetitions
            print(json.dumps({
                "error": "fleet walk is not deterministic across "
                         "repetitions",
            }))
            return 1
        if elapsed is None or dt < elapsed:
            elapsed, shared = dt, sim
        if report is None:
            report = rep
    sims = hits = steps = batched = 0
    fallbacks = {}
    for rt in shared._runtimes.values():
        s = rt.ctx.stats
        sims += s["sims"]
        steps += s["steps"]
        hits += s["cache_hits"] + s["canon_hits"] + s["clamp_hits"]
        batched += s.get("batched", 0)
        for k, v in s.items():
            if k.startswith("fallback_"):
                key = k[len("fallback_"):]
                fallbacks[key] = fallbacks.get(key, 0) + v

    result = {
        "metric": "fleet_jobs_steps_per_sec",
        "value": round(total_steps / elapsed, 3) if elapsed else 0.0,
        "unit": "jobs-steps/s",
        "n_jobs": len(trace["jobs"]),
        "templates": len(trace["templates"]),
        "world": sum(p["chips"] for p in trace["fleet"]["pods"]),
        "total_steps": total_steps,
        "elapsed_s": round(elapsed, 3),
        "costings": shared.stats["costings"],
        "sims": sims,
        "step_cache_hit_rate": round(hits / max(1, steps), 4),
        "fleet_goodput": round(report["fleet_goodput"], 6),
        "slo_fraction": round(report["slo"]["fraction"], 6),
        "replay_backend": args.replay_backend,
        "batched": batched,
        "fallbacks": dict(sorted(fallbacks.items())),
    }
    fb_total = sum(fallbacks.values())
    result["fallback_rate"] = round(
        fb_total / max(1, batched + fb_total), 4
    )
    ok = True
    if args.max_fallback_rate:
        result["fallback_rate_ok"] = (
            result["fallback_rate"] <= args.max_fallback_rate
        )
        ok = ok and result["fallback_rate_ok"]
    if not args.skip_naive:
        naive_sim = FleetSimulator(
            copy.deepcopy(trace), elastic=False, naive=True,
        )
        naive_sim.prepare()
        t0 = time.perf_counter()
        naive_report = naive_sim.run()
        naive_elapsed = time.perf_counter() - t0
        result["naive_elapsed_s"] = round(naive_elapsed, 3)
        result["naive_jobs_steps_per_sec"] = (
            round(total_steps / naive_elapsed, 3) if naive_elapsed
            else 0.0
        )
        result["speedup"] = (
            round(naive_elapsed / elapsed, 2) if elapsed else 0.0
        )
        # the correctness oracle: with elastic off, the shared walk's
        # per-job GoodputReports (and the whole payload) must equal
        # the naive loop's byte-for-byte
        result["bit_identical"] = report == naive_report
        if not result["bit_identical"]:
            ok = False
        if args.min_speedup and result["speedup"] < args.min_speedup:
            result["speedup_ok"] = False
            ok = False
        elif args.min_speedup:
            result["speedup_ok"] = True
    if args.jobs:
        t0 = time.perf_counter()
        par_report = FleetSimulator(
            copy.deepcopy(trace), elastic=False, jobs=args.jobs,
            options=options,
        ).run()
        result["parallel_elapsed_s"] = round(
            time.perf_counter() - t0, 3
        )
        result["parallel_identical"] = report == par_report
        if not result["parallel_identical"]:
            ok = False
    if args.explain:
        from simumax_tpu.observe.fleetledger import (
            FLEET_LEDGER_ORDER,
            build_fleet_explain,
            fleet_chrome_trace,
        )

        # same protocol as the plain measurement: fresh simulator per
        # rep, prepare() untimed, fastest rep recorded — the delta
        # isolates the attribution work, not process-cache warmup
        ex_elapsed = None
        ex_report = None
        for _ in range(max(1, args.reps)):
            ex_sim = FleetSimulator(copy.deepcopy(trace),
                                    elastic=False, options=options)
            ex_sim.prepare()
            t0 = time.perf_counter()
            rep_i = dict(ex_sim.run())
            rep_i["explain"] = build_fleet_explain(ex_sim)
            dt = time.perf_counter() - t0
            if ex_elapsed is None or dt < ex_elapsed:
                ex_elapsed, ex_report = dt, rep_i
        result["explain_elapsed_s"] = round(ex_elapsed, 3)
        overhead = (ex_elapsed / elapsed - 1.0) if elapsed else 0.0
        result["explain_overhead"] = round(overhead, 4)
        result["explain_overhead_ok"] = (
            overhead <= args.max_explain_overhead
        )
        # bit-identity oracle: attaching forensics cannot change one
        # byte of the base payload
        base_payload = {k: v for k, v in ex_report.items()
                        if k != "explain"}
        result["explain_identical"] = base_payload == report
        # bucket-sum oracle: per-job and fleet attribution each sum
        # to their wall/occupancy total within 1e-6
        ledger = ex_report["explain"]["ledger"]
        sums_ok = all(
            abs(sum(j["buckets"].values()) - j["wall_time_s"]) < 1e-6
            for j in ledger["per_job"]
        ) and abs(
            sum(ledger["buckets"][k] for k in FLEET_LEDGER_ORDER)
            - ledger["total_chip_s"]
        ) < 1e-6 * max(1.0, ledger["total_chip_s"])
        result["explain_bucket_sums_ok"] = sums_ok
        # Chrome-trace validity via the shared test machinery
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "tests"))
        try:
            from test_trace_validity import check_chrome_trace

            check_chrome_trace(fleet_chrome_trace(ex_report))
            result["explain_trace_valid"] = True
        except (ImportError, AssertionError) as exc:
            result["explain_trace_valid"] = False
            result["explain_trace_error"] = str(exc)[:200]
        result["explain_probes"] = len(
            ex_report["explain"]["probes"]
        )
        ok = ok and all(result[k] for k in (
            "explain_overhead_ok", "explain_identical",
            "explain_bucket_sums_ok", "explain_trace_valid",
        ))
    if args.elastic_demo:
        t0 = time.perf_counter()
        el_report = FleetSimulator(copy.deepcopy(trace)).run()
        result["elastic_elapsed_s"] = round(
            time.perf_counter() - t0, 3
        )
        result["elastic_reshapes"] = sum(
            j["reshapes"] for j in el_report["jobs"]
        )
    if args.baseline:
        with open(args.baseline) as f:
            base = json.load(f)
        if not isinstance(base.get("value"), (int, float)):
            print(json.dumps({
                "error": f"baseline {args.baseline} has no numeric "
                         f"'value' field; re-record it with a plain "
                         f"bench run",
            }))
            return 2
        for key in ("n_jobs", "templates", "world", "total_steps"):
            theirs = base.get(key, result[key])
            if theirs != result[key]:
                print(json.dumps({
                    "error": f"baseline {key} {theirs!r} != this "
                             f"run's {result[key]!r}; not comparable "
                             f"— re-record the baseline",
                }))
                return 2
        floor = base["value"] * (1.0 - args.max_regression)
        result["baseline_value"] = base["value"]
        result["regression"] = (
            round(1.0 - result["value"] / base["value"], 4)
            if base["value"] else 0.0
        )
        result["regression_ok"] = result["value"] >= floor
        ok = ok and result["regression_ok"]
        nv = base.get("naive_jobs_steps_per_sec")
        if args.min_naive_speedup and isinstance(nv, (int, float)):
            naive_floor = (nv * args.min_naive_speedup
                           * (1.0 - args.max_regression))
            result["baseline_naive_jobs_steps_per_sec"] = nv
            result["naive_speedup_ok"] = result["value"] >= naive_floor
            ok = ok and result["naive_speedup_ok"]
    print(json.dumps(result))
    record_safely(result)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

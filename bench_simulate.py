"""Discrete-event-simulator micro-benchmark: events/sec and peak RSS of
a pod-size world-rank simulation (no TPU required — the workload is the
engine itself).

Measures the ISSUE-4 perf stack end to end: the ready-heap scheduler
with wake indexes (``simulator/engine.py``), rank-symmetry reduction
(``simulator/reduce.py``) and the bounded-memory streaming trace writer
(``simulator/trace.py``).

Prints exactly ONE JSON line::

    {"metric": "simulate_events_per_sec", "value": ..., "unit":
     "events/s", "world": ..., "mode": "reduced"|"full", "granularity":
     ..., "events": ..., "n_classes": ..., "elapsed_s": ...,
     "peak_rss_mib": ..., "end_time_ms": ...}

``value`` counts *expanded* (full-world-equivalent) events per second
of engine wall time, so reduced and full runs are comparable: both
report how fast the tool answers the same 1024-rank question.

Usage::

    python bench_simulate.py                        # reduced, 1024 ranks
    python bench_simulate.py --mode full            # exact full-world run
    python bench_simulate.py --granularity leaf
    python bench_simulate.py --stream-trace         # bounded-RSS trace write
    python bench_simulate.py --perturb 0:1.3,7:1.5  # straggler injection
    python bench_simulate.py --baseline BENCH_prev.json \
        --max-regression 0.1      # regression gate (exit 1 on breach)
    python bench_simulate.py --critical-path        # overhead gate:
        # recorder-on vs off makespans must be bit-identical, and the
        # events/s overhead of recording + analyzing the dependency
        # skeleton must stay under --max-critpath-overhead (0.15)

Recorded alongside ``bench_sweep.py`` in the bench harness; numbers are
committed in ``docs/simulation.md``.
"""

import argparse
import json
import os
import resource
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

try:
    from tools.bench_history import record_safely
except ImportError:  # script copied out of the repo: no trajectory
    def record_safely(result):
        return None

import warnings

warnings.filterwarnings("ignore")

from simumax_tpu.core.config import (
    get_model_config,
    get_strategy_config,
    get_system_config,
)
from simumax_tpu.perf import PerfLLM


def build_perf(world: int, mbc: int):
    """Fixed synthetic pod config: tp4 x pp4 x dp(world/16) of a
    layer-trimmed llama3-8b on as many v5e slices as the world needs."""
    st = get_strategy_config("tp1_pp2_dp4_mbs1")
    st.tp_size = 4
    st.pp_size = 4
    st.world_size = world
    st.micro_batch_num = mbc
    st.__post_init__()
    model = get_model_config("llama3-8b")
    model.layer_num = 8
    system = get_system_config("tpu_v5e_256")
    system.num_slices = max(1, -(-world // system.chips_per_slice))
    perf = PerfLLM()
    perf.configure(st, model, system)
    perf.run_estimate()
    return perf


def parse_perturb(spec):
    out = {}
    if spec:
        for part in spec.split(","):
            r, f = part.split(":")
            out[int(r)] = float(f)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--world", type=int, default=1024,
                    help="global ranks to simulate (default 1024)")
    ap.add_argument("--mode", choices=("reduced", "full"),
                    default="reduced",
                    help="symmetry-reduced (default) or exact full-world")
    ap.add_argument("--granularity", choices=("chunk", "leaf"),
                    default="leaf")
    ap.add_argument("--mbc", type=int, default=8,
                    help="microbatches per iteration (default 8)")
    ap.add_argument("--perturb", metavar="R:F,...",
                    help="straggler injection, e.g. 0:1.3,7:1.5 "
                         "(shatters the touched symmetry classes)")
    ap.add_argument("--stream-trace", action="store_true",
                    help="stream trace.json to a temp dir while "
                         "simulating (the bounded-RSS path)")
    ap.add_argument(
        "--critical-path", action="store_true",
        help="critical-path overhead gate: run the same simulation "
             "with and without the dependency recorder, assert the "
             "makespans are bit-identical, report the recorder-on "
             "events/s as `value` plus `critpath_overhead` vs the "
             "recorder-off run, and fail (exit 1) when the overhead "
             "exceeds --max-critpath-overhead",
    )
    ap.add_argument(
        "--max-critpath-overhead", type=float, default=0.15,
        metavar="FRAC",
        help="with --critical-path: max tolerated events/s overhead of "
             "recorder-on vs recorder-off on THIS machine "
             "(default 0.15)",
    )
    ap.add_argument(
        "--repeats", type=int, default=5, metavar="N",
        help="with --critical-path: timed repetitions per mode; the "
             "min elapsed of each side is compared (wall-clock noise "
             "robustness; default 5)",
    )
    ap.add_argument(
        "--baseline", metavar="JSON",
        help="previously saved bench JSON line to gate against "
             "(compares events/sec at the same world/mode/granularity)",
    )
    ap.add_argument(
        "--max-regression", type=float, default=0.1, metavar="FRAC",
        help="fail (exit 1) when events/sec drops more than this "
             "fraction below the baseline (default 0.1)",
    )
    args = ap.parse_args(argv)

    perf = build_perf(args.world, args.mbc)
    perturbation = parse_perturb(args.perturb)
    save_path = None
    tmp = None
    if args.stream_trace:
        tmp = tempfile.TemporaryDirectory(prefix="bench_simulate_")
        save_path = tmp.name
    def one_run(critical_path=False):
        t0 = time.perf_counter()
        res = perf.simulate(
            save_path,
            granularity=args.granularity,
            world_ranks=True,
            track_memory=False,
            perturbation=perturbation,
            reduce=args.mode == "reduced",
            stream_trace=args.stream_trace,
            critical_path=critical_path,
        )
        return res, time.perf_counter() - t0

    off = None
    if args.critical_path:
        one_run(critical_path=False)  # warmup: builds/caches off-clock
        off, off_elapsed = one_run(critical_path=False)
        r, elapsed = one_run(critical_path=True)
        for _ in range(max(0, args.repeats - 1)):
            _, t = one_run(critical_path=False)
            off_elapsed = min(off_elapsed, t)
            _, t = one_run(critical_path=True)
            elapsed = min(elapsed, t)
    else:
        r, elapsed = one_run(critical_path=False)
    trace_bytes = None
    if save_path:
        trace_bytes = os.path.getsize(os.path.join(save_path, "trace.json"))
        tmp.cleanup()
    peak_rss_mib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0
    reduction = r.get("reduction") or {}
    result = {
        "metric": "simulate_events_per_sec",
        "value": round(r["num_events"] / elapsed, 1) if elapsed else 0.0,
        "unit": "events/s",
        "world": args.world,
        "mode": args.mode,
        "granularity": args.granularity,
        "mbc": args.mbc,
        "perturbed_ranks": len(perturbation),
        "events": r["num_events"],
        "n_classes": reduction.get("n_classes"),
        "engine_events": reduction.get("engine_events", r["num_events"]),
        "elapsed_s": round(elapsed, 3),
        "peak_rss_mib": round(peak_rss_mib, 1),
        "stream_trace": args.stream_trace,
        "end_time_ms": round(r["end_time_ms"], 3),
    }
    if trace_bytes is not None:
        result["trace_bytes"] = trace_bytes
    ok = True
    if args.critical_path:
        # the tentpole contract first: recording the dependency
        # skeleton must not move the simulated makespan by one bit
        if r["end_time"] != off["end_time"]:
            print(json.dumps({
                "error": "critical-path-on makespan differs from off: "
                         f"{r['end_time']!r} vs {off['end_time']!r}",
            }))
            return 1
        off_value = off["num_events"] / off_elapsed if off_elapsed else 0.0
        overhead = (
            1.0 - result["value"] / off_value if off_value else 0.0
        )
        result["critical_path"] = True
        result["off_value"] = round(off_value, 1)
        result["critpath_overhead"] = round(overhead, 4)
        result["critpath_overhead_ok"] = (
            overhead <= args.max_critpath_overhead
        )
        ok = ok and result["critpath_overhead_ok"]
    if args.baseline:
        with open(args.baseline) as f:
            base = json.load(f)
        if "value" not in base or not isinstance(
            base.get("value"), (int, float)
        ):
            print(json.dumps({
                "error": f"baseline {args.baseline} has no numeric "
                         f"'value' field; re-record it with a plain "
                         f"bench run",
            }))
            return 2
        # compare like with like: reduced-vs-full or leaf-vs-chunk
        # differ by orders of magnitude for non-regression reasons
        for key, ours in (("world", args.world), ("mode", args.mode),
                          ("granularity", args.granularity),
                          ("mbc", args.mbc)):
            theirs = base.get(key, ours)
            if theirs != ours:
                print(json.dumps({
                    "error": f"baseline {key} {theirs!r} != this run's "
                             f"{ours!r}; not comparable — re-record the "
                             f"baseline with matching flags",
                }))
                return 2
        floor = base["value"] * (1.0 - args.max_regression)
        result["baseline_value"] = base["value"]
        result["regression"] = (
            round(1.0 - result["value"] / base["value"], 4)
            if base["value"] else 0.0
        )
        ok = result["value"] >= floor
        result["regression_ok"] = ok
    print(json.dumps(result))
    record_safely(result)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Interactive web app (L8) — structured config editors, estimate +
memory + simulator + search tabs, artifact download.

Reference: ``app/streamlit_app.py`` (sidebar per-field editors for
hardware/parallelism/model, result rendering, zip download). Requires
``streamlit`` (not part of the baked environment): ``pip install
streamlit`` then ``streamlit run app/streamlit_app.py``. The same
workflows are available without extra deps through
``python -m simumax_tpu`` (see ``simumax_tpu/cli.py``); the full render
path is exercised headlessly by ``tests/test_app.py``.

Every evaluation routes through the :class:`Planner` facade
(``simumax_tpu/service/planner.py``) instead of building ``PerfLLM``
objects inline: streamlit re-runs this whole script on *every* widget
interaction, and the planner's persistent content-addressed cache
(shared with the CLI and the ``serve`` server — ``docs/service.md``)
turns those re-runs into ~ms cache hits instead of full model
rebuilds. Results are bit-identical to direct evaluation.
"""

import io
import json
import os
import sys
import zipfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import streamlit as st
except ImportError:  # pragma: no cover
    print(__doc__)
    sys.exit("streamlit is not installed; use `python -m simumax_tpu` instead")

from simumax_tpu.core.config import (
    ConfigError,
    ModelConfig,
    StrategyConfig,
    get_model_config,
    get_strategy_config,
    get_system_config,
    list_configs,
)
from simumax_tpu.core.errors import FeasibilityError
from simumax_tpu.service.planner import Planner

# one planner per process; streamlit's per-interaction script re-runs
# all hit the same persistent store, so only the first evaluation of a
# configuration pays for a model build
_planner = st.cache_resource(Planner) if hasattr(st, "cache_resource") \
    else Planner
planner = _planner()

st.set_page_config(page_title="simumax-tpu", layout="wide")
st.title("simumax-tpu — analytical LLM training simulator for TPU")

cfgs = list_configs()

# -- sidebar: structured editors ------------------------------------------


def _num(label, value, min_value=1, step=1):
    return int(st.sidebar.number_input(
        label, value=int(value), min_value=min_value, step=step
    ))


with st.sidebar:
    st.subheader("configs")
    model_name = st.selectbox("model", cfgs["models"], index=max(
        cfgs["models"].index("llama3-8b") if "llama3-8b" in cfgs["models"] else 0, 0))
    strategy_name = st.selectbox("strategy", cfgs["strategy"])
    system_name = st.selectbox("system", cfgs["system"])

model = get_model_config(model_name)
strategy = get_strategy_config(strategy_name)
system = get_system_config(system_name)


def _fnum(label, value, min_value=0.001):
    # plain st.number_input so the widget lands INSIDE the active
    # `with <container>` block (st.sidebar.* always targets sidebar root)
    return float(st.number_input(
        label, value=float(value), min_value=float(min_value)
    ))


# -- hardware editor (reference app's 硬件参数配置 section) ---------------
with st.sidebar.expander("hardware overrides"):
    base_tflops = system.accelerator.op["default"].tflops
    new_tflops = _fnum("bf16 TFLOPs/chip", base_tflops)
    new_mem = _fnum("HBM GiB/chip", system.accelerator.mem_gbs)
    base_hbm = system.accelerator.bandwidth["default"].gbps
    new_hbm = _fnum("HBM GB/s", base_hbm)
    new_ici = _fnum("ICI link GB/s", system.ici.link_gbps)
    if new_tflops != base_tflops:
        scale = new_tflops / base_tflops
        for op in system.accelerator.op.values():
            op.tflops *= scale  # int8 classes keep their 2x ratio
    system.accelerator.mem_gbs = new_mem
    if new_hbm != base_hbm:
        scale = new_hbm / base_hbm
        for bw in system.accelerator.bandwidth.values():
            bw.gbps *= scale
    system.ici.link_gbps = new_ici

st.sidebar.subheader("parallelism")
strategy.world_size = _num("world_size", strategy.world_size)
strategy.tp_size = _num("tp", strategy.tp_size)
strategy.cp_size = _num("cp", strategy.cp_size)
strategy.ep_size = _num("ep", strategy.ep_size)
strategy.pp_size = _num("pp", strategy.pp_size)
strategy.interleaving_size = _num("vpp chunks", strategy.interleaving_size)
strategy.zero_state = _num("ZeRO state", strategy.zero_state, min_value=0)

st.sidebar.subheader("batch / sequence")
strategy.seq_len = _num("seq_len", strategy.seq_len, step=1024)
strategy.micro_batch_size = _num("micro_batch_size", strategy.micro_batch_size)
strategy.micro_batch_num = _num("micro_batch_num", strategy.micro_batch_num)
_dtypes = ["bf16", "fp32"]
strategy.dtype = st.sidebar.selectbox(
    "dtype", _dtypes,
    index=_dtypes.index(strategy.dtype) if strategy.dtype in _dtypes else 0,
)
with st.sidebar.expander("advanced pipeline options"):
    # uneven PP (reference app's PP层数高级选项): 0 = even split.
    # plain st.number_input so the widgets land inside the expander.
    strategy.num_layers_in_first_pipeline_stage = int(st.number_input(
        "layers in first stage (0 = even)",
        value=int(strategy.num_layers_in_first_pipeline_stage), min_value=0,
    ))
    strategy.num_layers_in_last_pipeline_stage = int(st.number_input(
        "layers in last stage (0 = even)",
        value=int(strategy.num_layers_in_last_pipeline_stage), min_value=0,
    ))

st.sidebar.subheader("recompute")
_grans = ["none", "full_block", "selective", "attn_only", "mlp_only"]
_cur_gran = (
    strategy.recompute_granularity if strategy.enable_recompute else "none"
)
gran = st.sidebar.selectbox(
    "granularity", _grans,
    index=_grans.index(_cur_gran) if _cur_gran in _grans else 0,
)
strategy.enable_recompute = gran != "none"
if strategy.enable_recompute:
    strategy.recompute_granularity = gran
    strategy.recompute_layer_num = _num(
        "recompute layers (-1 = all)", strategy.recompute_layer_num,
        min_value=-1,
    )

st.sidebar.subheader("model overrides")
model.layer_num = _num("layers", model.layer_num)
model.hidden_size = _num("hidden_size", model.hidden_size, step=128)
model.intermediate_size = _num("ffn size", model.intermediate_size, step=128)
model.head_num = _num("heads", model.head_num)
model.kv_head_num = _num("kv heads", model.kv_head_num)
if model.model_type == "moe":
    model.expert_num = _num("experts", model.expert_num)
    model.topk = _num("topk", model.topk)

with st.expander("edit raw model json (advanced)"):
    # streamlit retains edited widget text across reruns, which would
    # silently discard later sidebar edits if the raw JSON always won;
    # apply it only while the checkbox is on
    model_json = st.text_area(
        "model json", json.dumps(model.to_dict(), indent=2), height=240
    )
    if st.checkbox("apply raw model json (overrides sidebar)"):
        model = ModelConfig.init_from_dict(json.loads(model_json))
with st.expander("edit raw strategy json (advanced)"):
    strategy_json = st.text_area(
        "strategy json", json.dumps(strategy.to_dict(), indent=2, default=str),
        height=240,
    )
    if st.checkbox("apply raw strategy json (overrides sidebar)"):
        data = json.loads(strategy_json)
        data.pop("recompute", None)
        strategy = StrategyConfig.init_from_dict(data)

strategy.__post_init__()  # re-derive dp_size/recompute from the edits

run_sim = st.checkbox("also run the event simulator (Chrome trace)")

tab_est, tab_mem, tab_sim, tab_search = st.tabs(
    ["estimate", "memory", "simulator", "search"]
)

if st.button("estimate"):
    try:
        # planner facade: persistent content-addressed cache shared
        # with the CLI and the serve server; bit-identical to a direct
        # PerfLLM evaluation
        result = planner.estimate(model, strategy, system)
    except ConfigError as e:
        st.error(f"infeasible config: {e}")
        st.stop()
    cost, mem = result["compute_result"], result["mem_result"]

    with tab_est:
        c1, c2, c3, c4 = st.columns(4)
        c1.metric("iteration", f"{cost['iter_time_ms']:.1f} ms")
        c2.metric("MFU", f"{cost['mfu']*100:.2f} %")
        c3.metric("TFLOPS/chip", f"{cost['tflops_per_chip']:.1f}")
        c4.metric(
            "peak HBM",
            f"{mem['max_peak_gib']:.2f} GiB",
            delta="fits" if mem["fits"] else "DOES NOT FIT",
            delta_color="normal" if mem["fits"] else "inverse",
        )
        st.subheader("time breakdown")
        tb = cost.get("time_breakdown", {})
        # *_per_microbatch entries are one microbatch; scale them so
        # every row is per-iteration and the rows sum meaningfully
        mbc = max(strategy.micro_batch_num, 1)
        st.dataframe([
            {
                "phase": k.replace("_per_microbatch", ""),
                "ms": round(
                    v * 1e3 * (mbc if k.endswith("_per_microbatch") else 1),
                    3,
                ),
            }
            for k, v in tb.items()
        ])
        st.subheader("mesh placement")
        st.json(result["net_info"])
        misses = result["efficiency_misses"]
        if misses:
            st.info(
                f"{sum(len(v) for v in misses.values())} efficiency-table "
                "misses — run `python -m simumax_tpu calibrate` on a TPU "
                "to refine the prediction."
            )
        # warnings / suggestions (reference app's 警告信息 + 提示/建议)
        st.subheader("warnings / suggestions")
        warnings = []
        if not mem["fits"]:
            warnings.append(
                f"peak {mem['max_peak_gib']:.1f} GiB exceeds usable HBM — "
                "enable recompute, raise zero_state (FSDP=3), increase "
                "tp/pp, or use more chips"
            )
        # pp across DCN is the recommended multi-slice layout (tiny p2p
        # volume) — only warn when a bandwidth-heavy dim spills; dp_cp
        # is the same physical group as dp, so don't list it twice.
        # net_info carries the CommPath descriptions ("dcn[...]" marks
        # a span beyond the slice)
        dcn_dims = [
            d for d, desc in result["net_info"].items()
            if "dcn[" in desc and d not in ("pp", "dp_cp")
        ]
        if dcn_dims:
            hint = (
                "enable overlap_grad_reduce/overlap_param_gather to hide "
                "the DP gradient traffic, or try mesh_order='tp,cp,dp,pp' "
                "to put pipeline p2p across slices instead"
                if "dp" in dcn_dims
                else "prefer layouts that keep tp/cp/ep inside the slice"
            )
            warnings.append(
                f"parallel dims {', '.join(dcn_dims)} spill onto DCN "
                f"(~10-100x less bandwidth than ICI) — {hint}"
            )
        bubble = cost.get("bubble_time", 0.0) / max(cost["iter_time"], 1e-9)
        if bubble > 0.2:
            warnings.append(
                f"pipeline bubble is {bubble * 100:.0f}% of the "
                "iteration — raise micro_batch_num or use interleaving "
                "(vpp)"
            )
        if warnings:
            for w in warnings:
                st.write(f"- {w}")
        else:
            st.write("none — configuration looks healthy")
        with st.expander("realized collective bandwidths (GB/s)"):
            st.json(result["real_comm_bw"])
        dual = result.get("dualpp")
        if dual:
            st.subheader("DualPipe projection")
            st.write(
                f"bidirectional schedule: "
                f"{dual['dualpp_iter_time'] * 1e3:.1f} ms "
                f"({dual['speedup']:.2f}x vs 1F1B) at "
                f"{dual['max_peak_gib']:.1f} GiB peak "
                f"(2 stage chunks per rank vs "
                f"{dual['baseline_peak_gib']:.1f} GiB)"
            )

    with tab_mem:
        st.subheader("per-stage memory")
        st.dataframe(mem["stages"])
        # per-stage breakdown (reference app's 模型内存细分 expander)
        # model_bytes = weight + grad + optimizer_state (an aggregate)
        # and peak/replay_peak are metrics, not components — exclude so
        # the component rows sum to real memory
        _components = (
            "weight_bytes", "grad_bytes", "optimizer_state_bytes",
            "act_cache_per_microbatch_bytes",
        )
        for s in mem["stages"]:
            with st.expander(f"stage {s['stage']} breakdown"):
                st.dataframe([
                    {"component": k.replace("_bytes", ""),
                     "GiB": round(s[k] / 2**30, 3)}
                    for k in _components if k in s
                ])

    artifacts = {
        "base_info.json": result["base_info"],
        "mem_result.json": mem,
        "compute_result.json": cost,
        "net_info.json": result["net_info"],
    }
    if run_sim:
        # artifact-producing simulate rides the facade too (uncached —
        # the trace/snapshot files live outside the store)
        sim = planner.simulate(model, strategy, system,
                               save_path="tmp/app_sim")
        with tab_sim:
            st.subheader("event simulator")
            st.write(
                f"event-simulated iteration: {sim['end_time_ms']:.2f} ms "
                f"({sim['num_events']} events)"
            )
            for m in sim["memory"]:
                st.write(
                    f"stage {m['rank']}: simulated peak "
                    f"{m['peak_gib']:.2f} GiB at {m['peak_time_ms']:.1f} ms"
                )
                cats = m.get("peak_by_category") or {}
                if cats:
                    st.subheader(f"stage {m['rank']} — who holds the peak")
                    st.dataframe([
                        {"holder": k, "GiB": round(v / 2**30, 3)}
                        for k, v in cats.items()
                    ])
            # memory timeline chart from the snapshot artifact
            snap_path = os.path.join("tmp/app_sim", "simu_memory_snapshot.json")
            if os.path.exists(snap_path):
                with open(snap_path) as f:
                    snaps = json.load(f)
                for snap in snaps[:1]:
                    st.line_chart(
                        {"GiB": [s["bytes"] / 2**30
                                 for s in snap["timeline"]]},
                    )
        with open(sim["trace_path"]) as f:
            artifacts["trace.json"] = json.load(f)

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        for name, data in artifacts.items():
            z.writestr(name, json.dumps(data, indent=1, default=str))
    st.download_button("download artifacts (.zip)", buf.getvalue(),
                       "simumax_tpu_results.zip")

with tab_search:
    st.subheader("batch-split search at this layout")
    gbs = int(st.number_input(
        "global batch size", value=max(
            strategy.micro_batch_size * strategy.micro_batch_num
            * max(strategy.dp_size, 1), 1,
        ), min_value=1,
    ))
    if st.button("search batch split"):
        dp = strategy.dp_size
        if dp < 1:
            st.error(
                f"infeasible layout: world_size {strategy.world_size} < "
                f"tp*cp*pp = "
                f"{strategy.tp_size * strategy.cp_size * strategy.pp_size}"
            )
            st.stop()
        if gbs % dp:
            gbs = max(gbs // dp, 1) * dp
            st.info(f"global batch size rounded to {gbs} "
                    f"(must divide by dp={dp})")
        try:
            best = planner.batch_split(
                model, strategy, system, global_batch_size=gbs
            )["row"]
        except FeasibilityError as e:
            st.error(f"infeasible split: {e}")
            st.stop()
        if best is None:
            st.error("no feasible (mbs, mbc) split at this layout")
        else:
            st.dataframe([{
                k: best[k] for k in (
                    "mbs", "mbc", "mfu", "iter_ms", "peak_gib", "fits"
                )
            }])

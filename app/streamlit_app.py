"""Interactive web app (L8) — config picker/editor, runs PerfLLM,
renders results, offers artifact download.

Reference: ``app/streamlit_app.py`` (862 LoC). Requires ``streamlit``
(not part of the baked environment): ``pip install streamlit`` then
``streamlit run app/streamlit_app.py``. The same workflows are available
without extra deps through ``python -m simumax_tpu`` (see
``simumax_tpu/cli.py``).
"""

import io
import json
import os
import sys
import zipfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:
    import streamlit as st
except ImportError:  # pragma: no cover
    print(__doc__)
    sys.exit("streamlit is not installed; use `python -m simumax_tpu` instead")

from simumax_tpu import PerfLLM
from simumax_tpu.core.config import (
    ModelConfig,
    StrategyConfig,
    get_model_config,
    get_strategy_config,
    get_system_config,
    list_configs,
)

st.set_page_config(page_title="simumax-tpu", layout="wide")
st.title("simumax-tpu — analytical LLM training simulator for TPU")

cfgs = list_configs()
col1, col2, col3 = st.columns(3)
with col1:
    model_name = st.selectbox("model", cfgs["models"], index=max(
        cfgs["models"].index("llama3-8b") if "llama3-8b" in cfgs["models"] else 0, 0))
with col2:
    strategy_name = st.selectbox("strategy", cfgs["strategy"])
with col3:
    system_name = st.selectbox("system", cfgs["system"])

model = get_model_config(model_name)
strategy = get_strategy_config(strategy_name)

with st.expander("edit model config"):
    model_json = st.text_area(
        "model json", json.dumps(model.to_dict(), indent=2), height=240
    )
    model = ModelConfig.init_from_dict(json.loads(model_json))
with st.expander("edit strategy config"):
    strategy_json = st.text_area(
        "strategy json", json.dumps(strategy.to_dict(), indent=2, default=str),
        height=240,
    )
    data = json.loads(strategy_json)
    data.pop("recompute", None)
    strategy = StrategyConfig.init_from_dict(data)

run_sim = st.checkbox("also run the event simulator (Chrome trace)")

if st.button("estimate"):
    perf = PerfLLM().configure(strategy, model, system_name)
    perf.run_estimate()
    result = perf.analysis(verbose=False)
    cost, mem = result["compute_result"], result["mem_result"]

    c1, c2, c3, c4 = st.columns(4)
    c1.metric("iteration", f"{cost['iter_time_ms']:.1f} ms")
    c2.metric("MFU", f"{cost['mfu']*100:.2f} %")
    c3.metric("TFLOPS/chip", f"{cost['tflops_per_chip']:.1f}")
    c4.metric(
        "peak HBM",
        f"{mem['max_peak_gib']:.2f} GiB",
        delta="fits" if mem["fits"] else "DOES NOT FIT",
        delta_color="normal" if mem["fits"] else "inverse",
    )
    st.subheader("per-stage memory")
    st.dataframe(mem["stages"])
    st.subheader("mesh placement")
    st.json(result["net_info"])
    misses = result["efficiency_misses"]
    if misses:
        st.info(
            f"{sum(len(v) for v in misses.values())} efficiency-table "
            "misses — run `python -m simumax_tpu calibrate` on a TPU to "
            "refine the prediction."
        )

    artifacts = {
        "base_info.json": result["base_info"],
        "mem_result.json": mem,
        "compute_result.json": cost,
        "net_info.json": result["net_info"],
    }
    if run_sim:
        sim = perf.simulate("tmp/app_sim")
        st.subheader("simulator")
        st.write(
            f"event-simulated iteration: {sim['end_time_ms']:.2f} ms "
            f"({sim['num_events']} events)"
        )
        with open(sim["trace_path"]) as f:
            artifacts["trace.json"] = json.load(f)

    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        for name, data in artifacts.items():
            z.writestr(name, json.dumps(data, indent=1, default=str))
    st.download_button("download artifacts (.zip)", buf.getvalue(),
                       "simumax_tpu_results.zip")

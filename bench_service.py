"""Planning-service micro-benchmark: replay a burst of mixed
estimate / explain / search queries against the ``serve`` HTTP server
and measure it like a service — queries/s, cache hit rate, p50/p99
latency — cold (fresh content-addressed store) and warm (same burst
replayed against the populated store).

The burst is seeded and deterministic: ``--queries N`` requests with a
controlled ``--overlap`` fraction of intra-burst repeats, drawn from a
pool of unique (model, strategy, system, seq_len, mbc) combos at a
~75/20/5 estimate/explain/search mix. A sample of responses is checked
bit-identical against direct cache-off evaluation (the PR-8 parity
discipline applied to the cache layer).

Prints exactly ONE JSON line::

    {"metric": "service_qps_warm", "value": ..., "unit": "q/s",
     "qps_cold": ..., "speedup": ..., "hit_rate_warm": ...,
     "p50_warm_ms": ..., "p99_warm_ms": ..., "parity_ok": true, ...}

Usage::

    python bench_service.py                      # full burst
    python bench_service.py --queries 120 --threads 4   # quick look
    python bench_service.py \
        --baseline results/bench_service_baseline.json \
        --max-regression 0.7                     # regression gate

Gates (exit 1 on breach): the warm replay must reach
``--min-hit-rate`` (default 0.9) and ``--min-speedup`` x the cold qps
(default 3 — machine-relative but deliberately wide: a contended
2-vCPU runner can halve the warm phase; the recorded baseline
documents >=10x on a quiet machine); ``--baseline`` additionally gates
absolute warm qps like the other two benches.
"""

import argparse
import json
import os
import queue
import random
import shutil
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

try:
    from tools.bench_history import record_safely
except ImportError:  # script copied out of the repo: no trajectory
    def record_safely(result):
        return None

import warnings

warnings.filterwarnings("ignore")

import http.client

#: unique-query pool axes. Dense models only — every strategy below is
#: valid for all of them, so the pool is a clean product
#: (6 x 6 x 3 x 3 x 3 = 972 distinct estimate/explain bodies).
MODELS = ("llama3-8b", "llama2-7b", "llama2-13b", "qwen3-32b",
          "llama3-70b", "aquila2-7b")
STRATEGIES = ("tp1_pp2_dp4_mbs1", "tp2_pp1_dp4_mbs1", "tp4_pp1_dp2_mbs1",
              "tp8_pp1_dp1_mbs1", "tp1_pp1_dp8_mbs1", "tp4_pp4_dp16_mbs1")
SYSTEMS = ("tpu_v5e_256", "tpu_v5p_256", "tpu_v6e_256")
SEQ_LENS = (2048, 4096, 8192)
MBCS = (4, 8, 16)

#: endpoint mix of the unique pool (estimate-heavy, like interactive
#: planning traffic; search is per-query ~30x an estimate)
MIX = (("/v1/estimate", 0.75), ("/v1/explain", 0.20),
       ("/v1/search", 0.05))


def build_burst(n_queries: int, overlap: float, seed: int = 0):
    """Deterministic (endpoint, body) burst: ``n_unique`` *genuinely
    distinct* queries (deduplicated on canonical body + endpoint, so
    the cold phase really is 0% warm) plus ``overlap * n`` seeded
    repeats, shuffled."""
    rng = random.Random(seed)
    n_unique = max(1, int(round(n_queries * (1.0 - overlap))))
    combos = [
        (m, s, sysn, seq, mbc)
        for m in MODELS for s in STRATEGIES for sysn in SYSTEMS
        for seq in SEQ_LENS for mbc in MBCS
    ]
    rng.shuffle(combos)
    unique = []
    seen = set()
    searches = 0
    i = 0
    while len(unique) < n_unique:
        if i >= 4 * len(combos):
            raise SystemExit(
                f"query pool exhausted at {len(unique)} unique queries "
                f"(< requested {n_unique}); lower --queries or raise "
                f"--overlap"
            )
        m, s, sysn, seq, mbc = combos[i % len(combos)]
        r = len(unique) / max(1, n_unique)
        i += 1
        if r < MIX[0][1]:
            ep = "/v1/estimate"
        elif r < MIX[0][1] + MIX[1][1]:
            ep = "/v1/explain"
        else:
            ep = "/v1/search"
        if ep == "/v1/search":
            # small grids; cycle gbs so searches stay distinct even
            # though they ignore the strategy/seq axes
            searches += 1
            body = {
                "model": m, "system": sysn,
                "gbs": 32 * (1 + searches % 8), "world": 32,
                "tp": "1,2", "pp": "1", "zero": "1", "topk": 3,
            }
        else:
            body = {
                "model": m,
                "strategy": {"name": s, "seq_len": seq,
                             "micro_batch_num": mbc},
                "system": sysn,
            }
        dedup = (ep, json.dumps(body, sort_keys=True))
        if dedup in seen:
            continue
        seen.add(dedup)
        unique.append((ep, body))
    burst = list(unique)
    while len(burst) < n_queries:
        burst.append(unique[rng.randrange(len(unique))])
    rng.shuffle(burst)
    return burst, unique


def resolve_strategy_body(body: dict) -> dict:
    """Expand the compact ``{"name": ..., "seq_len": ...}`` strategy
    spelling into an inline config dict (exercises the server's inline-
    config path and keeps seq_len variants content-addressed apart)."""
    from simumax_tpu.core.config import get_strategy_config

    out = dict(body)
    strat = out.get("strategy")
    if isinstance(strat, dict) and "name" in strat:
        cfg = get_strategy_config(strat["name"])
        if strat.get("seq_len"):
            cfg.seq_len = int(strat["seq_len"])
        if strat.get("micro_batch_num"):
            cfg.micro_batch_num = int(strat["micro_batch_num"])
        out["strategy"] = cfg.to_dict()
    return out


def replay(port: int, burst, threads: int):
    """Replay the burst with ``threads`` concurrent clients; returns
    (elapsed_s, sorted per-request latencies, error count)."""
    work = queue.Queue()
    for i, item in enumerate(burst):
        work.put((i, item))
    lat = [0.0] * len(burst)
    errors = [0]
    lock = threading.Lock()

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        while True:
            try:
                i, (ep, body) = work.get_nowait()
            except queue.Empty:
                conn.close()
                return
            payload = json.dumps(resolve_strategy_body(body))
            t0 = time.perf_counter()
            try:
                conn.request("POST", ep, payload,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            except (OSError, http.client.HTTPException):
                ok = False
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=300
                )
            lat[i] = time.perf_counter() - t0
            if not ok:
                with lock:
                    errors[0] += 1

    t0 = time.perf_counter()
    ts = [threading.Thread(target=client) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return time.perf_counter() - t0, sorted(lat), errors[0]


def get_json(port: int, path: str) -> dict:
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", path)
    data = json.loads(conn.getresponse().read())
    conn.close()
    return data


def pct(sorted_vals, q: float) -> float:
    # the server's own percentile implementation, so the benched
    # p50/p99 are computed exactly like the /stats ones
    from simumax_tpu.service.server import percentile

    return percentile(sorted_vals, q)


def check_parity(port: int, unique, seed: int = 0, samples: int = 4):
    """A seeded sample of responses must be byte-identical to direct
    cache-off evaluation. The search probe is pinned to a grid known to
    *evaluate* cells (llama3-8b fits on v5p, nothing prunes), so the
    warm per-cell-served path is genuinely exercised — a fully-pruned
    sample would compare two trivially identical payloads."""
    from simumax_tpu.service.planner import Planner
    from simumax_tpu.service.server import response_bytes

    rng = random.Random(seed + 1)
    picks = [unique[rng.randrange(len(unique))] for _ in range(samples)]
    search = next((u for u in unique if u[0] == "/v1/search"), None)
    if search is not None:
        picks.append(search)
    probe = ("/v1/search", {
        "model": "llama3-8b", "system": "tpu_v5p_256", "gbs": 32,
        "world": 32, "tp": "1,2", "pp": "1", "zero": "1", "topk": 3,
    })
    picks.append(probe)
    off = Planner(enabled=False)
    for ep, body in picks:
        body = resolve_strategy_body(body)
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        conn.request("POST", ep, json.dumps(body),
                     {"Content-Type": "application/json"})
        served = conn.getresponse().read()
        conn.close()
        if ep == "/v1/estimate":
            direct = off.estimate(body["model"], body["strategy"],
                                  body["system"])
        elif ep == "/v1/explain":
            direct = off.explain(body["model"], body["strategy"],
                                 body["system"])
        else:
            direct = off.search(
                body["model"], body["system"], body["gbs"],
                world=body["world"],
                tp_list=tuple(int(x) for x in body["tp"].split(",")),
                pp_list=tuple(int(x) for x in body["pp"].split(",")),
                zero_list=tuple(
                    int(x) for x in body["zero"].split(",")),
                topk=body.get("topk", 5),
            )
            c = direct["cells"]
            scored = (c["total"] - c["pruned"] - c["deduped"]
                      - c["quarantined"])
            if body == resolve_strategy_body(probe[1]) and scored <= 0:
                return False, f"{ep} (probe grid scored no cells)"
        if response_bytes(direct) != served:
            return False, ep
    return True, None


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--queries", type=int, default=1000,
                    help="burst size (default 1000)")
    ap.add_argument("--overlap", type=float, default=0.1,
                    help="intra-burst repeat fraction (default 0.1)")
    ap.add_argument("--threads", type=int, default=4,
                    help="concurrent client connections (default 4)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cache-dir", metavar="DIR",
                    help="store root for the run (default: a fresh "
                         "temp dir, deleted afterwards — the bench "
                         "must start cold)")
    ap.add_argument("--min-hit-rate", type=float, default=0.9,
                    help="warm-replay store hit-rate floor (default "
                         "0.9; exit 1 below it)")
    ap.add_argument("--min-speedup", type=float, default=3.0,
                    help="warm/cold qps ratio floor (default 3 — wide "
                         "because a contended 2-vCPU runner can halve "
                         "the warm phase; the recorded baseline "
                         "documents the >=10x quiet-machine number)")
    ap.add_argument("--baseline", metavar="JSON",
                    help="previously saved bench JSON line to gate "
                         "absolute warm qps against")
    ap.add_argument("--max-regression", type=float, default=0.05,
                    metavar="FRAC",
                    help="fail when warm qps drops more than this "
                         "fraction below the baseline (default 0.05)")
    ap.add_argument("--skip-parity", action="store_true",
                    help="skip the bit-identity sample check (it "
                         "re-evaluates a few queries cache-off)")
    ap.add_argument("--trace", action="store_true",
                    help="arm span recording (observe/telemetry.py) "
                         "for the whole burst — the telemetry-overhead "
                         "gate runs the bench this way and compares "
                         "against the tracing-off baseline")
    args = ap.parse_args(argv)

    from simumax_tpu.service.planner import Planner
    from simumax_tpu.service.server import make_server

    if args.trace:
        from simumax_tpu.observe.telemetry import get_tracer

        get_tracer().configure(enabled=True)

    tmp = None
    cache_dir = args.cache_dir
    if not cache_dir:
        tmp = tempfile.mkdtemp(prefix="simumax-bench-service-")
        cache_dir = tmp
    planner = Planner(cache_dir=cache_dir)
    srv = make_server(planner, "127.0.0.1", 0)
    port = srv.server_address[1]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    try:
        burst, unique = build_burst(args.queries, args.overlap,
                                    args.seed)
        cold_s, cold_lat, cold_err = replay(port, burst, args.threads)
        stats_mid = get_json(port, "/stats")
        warm_s, warm_lat, warm_err = replay(port, burst, args.threads)
        stats_end = get_json(port, "/stats")
        parity_ok, parity_ep = (True, None) if args.skip_parity \
            else check_parity(port, unique, args.seed)
    finally:
        srv.shutdown()
        srv.server_close()
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)

    def counters(s):
        return s["store"]["counters"]

    warm_hits = (counters(stats_end)["hits"]
                 - counters(stats_mid)["hits"])
    warm_misses = (counters(stats_end)["misses"]
                   - counters(stats_mid)["misses"])
    lookups = warm_hits + warm_misses
    hit_rate = warm_hits / lookups if lookups else 0.0
    qps_cold = len(burst) / cold_s if cold_s else 0.0
    qps_warm = len(burst) / warm_s if warm_s else 0.0
    speedup = qps_warm / qps_cold if qps_cold else 0.0
    result = {
        "metric": "service_qps_warm",
        "value": round(qps_warm, 2),
        "unit": "q/s",
        "queries": len(burst),
        "unique_queries": len(unique),
        "overlap": args.overlap,
        "threads": args.threads,
        "qps_cold": round(qps_cold, 2),
        "speedup": round(speedup, 2),
        "hit_rate_warm": round(hit_rate, 4),
        "warm_hits": warm_hits,
        "warm_lookups": lookups,
        "p50_cold_ms": round(pct(cold_lat, 0.50) * 1e3, 2),
        "p99_cold_ms": round(pct(cold_lat, 0.99) * 1e3, 2),
        "p50_warm_ms": round(pct(warm_lat, 0.50) * 1e3, 2),
        "p99_warm_ms": round(pct(warm_lat, 0.99) * 1e3, 2),
        "cold_elapsed_s": round(cold_s, 3),
        "warm_elapsed_s": round(warm_s, 3),
        "errors": cold_err + warm_err,
        "parity_ok": parity_ok,
    }
    ok = True
    if cold_err or warm_err:
        result["errors_ok"] = ok = False
    if not parity_ok:
        result["parity_endpoint"] = parity_ep
        ok = False
    if hit_rate < args.min_hit_rate:
        result["hit_rate_ok"] = ok = False
    if speedup < args.min_speedup:
        result["speedup_ok"] = ok = False
    if args.baseline:
        with open(args.baseline) as f:
            base = json.load(f)
        if not isinstance(base.get("value"), (int, float)):
            print(json.dumps({
                "error": f"baseline {args.baseline} has no numeric "
                         f"'value' field; re-record it with a plain "
                         f"bench run",
            }))
            return 2
        for key, ours in (("queries", len(burst)),
                          ("overlap", args.overlap),
                          ("threads", args.threads)):
            theirs = base.get(key, ours)
            if theirs != ours:
                print(json.dumps({
                    "error": f"baseline {key} {theirs!r} != this "
                             f"run's {ours!r}; not comparable — "
                             f"re-record the baseline with matching "
                             f"flags",
                }))
                return 2
        floor = base["value"] * (1.0 - args.max_regression)
        result["baseline_value"] = base["value"]
        result["regression"] = (
            round(1.0 - qps_warm / base["value"], 4)
            if base["value"] else 0.0
        )
        result["regression_ok"] = qps_warm >= floor
        ok = ok and result["regression_ok"]
    if args.trace:
        result["trace"] = True
    print(json.dumps(result))
    record_safely(result)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""Planning-service micro-benchmark: replay a burst of mixed
estimate / explain / search queries against the ``serve`` HTTP server
and measure it like a service — queries/s, cache hit rate, p50/p99
latency — cold (fresh content-addressed store) and warm (same burst
replayed against the populated store).

The burst is seeded and deterministic: ``--queries N`` requests with a
controlled ``--overlap`` fraction of intra-burst repeats, drawn from a
pool of unique (model, strategy, system, seq_len, mbc) combos at a
~75/20/5 estimate/explain/search mix. A sample of responses is checked
bit-identical against direct cache-off evaluation (the PR-8 parity
discipline applied to the cache layer).

Prints exactly ONE JSON line::

    {"metric": "service_qps_warm", "value": ..., "unit": "q/s",
     "qps_cold": ..., "speedup": ..., "hit_rate_warm": ...,
     "p50_warm_ms": ..., "p99_warm_ms": ..., "parity_ok": true, ...}

Usage::

    python bench_service.py                      # full burst
    python bench_service.py --queries 120 --threads 4   # quick look
    python bench_service.py \
        --baseline results/bench_service_baseline.json \
        --max-regression 0.7                     # regression gate

Gates (exit 1 on breach): the warm replay must reach
``--min-hit-rate`` (default 0.9) and ``--min-speedup`` x the cold qps
(default 3 — machine-relative but deliberately wide: a contended
2-vCPU runner can halve the warm phase; the recorded baseline
documents >=10x on a quiet machine); ``--baseline`` additionally gates
absolute warm qps like the other two benches.

Siege mode (L13)::

    python bench_service.py --siege --workers 4 --admission 32 \
        --queries 100000 --vs-single results/siege_single.json

replays a Zipf-skewed production-shaped burst instead of the uniform
one: a fill phase visits a ``--siege-pool``-sized unique pool once
(cold), then ``--queries`` popularity-skewed draws (``--zipf`` alpha)
hammer the warm server — the hot head rides the pool's response
memory cache, exactly like production traffic with a popular working
set. With ``--admission`` an **overload phase** follows: fresh
all-cold queries from ``--overload-threads`` clients (default far
more than the workers can serve) must be load-shed with 429 +
``Retry-After`` while the p99 of *admitted* requests stays under
``--max-overload-p99-ms`` and every admitted request gets an answer.
``--vs-single`` + ``--min-pool-speedup`` gate the siege qps against a
same-machine single-process (``--workers 0``) siege baseline —
re-record it on the same box, never compare against another machine's
number. ``--dump-forensics DIR`` writes the final ``/stats`` and
``/metrics`` bodies for CI artifact upload.

Fleet siege (L19)::

    python bench_service.py --siege --nodes 3 --workers 2 \
        --admission 16 --queries 30000 \
        --vs-node ci-siege-single.json --min-fleet-speedup 2.4

forks ``--nodes`` fleet node *processes* on localhost ports joined in
one consistent-hash ring (the ``serve --nodes`` topology) and replays
the same Zipf burst with client-side affinity routing: every query
goes to the node that owns its route key — PR 13's affinity routing
one level up — so the store shards stay disjoint and the fleet scales
near-linearly where cores allow. The parity sample is deliberately
sent to NON-owner nodes: the bytes must cross the router hop and
still be bit-identical to direct cache-off evaluation. The overload
phase hammers node n0 alone, so admission has to compose across the
router and the owner's pool (relayed 429s pass through verbatim).
``--vs-node`` + ``--min-fleet-speedup`` gate fleet qps against a
same-machine single-node siege recorded with matching traffic flags
(the CI gate asks >=0.8*N on multi-core runners; the gate is
meaningful only with >= nodes+1 cores — the recorded baseline
annotates ``cores``). ``--dump-forensics`` writes per-node
``/stats`` + ``/metrics`` + ``/ring/state``.

Chaos siege (L20)::

    python bench_service.py --siege --nodes 3 --queries 2000 \
        --chaos service_chaos_killrejoin --dump-forensics out/

replays a declarative fault scenario (``configs/faults/*.json``,
schema ``simumax-service-chaos-v1``) against the live fleet: seeded
SIGSTOP/SIGKILL of node processes, store-shard corruption, and
drop/delay injection at the router socket layer, while the Zipf burst
keeps cycling with client-side failover. The gates are the
self-healing invariants, not throughput: no admitted request lost or
answered wrong (parity sampled *during* the outage), membership
convergence within the failure detector's probe bound after both the
kill and the scripted rejoin, quarantine of every corrupted entry by
the respawned node's recovery sweep, re-replication restoring its
owner coverage, and (with ``--admission``) an overload p99 within 2x
the chaos-free ``--max-overload-p99-ms`` bound even with the net
faults still armed. See ``docs/service.md`` "Failure semantics".
"""

import argparse
import json
import os
import queue
import random
import shutil
import signal
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

try:
    from tools.bench_history import record_safely
except ImportError:  # script copied out of the repo: no trajectory
    def record_safely(result):
        return None

import warnings

warnings.filterwarnings("ignore")

import http.client

#: unique-query pool axes. Dense models only — every strategy below is
#: valid for all of them, so the pool is a clean product
#: (6 x 6 x 3 x 3 x 3 = 972 distinct estimate/explain bodies).
MODELS = ("llama3-8b", "llama2-7b", "llama2-13b", "qwen3-32b",
          "llama3-70b", "aquila2-7b")
STRATEGIES = ("tp1_pp2_dp4_mbs1", "tp2_pp1_dp4_mbs1", "tp4_pp1_dp2_mbs1",
              "tp8_pp1_dp1_mbs1", "tp1_pp1_dp8_mbs1", "tp4_pp4_dp16_mbs1")
SYSTEMS = ("tpu_v5e_256", "tpu_v5p_256", "tpu_v6e_256")
SEQ_LENS = (2048, 4096, 8192)
MBCS = (4, 8, 16)

#: endpoint mix of the unique pool (estimate-heavy, like interactive
#: planning traffic; search is per-query ~30x an estimate)
MIX = (("/v1/estimate", 0.75), ("/v1/explain", 0.20),
       ("/v1/search", 0.05))


def build_burst(n_queries: int, overlap: float, seed: int = 0):
    """Deterministic (endpoint, body) burst: ``n_unique`` *genuinely
    distinct* queries (deduplicated on canonical body + endpoint, so
    the cold phase really is 0% warm) plus ``overlap * n`` seeded
    repeats, shuffled."""
    rng = random.Random(seed)
    n_unique = max(1, int(round(n_queries * (1.0 - overlap))))
    combos = [
        (m, s, sysn, seq, mbc)
        for m in MODELS for s in STRATEGIES for sysn in SYSTEMS
        for seq in SEQ_LENS for mbc in MBCS
    ]
    rng.shuffle(combos)
    unique = []
    seen = set()
    searches = 0
    i = 0
    while len(unique) < n_unique:
        if i >= 4 * len(combos):
            raise SystemExit(
                f"query pool exhausted at {len(unique)} unique queries "
                f"(< requested {n_unique}); lower --queries or raise "
                f"--overlap"
            )
        m, s, sysn, seq, mbc = combos[i % len(combos)]
        r = len(unique) / max(1, n_unique)
        i += 1
        if r < MIX[0][1]:
            ep = "/v1/estimate"
        elif r < MIX[0][1] + MIX[1][1]:
            ep = "/v1/explain"
        else:
            ep = "/v1/search"
        if ep == "/v1/search":
            # small grids; cycle gbs so searches stay distinct even
            # though they ignore the strategy/seq axes
            searches += 1
            body = {
                "model": m, "system": sysn,
                "gbs": 32 * (1 + searches % 8), "world": 32,
                "tp": "1,2", "pp": "1", "zero": "1", "topk": 3,
            }
        else:
            body = {
                "model": m,
                "strategy": {"name": s, "seq_len": seq,
                             "micro_batch_num": mbc},
                "system": sysn,
            }
        dedup = (ep, json.dumps(body, sort_keys=True))
        if dedup in seen:
            continue
        seen.add(dedup)
        unique.append((ep, body))
    burst = list(unique)
    while len(burst) < n_queries:
        burst.append(unique[rng.randrange(len(unique))])
    rng.shuffle(burst)
    return burst, unique


def zipf_burst(unique, n: int, alpha: float, seed: int = 0):
    """``n`` popularity-skewed draws from the unique pool: ranks are a
    seeded shuffle of the pool (popularity is independent of build
    order) and rank ``r`` is drawn with weight ``1/(r+1)^alpha`` — the
    classic Zipf head/tail shape of production query traffic."""
    rng = random.Random(seed + 11)
    order = list(range(len(unique)))
    rng.shuffle(order)
    weights = [1.0 / (r + 1) ** alpha for r in range(len(order))]
    picks = rng.choices(range(len(order)), weights=weights, k=n)
    return [unique[order[r]] for r in picks]


#: overload-phase mbc values — disjoint from MBCS, so every overload
#: body is a *new* content identity: all-cold traffic that saturates
#: the workers and forces admission control to act
OVERLOAD_MBCS = (6, 12, 24)


def overload_burst(n: int, seed: int = 0):
    """``n`` genuinely cold estimate queries (content identities
    disjoint from the siege pool) for the overload phase."""
    rng = random.Random(seed + 23)
    combos = [
        (m, s, sysn, seq, mbc)
        for m in MODELS for s in STRATEGIES for sysn in SYSTEMS
        for seq in SEQ_LENS for mbc in OVERLOAD_MBCS
    ]
    rng.shuffle(combos)
    out = []
    for m, s, sysn, seq, mbc in combos[:n]:
        out.append(("/v1/estimate", {
            "model": m,
            "strategy": {"name": s, "seq_len": seq,
                         "micro_batch_num": mbc},
            "system": sysn,
        }))
    return out


def resolve_strategy_body(body: dict) -> dict:
    """Expand the compact ``{"name": ..., "seq_len": ...}`` strategy
    spelling into an inline config dict (exercises the server's inline-
    config path and keeps seq_len variants content-addressed apart)."""
    from simumax_tpu.core.config import get_strategy_config

    out = dict(body)
    strat = out.get("strategy")
    if isinstance(strat, dict) and "name" in strat:
        cfg = get_strategy_config(strat["name"])
        if strat.get("seq_len"):
            cfg.seq_len = int(strat["seq_len"])
        if strat.get("micro_batch_num"):
            cfg.micro_batch_num = int(strat["micro_batch_num"])
        out["strategy"] = cfg.to_dict()
    return out


def serialize_burst(burst):
    """Pre-serialize every request body ONCE (clients of a production
    service send ready-made bytes; re-deriving configs per request
    would bill client-side work to the serving path under test)."""
    cache = {}
    out = []
    for ep, body in burst:
        key = (ep, json.dumps(body, sort_keys=True))
        payload = cache.get(key)
        if payload is None:
            payload = cache[key] = json.dumps(
                resolve_strategy_body(body))
        out.append((ep, payload))
    return out


def replay(port: int, burst, threads: int):
    """Replay the burst with ``threads`` concurrent clients; returns
    (elapsed_s, sorted per-request latencies, error count)."""
    work = queue.Queue()
    for i, item in enumerate(serialize_burst(burst)):
        work.put((i, item))
    lat = [0.0] * len(burst)
    errors = [0]
    lock = threading.Lock()

    def client():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=300)
        while True:
            try:
                i, (ep, payload) = work.get_nowait()
            except queue.Empty:
                conn.close()
                return
            t0 = time.perf_counter()
            try:
                conn.request("POST", ep, payload,
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                resp.read()
                ok = resp.status == 200
            except (OSError, http.client.HTTPException):
                ok = False
                conn.close()
                conn = http.client.HTTPConnection(
                    "127.0.0.1", port, timeout=300
                )
            lat[i] = time.perf_counter() - t0
            if not ok:
                with lock:
                    errors[0] += 1

    t0 = time.perf_counter()
    ts = [threading.Thread(target=client) for _ in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    return time.perf_counter() - t0, sorted(lat), errors[0]


def _request_bytes(ep: str, payload: str) -> bytes:
    """One pre-built HTTP/1.1 request. Siege clients accept gzip like
    any production HTTP client: large hot responses ride the
    memcache's cached transport encoding."""
    body = payload.encode("utf-8")
    return (b"POST " + ep.encode("ascii") + b" HTTP/1.1\r\n"
            b"Host: bench\r\nContent-Type: application/json\r\n"
            b"Accept-Encoding: gzip\r\n"
            b"Content-Length: " + str(len(body)).encode("ascii")
            + b"\r\n\r\n" + body)


def _read_response(sock, buf: bytes):
    """Read exactly one Content-Length response; returns
    (status, remaining buffer)."""
    while b"\r\n\r\n" not in buf:
        chunk = sock.recv(1 << 16)
        if not chunk:
            raise OSError("server closed the connection")
        buf += chunk
    head, buf = buf.split(b"\r\n\r\n", 1)
    status = int(head.split(b" ", 2)[1])
    clen = 0
    for line in head.split(b"\r\n")[1:]:
        if line[:15].lower() == b"content-length:":
            clen = int(line[15:])
    while len(buf) < clen:
        chunk = sock.recv(1 << 16)
        if not chunk:
            raise OSError("server closed mid-body")
        buf += chunk
    return status, buf[clen:]


def _pipelined_worker(port: int, reqs, depth: int, lat, counts):
    """One siege connection: keeps up to ``depth`` requests in flight
    (HTTP/1.1 pipelining — the standard siege-harness technique that
    amortizes per-request syscalls on both sides of the socket) and
    accounts every response. Appends 2xx latencies to ``lat`` and
    bumps ``counts`` in place (caller owns synchronization)."""
    import collections
    import socket as _socket

    n = len(reqs)
    sent_at = [0.0] * n
    i = done = 0
    inflight = collections.deque()
    while done < n:
        try:
            sock = _socket.create_connection(("127.0.0.1", port),
                                             timeout=600)
            sock.setsockopt(_socket.IPPROTO_TCP,
                            _socket.TCP_NODELAY, 1)
            buf = b""
            while done < n:
                out = bytearray()
                fresh = []
                while len(inflight) < depth and i < n:
                    out += reqs[i]
                    inflight.append(i)
                    fresh.append(i)
                    i += 1
                if out:
                    now = time.perf_counter()
                    for idx in fresh:
                        sent_at[idx] = now
                    sock.sendall(out)
                status, buf = _read_response(sock, buf)
                idx = inflight.popleft()
                done += 1
                if status == 200:
                    counts["ok"] += 1
                    lat.append(time.perf_counter() - sent_at[idx])
                elif status == 429:
                    counts["shed"] += 1
                else:
                    counts["error"] += 1
        except OSError:
            # a dropped connection loses its window: every in-flight
            # request got no answer — that IS an error, counted once,
            # and the rest of the shard continues on a fresh connection
            counts["error"] += len(inflight)
            done += len(inflight)
            inflight.clear()
        finally:
            try:
                sock.close()
            except (OSError, UnboundLocalError):
                pass


def _counted_clients(port: int, items, threads: int, depth: int = 1):
    """``threads`` keep-alive raw-socket connections drain the
    pre-serialized ``items`` (round-robin shards), each with a
    ``depth``-deep pipeline; returns (2xx latencies, counts)."""
    reqs = [_request_bytes(ep, payload) for ep, payload in items]
    shards = [reqs[i::threads] for i in range(threads)]
    results = []
    ts = []
    for shard in shards:
        if not shard:
            continue
        lat = []
        counts = {"ok": 0, "shed": 0, "error": 0}
        results.append((lat, counts))
        ts.append(threading.Thread(
            target=_pipelined_worker,
            args=(port, shard, max(1, depth), lat, counts)))
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    lat = []
    counts = {"ok": 0, "shed": 0, "error": 0}
    for plat, pcounts in results:
        lat.extend(plat)
        for k, v in pcounts.items():
            counts[k] += v
    return lat, counts


def _client_proc(port, shard, conns, depth, out_q):
    # forked siege client: fresh sockets, no shared state with the
    # in-process server — pure bytes/socket work
    lat, counts = _counted_clients(port, shard, conns, depth=depth)
    out_q.put((lat, counts))


def replay_counted(port: int, burst, threads: int, procs: int = 1,
                   depth: int = 1):
    """Siege-phase replay with full status accounting. Returns
    ``(elapsed_s, sorted 2xx latencies, counts)`` where counts has
    ``ok`` / ``shed`` (429) / ``error`` — and their sum is
    ``len(burst)``: every request got an answer (the admission
    contract: shed fast or served, never dropped or hung).

    With ``procs > 1`` the clients run in that many forked
    *processes* (``threads`` connections split across them) — siege
    clients must not share the server's GIL, exactly like the remote
    clients of a production deployment."""
    items = serialize_burst(burst)
    if procs <= 1:
        t0 = time.perf_counter()
        lat, counts = _counted_clients(port, items, threads,
                                       depth=depth)
        return time.perf_counter() - t0, sorted(lat), counts
    import multiprocessing

    ctx = multiprocessing.get_context("fork")
    out_q = ctx.Queue()
    # round-robin shards keep the hot/cold mix balanced per process
    shards = [items[i::procs] for i in range(procs)]
    conns = max(1, threads // procs)
    ps = [ctx.Process(target=_client_proc,
                      args=(port, shard, conns, depth, out_q),
                      daemon=True)
          for shard in shards if shard]
    t0 = time.perf_counter()
    for p in ps:
        p.start()
    lat = []
    counts = {"ok": 0, "shed": 0, "error": 0}
    for _ in ps:
        plat, pcounts = out_q.get()
        lat.extend(plat)
        for k, v in pcounts.items():
            counts[k] += v
    elapsed = time.perf_counter() - t0
    for p in ps:
        p.join()
    return elapsed, sorted(lat), counts


def start_server(args):
    """Build the bench server exactly like ``cmd_serve`` does:
    threaded by default, pooled (+ admission) under ``--workers`` /
    ``--admission``. Returns ``(srv, port, cleanup)``."""
    from simumax_tpu.service.planner import Planner
    from simumax_tpu.service.server import (
        AdmissionController,
        make_server,
    )

    tmp = None
    cache_dir = args.cache_dir
    if not cache_dir:
        tmp = tempfile.mkdtemp(prefix="simumax-bench-service-")
        cache_dir = tmp
    pool = None
    workers = getattr(args, "workers", 0)
    if workers:
        from simumax_tpu.service.pool import WorkerPool

        pool = WorkerPool(cache_dir=cache_dir, workers=workers)
        planner = Planner(store=pool.store)
    else:
        planner = Planner(cache_dir=cache_dir)
    admission = None
    backlog = getattr(args, "admission", 0)
    if backlog:
        admission = AdmissionController(backlog, pool=pool)
    srv = make_server(planner, "127.0.0.1", 0, pool=pool,
                      admission=admission)
    port = srv.server_address[1]
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()

    def cleanup():
        srv.shutdown()
        srv.server_close()
        if tmp:
            shutil.rmtree(tmp, ignore_errors=True)

    return srv, port, cleanup


def _fleet_node_proc(idx: int, ports, cache_root: str, workers: int,
                     admission_n: int, probe_s: float = 0.0,
                     probe_seed: int = 0):
    """One forked fleet node: planner (+ optional worker pool wired
    into the fleet flight table), admission, ring surface — exactly
    the ``serve --ring ... --join n<idx>`` topology. ``probe_s``
    arms the failure detector (the chaos bench runs with it on, the
    plain fleet siege without)."""
    from simumax_tpu.service.node import attach_fleet
    from simumax_tpu.service.planner import Planner
    from simumax_tpu.service.ring import format_ring_spec
    from simumax_tpu.service.server import (
        AdmissionController,
        make_server,
    )

    members = {f"n{i}": ("127.0.0.1", p) for i, p in enumerate(ports)}
    spec = format_ring_spec(members)
    node_id = f"n{idx}"
    cache_dir = os.path.join(cache_root, node_id)
    pool = None
    if workers:
        from simumax_tpu.service.pool import WorkerPool

        pool = WorkerPool(cache_dir=cache_dir, workers=workers,
                          fleet_spec=(node_id, spec))
        planner = Planner(store=pool.store)
    else:
        planner = Planner(cache_dir=cache_dir)
    admission = AdmissionController(admission_n, pool=pool) \
        if admission_n else None
    srv = make_server(planner, "127.0.0.1", ports[idx], pool=pool,
                      admission=admission)
    attach_fleet(srv, node_id, spec, probe_s=probe_s,
                 probe_seed=probe_seed)

    def _term(signum, frame):
        # cleanup() SIGTERMs this node: reap the daemon pool workers
        # before dying — a SIGTERM'd parent skips Python cleanup, and
        # an orphaned worker inherits (and holds open) the bench's
        # stdout/stderr pipes forever, so the run looks hung
        if pool is not None:
            pool.close()
        os._exit(0)

    signal.signal(signal.SIGTERM, _term)
    srv.serve_forever()


def _wait_healthy(port: int, deadline_s: float, on_fail=None):
    deadline = time.monotonic() + deadline_s
    while True:
        try:
            if get_json(port, "/healthz").get("status") == "ok":
                return
        except (OSError, ValueError, http.client.HTTPException):
            pass
        if time.monotonic() > deadline:
            if on_fail is not None:
                on_fail()
            raise SystemExit(
                f"fleet node on port {port} never became healthy")
        time.sleep(0.1)


class FleetHandle:
    """The forked fleet plus the process-level hooks the chaos
    injector drives: pid lookup (changes across a kill+start cycle),
    respawn on the *same* port and store shard (the rejoin path), and
    per-node shard roots (the corruption target)."""

    def __init__(self, ports, procs, spawn, cache_root, tmp):
        self.ports = ports
        self.procs = procs
        self._spawn = spawn
        self.cache_root = cache_root
        self._tmp = tmp

    def pid_of(self, idx: int):
        p = self.procs[idx]
        return p.pid if p.is_alive() else None

    def store_root(self, idx: int) -> str:
        return os.path.join(self.cache_root, f"n{idx}")

    def respawn(self, idx: int):
        """Restart a killed node on its original port and shard — the
        rejoin the surviving detectors must observe. The respawned
        process re-runs the store's crash-recovery sweep on whatever
        the SIGKILL (and any corruption event) left on disk."""
        old = self.procs[idx]
        if old.is_alive():
            return
        old.join(5)
        p = self._spawn(idx)
        p.start()
        self.procs[idx] = p
        _wait_healthy(self.ports[idx], 60.0)

    def cleanup(self):
        for p in self.procs:
            if p.is_alive():
                p.terminate()
        for p in self.procs:
            p.join(5)
        if self._tmp:
            shutil.rmtree(self._tmp, ignore_errors=True)


def start_fleet(args, probe_s: float = 0.0, probe_seed: int = 0):
    """Fork ``--nodes`` fleet node processes on free localhost ports;
    returns a :class:`FleetHandle` once every /healthz answers."""
    import multiprocessing
    import socket as _socket

    ctx = multiprocessing.get_context("fork")
    socks = []
    for _ in range(args.nodes):
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    tmp = None
    cache_root = args.cache_dir
    if not cache_root:
        tmp = tempfile.mkdtemp(prefix="simumax-bench-fleet-")
        cache_root = tmp

    def spawn(i):
        # NOT daemonic: a pooled node must fork its own worker
        # processes (daemons may not have children); cleanup() — or a
        # chaos SIGKILL plus respawn — reaps them instead
        return ctx.Process(target=_fleet_node_proc,
                           args=(i, ports, cache_root, args.workers,
                                 args.admission, probe_s, probe_seed),
                           daemon=False, name=f"bench-node-n{i}")

    procs = [spawn(i) for i in range(args.nodes)]
    for p in procs:
        p.start()

    def on_fail():
        for p in procs:
            p.terminate()

    for port in ports:
        _wait_healthy(port, 60.0, on_fail=on_fail)
    return FleetHandle(ports, procs, spawn, cache_root, tmp)


def partition_by_owner(burst, n_nodes: int):
    """Client-side affinity routing: split ``(endpoint, body)`` items
    by ring owner of each request's route key — the same deterministic
    placement every node's router computes, so a partitioned client
    hits only owners and no request pays a forwarding hop."""
    from simumax_tpu.service.ring import HashRing
    from simumax_tpu.service.router import route_key

    ring = HashRing([f"n{i}" for i in range(n_nodes)])
    shards = [[] for _ in range(n_nodes)]
    for ep, body in burst:
        owner = ring.owner(route_key(ep, resolve_strategy_body(body)))
        shards[int(owner[1:])].append((ep, body))
    return shards


def replay_fleet(ports, burst, threads: int, depth: int = 1):
    """Partitioned fleet replay: one forked client process per node
    drains that node's owner shard with ``threads`` pipelined
    connections. Returns ``(elapsed_s, sorted 2xx latencies, counts,
    shard_sizes)`` — elapsed is wall clock over ALL nodes, so q/s
    reflects true fleet throughput, not a per-node average."""
    import multiprocessing

    ctx = multiprocessing.get_context("fork")
    shards = partition_by_owner(burst, len(ports))
    out_q = ctx.Queue()
    ps = []
    for port, shard in zip(ports, shards):
        if not shard:
            continue
        ps.append(ctx.Process(
            target=_client_proc,
            args=(port, serialize_burst(shard), threads, depth, out_q),
            daemon=True))
    t0 = time.perf_counter()
    for p in ps:
        p.start()
    lat = []
    counts = {"ok": 0, "shed": 0, "error": 0}
    for _ in ps:
        plat, pcounts = out_q.get()
        lat.extend(plat)
        for k, v in pcounts.items():
            counts[k] += v
    elapsed = time.perf_counter() - t0
    for p in ps:
        p.join()
    return elapsed, sorted(lat), counts, [len(s) for s in shards]


def _non_owner_port(ports):
    """Port selector for the fleet parity sample: always a node that
    does NOT own the request, so the compared bytes crossed the
    router hop."""
    from simumax_tpu.service.ring import HashRing
    from simumax_tpu.service.router import route_key

    ring = HashRing([f"n{i}" for i in range(len(ports))])

    def pick(ep, body):
        owner = int(ring.owner(route_key(ep, body))[1:])
        return ports[(owner + 1) % len(ports)]

    return pick


def dump_forensics(port: int, out_dir: str):
    """Write the final /stats and /metrics bodies — plus, when
    ``--trace`` armed the tracer, the retained request span trees as
    a chrome trace — so a failed CI gate ships its serving-side
    evidence as artifacts."""
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "stats.json"), "w") as f:
        json.dump(get_json(port, "/stats"), f, indent=2, default=str)
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
    conn.request("GET", "/metrics")
    body = conn.getresponse().read()
    conn.close()
    with open(os.path.join(out_dir, "metrics.txt"), "wb") as f:
        f.write(body)
    from simumax_tpu.observe.telemetry import (
        get_tracer,
        write_chrome_trace,
    )

    tracer = get_tracer()
    if tracer.enabled:
        spans = tracer.drain()
        if spans:
            write_chrome_trace(
                spans, os.path.join(out_dir, "trace.json"))


def get_json(port: int, path: str, timeout: float = 60) -> dict:
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("GET", path)
    data = json.loads(conn.getresponse().read())
    conn.close()
    return data


def post_json(port: int, path: str, body: dict,
              timeout: float = 60) -> dict:
    conn = http.client.HTTPConnection("127.0.0.1", port,
                                      timeout=timeout)
    conn.request("POST", path, json.dumps(body),
                 {"Content-Type": "application/json"})
    data = json.loads(conn.getresponse().read())
    conn.close()
    return data


def pct(sorted_vals, q: float) -> float:
    # the server's own percentile implementation, so the benched
    # p50/p99 are computed exactly like the /stats ones
    from simumax_tpu.service.server import percentile

    return percentile(sorted_vals, q)


def check_parity(port: int, unique, seed: int = 0, samples: int = 4,
                 port_for=None):
    """A seeded sample of responses must be byte-identical to direct
    cache-off evaluation. The search probe is pinned to a grid known to
    *evaluate* cells (llama3-8b fits on v5p, nothing prunes), so the
    warm per-cell-served path is genuinely exercised — a fully-pruned
    sample would compare two trivially identical payloads. The fleet
    passes ``port_for`` to aim every sample at a NON-owner node: the
    compared bytes then crossed the router hop."""
    from simumax_tpu.service.planner import Planner
    from simumax_tpu.service.server import response_bytes

    rng = random.Random(seed + 1)
    picks = [unique[rng.randrange(len(unique))] for _ in range(samples)]
    search = next((u for u in unique if u[0] == "/v1/search"), None)
    if search is not None:
        picks.append(search)
    probe = ("/v1/search", {
        "model": "llama3-8b", "system": "tpu_v5p_256", "gbs": 32,
        "world": 32, "tp": "1,2", "pp": "1", "zero": "1", "topk": 3,
    })
    picks.append(probe)
    off = Planner(enabled=False)
    for ep, body in picks:
        body = resolve_strategy_body(body)
        target = port_for(ep, body) if port_for else port
        conn = http.client.HTTPConnection("127.0.0.1", target,
                                          timeout=300)
        conn.request("POST", ep, json.dumps(body),
                     {"Content-Type": "application/json"})
        served = conn.getresponse().read()
        conn.close()
        if ep == "/v1/estimate":
            direct = off.estimate(body["model"], body["strategy"],
                                  body["system"])
        elif ep == "/v1/explain":
            direct = off.explain(body["model"], body["strategy"],
                                 body["system"])
        else:
            direct = off.search(
                body["model"], body["system"], body["gbs"],
                world=body["world"],
                tp_list=tuple(int(x) for x in body["tp"].split(",")),
                pp_list=tuple(int(x) for x in body["pp"].split(",")),
                zero_list=tuple(
                    int(x) for x in body["zero"].split(",")),
                topk=body.get("topk", 5),
            )
            c = direct["cells"]
            scored = (c["total"] - c["pruned"] - c["deduped"]
                      - c["quarantined"])
            if body == resolve_strategy_body(probe[1]) and scored <= 0:
                return False, f"{ep} (probe grid scored no cells)"
        if response_bytes(direct) != served:
            return False, ep
    return True, None


def run_siege(args) -> int:
    """The production-shaped siege: fill (cold) -> Zipf siege (warm,
    the headline metric) -> overload (all-cold hammer vs admission
    control) -> parity sample. One JSON line, exit 1 on any gate."""
    srv, port, cleanup = start_server(args)
    overload = None
    try:
        _burst, unique = build_burst(args.siege_pool, 0.0, args.seed)
        fill_s, _fill_lat, fill_counts = replay_counted(
            port, unique, args.threads, procs=args.client_procs,
            depth=args.pipeline)
        siege = zipf_burst(unique, args.queries, args.zipf, args.seed)
        siege_s, siege_lat, siege_counts = replay_counted(
            port, siege, args.threads, procs=args.client_procs,
            depth=args.pipeline)
        stats_end = get_json(port, "/stats")
        if args.admission and args.overload_queries:
            # depth 1: overload latency/shed semantics are per-request
            oburst = overload_burst(args.overload_queries, args.seed)
            overload = replay_counted(port, oburst,
                                      args.overload_threads,
                                      procs=args.client_procs)
        parity_ok, parity_ep = (True, None) if args.skip_parity \
            else check_parity(port, unique, args.seed)
        if args.dump_forensics:
            dump_forensics(port, args.dump_forensics)
    finally:
        cleanup()

    qps_siege = len(siege) / siege_s if siege_s else 0.0
    qps_fill = len(unique) / fill_s if fill_s else 0.0
    result = {
        "metric": "service_qps_siege",
        "value": round(qps_siege, 2),
        "unit": "q/s",
        # mode encodes the traffic shape (pool size + skew): history
        # series with different shapes never baseline each other
        "mode": f"siege-pool{args.siege_pool}-z{args.zipf}",
        "queries": len(siege),
        "threads": args.threads,
        "client_procs": args.client_procs,
        "pipeline": args.pipeline,
        "workers": args.workers,
        "admission": args.admission,
        "qps_fill": round(qps_fill, 2),
        "fill_queries": len(unique),
        "p50_siege_ms": round(pct(siege_lat, 0.50) * 1e3, 2),
        "p99_siege_ms": round(pct(siege_lat, 0.99) * 1e3, 2),
        "fill_elapsed_s": round(fill_s, 3),
        "siege_elapsed_s": round(siege_s, 3),
        "errors": fill_counts["error"] + siege_counts["error"],
        "shed_outside_overload": fill_counts["shed"]
        + siege_counts["shed"],
        "parity_ok": parity_ok,
    }
    if args.workers:
        mc = (stats_end.get("pool") or {}).get("memcache") or {}
        result["memcache_hits"] = mc.get("hits", 0)
        result["memcache_entries"] = mc.get("entries", 0)
    ok = True
    if result["errors"]:
        result["errors_ok"] = ok = False
    if result["shed_outside_overload"]:
        # fill/siege clients never outnumber the admission budget; a
        # shed here means the bench was misconfigured
        result["shed_ok"] = ok = False
    if not parity_ok:
        result["parity_endpoint"] = parity_ep
        ok = False
    if overload is not None:
        o_s, o_lat, o_counts = overload
        answered = sum(o_counts.values())
        o_p99_ms = pct(o_lat, 0.99) * 1e3 if o_lat else 0.0
        result.update({
            # the actual burst length: overload_burst caps at its
            # cold-combo pool, so a large --overload-queries yields
            # fewer queries than asked
            "overload_queries": len(oburst),
            "overload_threads": args.overload_threads,
            "overload_elapsed_s": round(o_s, 3),
            "overload_admitted": o_counts["ok"],
            "overload_shed": o_counts["shed"],
            "overload_errors": o_counts["error"],
            "overload_p99_ms": round(o_p99_ms, 2),
        })
        # the admission contract, gated: every request answered (none
        # dropped/hung), real shedding happened, admitted p99 bounded
        if answered != len(oburst) or o_counts["error"]:
            result["overload_answered_ok"] = ok = False
        if not o_counts["shed"]:
            result["overload_shed_ok"] = ok = False
        if o_p99_ms > args.max_overload_p99_ms:
            result["overload_p99_ok"] = ok = False
    if args.vs_single:
        with open(args.vs_single) as f:
            base = json.load(f)
        if base.get("workers", -1) != 0 \
                or base.get("metric") != "service_qps_siege":
            print(json.dumps({
                "error": f"--vs-single {args.vs_single} is not a "
                         f"single-process siege baseline (need "
                         f"workers=0, metric=service_qps_siege); "
                         f"re-record it on this machine with "
                         f"--siege --workers 0",
            }))
            return 2
        for key in ("mode", "queries", "threads", "client_procs",
                    "pipeline"):
            if base.get(key) != result[key]:
                print(json.dumps({
                    "error": f"--vs-single {key} {base.get(key)!r} != "
                             f"this run's {result[key]!r}; not "
                             f"comparable — re-record with matching "
                             f"flags",
                }))
                return 2
        speedup = qps_siege / base["value"] if base["value"] else 0.0
        result["single_qps"] = base["value"]
        result["pool_speedup"] = round(speedup, 2)
        if args.workers and speedup < args.min_pool_speedup:
            result["pool_speedup_ok"] = ok = False
    if args.baseline:
        with open(args.baseline) as f:
            base = json.load(f)
        if not isinstance(base.get("value"), (int, float)):
            print(json.dumps({
                "error": f"baseline {args.baseline} has no numeric "
                         f"'value' field",
            }))
            return 2
        for key in ("mode", "queries", "threads", "workers",
                    "admission"):
            if base.get(key, result[key]) != result[key]:
                print(json.dumps({
                    "error": f"baseline {key} {base.get(key)!r} != "
                             f"this run's {result[key]!r}; not "
                             f"comparable",
                }))
                return 2
        floor = base["value"] * (1.0 - args.max_regression)
        result["baseline_value"] = base["value"]
        result["regression_ok"] = qps_siege >= floor
        ok = ok and result["regression_ok"]
    print(json.dumps(result))
    record_safely(result)
    return 0 if ok else 1


def dump_fleet_forensics(ports, out_dir: str):
    """Per-node /stats + /metrics + /ring/state (which carries the
    recovery report and the quarantine listing) under ``out_dir/n<i>``
    — a failed fleet or chaos gate ships every node's serving- and
    ring-side evidence. A node the chaos scenario left dead gets an
    ``unreachable.txt`` marker instead of a crash."""
    for i, port in enumerate(ports):
        sub = os.path.join(out_dir, f"n{i}")
        os.makedirs(sub, exist_ok=True)
        try:
            stats = get_json(port, "/stats")
            ring_state = get_json(port, "/ring/state")
            conn = http.client.HTTPConnection("127.0.0.1", port,
                                              timeout=60)
            conn.request("GET", "/metrics")
            body = conn.getresponse().read()
            conn.close()
        except (OSError, ValueError,
                http.client.HTTPException) as exc:
            with open(os.path.join(sub, "unreachable.txt"), "w") as f:
                f.write(f"n{i} on port {port}: {exc}\n")
            continue
        with open(os.path.join(sub, "stats.json"), "w") as f:
            json.dump(stats, f, indent=2, default=str)
        with open(os.path.join(sub, "metrics.txt"), "wb") as f:
            f.write(body)
        with open(os.path.join(sub, "ring_state.json"), "w") as f:
            json.dump(ring_state, f, indent=2, default=str)
        with open(os.path.join(sub, "quarantine.json"), "w") as f:
            json.dump(ring_state.get("quarantine", []), f, indent=2,
                      default=str)


def run_fleet_siege(args) -> int:
    """The multi-node siege: fill + Zipf replay with client-side
    affinity routing across ``--nodes`` forked fleet nodes, a
    NON-owner parity sample (bytes must survive the router hop),
    an overload phase hammering n0 alone (admission composes across
    router and pool), and a fleet-speedup gate vs a same-machine
    single-node baseline. One JSON line, exit 1 on any gate."""
    fleet = start_fleet(args)
    ports, cleanup = fleet.ports, fleet.cleanup
    overload = None
    try:
        _burst, unique = build_burst(args.siege_pool, 0.0, args.seed)
        fill_s, _fill_lat, fill_counts, _fs = replay_fleet(
            ports, unique, args.threads, depth=args.pipeline)
        siege = zipf_burst(unique, args.queries, args.zipf, args.seed)
        siege_s, siege_lat, siege_counts, shard_sizes = replay_fleet(
            ports, siege, args.threads, depth=args.pipeline)
        if args.admission and args.overload_queries:
            # all-cold hammer on ONE node: n0 sheds what it cannot
            # take, forwards what it does not own, and relays the
            # owners' 429s verbatim — admission composes end to end
            oburst = overload_burst(args.overload_queries, args.seed)
            overload = replay_counted(ports[0], oburst,
                                      args.overload_threads,
                                      procs=args.client_procs)
        parity_ok, parity_ep = (True, None) if args.skip_parity \
            else check_parity(ports[0], unique, args.seed,
                              port_for=_non_owner_port(ports))
        ring_states = [get_json(p, "/ring/state") for p in ports]
        if args.dump_forensics:
            dump_fleet_forensics(ports, args.dump_forensics)
    finally:
        cleanup()

    qps_siege = len(siege) / siege_s if siege_s else 0.0
    qps_fill = len(unique) / fill_s if fill_s else 0.0
    routers = [rs.get("router", {}) for rs in ring_states]
    remotes = [(rs.get("flights", {}) or {}).get("remote", {})
               for rs in ring_states]
    result = {
        "metric": "service_qps_siege",
        "value": round(qps_siege, 2),
        "unit": "q/s",
        "mode": f"siege-pool{args.siege_pool}-z{args.zipf}",
        "queries": len(siege),
        "threads": args.threads,
        "client_procs": args.client_procs,
        "pipeline": args.pipeline,
        "workers": args.workers,
        "admission": args.admission,
        "nodes": args.nodes,
        # the scaling gate is meaningful only with >= nodes+1 cores;
        # recorded baselines carry the recording machine's count
        "cores": os.cpu_count(),
        "qps_fill": round(qps_fill, 2),
        "fill_queries": len(unique),
        "p50_siege_ms": round(pct(siege_lat, 0.50) * 1e3, 2),
        "p99_siege_ms": round(pct(siege_lat, 0.99) * 1e3, 2),
        "fill_elapsed_s": round(fill_s, 3),
        "siege_elapsed_s": round(siege_s, 3),
        "shards": shard_sizes,
        "errors": fill_counts["error"] + siege_counts["error"],
        "shed_outside_overload": fill_counts["shed"]
        + siege_counts["shed"],
        "parity_ok": parity_ok,
        "router_forwards": sum(r.get("forwards", 0) for r in routers),
        "router_local": sum(r.get("local", 0) for r in routers),
        "router_retries": sum(r.get("retries", 0) for r in routers),
        "remote_follows": sum(r.get("remote_follows", 0)
                              for r in remotes),
    }
    ok = True
    if result["errors"]:
        result["errors_ok"] = ok = False
    if result["shed_outside_overload"]:
        result["shed_ok"] = ok = False
    if not parity_ok:
        result["parity_endpoint"] = parity_ep
        ok = False
    if overload is not None:
        o_s, o_lat, o_counts = overload
        answered = sum(o_counts.values())
        o_p99_ms = pct(o_lat, 0.99) * 1e3 if o_lat else 0.0
        result.update({
            "overload_queries": len(oburst),
            "overload_threads": args.overload_threads,
            "overload_elapsed_s": round(o_s, 3),
            "overload_admitted": o_counts["ok"],
            "overload_shed": o_counts["shed"],
            "overload_errors": o_counts["error"],
            "overload_p99_ms": round(o_p99_ms, 2),
        })
        if answered != len(oburst) or o_counts["error"]:
            result["overload_answered_ok"] = ok = False
        if not o_counts["shed"]:
            result["overload_shed_ok"] = ok = False
        if o_p99_ms > args.max_overload_p99_ms:
            result["overload_p99_ok"] = ok = False
    if args.vs_node:
        with open(args.vs_node) as f:
            base = json.load(f)
        if base.get("metric") != "service_qps_siege" \
                or base.get("nodes"):
            print(json.dumps({
                "error": f"--vs-node {args.vs_node} is not a "
                         f"single-node siege baseline (need "
                         f"metric=service_qps_siege without a "
                         f"'nodes' key); record one on this machine "
                         f"with --siege (no --nodes)",
            }))
            return 2
        for key in ("mode", "queries", "pipeline"):
            if base.get(key) != result[key]:
                print(json.dumps({
                    "error": f"--vs-node {key} {base.get(key)!r} != "
                             f"this run's {result[key]!r}; not "
                             f"comparable — re-record with matching "
                             f"flags",
                }))
                return 2
        speedup = qps_siege / base["value"] if base["value"] else 0.0
        result["single_node_qps"] = base["value"]
        result["fleet_speedup"] = round(speedup, 2)
        if args.min_fleet_speedup and speedup < args.min_fleet_speedup:
            result["fleet_speedup_ok"] = ok = False
    if args.baseline:
        with open(args.baseline) as f:
            base = json.load(f)
        if not isinstance(base.get("value"), (int, float)):
            print(json.dumps({
                "error": f"baseline {args.baseline} has no numeric "
                         f"'value' field",
            }))
            return 2
        for key in ("mode", "queries", "threads", "workers",
                    "admission", "nodes"):
            if base.get(key, result[key]) != result[key]:
                print(json.dumps({
                    "error": f"baseline {key} {base.get(key)!r} != "
                             f"this run's {result[key]!r}; not "
                             f"comparable",
                }))
                return 2
        floor = base["value"] * (1.0 - args.max_regression)
        result["baseline_value"] = base["value"]
        result["regression_ok"] = qps_siege >= floor
        ok = ok and result["regression_ok"]
    print(json.dumps(result))
    record_safely(result)
    return 0 if ok else 1


def replay_chaos(ports, burst, threads: int, stop,
                 deadline_ms: int = 8000):
    """Chaos-phase replay: ``threads`` client threads cycle the
    owner-routed burst until ``stop`` is set, so traffic is in flight
    across every scheduled injection. Every request carries an
    ``X-SimuMax-Deadline`` budget (a wedged SIGSTOPped peer costs one
    bounded hop, not a 120 s stall) and **fails over in ring order**:
    owner first, then successors — exactly the retry a production
    client performs against a sick fleet. A request is *admitted* the
    moment any node answers it; the "no admitted request lost" oracle
    then counts any non-2xx/429 answer as ``error`` and
    every-node-unreachable as ``lost`` (with one node down out of
    three, both must stay zero)."""
    from simumax_tpu.service.ring import HashRing
    from simumax_tpu.service.router import DEADLINE_HEADER, route_key

    ring = HashRing([f"n{i}" for i in range(len(ports))])
    n = len(ports)
    items = []
    for ep, body in burst:
        body = resolve_strategy_body(body)
        owner = int(ring.owner(route_key(ep, body))[1:])
        order = [(owner + k) % n for k in range(n)]
        items.append((ep, json.dumps(body), order))
    lock = threading.Lock()
    counts = {"ok": 0, "shed": 0, "error": 0, "lost": 0,
              "failovers": 0, "requests": 0}
    lat = []
    conn_timeout = deadline_ms / 1000.0 + 4.0
    headers = {"Content-Type": "application/json",
               DEADLINE_HEADER: str(deadline_ms)}

    def worker(tid):
        mine = items[tid::threads]
        while mine and not stop.is_set():
            for ep, raw, order in mine:
                if stop.is_set():
                    return
                t0 = time.perf_counter()
                status = None
                for pidx in order:
                    try:
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", ports[pidx],
                            timeout=conn_timeout)
                        conn.request("POST", ep, raw, headers)
                        resp = conn.getresponse()
                        resp.read()
                        status = resp.status
                        conn.close()
                        break
                    except (OSError, http.client.HTTPException):
                        with lock:
                            counts["failovers"] += 1
                with lock:
                    counts["requests"] += 1
                    if status is None:
                        counts["lost"] += 1
                    elif status == 200:
                        counts["ok"] += 1
                        lat.append(time.perf_counter() - t0)
                    elif status == 429:
                        counts["shed"] += 1
                    else:
                        counts["error"] += 1

    ts = [threading.Thread(target=worker, args=(t,), daemon=True)
          for t in range(threads)]
    t0 = time.perf_counter()
    for t in ts:
        t.start()

    def finish():
        stop.set()
        for t in ts:
            t.join(2 * conn_timeout)
        with lock:
            return (time.perf_counter() - t0, sorted(lat),
                    dict(counts))

    return finish


def _await_fired(injector, n_events: int, deadline_s: float) -> bool:
    deadline = time.monotonic() + deadline_s
    while len(injector.report()) < n_events:
        if time.monotonic() > deadline:
            return False
        time.sleep(0.05)
    return True


def _await_membership(ports, live, expect, deadline_s: float):
    """Poll the live nodes' /ring/state until every one reports
    exactly ``expect`` as its ring membership; returns (elapsed_s,
    per-node detector round counters at convergence) or (None, {})."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        views = {}
        rounds = {}
        for i in live:
            try:
                rs = get_json(ports[i], "/ring/state", timeout=5)
            except (OSError, ValueError, http.client.HTTPException):
                break
            views[i] = sorted(rs.get("ring", {}).get("nodes", ()))
            rounds[i] = rs.get("detector", {}).get("rounds", 0)
        if len(views) == len(live) \
                and all(v == sorted(expect) for v in views.values()):
            return time.monotonic() - t0, rounds
        time.sleep(0.05)
    return None, {}


def run_chaos_siege(args) -> int:
    """``--siege --nodes N --chaos SCENARIO``: the fleet siege under
    scheduled faults, gated on the self-healing invariants instead of
    throughput. Flow: fill the fleet cold, seed replicas with explicit
    ``/ring/replicate`` rounds, then start the injector clock and keep
    the Zipf burst cycling (with client failover) across every event.
    The main thread follows the scenario timeline and checks the
    oracles: after a ``kill``, the survivors must converge on the
    shrunk membership within the probe bound (wall clock AND detector
    rounds); a parity sample taken **during the outage** must still be
    bit-identical across the forwarding hop; after the scripted
    ``start``, the full membership must converge back, the respawned
    node's recovery sweep must have quarantined the scenario's
    corrupted entries, and replicate rounds must restore its owner
    coverage (every corrupted key present in its manifest again). An
    optional overload phase then runs with the chaos-era net faults
    still armed, gated at 2x the chaos-free p99 bound. One JSON line,
    exit 1 on any gate."""
    from simumax_tpu.service.chaos import (
        NET_ENV,
        ChaosInjector,
        load_scenario,
    )
    from simumax_tpu.service.node import DOWN_AFTER

    scenario = load_scenario(args.chaos)
    net = scenario.net_env()
    if net:
        # inherited by the forked fleet nodes: each router's _send
        # gets the seeded drop/delay schedule installed
        os.environ[NET_ENV] = net
    fleet = start_fleet(args, probe_s=scenario.probe_s,
                        probe_seed=scenario.seed)
    ports = fleet.ports
    all_nodes = [f"n{i}" for i in range(len(ports))]
    injector = ChaosInjector(scenario, fleet.pid_of, fleet.respawn,
                             fleet.store_root)
    ok = True
    result = {
        "metric": "service_chaos_siege",
        "unit": "q/s",
        "mode": f"chaos-{os.path.splitext(scenario.name)[0]}"
                f"-pool{args.siege_pool}-z{args.zipf}",
        "nodes": args.nodes,
        "workers": args.workers,
        "admission": args.admission,
        "probe_s": scenario.probe_s,
        "seed": scenario.seed,
        "cores": os.cpu_count(),
    }
    try:
        # -- fill cold, then seed replicas so every entry survives
        # losing its owner (two rounds: owner -> first successor ->
        # second successor needs the transitive hop)
        _burst, unique = build_burst(args.siege_pool, 0.0, args.seed)
        fill_s, _fl, fill_counts, _fs = replay_fleet(
            ports, unique, args.threads, depth=args.pipeline)
        for _ in range(2):
            for port in ports:
                post_json(port, "/ring/replicate", {}, timeout=120)
        result["qps_fill"] = round(
            len(unique) / fill_s if fill_s else 0.0, 2)
        result["fill_errors"] = fill_counts["error"]
        ok = ok and not fill_counts["error"]

        # -- chaos: burst cycles in background threads while the main
        # thread walks the scenario timeline checking oracles
        siege = zipf_burst(unique, args.queries, args.zipf, args.seed)
        stop = threading.Event()
        finish = replay_chaos(ports, siege, args.threads, stop)
        injector.start()
        last_at = scenario.events[-1]["at_s"] if scenario.events \
            else 0.0
        for n_fired, event in enumerate(scenario.events, start=1):
            if not _await_fired(injector, n_fired,
                                event["at_s"] + 30.0):
                result["injector_stalled_at"] = event
                ok = False
                break
            idx = event["node"]
            if event["kind"] == "kill":
                live = [i for i in range(len(ports)) if i != idx]
                expect = [f"n{i}" for i in live]
                r0 = {}
                for i in live:
                    try:
                        r0[i] = get_json(
                            ports[i], "/ring/state",
                            timeout=5).get("detector",
                                           {}).get("rounds", 0)
                    except (OSError, ValueError,
                            http.client.HTTPException):
                        r0[i] = 0
                dt, r1 = _await_membership(ports, live, expect,
                                           args.max_converge_s)
                key = f"converge_down_n{idx}_s"
                result[key] = round(dt, 3) if dt is not None else None
                if dt is None:
                    result[f"converge_down_n{idx}_ok"] = ok = False
                    continue
                rounds = max((r1.get(i, 0) - r0.get(i, 0)
                              for i in live), default=0)
                result[f"converge_down_n{idx}_rounds"] = rounds
                # bound: DOWN_AFTER consecutive misses plus the
                # probe that was already in flight and jitter slack
                if rounds > 2 * DOWN_AFTER + 2:
                    result[f"converge_rounds_n{idx}_ok"] = ok = False
                # bit-identity through forwarding **during the
                # outage**: every sample aimed at a live node that
                # does not own it, so the bytes cross the degraded
                # ring's router hop
                live_ports = [ports[i] for i in live]

                def pick(ep, body, _lp=live_ports):
                    k = route_key_for(ep, body)
                    return _lp[sum(ord(c) for c in k) % len(_lp)]

                churn_ok, churn_ep = check_parity(
                    live_ports[0], unique, args.seed,
                    port_for=pick)
                result["parity_churn_ok"] = churn_ok
                if not churn_ok:
                    result["parity_churn_endpoint"] = churn_ep
                    ok = False
            elif event["kind"] == "start":
                live = list(range(len(ports)))
                dt, _r = _await_membership(ports, live, all_nodes,
                                           args.max_converge_s)
                key = f"converge_rejoin_n{idx}_s"
                result[key] = round(dt, 3) if dt is not None else None
                if dt is None:
                    result[f"converge_rejoin_n{idx}_ok"] = ok = False
        injector.join(last_at + 90.0)
        elapsed, lat, counts = finish()

        result.update({
            "value": round(counts["requests"] / elapsed
                           if elapsed else 0.0, 2),
            "chaos_requests": counts["requests"],
            "chaos_failovers": counts["failovers"],
            "chaos_elapsed_s": round(elapsed, 3),
            "p50_chaos_ms": round(pct(lat, 0.50) * 1e3, 2)
            if lat else 0.0,
            "p99_chaos_ms": round(pct(lat, 0.99) * 1e3, 2)
            if lat else 0.0,
            "lost_admitted": counts["error"] + counts["lost"],
            "injections": injector.report(),
        })
        if counts["error"] or counts["lost"]:
            result["lost_admitted_ok"] = ok = False

        # -- epoch accounting: every live ring observed the churn
        ring_states = {}
        for i, port in enumerate(ports):
            try:
                ring_states[i] = get_json(port, "/ring/state",
                                          timeout=10)
            except (OSError, ValueError, http.client.HTTPException):
                pass
        epochs = {i: rs.get("ring", {}).get("epoch", 0)
                  for i, rs in ring_states.items()}
        result["epochs"] = epochs
        survivors = [i for i in epochs
                     if i not in scenario.killed_nodes]
        if scenario.killed_nodes and not all(
                epochs.get(i, 0) >= 2 for i in survivors):
            # each kill+rejoin cycle is >= 2 bumps on a survivor
            result["epoch_ok"] = ok = False

        # -- corruption -> quarantine -> re-pull restores coverage
        corrupted = []
        for rec in injector.report():
            for path in rec.get("corrupted", ()):
                rel = os.path.relpath(
                    path, fleet.store_root(rec["node"]))
                parts = rel.split(os.sep)
                corrupted.append(
                    (rec["node"], parts[0],
                     os.path.basename(path)[:-len(".entry")]))
        result["corrupted_entries"] = len(corrupted)
        if corrupted:
            by_node = sorted({c[0] for c in corrupted})
            quarantined = 0
            for i in by_node:
                rec = ring_states.get(i, {}).get("recovery", {})
                quarantined += len(rec.get("quarantined", ()))
            result["recovery_quarantined"] = quarantined
            if quarantined < len(corrupted):
                result["quarantine_ok"] = ok = False
            deadline = time.monotonic() + args.max_converge_s
            missing = list(corrupted)
            while missing and time.monotonic() < deadline:
                for port in ports:
                    try:
                        post_json(port, "/ring/replicate", {},
                                  timeout=120)
                    except (OSError, ValueError,
                            http.client.HTTPException):
                        pass
                still = []
                for i, ns, key in missing:
                    try:
                        rows = post_json(
                            ports[i], "/ring/entries",
                            {"namespace": ns},
                            timeout=10).get("entries", ())
                    except (OSError, ValueError,
                            http.client.HTTPException):
                        still.append((i, ns, key))
                        continue
                    if not any(r.get("key") == key for r in rows):
                        still.append((i, ns, key))
                missing = still
            result["coverage_missing"] = [
                f"n{i}:{ns}/{key}" for i, ns, key in missing]
            if missing:
                result["coverage_ok"] = ok = False

        # -- the healed fleet must serve the whole pool again,
        # bit-identically, with affinity routing and zero errors
        final_s, _l, final_counts, _fs2 = replay_fleet(
            ports, unique, args.threads, depth=args.pipeline)
        result["final_replay_errors"] = final_counts["error"]
        if final_counts["error"]:
            result["final_replay_ok"] = ok = False
        parity_ok, parity_ep = (True, None) if args.skip_parity \
            else check_parity(ports[0], unique, args.seed,
                              port_for=_non_owner_port(ports))
        result["parity_ok"] = parity_ok
        if not parity_ok:
            result["parity_endpoint"] = parity_ep
            ok = False

        # -- overload with the net faults still armed: shedding must
        # keep the admitted p99 within 2x the chaos-free bound
        if args.admission and args.overload_queries:
            oburst = overload_burst(args.overload_queries, args.seed)
            o_s, o_lat, o_counts = replay_counted(
                ports[0], oburst, args.overload_threads,
                procs=args.client_procs)
            answered = sum(o_counts.values())
            o_p99_ms = pct(o_lat, 0.99) * 1e3 if o_lat else 0.0
            result.update({
                "overload_admitted": o_counts["ok"],
                "overload_shed": o_counts["shed"],
                "overload_errors": o_counts["error"],
                "overload_p99_ms": round(o_p99_ms, 2),
            })
            if answered != len(oburst) or o_counts["error"]:
                result["overload_answered_ok"] = ok = False
            if o_p99_ms > 2 * args.max_overload_p99_ms:
                result["overload_p99_ok"] = ok = False
        if args.dump_forensics:
            dump_fleet_forensics(ports, args.dump_forensics)
    finally:
        injector.close()
        if net:
            os.environ.pop(NET_ENV, None)
        fleet.cleanup()
    print(json.dumps(result))
    record_safely(result)
    return 0 if ok else 1


def route_key_for(ep: str, body: dict) -> str:
    from simumax_tpu.service.router import route_key

    return route_key(ep, body)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--queries", type=int, default=1000,
                    help="burst size (default 1000)")
    ap.add_argument("--overlap", type=float, default=0.1,
                    help="intra-burst repeat fraction (default 0.1)")
    ap.add_argument("--threads", type=int, default=4,
                    help="concurrent client connections (default 4)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cache-dir", metavar="DIR",
                    help="store root for the run (default: a fresh "
                         "temp dir, deleted afterwards — the bench "
                         "must start cold)")
    ap.add_argument("--min-hit-rate", type=float, default=0.9,
                    help="warm-replay store hit-rate floor (default "
                         "0.9; exit 1 below it)")
    ap.add_argument("--min-speedup", type=float, default=3.0,
                    help="warm/cold qps ratio floor (default 3 — wide "
                         "because a contended 2-vCPU runner can halve "
                         "the warm phase; the recorded baseline "
                         "documents the >=10x quiet-machine number)")
    ap.add_argument("--baseline", metavar="JSON",
                    help="previously saved bench JSON line to gate "
                         "absolute warm qps against")
    ap.add_argument("--max-regression", type=float, default=0.05,
                    metavar="FRAC",
                    help="fail when warm qps drops more than this "
                         "fraction below the baseline (default 0.05)")
    ap.add_argument("--skip-parity", action="store_true",
                    help="skip the bit-identity sample check (it "
                         "re-evaluates a few queries cache-off)")
    ap.add_argument("--trace", action="store_true",
                    help="arm span recording (observe/telemetry.py) "
                         "for the whole burst — the telemetry-overhead "
                         "gate runs the bench this way and compares "
                         "against the tracing-off baseline")
    ap.add_argument("--siege", action="store_true",
                    help="siege mode: Zipf-skewed replay + overload "
                         "phase (see the module docstring)")
    ap.add_argument("--siege-pool", type=int, default=512,
                    metavar="N",
                    help="siege unique-pool size (default 512)")
    ap.add_argument("--zipf", type=float, default=1.1, metavar="A",
                    help="siege popularity skew: rank r drawn with "
                         "weight 1/(r+1)^A (default 1.1)")
    ap.add_argument("--workers", type=int, default=0, metavar="N",
                    help="siege only: serve through a pool of N "
                         "planner worker processes (0 = the threaded "
                         "single-process server)")
    ap.add_argument("--admission", type=int, default=0,
                    metavar="BACKLOG",
                    help="siege only: admission-control backlog "
                         "budget (0 = admit everything; required for "
                         "the overload phase)")
    ap.add_argument("--overload-queries", type=int, default=600,
                    metavar="N",
                    help="all-cold queries hammered in the overload "
                         "phase (default 600; 0 skips the phase)")
    ap.add_argument("--overload-threads", type=int, default=64,
                    metavar="N",
                    help="overload-phase client connections "
                         "(default 64 — far beyond the worker pool, "
                         "so shedding must engage)")
    ap.add_argument("--pipeline", type=int, default=8, metavar="D",
                    help="siege fill/replay pipeline depth per "
                         "connection (HTTP/1.1 pipelining, the "
                         "standard siege-harness technique; the "
                         "overload phase always runs depth 1)")
    ap.add_argument("--client-procs", type=int,
                    default=min(4, os.cpu_count() or 1), metavar="P",
                    help="siege only: run the replay clients in P "
                         "forked processes (connections split across "
                         "them) so client work never shares the "
                         "server's GIL — like production's remote "
                         "clients (default min(4, cpus))")
    ap.add_argument("--max-overload-p99-ms", type=float,
                    default=10000.0, metavar="MS",
                    help="overload-phase p99 bound over ADMITTED "
                         "requests (default 10000 ms; without "
                         "admission control the queue — and p99 — "
                         "grows without bound)")
    ap.add_argument("--vs-single", metavar="JSON",
                    help="single-process (--workers 0) siege JSON "
                         "line recorded on THIS machine; gates "
                         "--min-pool-speedup against it")
    ap.add_argument("--min-pool-speedup", type=float, default=10.0,
                    help="min pooled-vs-single siege qps ratio "
                         "(default 10)")
    ap.add_argument("--nodes", type=int, default=0, metavar="N",
                    help="siege only: fork N fleet node processes "
                         "(consistent-hash ring on localhost ports) "
                         "and replay with client-side affinity "
                         "routing; parity samples cross the router "
                         "hop via non-owner nodes")
    ap.add_argument("--vs-node", metavar="JSON",
                    help="single-node siege JSON line recorded on "
                         "THIS machine with matching traffic flags; "
                         "gates --min-fleet-speedup against it")
    ap.add_argument("--min-fleet-speedup", type=float, default=0.0,
                    metavar="X",
                    help="min fleet-vs-single-node siege qps ratio "
                         "(0 = record without gating; CI passes "
                         "0.8*N on multi-core runners — the gate "
                         "needs >= nodes+1 cores to mean anything)")
    ap.add_argument("--chaos", metavar="SCENARIO",
                    help="fleet-siege chaos mode: replay the named "
                         "fault scenario (a configs/faults/ "
                         "simumax-service-chaos-v1 JSON, or a path) "
                         "against the live fleet and gate on the "
                         "self-healing invariants — no admitted "
                         "request lost, ring convergence within the "
                         "probe bound, quarantine + re-replication "
                         "coverage, parity under churn (needs "
                         "--siege and --nodes >= 2)")
    ap.add_argument("--max-converge-s", type=float, default=15.0,
                    metavar="S",
                    help="chaos mode: wall-clock bound for ring "
                         "membership convergence after a kill or "
                         "rejoin (default 15)")
    ap.add_argument("--dump-forensics", metavar="DIR",
                    help="write the final /stats + /metrics bodies "
                         "to DIR (CI uploads them on gate failure)")
    args = ap.parse_args(argv)

    if args.trace:
        from simumax_tpu.observe.telemetry import get_tracer

        get_tracer().configure(enabled=True)

    if args.chaos and not (args.siege and args.nodes
                           and args.nodes > 1):
        print(json.dumps({
            "error": "--chaos needs --siege and --nodes >= 2 (the "
                     "scenario injects faults into a live fleet)",
        }))
        return 2
    if args.siege:
        if args.nodes and args.nodes > 1:
            if args.chaos:
                return run_chaos_siege(args)
            return run_fleet_siege(args)
        return run_siege(args)
    if args.nodes:
        print(json.dumps({
            "error": "--nodes is a siege-mode flag (add --siege)",
        }))
        return 2
    if args.workers or args.admission:
        print(json.dumps({
            "error": "--workers/--admission are siege-mode flags; "
                     "the classic burst keeps PR-9's single-process "
                     "baseline semantics (add --siege)",
        }))
        return 2

    srv, port, cleanup = start_server(args)
    try:
        burst, unique = build_burst(args.queries, args.overlap,
                                    args.seed)
        cold_s, cold_lat, cold_err = replay(port, burst, args.threads)
        stats_mid = get_json(port, "/stats")
        warm_s, warm_lat, warm_err = replay(port, burst, args.threads)
        stats_end = get_json(port, "/stats")
        parity_ok, parity_ep = (True, None) if args.skip_parity \
            else check_parity(port, unique, args.seed)
    finally:
        cleanup()

    def counters(s):
        return s["store"]["counters"]

    warm_hits = (counters(stats_end)["hits"]
                 - counters(stats_mid)["hits"])
    warm_misses = (counters(stats_end)["misses"]
                   - counters(stats_mid)["misses"])
    lookups = warm_hits + warm_misses
    hit_rate = warm_hits / lookups if lookups else 0.0
    qps_cold = len(burst) / cold_s if cold_s else 0.0
    qps_warm = len(burst) / warm_s if warm_s else 0.0
    speedup = qps_warm / qps_cold if qps_cold else 0.0
    result = {
        "metric": "service_qps_warm",
        "value": round(qps_warm, 2),
        "unit": "q/s",
        "queries": len(burst),
        "unique_queries": len(unique),
        "overlap": args.overlap,
        "threads": args.threads,
        "qps_cold": round(qps_cold, 2),
        "speedup": round(speedup, 2),
        "hit_rate_warm": round(hit_rate, 4),
        "warm_hits": warm_hits,
        "warm_lookups": lookups,
        "p50_cold_ms": round(pct(cold_lat, 0.50) * 1e3, 2),
        "p99_cold_ms": round(pct(cold_lat, 0.99) * 1e3, 2),
        "p50_warm_ms": round(pct(warm_lat, 0.50) * 1e3, 2),
        "p99_warm_ms": round(pct(warm_lat, 0.99) * 1e3, 2),
        "cold_elapsed_s": round(cold_s, 3),
        "warm_elapsed_s": round(warm_s, 3),
        "errors": cold_err + warm_err,
        "parity_ok": parity_ok,
    }
    ok = True
    if cold_err or warm_err:
        result["errors_ok"] = ok = False
    if not parity_ok:
        result["parity_endpoint"] = parity_ep
        ok = False
    if hit_rate < args.min_hit_rate:
        result["hit_rate_ok"] = ok = False
    if speedup < args.min_speedup:
        result["speedup_ok"] = ok = False
    if args.baseline:
        with open(args.baseline) as f:
            base = json.load(f)
        if not isinstance(base.get("value"), (int, float)):
            print(json.dumps({
                "error": f"baseline {args.baseline} has no numeric "
                         f"'value' field; re-record it with a plain "
                         f"bench run",
            }))
            return 2
        for key, ours in (("queries", len(burst)),
                          ("overlap", args.overlap),
                          ("threads", args.threads)):
            theirs = base.get(key, ours)
            if theirs != ours:
                print(json.dumps({
                    "error": f"baseline {key} {theirs!r} != this "
                             f"run's {ours!r}; not comparable — "
                             f"re-record the baseline with matching "
                             f"flags",
                }))
                return 2
        floor = base["value"] * (1.0 - args.max_regression)
        result["baseline_value"] = base["value"]
        result["regression"] = (
            round(1.0 - qps_warm / base["value"], 4)
            if base["value"] else 0.0
        )
        result["regression_ok"] = qps_warm >= floor
        ok = ok and result["regression_ok"]
    if args.trace:
        result["trace"] = True
    print(json.dumps(result))
    record_safely(result)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())

"""JAX self-calibration tools (L9).

Reference: ``simu_tools/efficency_test`` (GEMM/grouped-GEMM/attention
efficiency sweeps -> ``accurate_efficient_factor`` tables;
nccl-tests linear fits -> network classes), re-built on JAX so a live
TPU slice calibrates its own ``configs/system/*.json``:

* :func:`calibrate_for_perf` — measure exactly the shape keys a
  ``PerfLLM`` run reported as efficiency-table misses and write them
  back into the system config (the miss-driven loop the reference
  documents in ``docs/system.md:48-57``);
* ``gemm_bench`` / ``attention_bench`` — per-shape MXU efficiency;
* ``collective_bench`` — ICI/DCN alpha-beta fits from psum/all_gather/
  ppermute/all_to_all sweeps over a real mesh.
"""

from simumax_tpu.calibration.autocal import calibrate_for_perf, calibrate_system  # noqa: F401

"""ICI/DCN collective microbenchmarks + alpha-beta fits.

Reference: ``simu_tools/efficency_test/nccl_fit.py`` (time = a*bytes + b
linear fit over nccl-tests output) and ``one_click_common.fit_bw_latency``
— re-built as JAX collectives over a real device mesh: psum (all_reduce),
all_gather, psum_scatter (reduce_scatter), all_to_all and ppermute
sweeps per mesh axis, fitted to the same linear model and written back
as per-op ``efficient_factor`` / ``latency_us`` against the system
config's theoretical span bandwidth.

Runs on any mesh (virtual CPU devices work for plumbing tests; real
numbers need a TPU slice).
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from simumax_tpu.calibration.timing import time_fn
from simumax_tpu.core.errors import CalibrationError

_OPS = ("all_reduce", "all_gather", "reduce_scatter", "all2all", "p2p")


def _collective_fn(op: str, axis: str):
    if op == "all_reduce":
        return lambda x: jax.lax.psum(x, axis)
    if op == "all_gather":
        return lambda x: jax.lax.all_gather(x, axis)
    if op == "reduce_scatter":
        return lambda x: jax.lax.psum_scatter(x, axis, tiled=True)
    if op == "all2all":
        return lambda x: jax.lax.all_to_all(
            x, axis, split_axis=0, concat_axis=0, tiled=True
        )
    if op == "p2p":
        def permute(x):
            n = jax.lax.axis_size(axis)
            perm = [(i, (i + 1) % n) for i in range(n)]
            return jax.lax.ppermute(x, axis, perm)

        return permute
    raise CalibrationError(f"no collective benchmark for op {op!r}", op=op)


def measure_collective(
    mesh: Mesh, axis: str, op: str, nbytes: float, dtype=jnp.bfloat16
) -> float:
    """Wall time of one collective of ``nbytes`` *full logical tensor*
    bytes over a mesh axis (matches ``compute_net_op_time`` semantics)."""
    n = mesh.shape[axis]
    # local shards must themselves split by n for tiled rs/a2a
    elems = max(int(nbytes / jnp.dtype(dtype).itemsize), n * n)
    elems -= elems % (n * n)
    x = jnp.ones((elems,), dtype)
    spec = P(axis)  # shard the vector over the measured axis
    out_spec = P(None) if op == "all_gather" else spec

    @functools.partial(jax.jit, in_shardings=NamedSharding(mesh, spec))
    def run(x):
        return jax.shard_map(
            _collective_fn(op, axis),
            mesh=mesh,
            in_specs=spec,
            out_specs=out_spec,
            check_vma=False,
        )(x)

    with mesh:
        return time_fn(run, x)


def fit_alpha_beta(sizes: List[float], times: List[float]) -> Tuple[float, float]:
    """Least-squares fit time = bytes/bw + alpha -> (bw_bytes_per_s,
    alpha_seconds). Reference ``nccl_fit.py:27-60``."""
    A = np.vstack([sizes, np.ones(len(sizes))]).T
    slope, alpha = np.linalg.lstsq(A, np.array(times), rcond=None)[0]
    bw = 1.0 / slope if slope > 0 else float("inf")
    return bw, max(alpha, 0.0)


def sweep_axis(
    mesh: Mesh,
    axis: str,
    ops: Tuple[str, ...] = _OPS,
    sizes_mb: Tuple[float, ...] = (1, 4, 16, 64),
) -> Dict[str, dict]:
    """Measure+fit every collective op along one mesh axis."""
    out = {}
    for op in ops:
        sizes, times = [], []
        for mb in sizes_mb:
            nbytes = mb * 2**20
            t = measure_collective(mesh, axis, op, nbytes)
            sizes.append(nbytes)
            times.append(t)
        bw, alpha = fit_alpha_beta(sizes, times)
        out[op] = {
            "fitted_bw_gbps": bw / 1e9,
            "fitted_latency_us": alpha * 1e6,
            "samples": list(zip([s / 2**20 for s in sizes], times)),
        }
    return out


def update_system_from_sweep(system, axis_extent: int, sweep: Dict[str, dict]):
    """Write fitted per-op efficiencies back into ``system.ici.op``
    against the theoretical span bandwidth (the write-back step of the
    reference's one-click pipeline)."""
    path = system.place_group("_cal", 1, axis_extent)
    for op, fit in sweep.items():
        # theoretical time for 64 MiB at eff=1.0
        probe = 64 * 2**20
        spec = system.ici.op.setdefault(op, type(next(iter(system.ici.op.values())))())
        spec.efficient_factor = 1.0
        theory = system.compute_net_op_time(op, probe, path)
        slope_time = probe / (fit["fitted_bw_gbps"] * 1e9)
        if theory > 0 and slope_time > 0:
            spec.efficient_factor = min(theory / slope_time, 1.0)
        spec.latency_us = fit["fitted_latency_us"]
    return system

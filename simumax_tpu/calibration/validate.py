"""Validation harness: predicted vs XLA-compiled memory and measured
step time (SURVEY hard-part #1: anchoring the memory model against
``compiled.memory_analysis()``).

On a real TPU backend, ``xla_memory_report`` returns the buffer
assignment XLA actually uses for the jaxref train step (argument /
output / temp / peak bytes); ``validate_memory`` compares the
analytical prediction against it. On CPU backends the XLA numbers are
not representative (host buffer accounting) — the harness still runs
for plumbing tests but real anchoring needs a TPU.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp


def xla_memory_report(
    model_config, batch_size: int = 1, seq_len: int = 2048,
    layer_num: Optional[int] = None, remat: bool = False,
) -> Dict[str, float]:
    """Compile the jaxref train step for this model and return XLA's
    memory analysis (bytes). This is the hardware anchor: the tunnel
    backend returns no ``memory_stats()``, but the buffer assignment is
    exactly what XLA allocates on the real chip."""
    from simumax_tpu.jaxref.model import (
        LlamaConfig,
        init_params,
        make_train_step,
    )

    cfg = LlamaConfig.from_model_config(model_config, layer_num=layer_num)
    params = jax.eval_shape(
        lambda k: init_params(cfg, k), jax.random.PRNGKey(0)
    )
    init_opt, step = make_train_step(cfg, shard=False, remat=remat)
    opt = jax.eval_shape(init_opt, params)
    ids = jax.ShapeDtypeStruct((batch_size, seq_len), jnp.int32)
    lowered = jax.jit(step, donate_argnums=(0, 1)).lower(
        params, opt, (ids, ids)
    )
    ma = lowered.compile().memory_analysis()
    fields = (
        "argument_size_in_bytes",
        "output_size_in_bytes",
        "temp_size_in_bytes",
        "peak_memory_in_bytes",
    )
    return {f: float(getattr(ma, f, 0.0)) for f in fields}


def validate_memory(perf, layer_num: Optional[int] = None) -> Dict[str, float]:
    """Compare ``perf``'s predicted peak (single-chip strategy) against
    the XLA buffer assignment for the equivalent jaxref step."""
    st = perf.strategy
    assert st.world_size == 1, "memory validation compares one chip"
    xla = xla_memory_report(
        perf.model_config, st.micro_batch_size, st.seq_len, layer_num
    )
    mem = perf.analysis_mem()
    predicted = mem["max_peak_bytes"]
    # XLA peak under donation ~= live args + temps
    xla_peak = xla["peak_memory_in_bytes"]
    return {
        **xla,
        "predicted_peak_bytes": predicted,
        "ratio": predicted / xla_peak if xla_peak else float("nan"),
    }


def hlo_collective_bytes(compiled_text: str) -> Dict[str, float]:
    """Sum the result-shape bytes of each collective op family in a
    compiled HLO module text (``compiled.as_text()``) — a hardware-free
    anchor for the analytical collective-volume accounting."""
    import re

    dt_bytes = {"bf16": 2, "f16": 2, "f32": 4, "f64": 8, "s32": 4,
                "u32": 4, "s64": 8, "u64": 8, "s16": 2, "u16": 2,
                "f8e4m3fn": 1, "f8e5m2": 1, "s8": 1, "u8": 1, "pred": 1}
    out: Dict[str, float] = {}

    def shape_bytes(shapes: str) -> float:
        total = 0.0
        for dt, dims in re.findall(r"(\w+)\[([\d,]*)\]", shapes):
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            total += n * dt_bytes.get(dt, 4)
        return total

    # plain results:  %x = f32[a,b]{...} all-gather(...)
    # tuple results (combined ops): %x = (f32[..], f32[..]) all-reduce(...)
    # async pairs (TPU): only the -start op is counted (its -done shares
    # the shape); tiled layouts like {1,0:T(8,128)} may contain parens,
    # so the tuple branch matches balanced-bracket shape lists only.
    pat = re.compile(
        r"=\s*(\((?:[^()]|\([^()]*\))*\)|\w+\[[\d,]*\][^=\n]*?)\s"
        r"(all-gather|reduce-scatter|all-reduce|all-to-all|"
        r"collective-permute)(?:-start)?\("
    )
    for m in pat.finditer(compiled_text):
        out[m.group(2)] = out.get(m.group(2), 0.0) + shape_bytes(m.group(1))
    return out


def hlo_replica_groups(compiled_text: str) -> Dict[str, list]:
    """Extract the device-group structure of every collective in a
    compiled HLO module: ``{op_family: [[group, ...], ...]}`` — one list
    of groups per op instance. For ``collective-permute`` the
    source-target pairs are returned as 2-lists.

    Together with :func:`hlo_collective_bytes` this anchors not just the
    *volume* but the *placement input* of the analytical collective
    model: the (stride, size, contiguity) of each replica group is
    exactly the ``(inner_size, group_size)`` the model feeds to
    ``SystemConfig.place_group``.
    """
    import re

    out: Dict[str, list] = {}
    pat = re.compile(
        r"(all-gather|reduce-scatter|all-reduce|all-to-all|"
        r"collective-permute)(?:-start)?\([^\n]*?"
        r"(?:replica_groups=\{(.*?)\}\}|"
        r"source_target_pairs=\{(.*?)\}\})"
    )
    for m in pat.finditer(compiled_text):
        fam, rg, stp = m.group(1), m.group(2), m.group(3)
        body = rg if rg is not None else stp
        groups = [
            [int(x) for x in g.split(",") if x.strip()]
            for g in re.findall(r"\{([\d,]*)", "{" + body + "}")
            if g.strip()
        ]
        out.setdefault(fam, []).append(groups)
    return out


def group_structure(groups: list) -> Dict[str, object]:
    """(size, stride, contiguous) of a replica-group list — the
    placement signature ``place_group`` consumes. Requires all groups in
    the list to share one structure (true for XLA mesh collectives)."""
    sizes = {len(g) for g in groups}
    assert len(sizes) == 1, f"ragged replica groups: {groups}"
    size = sizes.pop()
    strides = set()
    for g in groups:
        if len(g) >= 2:
            ds = {b - a for a, b in zip(g, g[1:])}
            assert len(ds) == 1, f"non-uniform stride in group {g}"
            strides.add(ds.pop())
    stride = strides.pop() if strides else 1
    assert not strides, f"mixed strides across groups: {groups}"
    return {"size": size, "stride": stride, "contiguous": stride == 1}

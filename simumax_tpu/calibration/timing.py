"""Timing utilities for microbenchmarks.

Through remote-tunnel TPU backends, ``jax.block_until_ready`` can return
at dispatch time rather than execution completion, so every measurement
here forces completion by fetching a scalar from the result, amortizes
the fixed round-trip over ``amortize`` chained calls, and subtracts the
separately measured fetch round-trip.
"""

from __future__ import annotations

import math
import time
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from simumax_tpu.core.errors import CalibrationError

_rtt_cache: Optional[float] = None


def reject_outliers(samples: Sequence[float], z: float = 3.5) -> List[float]:
    """Drop non-finite samples and MAD outliers.

    A sample is an outlier when its modified z-score
    ``|x - median| / (1.4826 * MAD)`` exceeds ``z`` — robust against the
    occasional GC pause / tunnel hiccup that a mean (or even a plain
    median of few samples) would let skew the measurement. Raises
    :class:`CalibrationError` when nothing finite remains."""
    finite = [float(s) for s in samples if math.isfinite(s)]
    if not finite:
        raise CalibrationError(
            f"no finite timing samples (got {list(samples)!r})",
            phase="calibrate",
        )
    med = float(np.median(finite))
    mad = float(np.median([abs(x - med) for x in finite]))
    if mad == 0.0:
        return finite
    kept = [x for x in finite if abs(x - med) / (1.4826 * mad) <= z]
    return kept or [med]


def robust_median(samples: Sequence[float], z: float = 3.5) -> float:
    """Median of the MAD-filtered samples (median-of-k hardening)."""
    return float(np.median(reject_outliers(samples, z)))


def _fetch_scalar(out) -> float:
    """Pull one scalar from (the first leaf of) ``out`` — forces the
    producing computation to finish even on async tunnel backends."""
    leaf = jax.tree.leaves(out)[0]
    return float(jnp.ravel(leaf)[0])


def fetch_rtt(refresh: bool = False) -> float:
    """Median scalar-fetch round-trip (seconds) on the default backend."""
    global _rtt_cache
    if _rtt_cache is not None and not refresh:
        return _rtt_cache
    x = jnp.ones((8,), jnp.float32)
    f = jax.jit(jnp.sum)
    _fetch_scalar(f(x))
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        _fetch_scalar(f(x))
        samples.append(time.perf_counter() - t0)
    samples.sort()
    _rtt_cache = samples[len(samples) // 2]
    return _rtt_cache


def time_fn(
    fn: Callable,
    *args,
    warmup: int = 1,
    iters: int = 3,
    amortize: int = 8,
) -> float:
    """Robust-median per-call seconds of ``fn(*args)``.

    Each sample chains ``amortize`` calls and fetches a scalar from the
    last result; the fetch round-trip is subtracted. Calls must be
    side-effect-free (results independent) — the chain exists purely to
    amortize dispatch/fetch overhead. Samples are hardened with MAD
    outlier rejection (:func:`robust_median`) so a single scheduler
    stall cannot skew the calibrated efficiency.
    """
    rtt = fetch_rtt()
    for _ in range(warmup):
        _fetch_scalar(fn(*args))
    samples = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args)
        for _ in range(amortize - 1):
            out = fn(*args)
        _fetch_scalar(out)
        total = time.perf_counter() - t0
        samples.append(max(total - rtt, 1e-9) / amortize)
    return robust_median(samples)


def time_stateful(step: Callable, warmup: int = 1, iters: int = 8) -> float:
    """Per-call seconds for a stateful step (e.g. a training step that
    threads params/opt state). ``step()`` must return something
    fetchable and carry its own state forward; successive calls are
    data-dependent so one final fetch forces the whole chain."""
    rtt = fetch_rtt()
    for _ in range(warmup):
        _fetch_scalar(step())
    t0 = time.perf_counter()
    out = None
    for _ in range(iters):
        out = step()
    _fetch_scalar(out)
    total = time.perf_counter() - t0
    return max(total - rtt, 1e-9) / iters

"""Miss-driven self-calibration.

Reference workflow: ``simu_tools/efficency_test`` sweeps a fixed shape
grid on a live GPU and merges the results into ``<SYS_NAME>.json``
(``combine_efficiency.py``); users are told to watch ``miss_efficiency``
and re-calibrate (``docs/system.md:48-57``).

TPU redesign: instead of a fixed grid, :func:`calibrate_for_perf` reads
the exact shape keys a ``PerfLLM`` estimate *missed* in the efficiency
tables, measures precisely those GEMM / grouped-GEMM / attention shapes
with JAX on the local accelerator, and writes the measured efficiency
factors back — so one command closes the loop for any model/strategy.
"""

from __future__ import annotations

import json
import math
import time as _time
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from simumax_tpu.calibration.timing import time_fn
from simumax_tpu.core.errors import CalibrationError
from simumax_tpu.core.records import Diagnostics
from simumax_tpu.observe.report import get_reporter

_DTYPES = {
    "bf16": jnp.bfloat16,
    "fp16": jnp.float16,
    "fp32": jnp.float32,
    "int8": jnp.int8,
}

#: measured efficiencies must land in (0, EFF_MAX] — a couple of percent
#: above 1.0 is plausible clock/peak-spec slack, more means the
#: benchmark (or its FLOPs/traffic convention) is wrong
EFF_MAX = 1.05


def validate_efficiency(eff: float, op_key: str = "",
                        shape_key: str = "") -> float:
    """Guard a measured efficiency before it is written back into the
    system tables: must be finite and in ``(0, EFF_MAX]``."""
    if not isinstance(eff, (int, float)) or not math.isfinite(eff):
        raise CalibrationError(
            f"measured efficiency for {op_key}[{shape_key}] is not finite: "
            f"{eff!r}",
            phase="calibrate", op_key=op_key, shape_key=shape_key,
        )
    if not 0.0 < eff <= EFF_MAX:
        raise CalibrationError(
            f"measured efficiency {eff:.4f} for {op_key}[{shape_key}] is "
            f"outside (0, {EFF_MAX}] — benchmark or peak spec is wrong; "
            f"refusing to write it back",
            phase="calibrate", op_key=op_key, shape_key=shape_key,
            efficiency=eff,
        )
    return float(eff)


def with_retries(fn, *args, attempts: int = 3, backoff: float = 0.25,
                 label: str = "", **kwargs):
    """Run ``fn`` with bounded retry + exponential backoff.

    JAX microbenchmarks fail transiently (tunnel drops, device OOM from
    a neighbor, compile-cache races); a bounded retry keeps one flaky
    measurement from aborting a whole calibration pass. After
    ``attempts`` failures the last error is wrapped in a
    :class:`CalibrationError` so callers can skip the key and continue."""
    last: Optional[BaseException] = None
    for attempt in range(attempts):
        try:
            return fn(*args, **kwargs)
        except CalibrationError:
            raise  # already classified (e.g. all-NaN samples): no retry
        except Exception as exc:
            last = exc
            if attempt < attempts - 1:
                _time.sleep(backoff * (2 ** attempt))
    raise CalibrationError(
        f"microbenchmark {label or getattr(fn, '__name__', fn)!s} failed "
        f"after {attempts} attempts: {last}",
        phase="calibrate", attempts=attempts, last_error=repr(last),
    ) from last


def _parse_key(key: str) -> Dict[str, str]:
    out = {}
    for part in key.split(","):
        part = part.strip()
        if "=" in part:
            k, v = part.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _peak_tflops(system, op_key: str) -> float:
    spec = system.accelerator.op.get(op_key) or system.accelerator.op["default"]
    return spec.tflops


# -- GEMM ---------------------------------------------------------------------


_SCAN_K = 16


def _test_array(shape, dt):
    """Benchmark operand with non-trivial runtime values.

    Must be passed to the jitted benchmark as an ARGUMENT: captured
    ``jnp.ones`` become broadcast-constants that XLA folds right out of
    the benchmark (``sum(ones + c)`` simplifies to a scalar — this made
    the bandwidth benchmarks measure nothing)."""
    n = 1
    for s in shape:
        n *= s
    x = (jnp.arange(n, dtype=jnp.float32) % 251) * 0.01
    return x.reshape(shape).astype(dt)


def _chain_scan(op, length=_SCAN_K):
    """Jit of ``length`` data-dependent executions of
    ``op(carry, *arrays) -> new_carry`` via lax.scan — per-dispatch
    overhead (large through tunnel backends) is paid once. The scalar
    carry is threaded into the inputs to defeat loop-invariant
    hoisting; the operand arrays are jit arguments (see _test_array)."""

    def fn(*arrays):
        def body(carry, _):
            return op(carry, *arrays), None

        carry, _ = jax.lax.scan(
            body, jnp.float32(0.0), None, length=length
        )
        return carry

    return jax.jit(fn)


def _time_op(op, arrays, pilot_length=_SCAN_K, min_duration_factor=8.0,
             max_length=8192):
    """Per-execution seconds of ``op``, robust to tunnel RTT jitter.

    A fixed 16-step scan of a sub-millisecond op totals a few ms of
    device time, while the fetch round-trip through a tunnel backend is
    tens of ms with comparable jitter — the signal drowns (this made
    round-1 calibrated tables *worse* than the defaults). Pilot-measure
    with a short scan, then rescale the scan length so device time is
    ``min_duration_factor`` x RTT before the authoritative measurement.
    """
    from simumax_tpu.calibration.timing import fetch_rtt

    t = time_fn(
        _chain_scan(op, length=pilot_length), *arrays, amortize=1
    ) / pilot_length
    rtt = fetch_rtt()
    target = max(min_duration_factor * rtt, 0.2)
    if t * pilot_length >= target:
        return t
    length = int(min(max_length, math.ceil(target / max(t, 1e-8))))
    if length <= pilot_length:
        return t
    return time_fn(
        _chain_scan(op, length=length), *arrays, amortize=1, iters=5
    ) / length


def measure_gemm_efficiency(
    m: int, k: int, n: int, dtype: str, out_dtype: str, peak_tflops: float,
    batch: int = 1, groups: int = 1, layout: str = "NN",
) -> float:
    """Measured MXU efficiency of a ``[m,k] x [k,n]`` matmul in the
    given operand layout (NN fwd, NT dgrad, TN wgrad — the same operand
    transposition structure XLA sees in each backprop stage), per group
    when ``groups > 1`` (balanced grouped GEMM)."""
    dt = _DTYPES.get(dtype, jnp.bfloat16)
    odt = _DTYPES.get(out_dtype, dt)
    if groups > 1:
        arrays = [
            _test_array((groups, max(m // groups, 1), k), dt),
            _test_array((groups, k, n), dt),
        ]

        def op(carry, a, b):
            y = jax.lax.dot_general(
                a + carry.astype(dt), b,
                (((2,), (1,)), ((0,), (0,))),  # batched [g,m,k]x[g,k,n]
                preferred_element_type=odt,
            )
            # max needs every output element: defeats DCE slicing of the
            # dot while still fusing into its epilogue (no HBM round trip)
            return jnp.max(y).astype(jnp.float32) * 1e-30

        flops = 2.0 * groups * max(m // groups, 1) * k * n
    else:
        # operand shapes + contraction dims per layout
        if layout == "NT":
            a_shape, b_shape, dims = (m, k), (n, k), (((1,), (1,)), ((), ()))
        elif layout == "TN":
            a_shape, b_shape, dims = (k, m), (k, n), (((0,), (0,)), ((), ()))
        else:  # NN
            a_shape, b_shape, dims = (m, k), (k, n), (((1,), (0,)), ((), ()))
        if batch > 1:
            a_shape = (batch,) + a_shape
            dims = ((tuple(d + 1 for d in dims[0][0]), dims[0][1]), ((), ()))
        arrays = [_test_array(a_shape, dt), _test_array(b_shape, dt)]

        def op(carry, a, b):
            y = jax.lax.dot_general(
                a + carry.astype(dt), b, dims, preferred_element_type=odt
            )
            return jnp.max(y).astype(jnp.float32) * 1e-30

        flops = 2.0 * batch * m * k * n
    t = _time_op(op, arrays)
    eff = flops / t / (peak_tflops * 1e12)
    return min(eff, 1.0)


# -- attention ----------------------------------------------------------------


def measure_sdp_efficiency(
    b: int, sq: int, skv: int, hn: int, kv_hn: int, hd: int, hd_v: int,
    causal: bool, dtype: str, peak_tflops: float, backward: bool = False,
    sparse_ratio: float = 0.5, backend: str = "xla", flash: bool = True,
) -> Optional[float]:
    """Attention efficiency for the given backend: "xla" times
    ``jax.nn.dot_product_attention`` (what a jitted model runs),
    "pallas" the fused flash kernel (``jaxref.kernels.flash_attention``,
    MHA layout — GQA kv heads broadcast upstream, as the kernel
    requires). Returns None if the backend cannot run the shape."""
    dt = _DTYPES.get(dtype, jnp.bfloat16)
    q = _test_array((b, sq, hn, hd), dt)
    k = _test_array((b, skv, kv_hn, hd), dt)
    v = _test_array((b, skv, kv_hn, hd_v), dt)
    if backend == "pallas":
        from simumax_tpu.core.utils import pallas_attention_supported
        from simumax_tpu.jaxref.kernels import flash_attention

        if hd != hd_v:
            return None  # kernel assumes one head dim
        if not pallas_attention_supported(sq, skv, hd):
            return None  # runtime would fall back to XLA (shared gate)
        if kv_hn != hn:
            k = jnp.repeat(k, hn // kv_hn, axis=2)
            v = jnp.repeat(v, hn // kv_hn, axis=2)

        def attn(qq, kk, vv):
            return flash_attention(qq, kk, vv, causal=causal)
    else:
        def attn(qq, kk, vv):
            return jax.nn.dot_product_attention(qq, kk, vv, is_causal=causal)

    def fwd_op(carry, qq, kk, vv):
        o = attn(qq + carry.astype(dt), kk, vv)
        return jnp.max(o).astype(jnp.float32) * 1e-30

    t_f = _time_op(fwd_op, [q, k, v])
    if backward:
        def bwd_op(carry, qq, kk, vv):
            def loss(qx, kx, vx):
                return jnp.sum(attn(qx, kx, vx).astype(jnp.float32))

            # differentiate wrt q, k AND v — a dQ-only backward would
            # omit the dK/dV matmuls the bwd-FLOPs convention counts
            gq, gk, gv = jax.grad(loss, argnums=(0, 1, 2))(
                qq + carry.astype(dt), kk, vv
            )
            return (
                jnp.max(gq) + jnp.max(gk) + jnp.max(gv)
            ).astype(jnp.float32) * 1e-30

        t = _time_op(bwd_op, [q, k, v])
        # grad timing includes the forward pass; subtract it
        t = max(t - t_f, t_f * 0.5)
        # MUST match the model's bwd-FLOPs convention for this path
        # (CoreAttention.op_flops: 2.5x fwd for flash, 2.0x for math)
        mult = 2.5 if flash else 2.0
    else:
        t = t_f
        mult = 1.0
    flops = 2.0 * b * hn * sq * skv * (hd + hd_v) * mult
    if causal:
        flops *= 1.0 - sparse_ratio
    eff = flops / t / (peak_tflops * 1e12)
    return min(eff, 1.0)


# -- HBM bandwidth classes ----------------------------------------------------


def measure_bandwidth_efficiency(
    kind: str, peak_gbps: float, nbytes: float = 256 * 2**20,
    vocab: int = 32000,
) -> float:
    """Measured HBM efficiency for a bandwidth class (reference
    ``test_ce_permute_efficiency.py``): 'default' times a streaming
    reduction, 'permute_fwd' a pseudo-random row gather, 'permute_bwd'
    a row scatter-add, 'ce' a log-softmax cross-entropy pass. Each
    benchmark ends in a full reduction so the simplifier cannot elide
    the traffic. Returns achieved/peak bandwidth (of the modeled
    traffic — reads only where the reduction fuses away the write)."""
    if kind == "ce_fusion":
        raise CalibrationError(
            "ce_fusion is not measurable with the unfused CE benchmark "
            "(a fused kernel avoids its fp32 materialization); keep the "
            "configured prior or calibrate against a real fused kernel"
        )
    if kind == "fused_adam":
        return _measure_fused_adam(peak_gbps, nbytes)
    if kind.startswith("permute"):
        rows = max(int(nbytes // (2 * 1024)), 16)
        x = _test_array((rows, 1024), jnp.bfloat16)
        stride = 104729  # prime: pseudo-random, deterministic row order
        idx = (jnp.arange(rows) * stride) % rows
        if kind == "permute_bwd":
            def op(carry, xx, ii):
                y = jnp.zeros_like(xx).at[ii].add(xx + carry.astype(xx.dtype))
                return jnp.sum(y.astype(jnp.float32)) * 1e-30

            traffic = 3 * rows * 1024 * 2  # read + scatter write + reduce
        else:
            def op(carry, xx, ii):
                y = jnp.take(xx + carry.astype(xx.dtype), ii, axis=0)
                return jnp.sum(y.astype(jnp.float32)) * 1e-30

            traffic = rows * 1024 * 2  # random-order read (reduce fuses)
        arrays = [x, idx]
    elif kind.startswith("ce"):
        tokens = max(int(nbytes // (vocab * 2)), 8)
        logits = _test_array((tokens, vocab), jnp.bfloat16)
        targets = jnp.zeros((tokens,), jnp.int32)

        def op(carry, lg, tg):
            lp = jax.nn.log_softmax(
                (lg + carry.astype(lg.dtype)).astype(jnp.float32), -1
            )
            ll = jnp.take_along_axis(lp, tg[:, None], -1)
            return -jnp.mean(ll) * 1e-30

        # two streaming reduction passes over the bf16 logits; the
        # log-prob gather fuses (must match ParallelCE.op_accessed's
        # fwd = 2 x logits-bytes convention)
        traffic = tokens * vocab * 4
        arrays = [logits, targets]
    else:
        elems = max(int(nbytes // 2), 1024)

        def op(carry, xx):
            return jnp.sum((xx + carry.astype(xx.dtype)).astype(jnp.float32)) * 1e-30

        traffic = elems * 2  # streaming read (reduce fuses the write)
        arrays = [_test_array((elems,), jnp.bfloat16)]
    t = _time_op(op, arrays, pilot_length=8)
    eff = traffic / t / (peak_gbps * 1e9)
    return min(eff, 1.0)


def _measure_fused_adam(peak_gbps: float, nbytes: float = 256 * 2**20,
                        pilot_length: int = 8) -> float:
    """Measured HBM efficiency of the exact elementwise update the
    jaxref train step runs (``jaxref/model.py::make_fused_adam``): bf16
    param + grad, fp32 moments -> 22 B/param of traffic. param/moments
    are the scan CARRY (not reduced outputs), so every write really
    lands in HBM each iteration — a reduction epilogue would fuse the
    writes away and inflate the measured efficiency by ~22/12."""
    from simumax_tpu.calibration.timing import fetch_rtt, time_fn

    numel = max(int(nbytes // 22), 1024)
    g = _test_array((numel,), jnp.bfloat16)
    p0 = _test_array((numel,), jnp.bfloat16)
    mu0 = _test_array((numel,), jnp.float32)
    nu0 = _test_array((numel,), jnp.float32)

    def make(length):
        def fn(pp, mm, vv, gg):
            def body(carry, _):
                p, mu, nu = carry
                # loop-varying perturbation: stops XLA hoisting the
                # grad cast out of the scan (traffic must repeat)
                gf = (gg + p[:1] * jnp.bfloat16(1e-8)).astype(jnp.float32)
                m2 = 0.9 * mu + 0.1 * gf
                v2 = 0.95 * nu + 0.05 * jnp.square(gf)
                newp = p.astype(jnp.float32) - 1e-4 * m2 / (
                    jnp.sqrt(v2) + 1e-8
                )
                return (newp.astype(p.dtype), m2, v2), None

            (p, mu, nu), _ = jax.lax.scan(
                body, (pp, mm, vv), None, length=length
            )
            return jnp.sum(p.astype(jnp.float32)) * 1e-30

        return jax.jit(fn)

    t = time_fn(make(pilot_length), p0, mu0, nu0, g, amortize=1) / pilot_length
    rtt = fetch_rtt()
    target = max(8.0 * rtt, 0.2)
    if t * pilot_length < target:
        length = int(min(8192, math.ceil(target / max(t, 1e-8))))
        if length > pilot_length:
            t = time_fn(
                make(length), p0, mu0, nu0, g, amortize=1, iters=5
            ) / length
    traffic = numel * 22
    return min(traffic / t / (peak_gbps * 1e9), 1.0)


def calibrate_bandwidth_classes(system, verbose: bool = False,
                                nbytes: float = 256 * 2**20,
                                vocab: int = 32000):
    """Measure the HBM bandwidth classes in the system config and write
    the efficiencies back. ``ce_fusion`` is skipped: a fused CE kernel
    avoids exactly the fp32 materialization the unfused benchmark
    performs, so measuring it with this benchmark would erase the
    fusion benefit — its prior stays."""
    out = {}
    bw = system.accelerator.bandwidth
    if "fused_adam" not in bw:
        # same physical HBM as 'default', its own achieved efficiency
        from simumax_tpu.core.config import BandwidthSpec

        base = bw["default"]
        bw["fused_adam"] = BandwidthSpec(
            gbps=base.gbps, efficient_factor=base.efficient_factor,
            latency_us=base.latency_us,
        )
    for key, spec in bw.items():
        if key == "ce_fusion":
            continue
        eff = measure_bandwidth_efficiency(key, spec.gbps, nbytes, vocab)
        spec.efficient_factor = eff
        out[key] = eff
        if verbose:
            get_reporter().info(f"[cal] bandwidth {key}: eff {eff:.3f}",
                                event="calibrate_bw", key=key, eff=eff)
    return out


# -- miss-driven loop ---------------------------------------------------------


def calibrate_key(op_key: str, shape_key: str, system,
                  sparse_ratio: float = 0.5,
                  attempts: int = 3) -> Optional[float]:
    """Measure one (op table, shape key) pair; None if unsupported.

    Each microbenchmark runs under bounded retry with backoff
    (:func:`with_retries`); after exhausting retries a
    :class:`CalibrationError` propagates so the caller can quarantine
    the key."""
    kv = _parse_key(shape_key)
    peak = _peak_tflops(system, op_key)
    label = f"{op_key}[{shape_key}]"
    try:
        if op_key.endswith("group_matmul"):
            return with_retries(
                measure_gemm_efficiency,
                m=int(kv["M"]), k=int(kv["K"]), n=int(kv["N"]),
                dtype=kv.get("dtype", "bf16"),
                out_dtype="fp32" if kv.get("accumulate") == "True" else kv.get("dtype", "bf16"),
                peak_tflops=peak, groups=int(kv["ng"]),
                attempts=attempts, label=label,
            )
        if op_key.endswith("matmul"):
            return with_retries(
                measure_gemm_efficiency,
                m=int(kv["m"]), k=int(kv["k"]), n=int(kv["n"]),
                dtype="int8" if op_key.startswith("int8") else "bf16",
                out_dtype=kv.get("out_dtype", "bf16"),
                peak_tflops=peak, batch=int(kv.get("b", 1)),
                layout=kv.get("layout", "NN"),
                attempts=attempts, label=label,
            )
        if op_key in ("sdp_fwd", "sdp_bwd"):
            return with_retries(
                measure_sdp_efficiency,
                b=int(kv["b"]), sq=int(kv["sq"]), skv=int(kv["skv"]),
                hn=int(kv["hn"]), kv_hn=int(kv["kv_hn"]), hd=int(kv["hd"]),
                hd_v=int(kv.get("hd_v", kv["hd"])),
                causal=kv.get("causal") == "True",
                dtype=kv.get("dtype", "bf16"), peak_tflops=peak,
                backward=op_key == "sdp_bwd", sparse_ratio=sparse_ratio,
                backend=kv.get("backend", "xla"),
                flash=kv.get("flash", "True") == "True",
                attempts=attempts, label=label,
            )
    except (KeyError, ValueError):
        # malformed shape key for this op family: unsupported, not an
        # error worth retrying
        return None
    return None


def calibrate_for_perf(perf, max_keys: Optional[int] = None,
                       verbose: bool = False,
                       diagnostics: Optional[Diagnostics] = None,
                       ) -> Dict[str, Dict[str, float]]:
    """Measure every efficiency-table miss recorded by the last
    ``run_estimate()`` and write the results into the live SystemConfig.
    Returns {op_key: {shape_key: efficiency}}.

    Hardened: each key's benchmark retries transient failures
    (:func:`with_retries`) and its result must pass
    :func:`validate_efficiency` before write-back; keys that still fail
    are skipped and recorded in ``diagnostics`` instead of aborting the
    whole calibration pass."""
    system = perf.system
    sparse = perf.strategy.attention_sparse_ratio
    measured: Dict[str, Dict[str, float]] = {}
    count = 0
    for op_key, keys in list(system.miss_efficiency.items()):
        spec = system.accelerator.op.get(op_key)
        if spec is None:
            continue
        for shape_key in keys:
            if max_keys is not None and count >= max_keys:
                break
            try:
                eff = calibrate_key(op_key, shape_key, system, sparse)
                if eff is None:
                    continue
                eff = validate_efficiency(eff, op_key, shape_key)
            except CalibrationError as exc:
                if diagnostics is not None:
                    diagnostics.record_exception(
                        exc, category="calibration",
                        op_key=op_key, shape_key=shape_key,
                    )
                if verbose:
                    get_reporter().info(
                        f"[cal] SKIP {op_key}: {shape_key} ({exc})",
                        event="calibrate_skip", op_key=op_key,
                        shape_key=shape_key,
                    )
                continue
            spec.accurate_efficient_factor[shape_key] = eff
            measured.setdefault(op_key, {})[shape_key] = eff
            count += 1
            if verbose:
                get_reporter().info(
                    f"[cal] {op_key}: {shape_key} -> {eff:.3f}",
                    event="calibrate_key", op_key=op_key,
                    shape_key=shape_key, eff=eff,
                )
    # the functional optimizer is ~20-25% of a single-chip step: measure
    # its fused-update bandwidth class whenever the estimate relies on
    # an unmeasured fallback (miss-driven, same as the shape keys)
    if (perf.strategy.optimizer_style == "functional"
            and "fused_adam" not in system.accelerator.bandwidth):
        from simumax_tpu.core.config import BandwidthSpec

        base = system.accelerator.bandwidth["default"]
        try:
            eff = validate_efficiency(
                with_retries(_measure_fused_adam, base.gbps,
                             label="bandwidth[fused_adam]"),
                "bandwidth", "fused_adam",
            )
        except CalibrationError as exc:
            if diagnostics is not None:
                diagnostics.record_exception(
                    exc, category="calibration", op_key="bandwidth",
                    shape_key="fused_adam",
                )
            if verbose:
                get_reporter().info(
                    f"[cal] SKIP bandwidth fused_adam ({exc})",
                    event="calibrate_skip", op_key="bandwidth",
                    shape_key="fused_adam",
                )
            eff = None
        if eff is not None:
            system.accelerator.bandwidth["fused_adam"] = BandwidthSpec(
                gbps=base.gbps, efficient_factor=eff,
                latency_us=base.latency_us,
            )
            measured.setdefault("bandwidth", {})["fused_adam"] = eff
            if verbose:
                get_reporter().info(
                    f"[cal] bandwidth fused_adam -> {eff:.3f}",
                    event="calibrate_bw", key="fused_adam", eff=eff,
                )
    return measured


def calibrate_system(perf, save_path: Optional[str] = None, **kwargs):
    """calibrate_for_perf + re-estimate + optional write-back of the
    updated system config JSON (reference ``combine_efficiency.py`` +
    ``apply_ws_comm_model.py`` write-back).

    The saved config carries a provenance stamp (hardware-identity hash
    + date + version, ``SystemConfig.stamp_provenance``) so loading it
    against a different system config warns instead of silently skewing
    estimates."""
    measured = calibrate_for_perf(perf, **kwargs)
    perf.run_estimate()  # re-run with calibrated tables
    if save_path:
        perf.system.stamp_provenance()
        cfg = perf.system.to_dict()
        with open(save_path, "w") as f:
            json.dump(cfg, f, indent=2, default=lambda o: vars(o))
    return measured

"""PerfLLM orchestrator (L4).

Reference: ``simumax/core/perf_llm.py`` — ``configure`` (:1426),
``run_estimate`` (:489), ``build``/``get_num_layers_to_build`` (:539-835),
``analysis_mem`` (:1599-1969), ``analysis_cost`` (:1971-2910) with the
event-matched 1F1B replay (``calculate_1f1b_bubble`` :2097), DP comm
(:1513) and Megatron-style optimizer timing (:1470), straggler inflation
(:255-291), and ``analysis`` (:3585-3668).

TPU redesign: ``analysis_net`` places every parallel dim on the ICI torus
/ DCN via ``SystemConfig.place_group`` (mesh-axis model) instead of
choosing NVLink/PCIe link classes.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Union

from simumax_tpu.core.config import (
    CommPath,
    GiB,
    ModelConfig,
    StrategyConfig,
    SystemConfig,
    _require,
    get_model_config,
    get_strategy_config,
    get_system_config,
)
from simumax_tpu.core.errors import ConfigError
from simumax_tpu.core.module import BuildContext
from simumax_tpu.core.records import Diagnostics
from simumax_tpu.core.utils import dp_comm_buckets, human_time
from simumax_tpu.models.llm import LLMModel

#: stable schema tag of the :meth:`PerfLLM.analysis_mem` result dict
#: (documented in docs/observability.md; bump on breaking changes)
MEM_SCHEMA = "simumax-mem-v1"


def interleaved_stage_peak(order, cache, peakpt):
    """Schedule-position memory replay of one stage's interleaved op
    list — the single source for both ``_analysis_mem_interleaved``'s
    scalar peak and the memory ledger's peak live-set materialization
    (``observe/memledger.py``), so the two folds can never diverge.

    ``order`` is the stage's (kind, chunk, mb) op list; ``cache`` /
    ``peakpt`` map chunk_idx -> per-microbatch cache bytes / internal
    walk peak. At each op, the active chunk's own microbatch walk
    contributes its internal PeakPoint (which includes that
    microbatch's cache) on top of every OTHER outstanding microbatch's
    cache — no last-chunk heuristic (round-1 VERDICT weak #3).

    Returns ``(peak_sched, peak_outstanding, peak_counts,
    peak_active)``: the peak bytes over model memory, the number of
    outstanding microbatches at the peak, the per-chunk count of FULL
    caches held there (the active chunk's own microbatch already
    excluded), and the chunk whose internal walk the peak rode on
    (None when the plain outstanding-cache sum won the max)."""
    live = peak_sched = 0.0
    counts: Dict[int, int] = {}
    peak_outstanding = 0
    peak_counts: Dict[int, int] = {}
    peak_active: Optional[int] = None
    for kind, c, _ in order:
        if kind == "F":
            live += cache.get(c, 0.0)
            counts[c] = counts.get(c, 0) + 1
        cand = live - cache.get(c, 0.0) + peakpt.get(c, 0.0)
        if max(cand, live) > peak_sched:
            peak_sched = max(cand, live)
            peak_outstanding = sum(counts.values())
            peak_counts = dict(counts)
            if cand >= live:
                peak_active = c
                peak_counts[c] = peak_counts.get(c, 0) - 1
            else:
                peak_active = None
        if kind == "B":
            live -= cache.get(c, 0.0)
            counts[c] = counts.get(c, 0) - 1
    return peak_sched, peak_outstanding, peak_counts, peak_active


def place_strategy_paths(strategy: StrategyConfig,
                         system: SystemConfig) -> Dict[str, CommPath]:
    """Mesh placement of every parallel dim for one strategy (reference
    ``analysis_net`` perf_llm.py:369-474) — extracted to module level so
    the batched sweep kernel (``search/batched.py``) places layouts with
    exactly the code :meth:`PerfLLM.analysis_net` uses."""
    st, sysc = strategy, system
    tp, cp, dp, pp = st.tp_size, st.cp_size, st.dp_size, st.pp_size
    ep, etp = st.ep_size, st.etp_size
    sizes = {"tp": tp, "cp": cp, "dp": dp, "pp": pp}
    order = st.mesh_order.split(",")

    def inner(dim: str) -> int:
        n = 1
        for d in order:
            if d == dim:
                return n
            n *= sizes[d]
        raise KeyError(dim)

    paths = {
        d: sysc.place_group(d, inner(d), sizes[d]) for d in sizes
    }
    # dp_cp (ZeRO sharding + grad reduce group) = the cp and dp dims
    # combined. With the default order they are adjacent and a single
    # placement reproduces the round-3 anchor behavior exactly; with
    # dp moved outermost the group is strided across pp, which the
    # hierarchical span concatenation expresses (innermost first).
    if st.mesh_order == "tp,cp,dp,pp":
        paths["dp_cp"] = sysc.place_group("dp_cp", tp, cp * dp)
    else:
        first, second = sorted(("cp", "dp"), key=order.index)
        combined = CommPath(dim="dp_cp", group_size=cp * dp)
        combined.spans = list(paths[first].spans) + list(
            paths[second].spans
        )
        paths["dp_cp"] = combined
    # MoE dims: etp shares the tp placement; ep strides over etp
    paths["etp"] = sysc.place_group("etp", 1, etp)
    paths["ep"] = sysc.place_group("ep", etp, ep)
    if st.mesh_order == "tp,cp,dp,pp":
        paths["edp"] = sysc.place_group("edp", etp * ep, st.edp_size)
    else:
        # non-default orders are guarded to ep=etp=1, where the edp
        # group is exactly tp x cp x dp — strided across pp when pp
        # is not outermost. Reuse those dims' placements so expert
        # gradients see the same DCN spans the dense dims do.
        assert ep == 1 and etp == 1, (ep, etp)
        combined = CommPath(dim="edp", group_size=st.edp_size)
        for d in order:
            if d != "pp":
                combined.spans.extend(paths[d].spans)
        paths["edp"] = combined
    return paths


def stage_layer_split(strategy: StrategyConfig,
                      model: ModelConfig) -> List[List[int]]:
    """counts[stage][vpp_rank] = transformer layers in that chunk
    (reference ``get_num_layers_to_build`` perf_llm.py:539) — extracted
    to module level for the same reason as
    :func:`place_strategy_paths`."""
    st, m = strategy, model
    pp, vp = st.pp_size, st.vp_size
    total_v = pp * vp
    counts = [[0] * vp for _ in range(pp)]
    layers = m.layer_num
    eff = layers
    if st.account_for_embedding_in_pipeline_split:
        eff += 1
    if st.account_for_loss_in_pipeline_split:
        eff += 1
    first = st.num_layers_in_first_pipeline_stage
    last = st.num_layers_in_last_pipeline_stage
    per_v = [0] * total_v
    if first or last:
        rem_v = total_v - (1 if first else 0) - (1 if last else 0)
        rem_layers = layers - (first or 0) - (last or 0)
        base = rem_layers // max(rem_v, 1)
        for v in range(total_v):
            per_v[v] = base
        if first:
            per_v[0] = first
        if last:
            per_v[-1] = last
    else:
        base = eff // total_v
        for v in range(total_v):
            per_v[v] = base
        if st.account_for_embedding_in_pipeline_split:
            per_v[0] -= 1
        if st.account_for_loss_in_pipeline_split:
            per_v[-1] -= 1
    # virtual stage v = chunk * pp + stage (Megatron interleaving)
    for v in range(total_v):
        chunk, stage = divmod(v, pp)
        counts[stage][chunk] = per_v[v]
    assert sum(sum(c) for c in counts) == layers
    return counts


def _resolve(cfg, cls, getter):
    if isinstance(cfg, cls):
        return cfg
    if isinstance(cfg, dict):
        return cls.init_from_dict(cfg)
    if isinstance(cfg, str):
        if os.path.isfile(cfg):
            return cls.init_from_config_file(cfg)
        return getter(cfg)
    raise TypeError(f"cannot resolve {cls.__name__} from {type(cfg)}")


def print_summary(result: dict) -> None:
    """Render the estimate headline (the ``perf`` CLI output) from an
    ``analysis`` result dict. Module-level so the planning service can
    render a cached payload without a built ``PerfLLM`` — one renderer,
    so cached and fresh output cannot diverge."""
    from simumax_tpu.observe.report import get_reporter

    log = get_reporter()
    cost, mem = result["compute_result"], result["mem_result"]
    info = result["base_info"]
    p = info["parallelism"]
    log.info(
        f"== {info['model']} on {info['system']} "
        f"(world={info['world_size']} tp={p['tp']} cp={p['cp']} "
        f"pp={p['pp']} dp={p['dp']} ep={p['ep']}) ==",
        event="perf_header", model=info["model"], system=info["system"],
    )
    log.info(
        f"iter time {human_time(cost['iter_time'])}  "
        f"MFU {cost['mfu']*100:.2f}%  "
        f"TFLOPS/chip {cost['tflops_per_chip']:.1f}  "
        f"TGS {cost['tgs']:.1f}",
        event="perf_cost", iter_time_ms=cost["iter_time_ms"],
        mfu=cost["mfu"], tgs=cost["tgs"],
    )
    log.info(
        f"peak HBM {mem['max_peak_gib']:.2f} GiB / "
        f"{mem['hbm_capacity_gib']:.0f} GiB  fits={mem['fits']}",
        event="perf_mem", peak_gib=mem["max_peak_gib"],
        fits=mem["fits"],
    )
    misses = result["efficiency_misses"]
    if misses:
        nmiss = sum(len(v) for v in misses.values())
        log.info(
            f"[calibration] {nmiss} efficiency-table misses "
            f"(run simumax_tpu.calibration to refine)",
            event="perf_misses", misses=nmiss,
        )


class PerfBase:
    """Config plumbing shared by perf frontends."""

    def __init__(self):
        self.strategy: Optional[StrategyConfig] = None
        self.model_config: Optional[ModelConfig] = None
        self.system: Optional[SystemConfig] = None
        #: central collector for this estimate's warnings / calibration
        #: coverage / quarantined failures (see docs/diagnostics.md).
        #: Inside a ``Diagnostics.activate()`` block (a sweep, a CLI run)
        #: this joins the run-level collector instead of starting a
        #: throwaway one, so per-candidate warnings reach the report.
        self.diagnostics = Diagnostics.active() or Diagnostics()

    def configure(
        self,
        strategy: Union[str, dict, StrategyConfig],
        model: Union[str, dict, ModelConfig],
        system: Union[str, dict, SystemConfig],
    ):
        with self.diagnostics.capture(category="config"):
            self.strategy = _resolve(strategy, StrategyConfig, get_strategy_config)
            self.model_config = _resolve(model, ModelConfig, get_model_config)
            self.system = _resolve(system, SystemConfig, get_system_config)
            self.strategy.sanity_check()
            self.model_config.sanity_check()
            self._cross_sanity_check()
        return self

    def _cross_sanity_check(self):
        """Reference ``perf_llm.py:1381-1424``."""
        st, m, sysc = self.strategy, self.model_config, self.system
        _require(
            st.world_size <= sysc.total_chips,
            f"strategy world_size {st.world_size} exceeds system "
            f"{sysc.total_chips} chips",
        )
        if st.dispatch_probs and m.model_type == "moe":
            _require(
                m.use_swiglu,
                "dispatch_probs fuses the prob-weighting into the SwiGLU "
                "expert activation (weighted-SiLU); a gelu MoE has no "
                "fusion point, so the combine cache cannot be dropped",
            )
        if st.recompute.mla_up_proj_recompute:
            _require(
                m.attention_type == "mla",
                "mla_up_proj recompute requires an MLA model "
                f"(model {m.model_name!r} uses {m.attention_type})",
            )
        if st.recompute.moe_act_recompute:
            _require(
                m.model_type == "moe",
                "moe_act recompute requires a MoE model "
                f"(model {m.model_name!r} is {m.model_type})",
            )
        head_shard = st.tp_size
        if st.cp_size > 1 and st.cp_comm_type == "a2a":
            head_shard *= st.cp_size  # Ulysses scatters heads over cp too
        _require(
            m.head_num % head_shard == 0,
            f"head_num {m.head_num} must divide tp"
            f"{'*cp' if head_shard != st.tp_size else ''} ({head_shard})",
        )
        if m.kv_head_num < st.tp_size:
            pass  # kv heads replicated within tp; allowed
        if m.model_type == "moe":
            _require(
                m.expert_num % st.ep_size == 0, "expert_num % ep != 0"
            )
        if st.use_flash_sdp and st.sdp_backend == "pallas":
            # same predicate the runtime dispatcher applies — reject
            # configs whose measurement would silently fall back to XLA
            # while the estimate charged Pallas rates
            from simumax_tpu.core.utils import pallas_attention_supported

            # post-collective shapes the kernel actually sees: under
            # cp=all_gather each rank runs its seq/cp query shard
            # against the FULL gathered KV; under a2a (and cp=1) both
            # are the full sequence
            if st.cp_size > 1 and st.cp_comm_type == "all_gather":
                sq_attn, skv_attn = st.seq_len // st.cp_size, st.seq_len
            else:
                sq_attn = skv_attn = st.seq_len
            _require(
                pallas_attention_supported(sq_attn, skv_attn, m.head_size),
                f"sdp_backend='pallas' needs lane-aligned attention "
                f"shapes (sq {sq_attn}, skv {skv_attn}, head_size "
                f"{m.head_size} must be multiples of 128) — the runtime "
                f"kernel would fall back to XLA; use sdp_backend='xla'",
            )
        if st.fp8:
            needed = [f"{st.quant_dtype}_matmul"]
            # sequential mode costs experts off the dense matmul table
            if m.model_type == "moe" and st.group_linear_mode == "parallel":
                needed.append(f"{st.quant_dtype}_group_matmul")
            for key in needed:
                _require(
                    key in sysc.accelerator.op,
                    f"system {sysc.sys_name!r} has no {key!r} efficiency "
                    f"table — this chip does not support {st.quant_dtype} "
                    f"matmuls (available: {sorted(sysc.accelerator.op)})",
                )
        total_stages = st.pp_size * st.vp_size
        layers = m.layer_num
        if st.num_layers_in_first_pipeline_stage:
            layers -= st.num_layers_in_first_pipeline_stage
        if st.num_layers_in_last_pipeline_stage:
            layers -= st.num_layers_in_last_pipeline_stage
        # remaining layers must split evenly over remaining virtual stages
        rem = total_stages
        if st.num_layers_in_first_pipeline_stage:
            rem -= 1
        if st.num_layers_in_last_pipeline_stage:
            rem -= 1
        eff = layers + (
            1 if st.account_for_embedding_in_pipeline_split else 0
        ) + (1 if st.account_for_loss_in_pipeline_split else 0)
        _require(
            eff % max(rem, 1) == 0,
            f"{layers} layers do not split evenly over {rem} virtual stages",
        )


class PerfLLM(PerfBase):
    """Analytical perf/memory estimation for one (system, strategy, model)
    triple. Usage: ``configure() -> run_estimate() -> analysis_mem() /
    analysis_cost() / analysis() / simulate()``."""

    def __init__(self):
        super().__init__()
        self.ctx: Optional[BuildContext] = None
        self.chunks: Dict[tuple, LLMModel] = {}  # (stage, vpp_rank) -> chunk
        self._mem_result = None
        self._cost_result = None
        self._interleaved_result = None
        self._dp_time_cache: Dict[int, dict] = {}
        #: per-op schedule intervals of the last analysis_cost replay:
        #: [(stage, kind, chunk, mb, start, end)] — the analytical
        #: trace export (observe/trace.py) lays these out as Chrome
        #: trace slices; kept off the result dict so saved JSONs stay
        #: headline-sized
        self._schedule_events: List[tuple] = []

    # ------------------------------------------------------------------
    # Net placement (reference ``analysis_net`` perf_llm.py:369-474)
    # ------------------------------------------------------------------
    def analysis_net(self) -> Dict[str, object]:
        return place_strategy_paths(self.strategy, self.system)

    # ------------------------------------------------------------------
    # Stage chunking (reference ``get_num_layers_to_build`` perf_llm.py:539)
    # ------------------------------------------------------------------
    def stage_layer_counts(self) -> List[List[int]]:
        """Return counts[stage][vpp_rank] = number of transformer layers."""
        return stage_layer_split(self.strategy, self.model_config)

    def build(self):
        """Construct per-(stage, vpp_rank) model chunks
        (reference ``build`` perf_llm.py:676-835)."""
        st = self.strategy
        self.model_config.maybe_pad_vocab_size(st.tp_size)
        paths = self.analysis_net()
        self.ctx = BuildContext(st, self.model_config, self.system, paths)
        counts = self.stage_layer_counts()
        self.chunks = {}
        offset = 0
        # build in virtual-stage (layer) order so offsets are consecutive
        for v in range(st.pp_size * st.vp_size):
            chunk_idx, stage = divmod(v, st.pp_size)
            n = counts[stage][chunk_idx]
            pre = v == 0
            post = v == st.pp_size * st.vp_size - 1
            self.chunks[(stage, chunk_idx)] = LLMModel(
                self.ctx,
                layer_num=n,
                layer_offset=offset,
                preprocess=pre,
                postprocess=post,
                stage_idx=stage,
                chunk_idx=chunk_idx,
                name=f"stage{stage}_chunk{chunk_idx}",
            )
            offset += n

    def _run(self):
        """Symbolic forward over every chunk (reference ``_run``
        perf_llm.py:2938-3047)."""
        for chunk in self.chunks.values():
            chunk.run()
            chunk.compute_activations()

    def run_estimate(self, capture_graph: bool = False,
                     debug: bool = False):
        assert self.strategy is not None, "call configure() first"
        with self.diagnostics.capture(category="placement"):
            self.build()
        env_graph = os.environ.get("ENABLE_SIMU_GRAPH", "").lower()
        if capture_graph or env_graph in ("1", "true", "yes", "on"):
            from simumax_tpu.core.graph import GraphBuilder

            self.ctx.graph = GraphBuilder()
        # per-path cost probes (reference debug_points -> cost_log.json)
        env_debug = os.environ.get("SIMU_DEBUG", "").lower()
        if debug or env_debug in ("1", "true", "yes", "on"):
            self.ctx.debug.enabled = True
        return self.estimate()

    def estimate(self):
        """Symbolic estimate over the already-built chunk graph (the
        estimate half of the build/estimate split; ``run_estimate`` is
        ``build() + estimate()``). Separated so the strategy sweep can
        re-estimate a layout under a new batch split (:meth:`rebatch`)
        without reconstructing the module tree."""
        assert self.ctx is not None, "call build() first"
        self.system.reset_status()
        with self.diagnostics.capture(category="estimate"):
            self._run()
        # merge (not snapshot) so a sweep's run-level collector
        # accumulates table coverage across every candidate it estimates
        self.diagnostics.record_efficiency(self.system)
        self._mem_result = None
        self._cost_result = None
        self._interleaved_result = None
        self._dp_time_cache = {}
        self._schedule_events = []
        return self

    #: strategy fields the built chunk graph does NOT depend on — they
    #: only enter at estimate/analysis time (input shapes, schedule
    #: replay), so :meth:`rebatch` may change them without a rebuild
    BATCH_ONLY_FIELDS = frozenset({"micro_batch_size", "micro_batch_num"})

    def rebatch(self, strategy: StrategyConfig):
        """Swap in a strategy differing from the current one only in
        :attr:`BATCH_ONLY_FIELDS` and re-estimate, reusing the built
        chunk graph (recompute wiring, stage split, mesh placement are
        all batch-independent). A micro_batch_num-only change skips even
        the symbolic re-run — only the schedule/memory analyses read it.

        This is the sweep's per-layout build cache fast path: the
        (mbs, mbc) searches inside one layout call this instead of
        rebuilding via ``configure() + run_estimate()``."""
        assert self.ctx is not None, "call build()/run_estimate() first"
        import dataclasses

        for f in dataclasses.fields(StrategyConfig):
            if f.name in self.BATCH_ONLY_FIELDS:
                continue
            if getattr(strategy, f.name) != getattr(self.strategy, f.name):
                raise ConfigError(
                    f"rebatch: field {f.name!r} differs from the built "
                    f"strategy — only {sorted(self.BATCH_ONLY_FIELDS)} may "
                    f"change without a rebuild; call configure() instead"
                )
        # validate BEFORE mutating: a failed sanity check must leave the
        # built estimate untouched (the caller may retry another split)
        with self.diagnostics.capture(category="config"):
            strategy.sanity_check()
        rerun = (
            strategy.micro_batch_size != self.strategy.micro_batch_size
        )
        self.strategy = strategy
        self.ctx.strategy = strategy
        self._mem_result = None
        self._cost_result = None
        self._interleaved_result = None
        self._dp_time_cache = {}
        self._schedule_events = []
        if rerun:
            return self.estimate()
        return self

    # ------------------------------------------------------------------
    # Memory analysis (reference perf_llm.py:1599-1969)
    # ------------------------------------------------------------------
    def stage_chunks(self, stage: int) -> List[LLMModel]:
        return [c for (s, _), c in sorted(self.chunks.items()) if s == stage]

    def analysis_mem(self) -> dict:
        """Per-stage peak-HBM prediction. Stable documented schema
        (``simumax-mem-v1``, see docs/observability.md):

        * ``stages[i]`` — per pipeline stage: ``model_bytes`` split into
          ``weight_bytes`` / ``grad_bytes`` / ``optimizer_state_bytes``,
          ``act_cache_per_microbatch_bytes``, ``live_microbatches``,
          ``replay_peak_bytes`` (the per-chunk activation-walk peak),
          ``peak_bytes`` / ``peak_gib``, and ``fits_margin_bytes``
          (usable HBM minus this stage's peak; negative = over);
        * top level — ``binding_stage`` (the max-peak stage every
          memory surface keys on), ``max_peak_bytes`` /
          ``max_peak_gib``, ``hbm_capacity_gib``, ``usable_bytes`` /
          ``usable_gib`` (capacity x ``mem_factor``), ``fits``, and
          ``fits_margin_bytes`` for the binding stage.

        The memory ledger (:meth:`memory_ledger`) decomposes each
        stage's ``peak_bytes`` into its live tensors."""
        if self._mem_result is not None:
            return self._mem_result
        st = self.strategy
        pp, mbc, vp = st.pp_size, st.micro_batch_num, st.vp_size
        if vp > 1:
            stages = self._analysis_mem_interleaved()
        else:
            stages = []
            for s in range(pp):
                chunks = self.stage_chunks(s)
                model_mem = sum(c.param_info.total_bytes for c in chunks)
                cache_per_mb = sum(c.act_info.cache_bytes for c in chunks)
                replay_peak = max(
                    (c.peak_point.bytes for c in chunks), default=0.0
                )
                live = min(mbc, pp - s)
                peak = model_mem + max(live - 1, 0) * cache_per_mb + replay_peak
                weight = sum(
                    c.param_info.weight_bytes + c.param_info.moe_weight_bytes
                    for c in chunks
                )
                grad = sum(
                    c.param_info.grad_bytes + c.param_info.moe_grad_bytes
                    for c in chunks
                )
                state = sum(
                    c.param_info.state_bytes + c.param_info.moe_state_bytes
                    for c in chunks
                )
                stages.append(
                    {
                        "stage": s,
                        "model_bytes": model_mem,
                        "weight_bytes": weight,
                        "grad_bytes": grad,
                        "optimizer_state_bytes": state,
                        "act_cache_per_microbatch_bytes": cache_per_mb,
                        "live_microbatches": live,
                        "replay_peak_bytes": replay_peak,
                        "peak_bytes": peak,
                        "peak_gib": peak / GiB,
                    }
                )
        cap = self.system.mem_bytes * st.mem_factor
        for s in stages:
            s["fits_margin_bytes"] = cap - s["peak_bytes"]
        max_peak = max(s["peak_bytes"] for s in stages)
        # the single source every memory surface (waterfall, forensics,
        # timeline artifacts) keys its "binding stage" on — first stage
        # at the max on ties (max returns the first maximal element)
        binding = max(range(len(stages)),
                      key=lambda i: stages[i]["peak_bytes"])
        result = {
            "schema": MEM_SCHEMA,
            "stages": stages,
            "binding_stage": binding,
            "max_peak_bytes": max_peak,
            "max_peak_gib": max_peak / GiB,
            "hbm_capacity_gib": self.system.mem_bytes / GiB,
            "usable_bytes": cap,
            "usable_gib": cap / GiB,
            "fits": all(s["peak_bytes"] <= cap for s in stages),
            "fits_margin_bytes": cap - max_peak,
        }
        self._mem_result = result
        return result

    # ------------------------------------------------------------------
    # Cost analysis
    # ------------------------------------------------------------------
    def _stage_phase_inputs(self, stage: int) -> dict:
        """Per-stage fwd/bwd compute + p2p times (reference
        ``_compute_single_batch_phase_inputs`` perf_llm.py:2644)."""
        chunks = self.stage_chunks(stage)
        fwd = sum(c.cost_info.fwd_time for c in chunks)
        bwd = sum(c.cost_info.bwd_time for c in chunks)
        p2p_bytes = chunks[0].boundary_bytes()
        p2p = self.system.compute_net_op_time("p2p", p2p_bytes, self.ctx.path("pp"))
        return {"fwd": fwd, "bwd": bwd, "p2p": p2p}

    def calculate_1f1b_bubble(self, phase_inputs: List[dict]) -> dict:
        """Event-matched non-interleaved 1F1B replay (reference
        ``calculate_1f1b_bubble`` perf_llm.py:2097-2306): per-stage op
        queues with p2p dependencies, no collective batching subtleties —
        on TPU the p2p is an XLA collective-permute on the pp mesh axis.
        """
        st = self.strategy
        pp, mbc = st.pp_size, st.micro_batch_num
        if pp == 1:
            from simumax_tpu.parallel.pipeline import single_stage_order

            ph = phase_inputs[0]
            events, t = [], 0.0
            for kind, i in single_stage_order(mbc):
                d = ph["fwd"] if kind == "F" else ph["bwd"]
                events.append((0, kind, 0, i, t, t + d))
                t += d
            total = mbc * (ph["fwd"] + ph["bwd"])
            return {"total": total, "bubble": 0.0, "per_stage_end": [total],
                    "events": events}

        # standard Megatron 1F1B op order per stage (shared with the
        # event simulator so the cross-check cannot desynchronize)
        from simumax_tpu.parallel.pipeline import one_f_one_b_order

        orders: List[List[tuple]] = [
            one_f_one_b_order(pp, s, mbc) for s in range(pp)
        ]

        # ``None`` marks "not yet completed"; a legitimate 0.0 completion
        # time (zero-cost degenerate stage) must not read as unready.
        F_end = [[None] * mbc for _ in range(pp)]
        B_end = [[None] * mbc for _ in range(pp)]
        stage_clock = [0.0] * pp
        events: List[tuple] = []  # (stage, kind, chunk, mb, start, end)
        # iterate op queues round-robin until all done (dependencies always
        # resolvable because 1F1B is deadlock-free)
        idx = [0] * pp
        remaining = sum(len(o) for o in orders)
        while remaining:
            progressed = False
            for s in range(pp):
                while idx[s] < len(orders[s]):
                    kind, i = orders[s][idx[s]]
                    ph = phase_inputs[s]
                    blocking = (
                        0.0 if self.strategy.pp_comm_async else ph["p2p"]
                    )
                    if kind == "F":
                        dep = 0.0 if s == 0 else F_end[s - 1][i]
                        if dep is None:
                            break  # dependency not ready yet
                        start = max(stage_clock[s], dep + (ph["p2p"] if s > 0 else 0.0))
                        end = start + ph["fwd"]
                        F_end[s][i] = end
                        events.append((s, "F", 0, i, start, end))
                        if s < pp - 1:
                            end += blocking  # blocking isend stalls sender
                    else:
                        dep = 0.0 if s == pp - 1 else B_end[s + 1][i]
                        if dep is None:
                            break
                        start = max(
                            stage_clock[s], dep + (ph["p2p"] if s < pp - 1 else 0.0)
                        )
                        end = start + ph["bwd"]
                        B_end[s][i] = end
                        events.append((s, "B", 0, i, start, end))
                        if s > 0:
                            end += blocking
                    stage_clock[s] = end
                    idx[s] += 1
                    remaining -= 1
                    progressed = True
            assert progressed, "1F1B schedule deadlocked (internal error)"

        per_stage_end = [stage_clock[s] for s in range(pp)]
        total = max(per_stage_end)
        work0 = mbc * (phase_inputs[0]["fwd"] + phase_inputs[0]["bwd"])
        return {
            "total": total,
            "bubble": total - work0,
            "per_stage_end": per_stage_end,
            "events": events,
        }

    def calculate_interleaved_schedule(self) -> dict:
        """Event-matched interleaved (VPP) schedule replay (reference
        ``_compute_interleaved_sync_schedule`` perf_llm.py:2322-2605):
        ops are (kind, chunk, microbatch); chunk c's forward output on
        the last stage feeds chunk c+1 on stage 0, and backward wraps
        the other way."""
        if self._interleaved_result is not None:
            return self._interleaved_result
        from simumax_tpu.parallel.pipeline import interleaved_order

        st = self.strategy
        pp, mbc, vp = st.pp_size, st.micro_batch_num, st.vp_size
        orders = [
            interleaved_order(pp, s, mbc, vp, st.vpp_group_size)
            for s in range(pp)
        ]
        fwd_t = {
            (s, c): sum(
                ch.cost_info.fwd_time
                for ch in self.stage_chunks(s)
                if ch.chunk_idx == c
            )
            for s in range(pp)
            for c in range(vp)
        }
        bwd_t = {
            (s, c): sum(
                ch.cost_info.bwd_time
                for ch in self.stage_chunks(s)
                if ch.chunk_idx == c
            )
            for s in range(pp)
            for c in range(vp)
        }
        p2p = self._stage_phase_inputs(0)["p2p"] if pp > 1 else 0.0

        F_end: Dict[tuple, float] = {}
        B_end: Dict[tuple, float] = {}
        clock = [0.0] * pp
        events: List[tuple] = []  # (stage, kind, chunk, mb, start, end)
        idx = [0] * pp
        remaining = sum(len(o) for o in orders)
        while remaining:
            progressed = False
            for s in range(pp):
                while idx[s] < len(orders[s]):
                    kind, c, mb = orders[s][idx[s]]
                    blocking = 0.0 if st.pp_comm_async else p2p
                    if kind == "F":
                        if s > 0:
                            dep = F_end.get((s - 1, c, mb))
                        elif c > 0:
                            dep = F_end.get((pp - 1, c - 1, mb))
                        else:
                            dep = 0.0
                        if dep is None:
                            break
                        start = max(clock[s], dep + (p2p if (s > 0 or c > 0) else 0.0))
                        end = start + fwd_t[(s, c)]
                        F_end[(s, c, mb)] = end
                        events.append((s, "F", c, mb, start, end))
                        if s < pp - 1 or c < vp - 1:
                            end += blocking  # blocking isend stalls sender
                    else:
                        if s < pp - 1:
                            dep = B_end.get((s + 1, c, mb))
                        elif c < vp - 1:
                            dep = B_end.get((0, c + 1, mb))
                        else:
                            dep = 0.0  # loss chunk: ready after own fwd
                        if dep is None:
                            break
                        start = max(
                            clock[s],
                            dep + (p2p if (s < pp - 1 or c < vp - 1) else 0.0),
                        )
                        end = start + bwd_t[(s, c)]
                        B_end[(s, c, mb)] = end
                        events.append((s, "B", c, mb, start, end))
                        if s > 0 or c > 0:
                            end += blocking
                    clock[s] = end
                    idx[s] += 1
                    remaining -= 1
                    progressed = True
            assert progressed, "interleaved schedule deadlocked"
        total = max(clock)
        work0 = sum(
            mbc * (fwd_t[(0, c)] + bwd_t[(0, c)]) for c in range(vp)
        )
        self._interleaved_result = {
            "total": total,
            "bubble": total - work0,
            "per_stage_end": clock,
            "orders": orders,
            "events": events,
        }
        return self._interleaved_result

    def _analysis_mem_interleaved(self) -> list:
        """Per-stage peak via interleaved schedule replay (reference
        sync-VPP phase-sequence memory replay perf_llm.py:1745-1928):
        walk each stage's (F/B, chunk, mb) op list accumulating
        per-chunk activation caches."""
        from simumax_tpu.parallel.pipeline import interleaved_order

        st = self.strategy
        orders = [
            interleaved_order(
                st.pp_size, s, st.micro_batch_num, st.vp_size,
                st.vpp_group_size,
            )
            for s in range(st.pp_size)
        ]
        stages = []
        for s in range(st.pp_size):
            cache = {
                ch.chunk_idx: ch.act_info.cache_bytes
                for ch in self.stage_chunks(s)
            }
            chunks = self.stage_chunks(s)
            peakpt = {
                ch.chunk_idx: ch.peak_point.bytes if ch.peak_point else 0.0
                for ch in chunks
            }
            model_mem = sum(ch.param_info.total_bytes for ch in chunks)
            # schedule-position replay shared with the memory ledger
            # (see interleaved_stage_peak)
            peak_sched, peak_outstanding, _, _ = interleaved_stage_peak(
                orders[s], cache, peakpt
            )
            replay_peak = max((peakpt[c] for c in peakpt), default=0.0)
            peak = model_mem + peak_sched
            stages.append(
                {
                    "stage": s,
                    "model_bytes": model_mem,
                    "weight_bytes": sum(
                        ch.param_info.weight_bytes + ch.param_info.moe_weight_bytes
                        for ch in chunks
                    ),
                    "grad_bytes": sum(
                        ch.param_info.grad_bytes + ch.param_info.moe_grad_bytes
                        for ch in chunks
                    ),
                    "optimizer_state_bytes": sum(
                        ch.param_info.state_bytes + ch.param_info.moe_state_bytes
                        for ch in chunks
                    ),
                    "act_cache_per_microbatch_bytes": sum(cache.values()) / st.vp_size,
                    "live_microbatches": peak_outstanding,
                    "replay_peak_bytes": replay_peak,
                    "peak_bytes": peak,
                    "peak_gib": peak / (1024**3),
                }
            )
        return stages

    def _compute_dp_time(self, stage: int = 0) -> dict:
        """Bucketed DP grad reduce-scatter + param all-gather for one
        stage's params, dense over dp_cp and MoE over edp (reference
        ``_compute_dp_time`` perf_llm.py:1513-1597). Stages can differ
        (embedding/head placement, leading dense layers in MoE models),
        so ``analysis_cost`` takes the max path over stages."""
        if stage in self._dp_time_cache:
            return self._dp_time_cache[stage]
        st, sysc = self.strategy, self.system
        dense_numel = moe_numel = 0.0
        for c in self.stage_chunks(stage):
            dense_numel += c.param_info.dense_numel
            moe_numel += c.param_info.moe_numel
        g_el = 2.0 if st.grad_reduce_in_bf16 else 4.0
        p_el = st.element_size
        t = 0.0
        detail = {}
        last_bucket_times = []  # per stream: its final bucket's rs time
        if st.dp_size * st.cp_size > 1 and dense_numel and st.zero_state < 3:
            # ZeRO-3 grads reduce-scatter per layer inside the backward
            # (leaf collectives) and params gather per layer in the next
            # forward — no step-end bulk comm for dense params
            path = self.ctx.path("dp_cp")
            group = st.dp_size * st.cp_size
            op = "reduce_scatter" if st.zero_state >= 1 else "all_reduce"
            bt = [
                sysc.compute_net_op_time(op, nb * g_el, path)
                for nb in dp_comm_buckets(dense_numel, group)
            ]
            rs = sum(bt)
            last_bucket_times.append(bt[-1])
            if st.zero_state == 2:
                # grads live sharded: reduce-scatter each microbatch
                rs *= st.micro_batch_num
            ag = (
                sum(
                    sysc.compute_net_op_time("all_gather", nb * p_el, path)
                    for nb in dp_comm_buckets(dense_numel, group)
                )
                if st.zero_state >= 1
                else 0.0
            )
            detail["dense_grad_rs_time"] = rs
            detail["dense_param_ag_time"] = ag
            t += rs + ag
        # tied-embedding grad sync between first/last stage replicas
        # (Megatron embedding-group all-reduce), ~a ring of two over the
        # pp path: two p2p transfers of the grad
        if (
            st.pp_size > 1
            and not self.model_config.untie_embeddings
            and stage in (0, st.pp_size - 1)
        ):
            emb_grad = (
                self.model_config.padded_vocab_size
                * self.model_config.hidden_size
                / st.tp_size
                * st.grad_element_size
            )
            t_tied = 2 * sysc.compute_net_op_time(
                "p2p", emb_grad, self.ctx.path("pp")
            )
            detail["tied_embedding_grad_ar_time"] = t_tied
            t += t_tied
        if st.edp_size > 1 and moe_numel and st.zero_state < 3:
            path = self.ctx.path("edp")
            op = "reduce_scatter" if st.zero_state >= 1 else "all_reduce"
            bt = [
                sysc.compute_net_op_time(op, nb * g_el, path)
                for nb in dp_comm_buckets(moe_numel, st.edp_size)
            ]
            rs = sum(bt)
            last_bucket_times.append(bt[-1])
            if st.zero_state == 2:
                rs *= st.micro_batch_num
            ag = (
                sum(
                    sysc.compute_net_op_time("all_gather", nb * p_el, path)
                    for nb in dp_comm_buckets(moe_numel, st.edp_size)
                )
                if st.zero_state >= 1
                else 0.0
            )
            detail["moe_grad_rs_time"] = rs
            detail["moe_param_ag_time"] = ag
            t += rs + ag
        # Megatron overlap flags: bucketed grad reduce hides under the
        # last microbatch's backward; the ZeRO-1 param all-gather hides
        # under the next iteration's first forward — only the excess is
        # exposed (keys below are what the simulator replays too)
        if t > 0 and (st.overlap_grad_reduce or st.overlap_param_gather):
            phases = self._stage_phase_inputs(stage)
            if st.overlap_grad_reduce:
                rs = (detail.get("dense_grad_rs_time", 0.0)
                      + detail.get("moe_grad_rs_time", 0.0))
                # ZeRO-2 reduce-scatters are issued per microbatch, each
                # hiding under its own backward; otherwise one bucketed
                # reduce overlaps only the last microbatch's backward.
                # Each stream's FINAL bucket only becomes ready when the
                # backward finishes, so it is never hideable (the dense
                # and MoE streams run on parallel channels — the longer
                # final bucket bounds the tail).
                n_windows = (
                    st.micro_batch_num if st.zero_state == 2 else 1
                )
                tail = max(last_bucket_times) if last_bucket_times else 0.0
                hidden = min(max(rs - tail * n_windows, 0.0),
                             phases["bwd"] * n_windows)
                if rs > 0:
                    scale = (rs - hidden) / rs
                    for k in ("dense_grad_rs_time", "moe_grad_rs_time"):
                        if k in detail:
                            detail[k] *= scale
                    detail["grad_reduce_hidden_time"] = hidden
                    t -= hidden
            if st.overlap_param_gather:
                ag = (detail.get("dense_param_ag_time", 0.0)
                      + detail.get("moe_param_ag_time", 0.0))
                # the gathers must complete once the first forward has
                # consumed the params; with VPP that first forward is
                # one chunk (1/vp of the stage's per-microbatch forward)
                hidden = min(ag, phases["fwd"] / st.vp_size)
                if ag > 0:
                    scale = (ag - hidden) / ag
                    for k in ("dense_param_ag_time", "moe_param_ag_time"):
                        if k in detail:
                            detail[k] *= scale
                    detail["param_gather_hidden_time"] = hidden
                    t -= hidden
        detail["total"] = t
        detail["exposed_rs"] = (
            detail.get("dense_grad_rs_time", 0.0)
            + detail.get("moe_grad_rs_time", 0.0)
            + detail.get("tied_embedding_grad_ar_time", 0.0)
        )
        detail["exposed_ag"] = (
            detail.get("dense_param_ag_time", 0.0)
            + detail.get("moe_param_ag_time", 0.0)
        )
        self._dp_time_cache[stage] = detail
        return detail

    def _compute_optim_time(self, stage: int = 0) -> float:
        """Optimizer-step time, memory-bound on HBM.

        "megatron" style models the distributed-optimizer phases
        (reference ``_compute_optim_time`` perf_llm.py:1470-1511):
        zero-grad, l2-norm, adam over fp32 master+moments, param copy.
        "functional" models one fused adam kernel as XLA emits for a
        functional JAX train step: read grad+param+moments, write
        param+moments.
        """
        st, sysc = self.strategy, self.system
        numel = 0.0
        for c in self.stage_chunks(stage):
            numel += c.param_info.dense_numel + c.param_info.moe_numel
        shard = numel / max(1, st.dp_size * st.cp_size) if st.zero_state else numel
        if st.optimizer_style == "functional":
            e = st.element_size
            # grad read + param read/write + two fp32 moments read/write;
            # the multi-stream fused update gets its own measured
            # bandwidth class when calibrated (falls back to default)
            traffic = shard * (st.grad_element_size + 2 * e + 16)
            return sysc.compute_mem_access_time(traffic, bw_key="fused_adam")
        t = 0.0
        t += sysc.compute_mem_access_time(numel * st.grad_element_size)  # zero grad
        t += sysc.compute_mem_access_time(shard * 4)  # l2 norm read
        t += sysc.compute_mem_access_time(shard * 28)  # adam r/w m,v,master+grad
        t += sysc.compute_mem_access_time(shard * (4 + st.element_size))  # cast copy
        return t

    def straggler_ratio(self) -> float:
        """Machine-variance inflation (reference perf_llm.py:255-291)."""
        st = self.strategy
        if not st.enable_straggler_model:
            return 1.0
        sysc = self.system
        hosts = max(1, st.world_size // max(1, sysc.chips_per_slice))
        n = min(hosts, st.dp_size, max(st.edp_size, 1))
        if n <= 1:
            return 1.0
        nhat = math.log2(n)
        return 1.0 + nhat / (nhat + 1.0) * 0.09 * math.sqrt(nhat)

    def analysis_cost(self) -> dict:
        if self._cost_result is not None:
            return self._cost_result
        st, m = self.strategy, self.model_config
        phase_inputs = [self._stage_phase_inputs(s) for s in range(st.pp_size)]
        if st.vp_size > 1:
            pp_res = self.calculate_interleaved_schedule()
            pp_res.pop("orders", None)
        else:
            pp_res = self.calculate_1f1b_bubble(phase_inputs)
        # per-op intervals feed the analytical trace export, not the
        # (JSON-saved) result dict
        self._schedule_events = pp_res.pop("events", [])
        # stages differ in params (embedding/head, MoE dense_layers), so
        # the iteration ends on the *max path*: each stage finishes its
        # backward, exposes its grad comm, all ranks barrier before the
        # step, then each runs its optimizer + param gather
        dp_by_stage = [self._compute_dp_time(s) for s in range(st.pp_size)]
        optim_by_stage = [
            self._compute_optim_time(s) for s in range(st.pp_size)
        ]
        ends = pp_res["per_stage_end"]
        s_rs = max(
            range(st.pp_size),
            key=lambda s: ends[s] + dp_by_stage[s]["exposed_rs"],
        )
        barrier_t = ends[s_rs] + dp_by_stage[s_rs]["exposed_rs"]
        s_tail = max(
            range(st.pp_size),
            key=lambda s: optim_by_stage[s] + dp_by_stage[s]["exposed_ag"],
        )
        tail = optim_by_stage[s_tail] + dp_by_stage[s_tail]["exposed_ag"]
        iter_time = barrier_t + tail
        # breakdown reports the binding (max-path) stages so the parts
        # still account for iter_time: iter = end[s_rs] + dp_comm + optim
        dp_res = dict(dp_by_stage[s_rs])
        dp_res["total"] = (
            dp_by_stage[s_rs]["exposed_rs"] + dp_by_stage[s_tail]["exposed_ag"]
        )
        optim = optim_by_stage[s_tail]
        ratio = self.straggler_ratio()
        iter_time *= ratio

        tokens = st.tokens_per_iter
        model_flops = m.train_flops_per_token(st.seq_len) * tokens
        per_chip = model_flops / st.world_size / iter_time
        peak = self.system.accelerator.op["default"].tflops * 1e12
        # time breakdown (stage 0 representative, per microbatch)
        chunks0 = self.stage_chunks(0)
        net_exposed = sum(c.cost_info.total_net_exposed for c in chunks0)
        compute_mb = sum(c.cost_info.compute.total for c in chunks0)
        recompute_mb = sum(c.cost_info.recompute_time for c in chunks0)
        # HBM-busy share of the rooflined compute (diagnostic: the
        # remainder is MXU-bound slack an async HBM stream could hide in)
        hbm_busy_mb = sum(c.cost_info.mem_bound.total for c in chunks0)
        breakdown = {
            "compute_per_microbatch": compute_mb,
            "exposed_comm_per_microbatch": net_exposed,
            "recompute_per_microbatch": recompute_mb,
            "hbm_busy_per_microbatch": hbm_busy_mb,
            "bubble": pp_res["bubble"],
            "dp_comm": dp_res["total"],
            "optimizer": optim,
        }
        result = {
            "iter_time": iter_time,
            "iter_time_ms": iter_time * 1e3,
            "pp_total_time": pp_res["total"],
            "bubble_time": pp_res["bubble"],
            "dp_comm": dp_res,
            "optim_time": optim,
            "straggle_ratio": ratio,
            "mfu": per_chip / peak,
            "tflops_per_chip": per_chip / 1e12,
            "tokens_per_sec": tokens / iter_time,
            "tgs": tokens / iter_time / st.world_size,
            "stage_phase_inputs": phase_inputs,
            "net_exposed_per_microbatch": net_exposed,
            "time_breakdown": breakdown,
            # attribution provenance (observe/ledger.py waterfall): the
            # schedule's per-stage finish times and the two binding
            # (max-path) stages the iteration end actually rode on
            "per_stage_end": list(ends),
            "binding_stage_rs": s_rs,
            "binding_stage_tail": s_tail,
            "exposed_rs_time": dp_by_stage[s_rs]["exposed_rs"],
            "exposed_ag_time": dp_by_stage[s_tail]["exposed_ag"],
        }
        self._cost_result = result
        return result

    # ------------------------------------------------------------------
    # Combined report (reference ``analysis`` perf_llm.py:3585-3668)
    # ------------------------------------------------------------------
    def analysis(self, save_path: Optional[str] = None, verbose: bool = True) -> dict:
        mem = self.analysis_mem()
        cost = self.analysis_cost()
        st = self.strategy
        result = {
            "base_info": {
                "model": self.model_config.model_name,
                "system": self.system.sys_name,
                "world_size": st.world_size,
                "parallelism": {
                    "tp": st.tp_size, "cp": st.cp_size, "pp": st.pp_size,
                    "dp": st.dp_size, "ep": st.ep_size, "etp": st.etp_size,
                    "vp": st.vp_size,
                },
                "seq_len": st.seq_len,
                "global_batch_size": st.global_batch_size,
                "param_numel": self.model_config.param_numel(),
            },
            "mem_result": mem,
            "compute_result": cost,
            "net_info": {k: p.describe() for k, p in self.ctx.paths.items()},
            "efficiency_misses": self.system.miss_efficiency,
        }
        self.diagnostics.record_efficiency(self.system)
        result["diagnostics"] = self.diagnostics.to_dict()
        if verbose:
            self._print_summary(result)
        if save_path:
            os.makedirs(save_path, exist_ok=True)
            self.diagnostics.write(os.path.join(save_path, "diagnostics.json"))
            for key in ("base_info", "mem_result", "compute_result", "net_info"):
                with open(os.path.join(save_path, f"{key}.json"), "w") as f:
                    json.dump(result[key], f, indent=2, default=str)
            with open(os.path.join(save_path, "op_table.json"), "w") as f:
                json.dump(
                    {
                        f"stage{s}": [
                            row
                            for c in self.stage_chunks(s)
                            for row in c.op_table()
                        ]
                        for s in range(self.strategy.pp_size)
                    },
                    f,
                    indent=1,
                )
            if self.ctx.graph is not None:
                self.ctx.graph.save_json(
                    os.path.join(save_path, "graph.json")
                )
                self.ctx.graph.save_dot(os.path.join(save_path, "graph.dot"))
            if self.ctx.debug.enabled and self.ctx.debug.rows:
                with open(os.path.join(save_path, "cost_log.json"), "w") as f:
                    json.dump(self.ctx.debug.rows, f, indent=1)
            # annotated module tree (reference model_arch dump)
            with open(os.path.join(save_path, "model_arch.txt"), "w") as f:
                for (stage, chunk_idx), chunk in sorted(self.chunks.items()):
                    f.write(f"===== stage {stage} chunk {chunk_idx} =====\n")
                    f.write(repr(chunk) + "\n")
            # the exact configs this estimate ran with (reference
            # *_config.json dumps)
            for name, cfg in (
                ("model_config", self.model_config),
                ("strategy_config", self.strategy),
                ("system_config", self.system),
            ):
                with open(os.path.join(save_path, f"{name}.json"), "w") as f:
                    f.write(cfg.to_json_string())
        return result

    def _print_summary(self, result: dict):
        print_summary(result)

    def ledger(self):
        """Collect the cost-attribution ledger of the current estimate
        (see ``observe/ledger.py`` / ``docs/observability.md``): per-op
        and per-collective spans with efficiency provenance, the
        MFU-loss waterfall, and the headline summary. Post-hoc over the
        retained symbolic tree — calling it never changes the estimate
        (ledger-on and ledger-off predictions are bit-identical)."""
        from simumax_tpu.observe.ledger import Ledger

        return Ledger.collect(self)

    def memory_ledger(self, timeline: bool = True):
        """Collect the per-tensor HBM ledger of the current estimate
        (``observe/memledger.py`` / ``docs/observability.md``): the full
        live set at each stage's predicted peak as ``MemSpan`` records,
        the peak-HBM waterfall (buckets sum to
        ``analysis_mem()["max_peak_bytes"]`` within 1e-6), and the
        analytical memory timeline in the simulator's snapshot schema.
        Post-hoc and read-only like :meth:`ledger` — headline numbers
        with and without collection are bit-identical."""
        from simumax_tpu.observe.memledger import MemoryLedger

        return MemoryLedger.collect(self, timeline=timeline)

    def memory_crosscheck(self, granularity: str = "leaf"):
        """Per-stage analytical-vs-DES peak cross-check
        (``observe/memledger.py::mem_crosscheck``): replay the step in
        the discrete-event simulator with memory tracking and compare
        each stage's simulated peak against this estimate's
        ``analysis_mem`` prediction — the memory analog of the sweep's
        ``sim_vs_analytical`` time column."""
        from simumax_tpu.observe.memledger import mem_crosscheck

        return mem_crosscheck(self, granularity=granularity)

    # simulate() is provided by L5 (simulator package); bound lazily
    def simulate(self, save_path: Optional[str] = None, **kwargs):
        """Discrete-event replay of the estimated iteration
        (``simulator/runner.py``). Key kwargs: ``granularity``
        ("leaf"/"chunk"), ``world_ranks`` (simulate every global rank),
        ``perturbation`` ({rank: compute multiplier} straggler
        injection), ``reduce`` (rank-symmetry reduction: "auto" / True /
        False), ``track_memory``, ``stream_trace`` (bounded-RSS
        incremental trace write), ``critical_path`` (record the
        event-dependency skeleton and attach the slack / blame /
        divergence report — ``observe/critpath.py``,
        ``docs/observability.md``). Reports into
        ``self.diagnostics``."""
        from simumax_tpu.simulator.runner import run_simulation

        return run_simulation(self, save_path, **kwargs)

    def critical_path(self, save_path: Optional[str] = None, **kwargs):
        """Convenience wrapper: :meth:`simulate` with
        ``critical_path=True``, returning just the critical-path report
        (per-event slack, the cross-rank path, the simulated waterfall
        summing to the DES makespan, sim-vs-analytical divergence, and
        per-rank / per-link slack headroom)."""
        return self.simulate(
            save_path, critical_path=True, **kwargs
        )["critical_path"]

    def predict_goodput(self, scenario, **kwargs):
        """Goodput prediction for a fault scenario over its job horizon
        (``simulator/faults.py``, ``docs/faults.md``): per-step
        discrete-event replays under the scenario's timed faults plus
        the checkpoint-write / restore-read / restart-replay cost
        model. Returns a ``GoodputReport`` whose wall-time buckets sum
        to the wall time exactly."""
        from simumax_tpu.simulator.faults import predict_goodput

        return predict_goodput(self, scenario, **kwargs)

    def analyze_faults(self, **kwargs):
        """Seeded Monte-Carlo goodput analysis: sample N random fault
        scenarios, predict each one's goodput, and sweep checkpoint
        intervals for the optimum (``simulator/faults.py::
        analyze_faults``)."""
        from simumax_tpu.simulator.faults import analyze_faults

        return analyze_faults(self, **kwargs)

    def rebatched_iter_time(self, micro_batch_num: int) -> float:
        """Analytical iteration time (seconds) of this built layout
        under a different micro-batch count, via the :meth:`rebatch`
        fast path — the fleet simulator's elastic-reshape re-costing
        (``fleet/sim.py``): after a dp shrink the surviving replicas
        carry ``gbs / (dp_eff * mbs)`` microbatches each, and only the
        schedule/memory analyses read ``micro_batch_num``, so the
        shrunk step is re-costed without rebuilding the module tree.

        Mutates this estimate's strategy (the caller owns a dedicated
        costing estimate; the fleet's per-template runtime keeps one
        beside the replay context's untouched estimate) and leaves it
        re-estimated at ``micro_batch_num`` on return."""
        from simumax_tpu.search.prune import clone_strategy

        st = clone_strategy(self.strategy)
        st.micro_batch_num = int(micro_batch_num)
        st.__post_init__()
        self.rebatch(st)
        return self.analysis_cost()["iter_time"]

    def analysis_dualpp(self, save_path: Optional[str] = None):
        """Per-rank DualPipe projection of this estimate (even pp only):
        bidirectional schedule, 2 stage chunks per rank, pp+1 in-flight
        activation bound. ``save_path`` renders the overlapped F&B cell
        timeline PNG. See ``parallel/dualpp.py``."""
        from simumax_tpu.parallel.dualpp import analyze

        return analyze(self, save_path)

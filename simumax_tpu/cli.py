"""Command-line surface (L8).

Reference user surfaces: 18 example scripts + the Streamlit app
(``app/streamlit_app.py``). The CLI covers the same workflows
non-interactively::

    python -m simumax_tpu list
    python -m simumax_tpu perf --model llama3-8b \
        --strategy tp1_pp2_dp4_mbs1 --system tpu_v5e_256 [--simulate DIR]
    python -m simumax_tpu search --model llama3-8b --system tpu_v5p_256 \
        --world 64 --gbs 128 --tp 1,2,4,8 --pp 1,2,4 [--csv sweep.csv]
    python -m simumax_tpu calibrate --model ... --strategy ... \
        --system ... --save my_system.json      # needs a live TPU
    python -m simumax_tpu straggler --model ... --strategy ... \
        --system ... --ranks 0:1.2,5:1.5        # per-rank slowdowns
"""

from __future__ import annotations

import argparse
import sys


def _ints(s: str):
    return tuple(int(x) for x in s.split(","))


def cmd_list(args):
    from simumax_tpu.core.config import list_configs

    for kind, names in list_configs().items():
        print(f"{kind}:")
        for n in names:
            print(f"  {n}")


def cmd_perf(args):
    from simumax_tpu import PerfLLM

    perf = PerfLLM().configure(args.strategy, args.model, args.system)
    perf.run_estimate(capture_graph=args.graph)
    perf.analysis(save_path=args.save)
    if args.simulate:
        result = perf.simulate(args.simulate)
        print(
            f"simulated: {result['end_time_ms']:.2f} ms, "
            f"trace at {result.get('trace_path')}"
        )


def cmd_search(args):
    from simumax_tpu.core.config import (
        get_model_config,
        get_strategy_config,
        get_system_config,
    )
    from simumax_tpu.search import search_best_parallel_strategy

    model = get_model_config(args.model)
    system = get_system_config(args.system)
    base = get_strategy_config(args.base_strategy)
    if args.world:
        base.world_size = args.world
    if args.seq_len:
        base.seq_len = args.seq_len
    zero_list = _ints(args.zero)
    bad = [z for z in zero_list if z not in (0, 1, 2, 3)]
    if bad:
        raise SystemExit(
            f"invalid --zero levels {bad}: expected a comma list of "
            "0-3 (e.g. --zero 1,3)"
        )
    rows = search_best_parallel_strategy(
        base, model, system, args.gbs,
        tp_list=_ints(args.tp), pp_list=_ints(args.pp),
        ep_list=_ints(args.ep), cp_list=_ints(args.cp),
        zero_list=zero_list,
        topk=args.topk, csv_path=args.csv, verbose=args.verbose,
        project_dualpp=args.dualpp,
    )
    for r in rows:
        dual = ""
        if r.get("dualpp_mfu") is not None:
            fits = "fits" if r["dualpp_fits"] else "OOM"
            dual = f"  [DualPipe: {r['dualpp_mfu']*100:.2f}% {fits}]"
        print(
            f"tp{r['tp']} cp{r['cp']} ep{r['ep']} pp{r['pp']} dp{r['dp']} "
            f"z{r['zero']} mbs{r['mbs']} mbc{r['mbc']} {r['recompute']}: "
            f"MFU {r['mfu']*100:.2f}%  iter {r['iter_ms']:.0f} ms  "
            f"peak {r['peak_gib']:.1f} GiB"
            + (f"  [DCN: {r['dcn_dims']}]" if r.get("dcn_dims") else "")
            + dual
        )


def cmd_calibrate(args):
    from simumax_tpu import PerfLLM
    from simumax_tpu.calibration import calibrate_system

    perf = PerfLLM().configure(args.strategy, args.model, args.system)
    perf.run_estimate()
    if args.bandwidth:
        from simumax_tpu.calibration.autocal import calibrate_bandwidth_classes

        calibrate_bandwidth_classes(
            perf.system, verbose=True,
            vocab=perf.model_config.padded_vocab_size,
        )
    if args.collectives:
        import jax

        from simumax_tpu.calibration.collective_bench import (
            sweep_axis,
            update_system_from_sweep,
        )
        from simumax_tpu.jaxref.model import make_mesh

        n = len(jax.devices())
        if n < 2:
            print("[cal] collectives: need >1 device, skipping")
        else:
            mesh = make_mesh(n, tp=n)
            sweep = sweep_axis(mesh, "tp")
            update_system_from_sweep(perf.system, n, sweep)
            for op, fit in sweep.items():
                print(f"[cal] {op}: {fit['fitted_bw_gbps']:.1f} GB/s, "
                      f"{fit['fitted_latency_us']:.1f} us")
    measured = calibrate_system(
        perf, save_path=args.save, max_keys=args.max_keys, verbose=True
    )
    n = sum(len(v) for v in measured.values())
    print(f"calibrated {n} shape keys"
          + (f"; wrote {args.save}" if args.save else ""))
    perf.analysis()


def cmd_dualpp(args):
    from simumax_tpu import PerfLLM

    perf = PerfLLM().configure(args.strategy, args.model, args.system)
    if perf.strategy.pp_size % 2 or perf.strategy.pp_size < 2:
        raise SystemExit(
            f"DualPipe requires an even pp >= 2 "
            f"(strategy has pp={perf.strategy.pp_size})"
        )
    if perf.strategy.vp_size != 1:
        raise SystemExit(
            "DualPipe and VPP interleaving are exclusive "
            f"(strategy has interleaving_size={perf.strategy.vp_size})"
        )
    perf.run_estimate()
    res = perf.analysis_dualpp(save_path=args.plot)
    print(
        f"1F1B baseline  {res['baseline_iter_time'] * 1e3:9.1f} ms  "
        f"peak {res['baseline_peak_gib']:.1f} GiB"
    )
    print(
        f"DualPipe       {res['dualpp_iter_time'] * 1e3:9.1f} ms  "
        f"peak {res['max_peak_gib']:.1f} GiB  "
        f"(speedup {res['speedup']:.3f}x, projected MFU "
        f"{res['projected_mfu'] * 100:.2f}%)"
    )
    for r in res["ranks"]:
        print(
            f"  rank {r['rank']}: stages {r['stages']}  "
            f"bubble {r['bubble'] * 1e3:7.1f} ms  "
            f"peak {r['peak_gib']:.1f} GiB"
        )
    if args.plot:
        print(f"F&B cell timeline -> {args.plot}")


def cmd_straggler(args):
    from simumax_tpu import PerfLLM
    from simumax_tpu.simulator.runner import analyze_stragglers

    perf = PerfLLM().configure(args.strategy, args.model, args.system)
    slow = {}
    for spec in args.ranks.split(","):
        try:
            r, f = spec.split(":")
            slow[int(r)] = float(f)
        except ValueError:
            raise SystemExit(
                f"bad --ranks entry {spec!r}: expected rank:multiplier "
                "(e.g. 0:1.2,5:1.5)"
            )
    world = perf.strategy.world_size
    bad = [r for r in slow if not 0 <= r < world]
    if bad:
        raise SystemExit(
            f"ranks {bad} out of range for world_size {world}"
        )
    perf.run_estimate()
    res = analyze_stragglers(perf, slow)
    print(
        f"baseline {res['baseline_ms']:.1f} ms -> perturbed "
        f"{res['perturbed_ms']:.1f} ms  (inflation {res['inflation']:.3f}, "
        f"worst injected multiplier {res['worst_multiplier']:.2f})"
    )


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="simumax_tpu",
        description="TPU-native analytical simulator for LLM training",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    sub.add_parser("list", help="list available configs").set_defaults(
        fn=cmd_list
    )

    pp = sub.add_parser("perf", help="estimate one configuration")
    pp.add_argument("--model", required=True)
    pp.add_argument("--strategy", required=True)
    pp.add_argument("--system", required=True)
    pp.add_argument("--save", help="directory for result JSONs")
    pp.add_argument("--simulate", help="run the event simulator; dir for trace")
    pp.add_argument("--graph", action="store_true", help="capture op graph")
    pp.set_defaults(fn=cmd_perf)

    ps = sub.add_parser("search", help="sweep parallel strategies")
    ps.add_argument("--model", required=True)
    ps.add_argument("--system", required=True)
    ps.add_argument("--base-strategy", default="tp1_pp1_dp8_mbs1")
    ps.add_argument("--world", type=int, default=0)
    ps.add_argument("--seq-len", type=int, default=0)
    ps.add_argument("--gbs", type=int, required=True)
    ps.add_argument("--tp", default="1,2,4,8")
    ps.add_argument("--pp", default="1,2,4")
    ps.add_argument("--ep", default="1")
    ps.add_argument("--cp", default="1")
    ps.add_argument("--zero", default="1", help="zero_state levels, e.g. 1,3")
    ps.add_argument("--topk", type=int, default=5)
    ps.add_argument("--csv")
    ps.add_argument("--verbose", action="store_true")
    ps.add_argument("--dualpp", action="store_true",
                    help="add a DualPipe projection column (even-pp rows)")
    ps.set_defaults(fn=cmd_search)

    pc = sub.add_parser(
        "calibrate", help="self-calibrate on the local TPU (miss-driven)"
    )
    pc.add_argument("--model", required=True)
    pc.add_argument("--strategy", required=True)
    pc.add_argument("--system", required=True)
    pc.add_argument("--save", help="write calibrated system config JSON")
    pc.add_argument("--max-keys", type=int, default=64)
    pc.add_argument("--bandwidth", action="store_true",
                    help="also calibrate HBM bandwidth classes")
    pc.add_argument("--collectives", action="store_true",
                    help="also sweep+fit collectives (needs >1 device)")
    pc.set_defaults(fn=cmd_calibrate)

    pd = sub.add_parser(
        "dualpp",
        help="DualPipe bidirectional-schedule projection (even pp)",
    )
    pd.add_argument("--model", required=True)
    pd.add_argument("--strategy", required=True)
    pd.add_argument("--system", required=True)
    pd.add_argument("--plot", help="PNG path for the F&B cell timeline")
    pd.set_defaults(fn=cmd_dualpp)

    pst = sub.add_parser(
        "straggler",
        help="world-rank simulation with per-rank slowdown injection",
    )
    pst.add_argument("--model", required=True)
    pst.add_argument("--strategy", required=True)
    pst.add_argument("--system", required=True)
    pst.add_argument(
        "--ranks", required=True,
        help="rank:multiplier list, e.g. 0:1.2,5:1.5",
    )
    pst.set_defaults(fn=cmd_straggler)

    args = p.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

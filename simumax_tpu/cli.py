"""Command-line surface (L8).

Reference user surfaces: 18 example scripts + the Streamlit app
(``app/streamlit_app.py``). The CLI covers the same workflows
non-interactively::

    python -m simumax_tpu list
    python -m simumax_tpu perf --model llama3-8b \
        --strategy tp1_pp2_dp4_mbs1 --system tpu_v5e_256 \
        [--simulate DIR [--world-ranks] [--reduce auto|on|off] \
         [--stream-trace]]
    python -m simumax_tpu search --model llama3-8b --system tpu_v5p_256 \
        --world 64 --gbs 128 --tp 1,2,4,8 --pp 1,2,4 [--csv sweep.csv]
    python -m simumax_tpu calibrate --model ... --strategy ... \
        --system ... --save my_system.json      # needs a live TPU
    python -m simumax_tpu straggler --model ... --strategy ... \
        --system ... --ranks 0:1.2,5:1.5        # per-rank slowdowns

Resilience surface (see ``docs/diagnostics.md``): ``perf`` / ``search``
/ ``calibrate`` accept ``--diagnostics PATH`` (write the JSON report)
and ``--strict`` (exit 3 on any warning / efficiency miss / quarantined
failure); ``search`` additionally takes ``--journal`` / ``--resume``
(JSONL sweep checkpointing), ``--candidate-timeout``, ``--jobs N``
(process-pool cell evaluation, default ``os.cpu_count()``) and
``--no-prune`` (see ``docs/search.md``). Config-family errors exit 2
with a one-line message instead of a traceback.

Fault/goodput surface (see ``docs/faults.md``): ``perf --simulate``
takes ``--faults SCENARIO.json`` (timed rank slowdowns, preemptions,
link degradation, rank deaths injected into the simulated step);
``faults`` predicts the goodput waterfall of a scenario over its job
horizon (``--scenario``) or Monte-Carlos the failure space for the
optimal checkpoint interval (``--monte-carlo N --seed S``);
``fleet`` walks a multi-job arrival trace over a shared pod fleet
(docs/fleet.md) for fleet-wide goodput, per-job SLO attainment, and
the scheduler-decision timeline. ``SimulationError`` escaping any
command exits 3 with a one-line message (the full engine dump goes to
``--diagnostics``).

Observability surface (see ``docs/observability.md``): ``explain``
renders the MFU-loss waterfall + top-N op table from the
cost-attribution ledger (``--json`` saves the full ledger, ``--csv``
the op table, ``--trace`` a Chrome trace of the analytical schedule);
``explain --memory`` renders the peak-HBM waterfall + per-tensor
holder table from the memory ledger, with OOM forensics (top holders +
what-if probes naming the cheapest fitting change) for non-fitting
configs, ``--crosscheck`` for the analytical-vs-DES per-stage peak
comparison, and ``--mem-artifacts DIR`` for the analytical memory
timeline in the simulator's artifact formats; ``critical-path`` runs
the discrete-event simulator with the event-dependency skeleton
recorded and reports per-event slack, the simulated critical-path
waterfall (buckets sum to the DES makespan), sim-vs-analytical
divergence and per-rank/per-link slack headroom (``perf --simulate
--critical-path`` attaches the same report to a perf run); ``diff``
compares two saved ledgers (``--memory`` for memory ledgers,
``--critical-path`` for critical-path reports). Every subcommand
accepts ``--log-level`` and ``--log-json`` (structured JSONL lines
with a run_id instead of the human format).

Service surface (see ``docs/service.md``): ``perf`` / ``explain`` /
``search`` route through the ``Planner`` facade and its persistent
content-addressed result cache by default (``--cache-dir`` /
``--no-cache``; output is bit-identical either way, sweeps re-evaluate
only cells missing from the store); ``serve`` runs the long-lived
JSON-over-HTTP planning server sharing the same cache; ``cache``
inspects/maintains it (``stats`` / ``ls`` / ``verify`` / ``clear``).
"""

from __future__ import annotations

import argparse
import contextlib
import json
import os
import sys

#: exit codes: 2 = bad config / usage, 3 = --strict violation or a
#: simulation-invariant failure (SimulationError family — the schedule
#: replay itself is wedged, not the user's configuration)
EXIT_CONFIG = 2
EXIT_STRICT = 3
EXIT_SIMULATION = 3


def _ints(s: str):
    return tuple(int(x) for x in s.split(","))


def _log():
    from simumax_tpu.observe.report import get_reporter

    return get_reporter()


def _emit_diagnostics(diag, args):
    """Emit the diagnostics report — also on the failure path (a run
    that aborted is exactly the run the report must explain).

    Writes the JSON to ``--diagnostics PATH`` when given (a compact
    summary goes to stdout), otherwise prints the full report as one
    ``[diagnostics]``-prefixed JSON line."""
    if not diag.run_id:
        # commands that never compute a content identity (perf,
        # calibrate, dualpp, straggler) still get one joinable id:
        # adopt the process reporter's, so --log-json lines and this
        # report cross-reference by run_id like explain/search do
        diag.adopt_run_id(_log().run_id)
    path = getattr(args, "diagnostics", None)
    if path:
        diag.write(path)
        _log().info(f"[diagnostics] {diag.summary_line()} -> {path}",
                    event="diagnostics", path=path)
    else:
        _log().info("[diagnostics] "
                    + json.dumps(diag.to_dict(), separators=(",", ":")),
                    event="diagnostics")


def _check_strict(diag, args):
    if getattr(args, "strict", False):
        violations = diag.violations()
        if violations:
            print(
                "error: strict mode: " + ", ".join(violations),
                file=sys.stderr,
            )
            sys.exit(EXIT_STRICT)


@contextlib.contextmanager
def _diagnosed(diag, args):
    """Run a command body with the report guaranteed on exit: a fatal
    ``SimuMaxError`` is recorded as the report's final error, the report
    is emitted in a ``finally`` (so aborts still produce it — a failed
    emit must not mask the real failure), then ``--strict`` is enforced
    only when the body itself succeeded — a failing body already
    carries its own exit code."""
    from simumax_tpu.core.errors import SimuMaxError

    try:
        yield
    except SimuMaxError as exc:
        diag.record_exception(exc, category="fatal")
        raise
    finally:
        try:
            _emit_diagnostics(diag, args)
        except OSError as exc:
            print(f"warning: could not write diagnostics report: {exc}",
                  file=sys.stderr)
    _check_strict(diag, args)


@contextlib.contextmanager
def _traced(args, name):
    """Run a command body under a root telemetry trace when
    ``--trace-requests PATH`` was given: span recording is armed, the
    body's spans (planner store lookups, single-flight waits,
    evaluations, sweep cells, DES replays) nest under one root span,
    and on exit the span tree is dumped to ``PATH`` (JSON) plus a
    Chrome-trace rendering at ``PATH.chrome.json`` for the trace
    viewer. Without the flag this is a no-op — no ids, no records."""
    path = getattr(args, "trace_requests", None)
    if not path:
        yield
        return
    from simumax_tpu.observe.telemetry import (
        get_tracer,
        span_tree,
        write_chrome_trace,
    )

    tracer = get_tracer()
    was_enabled = tracer.enabled
    tracer.configure(enabled=True)
    trace_id = None
    try:
        with tracer.trace(name) as trace_id:
            yield
    finally:
        # dump inside the finally: a command that raises mid-run is
        # exactly the one whose span tree is wanted, and the drain
        # must happen regardless or the recorded spans leak into the
        # next _traced command in this process
        tracer.configure(enabled=was_enabled)
        spans = tracer.drain()
        try:
            with open(path, "w", encoding="utf-8") as f:
                json.dump({"trace_id": trace_id, "command": name,
                           "spans": span_tree(spans)}, f, indent=1,
                          default=str)
            chrome = path + ".chrome.json"
            write_chrome_trace(spans, chrome)
        except OSError as exc:  # never mask the command's own error
            _log().warning(
                f"[trace] could not write {path}: {exc}",
                event="trace_requests_error", path=path,
            )
        else:
            _log().info(
                f"[trace] {len(spans)} spans (trace {trace_id}) -> "
                f"{path} (+ {chrome})",
                event="trace_requests", spans=len(spans),
                trace_id=trace_id, path=path,
            )


def cmd_list(args):
    from simumax_tpu.core.config import list_configs

    log = _log()
    for kind, names in list_configs().items():
        log.info(f"{kind}:", event="config_kind", kind=kind)
        for n in names:
            log.info(f"  {n}", event="config_name", kind=kind, name=n)


def _load_scenario(args, world_ranks):
    """Load ``--faults`` (when given) and apply the world-ranks
    implication: rank-scoped faults need every rank simulated. Returns
    ``(scenario, world_ranks)`` — shared by ``perf --simulate`` and
    ``critical-path`` so the implication rule cannot diverge."""
    if not args.faults:
        return None, world_ranks
    from simumax_tpu.simulator.faults import FaultScenario

    scenario = FaultScenario.from_json(args.faults)
    if not scenario.empty and not world_ranks:
        world_ranks = True
        _log().info(
            "[faults] scenario implies --world-ranks",
            event="faults_world_ranks",
        )
    return scenario, world_ranks


def _cache_enabled(args) -> bool:
    """Whether this invocation uses the persistent planner cache:
    default on, killed by ``--no-cache`` or ``SIMUMAX_TPU_NO_CACHE``."""
    return not (
        getattr(args, "no_cache", False)
        or os.environ.get("SIMUMAX_TPU_NO_CACHE")
    )


def _make_planner(args):
    from simumax_tpu.service.planner import Planner

    return Planner(cache_dir=getattr(args, "cache_dir", None))


def cmd_perf(args):
    with _traced(args, "perf"):
        return _cmd_perf(args)


def _cmd_perf(args):
    # artifact-producing runs (--save/--simulate/--graph) need the
    # built PerfLLM; everything else is a pure function of the configs
    # and routes through the planner so one-shot CLI calls populate
    # (and hit) the same persistent cache the server reads
    if _cache_enabled(args) and not (
        args.save or args.simulate or args.graph
    ):
        return _cmd_perf_planner(args)
    from simumax_tpu import PerfLLM

    perf = PerfLLM()
    perf.diagnostics.strict = args.strict
    with _diagnosed(perf.diagnostics, args):
        perf.configure(args.strategy, args.model, args.system)
        perf.run_estimate(capture_graph=args.graph)
        perf.analysis(save_path=args.save)
        if args.simulate:
            scenario, world_ranks = _load_scenario(
                args, args.world_ranks
            )
            with perf.diagnostics.capture(category="simulate"):
                result = perf.simulate(
                    args.simulate,
                    world_ranks=world_ranks,
                    reduce={"auto": "auto", "on": True,
                            "off": False}[args.reduce],
                    stream_trace=args.stream_trace,
                    faults=scenario,
                    critical_path=args.critical_path,
                )
            outcome = result.get("faults")
            if outcome:
                deaths = ", ".join(
                    f"rank {d['rank']} @ {d['time_ms']:.1f} ms"
                    for d in outcome["deaths"]
                ) or "none"
                _log().info(
                    f"faults: {outcome['applied_events']} events, "
                    f"completed={outcome['completed']}, deaths: {deaths}",
                    event="fault_outcome",
                    completed=outcome["completed"],
                    deaths=len(outcome["deaths"]),
                )
            reduction = result.get("reduction")
            extra = (
                f" ({reduction['n_classes']} symmetry classes for "
                f"{reduction['world_size']} ranks)" if reduction else ""
            )
            _log().info(
                f"simulated: {result['end_time_ms']:.2f} ms, "
                f"{result['num_events']} events{extra}, "
                f"trace at {result.get('trace_path')}",
                event="simulate", end_time_ms=result["end_time_ms"],
                num_events=result["num_events"],
                trace_path=result.get("trace_path"),
            )
            report = result.get("critical_path")
            if report:
                from simumax_tpu.observe.critpath import waterfall_lines

                for line in waterfall_lines(report):
                    _log().info(line, event="critpath_waterfall")
                if result.get("critical_path_path"):
                    _log().info(
                        f"critical-path report -> "
                        f"{result['critical_path_path']}",
                        event="critpath_report",
                        path=result["critical_path_path"],
                    )


def _cmd_perf_planner(args):
    """`perf` through the Planner facade: content-addressed persistent
    caching with byte-identical output (``docs/service.md``)."""
    from simumax_tpu.core.records import Diagnostics
    from simumax_tpu.perf import print_summary
    from simumax_tpu.service.planner import replay_coverage

    diag = Diagnostics(strict=args.strict)
    with _diagnosed(diag, args):
        planner = _make_planner(args)
        with diag.activate():
            payload, meta = planner.estimate(
                args.model, args.strategy, args.system, with_meta=True
            )
        # cached payloads carry the estimate's efficiency coverage, so
        # --strict and the diagnostics report behave identically
        # whether the answer was computed or served
        replay_coverage(diag, payload.get("efficiency_hits") or {},
                        payload.get("efficiency_misses") or {})
        _log().debug(
            f"[cache] estimate {meta['cache']} "
            f"(key {meta['key'][:16]}…)",
            event="cache_lookup", cache=meta["cache"], key=meta["key"],
        )
        print_summary(payload)


def cmd_search(args):
    from simumax_tpu.core.records import Diagnostics

    diag = Diagnostics(strict=args.strict)
    with _traced(args, "search"), _diagnosed(diag, args):
        _run_search(args, diag)


def _run_search(args, diag):
    from simumax_tpu.core.config import (
        get_model_config,
        get_strategy_config,
        get_system_config,
    )
    from simumax_tpu.search import search_best_parallel_strategy

    with diag.capture(category="config"):
        model = get_model_config(args.model)
        system = get_system_config(args.system)
        base = get_strategy_config(args.base_strategy)
    if args.world:
        base.world_size = args.world
    if args.seq_len:
        base.seq_len = args.seq_len
    zero_list = _ints(args.zero)
    bad = [z for z in zero_list if z not in (0, 1, 2, 3)]
    if bad:
        raise SystemExit(
            f"invalid --zero levels {bad}: expected a comma list of "
            "0-3 (e.g. --zero 1,3)"
        )
    # --resume without an explicit --journal extends the same journal,
    # so repeated interrupted runs keep one continuous checkpoint
    journal_path = args.journal or args.resume
    if args.jobs is not None and args.jobs < 1:
        raise SystemExit(
            f"invalid --jobs {args.jobs}: expected a positive worker "
            f"count (1 = serial; omit for os.cpu_count())"
        )
    jobs = args.jobs if args.jobs else (os.cpu_count() or 1)
    # persistent per-cell cache: overlapping grids (across runs,
    # processes, and the serve server) only evaluate the delta cells
    store = None
    profiles_key = None
    if _cache_enabled(args):
        from simumax_tpu.service.store import ContentStore

        store = ContentStore(getattr(args, "cache_dir", None))
        if args.engine == "batched":
            from simumax_tpu.service.planner import (
                batched_profiles_key,
                load_batched_profiles,
            )

            # key pinned pre-sweep: evaluations mutate the model
            profiles_key = batched_profiles_key(model, system)
            n = load_batched_profiles(store, model, system,
                                      key=profiles_key)
            if n:
                _log().debug(
                    f"[cache] seeded {n} block-kind profiles",
                    event="cache_profiles", profiles=n,
                )
    with diag.capture(category="search"):
        rows = search_best_parallel_strategy(
            base, model, system, args.gbs,
            tp_list=_ints(args.tp), pp_list=_ints(args.pp),
            ep_list=_ints(args.ep), cp_list=_ints(args.cp),
            zero_list=zero_list,
            topk=args.topk, csv_path=args.csv, verbose=args.verbose,
            project_dualpp=args.dualpp,
            candidate_timeout=args.candidate_timeout,
            journal_path=journal_path,
            resume=args.resume,
            diagnostics=diag,
            jobs=jobs,
            prune=not args.no_prune,
            simulate=args.simulate_check,
            engine=args.engine,
            verify_topk=args.verify_topk,
            store=store,
            search_mode="guided" if args.guided else "grid",
        )
    if store is not None and args.engine == "batched":
        from simumax_tpu.service.planner import save_batched_profiles

        save_batched_profiles(store, model, system, key=profiles_key)
    counters = diag.counters
    if counters.get("sweep_cells_cached"):
        _log().info(
            f"[sweep] served {int(counters['sweep_cells_cached'])}/"
            f"{int(counters['sweep_cells_total'])} cells from the "
            f"planner cache (status=cached rows in the CSV; --no-cache "
            f"to re-evaluate)",
            event="sweep_cached",
            cached=int(counters["sweep_cells_cached"]),
            total=int(counters["sweep_cells_total"]),
        )
    if counters.get("sweep_cells_pruned"):
        _log().info(
            f"[sweep] pruned {int(counters['sweep_cells_pruned'])}/"
            f"{int(counters['sweep_cells_total'])} cells before "
            f"evaluation (status=pruned rows in the CSV; --no-prune to "
            f"evaluate everything)",
            event="sweep_pruned",
            pruned=int(counters["sweep_cells_pruned"]),
            total=int(counters["sweep_cells_total"]),
        )
    for r in rows:
        dual = ""
        if r.get("dualpp_mfu") is not None:
            fits = "fits" if r["dualpp_fits"] else "OOM"
            dual = f"  [DualPipe: {r['dualpp_mfu']*100:.2f}% {fits}]"
        _log().info(
            f"tp{r['tp']} cp{r['cp']} ep{r['ep']} pp{r['pp']} dp{r['dp']} "
            f"z{r['zero']} mbs{r['mbs']} mbc{r['mbc']} {r['recompute']}: "
            f"MFU {r['mfu']*100:.2f}%  iter {r['iter_ms']:.0f} ms  "
            f"peak {r['peak_gib']:.1f} GiB"
            + (f"  [DCN: {r['dcn_dims']}]" if r.get("dcn_dims") else "")
            + dual,
            event="search_row", mfu=r["mfu"], iter_ms=r["iter_ms"],
            attribution=r.get("attribution"),
        )


def cmd_calibrate(args):
    from simumax_tpu import PerfLLM

    perf = PerfLLM()
    perf.diagnostics.strict = args.strict
    with _diagnosed(perf.diagnostics, args):
        _run_calibrate(args, perf)


def _run_calibrate(args, perf):
    from simumax_tpu.calibration import calibrate_system

    perf.configure(args.strategy, args.model, args.system)
    perf.run_estimate()
    if args.bandwidth:
        from simumax_tpu.calibration.autocal import calibrate_bandwidth_classes

        calibrate_bandwidth_classes(
            perf.system, verbose=True,
            vocab=perf.model_config.padded_vocab_size,
        )
    if args.collectives:
        import jax

        from simumax_tpu.calibration.collective_bench import (
            sweep_axis,
            update_system_from_sweep,
        )
        from simumax_tpu.jaxref.model import make_mesh

        n = len(jax.devices())
        if n < 2:
            _log().info("[cal] collectives: need >1 device, skipping",
                        event="calibrate")
        else:
            mesh = make_mesh(n, tp=n)
            sweep = sweep_axis(mesh, "tp")
            update_system_from_sweep(perf.system, n, sweep)
            for op, fit in sweep.items():
                _log().info(
                    f"[cal] {op}: {fit['fitted_bw_gbps']:.1f} GB/s, "
                    f"{fit['fitted_latency_us']:.1f} us",
                    event="calibrate_collective", op=op,
                )
    measured = calibrate_system(
        perf, save_path=args.save, max_keys=args.max_keys, verbose=True,
        diagnostics=perf.diagnostics,
    )
    n = sum(len(v) for v in measured.values())
    _log().info(f"calibrated {n} shape keys"
                + (f"; wrote {args.save}" if args.save else ""),
                event="calibrate_done", keys=n, save=args.save)
    perf.analysis()


def cmd_explain(args):
    # the memory/trace/crosscheck surfaces need the built PerfLLM; the
    # step-time ledger is a pure function of the configs and rides the
    # persistent planner cache
    if _cache_enabled(args) and not (
        args.memory or args.trace or args.crosscheck
        or args.mem_artifacts
    ):
        return _cmd_explain_planner(args)
    from simumax_tpu import PerfLLM

    perf = PerfLLM()
    perf.diagnostics.strict = args.strict
    with _diagnosed(perf.diagnostics, args):
        _run_explain(args, perf)


def _cmd_explain_planner(args):
    """`explain` through the Planner facade: the cached payload carries
    the full ledger dict plus the aggregated op rows, rendered by the
    same functions the live Ledger uses."""
    import csv as _csv

    from simumax_tpu.core.records import Diagnostics
    from simumax_tpu.observe.ledger import (
        top_op_lines_from_rows,
        waterfall_lines_from_dict,
    )
    from simumax_tpu.service.planner import replay_coverage

    diag = Diagnostics(strict=args.strict)
    with _diagnosed(diag, args):
        planner = _make_planner(args)
        with diag.activate():
            payload, meta = planner.explain(
                args.model, args.strategy, args.system, with_meta=True
            )
        led = payload["ledger"]
        replay_coverage(diag, led["efficiency"].get("hits") or {},
                        led["efficiency"].get("misses") or {})
        log = _log()
        log.debug(
            f"[cache] explain {meta['cache']} "
            f"(key {meta['key'][:16]}…)",
            event="cache_lookup", cache=meta["cache"], key=meta["key"],
        )
        for line in waterfall_lines_from_dict(led):
            log.info(line, event="waterfall")
        for line in top_op_lines_from_rows(payload["op_rows"], args.top):
            log.info(line, event="top_ops")
        miss = led["efficiency"]["miss_count"]
        if miss:
            log.info(
                f"[calibration] {miss} efficiency-table misses "
                f"contribute to these rows (MISS); `simumax_tpu "
                f"calibrate` refines them",
                event="explain_misses", misses=miss,
            )
        if args.json:
            with open(args.json, "w", encoding="utf-8") as f:
                json.dump(led, f, indent=1)
            log.info(f"ledger -> {args.json}", event="explain_ledger",
                     path=args.json, run_id=led["meta"]["run_id"])
        if args.csv:
            rows = payload["op_rows"]
            fields = [
                "path", "category", "module_type", "stage", "chunk",
                "fwd_time", "bwd_time", "net_exposed_time",
                "net_hidden_time", "time", "share", "flops",
                "bytes_accessed", "efficiency", "calibrated", "regime",
                "recompute",
            ]
            with open(args.csv, "w", newline="") as f:
                w = _csv.DictWriter(f, fieldnames=fields,
                                    extrasaction="ignore")
                w.writeheader()
                w.writerows(rows)
            log.info(f"op table -> {args.csv}", event="explain_csv",
                     path=args.csv, rows=len(rows))


def _run_explain(args, perf):
    import csv as _csv

    from simumax_tpu.observe.trace import write_analytical_trace

    log = _log()
    if (args.crosscheck or args.mem_artifacts) and not args.memory:
        # silently ignoring these would let the user believe the
        # cross-check ran clean when it never ran at all
        raise SystemExit(
            "error: --crosscheck/--mem-artifacts require --memory "
            "(they explain the peak-HBM prediction, not the step time)"
        )
    perf.configure(args.strategy, args.model, args.system)
    perf.run_estimate()
    if args.memory:
        return _run_explain_memory(args, perf)
    led = perf.ledger()
    for line in led.waterfall_lines():
        log.info(line, event="waterfall")
    for line in led.top_op_lines(args.top):
        log.info(line, event="top_ops")
    miss = led.efficiency["miss_count"]
    if miss:
        log.info(
            f"[calibration] {miss} efficiency-table misses contribute to "
            f"these rows (MISS); `simumax_tpu calibrate` refines them",
            event="explain_misses", misses=miss,
        )
    if args.json:
        led.save(args.json)
        log.info(f"ledger -> {args.json}", event="explain_ledger",
                 path=args.json, run_id=led.meta["run_id"])
    if args.csv:
        rows = led.op_rows()
        fields = [
            "path", "category", "module_type", "stage", "chunk",
            "fwd_time", "bwd_time", "net_exposed_time", "net_hidden_time",
            "time", "share", "flops", "bytes_accessed", "efficiency",
            "calibrated", "regime", "recompute",
        ]
        with open(args.csv, "w", newline="") as f:
            w = _csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
            w.writeheader()
            w.writerows(rows)
        log.info(f"op table -> {args.csv}", event="explain_csv",
                 path=args.csv, rows=len(rows))
    if args.trace:
        write_analytical_trace(perf, args.trace)
        log.info(
            f"analytical Chrome trace -> {args.trace} "
            f"(load in chrome://tracing or ui.perfetto.dev)",
            event="explain_trace", path=args.trace,
        )


def _run_explain_memory(args, perf):
    """`explain --memory`: peak-HBM waterfall + top holders from the
    per-tensor memory ledger, OOM forensics (incl. the what-if probe
    table) for non-fitting configs, and the analytical memory-timeline
    artifacts."""
    import csv as _csv

    from simumax_tpu.observe.memledger import (
        export_analytical_memory,
        oom_forensic_lines,
        oom_forensics,
    )

    log = _log()
    # the timeline snapshots only ship inside the --json artifact; skip
    # building them otherwise
    led = perf.memory_ledger(timeline=bool(args.json))
    for line in led.waterfall_lines():
        log.info(line, event="mem_waterfall")
    if led.headline["fits"]:
        for line in led.top_holder_lines(args.top):
            log.info(line, event="mem_top_holders")
    else:
        # the forensics block prints the same binding-stage top holders
        # — one list, not two copies of it
        report = oom_forensics(perf, top=args.top, probes=True,
                               spans=led.spans)
        for line in oom_forensic_lines(report):
            log.info(line, event="mem_forensics")
    if args.crosscheck:
        res = perf.memory_crosscheck()
        for r in res["stages"]:
            log.info(
                f"  stage {r['stage']}: analytical "
                f"{r['analytical_peak_gib']:.2f} GiB vs DES "
                f"{r['des_peak_gib']:.2f} GiB "
                f"(ratio {r['des_vs_analytical']:.4f})",
                event="mem_crosscheck", stage=r["stage"],
                ratio=r["des_vs_analytical"],
            )
    if args.json:
        led.save(args.json)
        log.info(f"memory ledger -> {args.json}",
                 event="explain_mem_ledger", path=args.json,
                 run_id=led.meta["run_id"])
    if args.csv:
        rows = led.span_rows()
        fields = [
            "path", "bucket", "kinds", "category", "module_type",
            "stage", "chunk", "bytes", "share", "count", "shape",
            "dtype", "sharding",
        ]
        with open(args.csv, "w", newline="") as f:
            w = _csv.DictWriter(f, fieldnames=fields, extrasaction="ignore")
            w.writeheader()
            w.writerows(rows)
        log.info(f"holder table -> {args.csv}", event="explain_mem_csv",
                 path=args.csv, rows=len(rows))
    if args.mem_artifacts:
        paths = export_analytical_memory(perf, args.mem_artifacts)
        log.info(
            f"analytical memory timeline -> {paths['snapshot']}, "
            f"memory-viz pickle -> {paths['memory_viz']} "
            f"(load at pytorch.org/memory_viz), counter trace -> "
            f"{paths['counters']}",
            event="explain_mem_artifacts", **paths,
        )
    if args.trace:
        from simumax_tpu.observe.trace import write_analytical_trace

        write_analytical_trace(perf, args.trace)
        log.info(
            f"analytical Chrome trace -> {args.trace} "
            f"(hbm_bytes counter tracks included)",
            event="explain_trace", path=args.trace,
        )


def cmd_diff(args):
    from simumax_tpu.observe.critpath import (
        diff_critpath,
        format_critpath_diff_lines,
        load_report,
    )
    from simumax_tpu.observe.ledger import (
        Ledger,
        diff_ledgers,
        format_diff_lines,
    )
    from simumax_tpu.observe.memledger import (
        MemoryLedger,
        diff_memory_ledgers,
        format_memory_diff_lines,
    )

    if sum(map(bool, (args.memory, args.critical_path,
                      args.fleet))) > 1:
        raise SystemExit(
            "error: --memory, --critical-path and --fleet are "
            "exclusive (pick the ledger family the inputs belong to)"
        )

    def load_fleet_report(path):
        with open(path, encoding="utf-8") as f:
            return json.load(f)

    # fleet reports are self-describing (schema simumax-fleet-v1):
    # auto-detect when no family flag narrows the choice
    fleet = args.fleet
    if not (args.memory or args.critical_path or fleet):
        try:
            fleet = all(
                isinstance(r, dict)
                and r.get("schema") == "simumax-fleet-v1"
                for r in (load_fleet_report(args.ledger_a),
                          load_fleet_report(args.ledger_b))
            )
        except (OSError, ValueError, json.JSONDecodeError):
            fleet = False
    if fleet:
        loader = load_fleet_report
    elif args.critical_path:
        loader = load_report
    elif args.memory:
        loader = MemoryLedger.load
    else:
        loader = Ledger.load
    try:
        a = loader(args.ledger_a)
        b = loader(args.ledger_b)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        raise SystemExit(f"error: {exc}")
    if fleet:
        from simumax_tpu.core.errors import ConfigError
        from simumax_tpu.observe.fleetledger import (
            diff_fleet_reports,
            format_fleet_diff_lines,
        )

        try:
            d = diff_fleet_reports(a, b, top=args.top)
        except ConfigError as exc:
            raise SystemExit(f"error: {exc}")
        lines = format_fleet_diff_lines(d, top=args.top)
    elif args.critical_path:
        d = diff_critpath(a, b, top=args.top)
        lines = format_critpath_diff_lines(d, top=args.top)
    elif args.memory:
        d = diff_memory_ledgers(a, b, top=args.top)
        lines = format_memory_diff_lines(d, top=args.top)
    else:
        d = diff_ledgers(a, b, top=args.top)
        lines = format_diff_lines(d, top=args.top)
    log = _log()
    for line in lines:
        log.info(line, event="diff")
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(d, f, indent=1)
        log.info(f"diff report -> {args.json}", event="diff_report",
                 path=args.json)


def cmd_faults(args):
    from simumax_tpu import PerfLLM

    perf = PerfLLM()
    perf.diagnostics.strict = args.strict
    with _diagnosed(perf.diagnostics, args):
        _run_faults(args, perf)


def _run_faults(args, perf):
    from simumax_tpu.observe.ledger import (
        goodput_attribution_line,
        goodput_waterfall_lines,
    )
    from simumax_tpu.simulator.faults import CheckpointSpec, FaultScenario

    log = _log()
    perf.configure(args.strategy, args.model, args.system)
    perf.run_estimate()

    def build_spec(scenario=None):
        """Scenario checkpoint block as the base, explicit CLI flags
        on top (flags always win); None when neither says anything."""
        base = CheckpointSpec.from_overrides(
            scenario.checkpoint if scenario is not None else None
        )
        flags = {}
        if args.ckpt_interval:
            flags["interval_steps"] = args.ckpt_interval
        if args.restart_overhead is not None:
            flags["restart_overhead_s"] = args.restart_overhead
        if not flags and (scenario is None or not scenario.checkpoint):
            return None
        return CheckpointSpec.from_overrides(flags, base)
    if args.jobs is not None and args.jobs < 1:
        raise SystemExit(
            f"invalid --jobs {args.jobs}: expected a positive worker "
            f"count"
        )
    if args.monte_carlo:
        with perf.diagnostics.capture(category="faults"):
            res = perf.analyze_faults(
                n_scenarios=args.monte_carlo, seed=args.seed,
                horizon_steps=args.horizon or 50, spec=build_spec(),
                granularity=args.granularity,
                jobs=args.jobs or 0, incremental=not args.exact,
            )
        g = res["goodput"]
        log.info(
            f"goodput over {res['n_scenarios']} scenarios "
            f"(seed {res['seed']}, horizon {res['horizon_steps']} "
            f"steps): mean {g['mean']*100:.2f}%  "
            f"p10 {g['p10']*100:.2f}%  p50 {g['p50']*100:.2f}%  "
            f"p90 {g['p90']*100:.2f}%",
            event="faults_mc", mean_goodput=g["mean"],
        )
        for k in sorted(res["goodput_by_interval"]):
            v = res["goodput_by_interval"][k]
            log.info(
                f"  checkpoint every {k:4d} steps: mean goodput "
                f"{v*100:.2f}%",
                event="faults_interval", interval=k, goodput=v,
            )
        log.info(
            f"optimal checkpoint interval: {res['best_interval_steps']} "
            f"steps (Young-Daly closed form: "
            f"{res['young_daly_interval_steps']})",
            event="faults_optimal",
            best_interval=res["best_interval_steps"],
        )
        if args.json:
            with open(args.json, "w", encoding="utf-8") as f:
                json.dump(res, f, indent=1)
            log.info(f"analysis -> {args.json}", event="faults_json",
                     path=args.json)
        return
    if not args.scenario:
        raise SystemExit(
            "error: faults needs --scenario SCENARIO.json or "
            "--monte-carlo N"
        )
    scenario = FaultScenario.from_json(args.scenario)
    if args.horizon:
        scenario.horizon_steps = args.horizon
    with perf.diagnostics.capture(category="faults"):
        report = perf.predict_goodput(
            scenario, spec=build_spec(scenario),
            granularity=args.granularity,
            incremental=not args.exact,
        )
    for line in goodput_waterfall_lines(report):
        log.info(line, event="goodput_waterfall")
    log.info(goodput_attribution_line(report), event="goodput_line",
             goodput=report.goodput)
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report.to_dict(), f, indent=1)
        log.info(f"goodput report -> {args.json}", event="faults_json",
                 path=args.json)


def cmd_fleet(args):
    """Multi-job fleet walk (docs/fleet.md): fleet goodput, per-job
    SLO attainment, scheduler-decision timeline — plus the causal
    goodput ledger / SLO counterfactuals / fleet Chrome trace under
    ``--explain`` / ``--chrome-trace`` (docs/fleet.md "Explaining a
    fleet run")."""
    from simumax_tpu.fleet.report import fleet_report_lines

    log = _log()
    explain = bool(args.explain or args.chrome_trace)
    if args.naive or not _cache_enabled(args):
        # the naive baseline (and cache-off runs) walk directly; the
        # default path routes through the planner so repeated
        # capacity-planning queries hit the persistent store
        from simumax_tpu.fleet.sim import simulate_fleet

        report = simulate_fleet(
            args.trace, jobs=args.jobs or 0, elastic=args.elastic,
            naive=args.naive, explain=explain,
        )
    else:
        from simumax_tpu.service.planner import Planner

        planner = Planner(cache_dir=getattr(args, "cache_dir", None))
        report, meta = planner.fleet(
            args.trace, jobs=args.jobs or 0, elastic=args.elastic,
            explain=explain, with_meta=True,
        )
        log.info(
            f"[cache {meta['cache']}] {meta['key'][:16]}",
            event="fleet_cache", cache=meta["cache"],
            key=meta["key"],
        )
    for line in fleet_report_lines(report, top_decisions=args.top):
        log.info(line, event="fleet")
    if explain:
        from simumax_tpu.observe.fleetledger import fleet_explain_lines

        for line in fleet_explain_lines(report):
            log.info(line, event="fleet_explain")
    if args.chrome_trace:
        from simumax_tpu.observe.fleetledger import write_fleet_trace

        write_fleet_trace(report, args.chrome_trace)
        log.info(
            f"fleet Chrome trace -> {args.chrome_trace} (pods as "
            f"pids, job lanes, causal flow arrows, goodput/"
            f"utilization counters)",
            event="fleet_trace", path=args.chrome_trace,
        )
    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        log.info(f"fleet report -> {args.json}", event="fleet_json",
                 path=args.json)


def cmd_critpath(args):
    from simumax_tpu import PerfLLM

    perf = PerfLLM()
    perf.diagnostics.strict = args.strict
    with _diagnosed(perf.diagnostics, args):
        _run_critpath(args, perf)


def _run_critpath(args, perf):
    from simumax_tpu.observe.critpath import (
        divergence_lines,
        headroom_lines,
        save_report,
        waterfall_lines,
    )

    log = _log()
    perf.configure(args.strategy, args.model, args.system)
    perf.run_estimate()
    scenario, world_ranks = _load_scenario(args, args.world_ranks)
    with perf.diagnostics.capture(category="simulate"):
        result = perf.simulate(
            args.save,
            granularity=args.granularity,
            world_ranks=world_ranks,
            reduce={"auto": "auto", "on": True, "off": False}[args.reduce],
            faults=scenario,
            critical_path=True,
            track_memory=False,
        )
    report = result["critical_path"]
    for line in waterfall_lines(report):
        log.info(line, event="critpath_waterfall")
    sl = report["slack"]
    log.info(
        f"slack: {sl['zero_slack_events']}/{sl['events']} events at zero "
        f"slack, p50 {sl['p50_us']:.1f} us, p90 {sl['p90_us']:.1f} us",
        event="critpath_slack", **sl,
    )
    for line in headroom_lines(report, top=args.top):
        log.info(line, event="critpath_headroom")
    div = report.get("divergence")
    if div:
        for line in divergence_lines(div, top=args.top):
            log.info(line, event="critpath_divergence")
    if args.save:
        log.info(
            f"artifacts: annotated trace -> {result.get('trace_path')}, "
            f"report -> {result.get('critical_path_path')}",
            event="critpath_artifacts",
            trace_path=result.get("trace_path"),
            report_path=result.get("critical_path_path"),
        )
    if args.json:
        save_report(report, args.json)
        log.info(f"critical-path report -> {args.json}",
                 event="critpath_report", path=args.json)


def cmd_dualpp(args):
    from simumax_tpu import PerfLLM

    perf = PerfLLM().configure(args.strategy, args.model, args.system)
    if perf.strategy.pp_size % 2 or perf.strategy.pp_size < 2:
        raise SystemExit(
            f"DualPipe requires an even pp >= 2 "
            f"(strategy has pp={perf.strategy.pp_size})"
        )
    if perf.strategy.vp_size != 1:
        raise SystemExit(
            "DualPipe and VPP interleaving are exclusive "
            f"(strategy has interleaving_size={perf.strategy.vp_size})"
        )
    perf.run_estimate()
    res = perf.analysis_dualpp(save_path=args.plot)
    log = _log()
    log.info(
        f"1F1B baseline  {res['baseline_iter_time'] * 1e3:9.1f} ms  "
        f"peak {res['baseline_peak_gib']:.1f} GiB",
        event="dualpp_baseline",
    )
    log.info(
        f"DualPipe       {res['dualpp_iter_time'] * 1e3:9.1f} ms  "
        f"peak {res['max_peak_gib']:.1f} GiB  "
        f"(speedup {res['speedup']:.3f}x, projected MFU "
        f"{res['projected_mfu'] * 100:.2f}%)",
        event="dualpp_projection", speedup=res["speedup"],
    )
    for r in res["ranks"]:
        log.info(
            f"  rank {r['rank']}: stages {r['stages']}  "
            f"bubble {r['bubble'] * 1e3:7.1f} ms  "
            f"peak {r['peak_gib']:.1f} GiB",
            event="dualpp_rank", rank=r["rank"],
        )
    if args.plot:
        log.info(f"F&B cell timeline -> {args.plot}", event="dualpp_plot")


def cmd_straggler(args):
    from simumax_tpu import PerfLLM
    from simumax_tpu.simulator.runner import analyze_stragglers

    perf = PerfLLM().configure(args.strategy, args.model, args.system)
    slow = {}
    for spec in args.ranks.split(","):
        try:
            r, f = spec.split(":")
            slow[int(r)] = float(f)
        except ValueError:
            raise SystemExit(
                f"bad --ranks entry {spec!r}: expected rank:multiplier "
                "(e.g. 0:1.2,5:1.5)"
            )
    world = perf.strategy.world_size
    bad = [r for r in slow if not 0 <= r < world]
    if bad:
        raise SystemExit(
            f"ranks {bad} out of range for world_size {world}"
        )
    perf.run_estimate()
    res = analyze_stragglers(perf, slow)
    _log().info(
        f"baseline {res['baseline_ms']:.1f} ms -> perturbed "
        f"{res['perturbed_ms']:.1f} ms  (inflation {res['inflation']:.3f}, "
        f"worst injected multiplier {res['worst_multiplier']:.2f})",
        event="straggler", inflation=res["inflation"],
    )


def cmd_serve(args):
    from simumax_tpu.service.planner import Planner
    from simumax_tpu.service.server import (
        AdmissionController,
        make_server,
        serve_forever,
    )

    max_bytes = (
        args.cache_max_mb * 1024 * 1024 if args.cache_max_mb else None
    )
    trace_log = None
    if args.trace_requests:
        # per-request span trees: one JSON line per served request,
        # appended for the server's lifetime
        from simumax_tpu.observe.telemetry import get_tracer

        os.makedirs(args.trace_requests, exist_ok=True)
        trace_log = os.path.join(args.trace_requests, "requests.jsonl")
        get_tracer().configure(enabled=True)
    enabled = _cache_enabled(args)
    node_id = ring_spec = None
    if args.ring or args.join:
        from simumax_tpu.core.errors import ConfigError
        from simumax_tpu.service.ring import (
            format_ring_spec,
            parse_ring_spec,
        )

        if not (args.ring and args.join):
            raise ConfigError("--ring and --join go together: the "
                              "spec names the fleet, --join picks "
                              "which member this process is")
        members = parse_ring_spec(args.ring)
        if args.join not in members:
            raise ConfigError(
                f"--join {args.join!r} is not a member of "
                f"--ring {args.ring!r}")
        node_id = args.join
        ring_spec = format_ring_spec(members)
        # bind where the ring says this member lives
        args.host, args.port = members[node_id]
    elif args.nodes and args.nodes > 1:
        from simumax_tpu.core.errors import ConfigError
        from simumax_tpu.search.executor import _mp_context
        from simumax_tpu.service.ring import format_ring_spec

        if not args.port:
            raise ConfigError("--nodes needs a concrete --port base "
                              "(ports are consecutive from it), not "
                              "an ephemeral 0")
        members = {f"n{i}": (args.host, args.port + i)
                   for i in range(args.nodes)}
        node_id, ring_spec = "n0", format_ring_spec(members)
        # fork the sibling nodes; each re-enters this command as an
        # explicit --ring/--join member. Not daemonic (a pooled node
        # forks its own workers, and daemons may not have children) —
        # atexit reaps the fleet when this (n0) process exits.
        import atexit

        ctx = _mp_context()
        siblings = []
        for i in range(1, args.nodes):
            child = argparse.Namespace(**vars(args))
            child.nodes = 0
            child.ring = ring_spec
            child.join = f"n{i}"
            p = ctx.Process(target=cmd_serve, args=(child,),
                            daemon=False, name=f"planner-node-n{i}")
            p.start()
            siblings.append(p)

        def _reap():
            for p in siblings:
                p.terminate()
            for p in siblings:
                p.join(5)

        atexit.register(_reap)
    if node_id is not None and enabled:
        # one store shard per fleet member: each node is the single
        # writer of its own root (peers replicate read-only)
        from simumax_tpu.service.store import default_cache_dir

        args.cache_dir = os.path.join(
            args.cache_dir or default_cache_dir(), f"fleet-{node_id}")
    pool = None
    if args.workers:
        from simumax_tpu.service.pool import WorkerPool

        pool = WorkerPool(
            cache_dir=args.cache_dir, enabled=enabled,
            workers=args.workers, max_bytes=max_bytes,
            request_timeout=args.request_timeout or None,
            trace=bool(args.trace_requests),
            fleet_spec=(node_id, ring_spec)
            if node_id is not None else None,
        )
        # the in-process planner still serves streaming sweeps and
        # /stats; it shares the pool's single-writer store (same
        # process), so parent and workers see one cache
        planner = Planner(store=pool.store, enabled=enabled)
    else:
        planner = Planner(
            cache_dir=args.cache_dir, enabled=enabled,
            max_bytes=max_bytes,
        )
    warmer = None
    if args.warm:
        from simumax_tpu.service.warmer import (
            Warmer,
            pool_runner,
            warm_cells,
        )

        runner = (
            pool_runner(pool, max_cells=args.warm_cells)
            if pool is not None else
            lambda spec: warm_cells(planner, spec,
                                    max_cells=args.warm_cells)
        )
        warmer = Warmer(
            runner, store=pool.store if pool is not None
            else planner.store,
            max_jobs=args.warm, max_cells=args.warm_cells,
        )
    admission = AdmissionController(args.admission, pool=pool) \
        if args.admission else None
    srv = make_server(planner, args.host, args.port,
                      trace_log=trace_log, pool=pool,
                      admission=admission, warmer=warmer)
    if node_id is not None:
        from simumax_tpu.service.node import attach_fleet

        attach_fleet(srv, node_id, ring_spec,
                     replicate_s=args.replicate_s,
                     probe_s=args.probe_s,
                     probe_seed=args.probe_seed)
    host, port = srv.server_address[:2]
    cache_desc = (
        planner.store.root if planner.enabled else "disabled"
    )
    mode_desc = (
        f"pool of {pool.workers} workers" if pool else "threaded"
    )
    _log().info(
        f"[serve] planning service on http://{host}:{port} "
        f"({mode_desc}; cache: {cache_desc}) — GET /healthz /stats "
        f"/metrics, POST /v1/estimate /v1/explain /v1/search "
        f"/v1/faults /v1/simulate /v1/fleet"
        + (f"; admission backlog {args.admission}" if admission
           else "")
        + (f"; warm queue {args.warm}" if warmer else "")
        + (f"; fleet node {node_id} of ring {ring_spec}"
           if node_id is not None else "")
        + (f"; request traces -> {trace_log}" if trace_log else ""),
        event="serve_start", host=host, port=port, cache=cache_desc,
        workers=args.workers, admission=args.admission,
        warm=args.warm, node=node_id or "",
    )
    serve_forever(srv)


def cmd_cache(args):
    from simumax_tpu.service.store import ContentStore

    store = ContentStore(args.cache_dir)
    log = _log()
    report = None
    if args.action == "stats":
        report = store.stats()
        log.info(f"cache root: {report['root']}", event="cache_root",
                 root=report["root"])
        for ns in sorted(report["namespaces"]):
            d = report["namespaces"][ns]
            log.info(
                f"  {ns:<10} {d['entries']:6d} entries  "
                f"{d['bytes'] / 2**20:8.2f} MiB",
                event="cache_ns", namespace=ns, **d,
            )
        log.info(
            f"  total: {report['total_bytes'] / 2**20:.2f} MiB of "
            f"{report['max_bytes'] / 2**20:.0f} MiB budget",
            event="cache_total", total_bytes=report["total_bytes"],
        )
        c = report["counters"]
        log.info(
            f"  session counters: {c['hits']} hits, {c['misses']} "
            f"misses, {c['puts']} puts, {c['evictions']} evictions, "
            f"{c['corrupt_dropped']} corrupt dropped",
            event="cache_counters", **c,
        )
        if report.get("quarantine_entries"):
            log.info(
                f"  quarantined: {report['quarantine_entries']} "
                f"entries under .quarantine/ (inspect, then clear "
                f"the directory to reclaim the bytes)",
                event="cache_quarantine",
                entries=report["quarantine_entries"],
            )
    elif args.action == "ls":
        entries = store.entries(args.namespace)
        report = {"entries": entries}
        for e in entries:
            log.info(
                f"  {e['namespace']:<10} {e['key'][:16]}…  "
                f"{e['bytes']:10d} B  {e['fmt']:<6} "
                f"v{e['code_version']}",
                event="cache_entry", **e,
            )
        log.info(f"{len(entries)} entries", event="cache_ls_total",
                 count=len(entries))
    elif args.action == "verify":
        report = store.verify(args.namespace, drop=args.drop)
        for c in report["corrupt"]:
            log.error(f"  corrupt: {c['path']} ({c['error']})",
                      event="cache_corrupt", **c)
        log.info(
            f"verified {report['checked']} entries: {report['ok']} ok, "
            f"{len(report['corrupt'])} corrupt"
            + (" (quarantined under .quarantine/)"
               if args.drop and report["corrupt"] else ""),
            event="cache_verify", checked=report["checked"],
            ok=report["ok"], corrupt=len(report["corrupt"]),
        )
    elif args.action == "clear":
        removed = store.clear(args.namespace)
        report = {"removed": removed, "namespace": args.namespace}
        log.info(
            f"cleared {removed} entries"
            + (f" from namespace {args.namespace!r}"
               if args.namespace else ""),
            event="cache_clear", removed=removed,
        )
    if args.json and report is not None:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        log.info(f"report -> {args.json}", event="cache_report",
                 path=args.json)
    if args.action == "verify" and report["corrupt"]:
        sys.exit(1)


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="simumax_tpu",
        description="TPU-native analytical simulator for LLM training",
    )
    sub = p.add_subparsers(dest="cmd", required=True)

    def _add_log_args(parser):
        parser.add_argument(
            "--log-level", default="info",
            choices=("debug", "info", "warning", "error"),
            help="minimum level for report lines (default: info). "
                 "Results are emitted at info, so warning/error "
                 "suppress the normal output too — useful only for "
                 "fully quiet automation that reads --json/--csv/"
                 "--diagnostics artifacts instead of stdout",
        )
        parser.add_argument(
            "--log-json", action="store_true",
            help="emit structured JSONL report lines (ts/level/run_id/"
                 "msg + fields) instead of the human format",
        )

    def _add_cache_args(parser):
        parser.add_argument(
            "--cache-dir", metavar="DIR",
            help="persistent content-addressed result cache root "
                 "(default: SIMUMAX_TPU_CACHE_DIR or "
                 "~/.cache/simumax-tpu; see docs/service.md)",
        )
        parser.add_argument(
            "--no-cache", action="store_true",
            help="evaluate directly, without reading or writing the "
                 "persistent cache (results are bit-identical either "
                 "way; SIMUMAX_TPU_NO_CACHE=1 is the env equivalent)",
        )

    pl = sub.add_parser("list", help="list available configs")
    _add_log_args(pl)
    pl.set_defaults(fn=cmd_list)

    def _add_diag_args(parser):
        parser.add_argument(
            "--diagnostics", metavar="PATH",
            help="write the diagnostics JSON report here "
                 "(default: printed as one [diagnostics] line)",
        )
        parser.add_argument(
            "--strict", action="store_true",
            help="exit 3 on any warning / efficiency-table miss / "
                 "quarantined failure",
        )

    def _add_trace_args(parser, serve: bool = False):
        if serve:
            parser.add_argument(
                "--trace-requests", metavar="DIR",
                help="record telemetry spans for every served request "
                     "and append each request's span tree as one JSON "
                     "line to DIR/requests.jsonl (trace ids match the "
                     "X-SimuMax-Trace response headers)",
            )
        else:
            parser.add_argument(
                "--trace-requests", metavar="PATH",
                help="record telemetry spans (store lookups, "
                     "single-flight waits, evaluations, sweep cells, "
                     "DES replays) for this run and dump the span "
                     "tree to PATH plus a Chrome trace to "
                     "PATH.chrome.json",
            )

    pp = sub.add_parser("perf", help="estimate one configuration")
    pp.add_argument("--model", required=True)
    pp.add_argument("--strategy", required=True)
    pp.add_argument("--system", required=True)
    pp.add_argument("--save", help="directory for result JSONs")
    pp.add_argument("--simulate", help="run the event simulator; dir for trace")
    pp.add_argument(
        "--world-ranks", action="store_true",
        help="simulate every global rank (true rendezvous per tp/cp/ep/"
             "dp group) instead of one representative per pp stage",
    )
    pp.add_argument(
        "--reduce", choices=("auto", "on", "off"), default="auto",
        help="world-rank symmetry reduction: simulate one rank per "
             "equivalence class and expand (default auto)",
    )
    pp.add_argument(
        "--stream-trace", action="store_true",
        help="write trace.json incrementally while simulating (peak RSS "
             "stays bounded at pod-size world-rank runs)",
    )
    pp.add_argument(
        "--faults", metavar="SCENARIO.json",
        help="inject a fault scenario (docs/faults.md schema) into the "
             "simulated step: rank slowdowns, preemptions, link "
             "degradation, rank deaths; implies --world-ranks",
    )
    pp.add_argument(
        "--critical-path", action="store_true",
        help="with --simulate: record the event-dependency skeleton and "
             "report per-event slack + the simulated critical-path "
             "waterfall (critpath.json artifact, trace events gain "
             "on_critical_path/slack_us args)",
    )
    pp.add_argument("--graph", action="store_true", help="capture op graph")
    _add_diag_args(pp)
    _add_log_args(pp)
    _add_cache_args(pp)
    _add_trace_args(pp)
    pp.set_defaults(fn=cmd_perf)

    pe = sub.add_parser(
        "explain",
        help="MFU-loss waterfall + top-N op attribution for one config "
             "(--memory: peak-HBM waterfall + per-tensor holders + OOM "
             "forensics)",
    )
    pe.add_argument("--model", required=True)
    pe.add_argument("--strategy", required=True)
    pe.add_argument("--system", required=True)
    pe.add_argument("--top", type=int, default=10,
                    help="rows in the top-op / top-holder table "
                         "(default 10)")
    pe.add_argument(
        "--memory", action="store_true",
        help="explain the peak-HBM prediction instead of the step time: "
             "per-tensor memory ledger, peak-memory waterfall, and (for "
             "non-fitting configs) OOM forensics with what-if probes",
    )
    pe.add_argument(
        "--crosscheck", action="store_true",
        help="with --memory: also run the discrete-event simulator with "
             "memory tracking and report per-stage analytical-vs-DES "
             "peak ratios",
    )
    pe.add_argument(
        "--mem-artifacts", metavar="DIR",
        help="with --memory: write the analytical memory timeline in "
             "the simulator's artifact formats (JSON snapshot, torch "
             "memory-viz pickle, Chrome counter trace)",
    )
    pe.add_argument("--json", metavar="PATH",
                    help="save the full attribution ledger JSON "
                         "(the input format of `simumax_tpu diff`; with "
                         "--memory, the memory-ledger JSON)")
    pe.add_argument("--csv", metavar="PATH",
                    help="save the per-op attribution table as CSV "
                         "(with --memory, the per-tensor holder table)")
    pe.add_argument("--trace", metavar="PATH",
                    help="save a Chrome/Perfetto trace of the analytical "
                         "schedule (same UI as simulate() traces)")
    _add_diag_args(pe)
    _add_log_args(pe)
    _add_cache_args(pe)
    pe.set_defaults(fn=cmd_explain)

    pdf = sub.add_parser(
        "diff",
        help="compare two saved attribution ledgers (explain --json), "
             "two memory ledgers with --memory, or two fleet reports "
             "with --fleet (auto-detected)",
    )
    pdf.add_argument("ledger_a", help="baseline ledger JSON")
    pdf.add_argument("ledger_b", help="comparison ledger JSON")
    pdf.add_argument("--top", type=int, default=20,
                     help="max per-op deltas to report (default 20)")
    pdf.add_argument(
        "--memory", action="store_true",
        help="the inputs are memory ledgers (explain --memory --json): "
             "diff peak-HBM buckets and per-tensor holders",
    )
    pdf.add_argument(
        "--critical-path", action="store_true",
        help="the inputs are critical-path reports (critical-path "
             "--json): diff DES makespans, simulated-waterfall buckets "
             "and slack headroom across two runs/scenarios",
    )
    pdf.add_argument(
        "--fleet", action="store_true",
        help="the inputs are fleet reports (fleet --json): diff "
             "fleet goodput / utilization / makespan / SLO "
             "attainment, per-job goodput movers, and — when both "
             "carry an --explain ledger — the attribution buckets "
             "(auto-detected from the schema when omitted)",
    )
    pdf.add_argument("--json", metavar="PATH",
                     help="also save the structured diff report")
    _add_log_args(pdf)
    pdf.set_defaults(fn=cmd_diff)

    pcp = sub.add_parser(
        "critical-path",
        help="discrete-event critical path: per-event slack, the "
             "simulated waterfall (sums to the DES makespan), "
             "sim-vs-analytical divergence, slack-headroom summaries",
    )
    pcp.add_argument("--model", required=True)
    pcp.add_argument("--strategy", required=True)
    pcp.add_argument("--system", required=True)
    pcp.add_argument(
        "--world-ranks", action="store_true",
        help="simulate every global rank (true rendezvous) instead of "
             "one representative per pp stage",
    )
    pcp.add_argument(
        "--reduce", choices=("auto", "on", "off"), default="auto",
        help="world-rank symmetry reduction (default auto); the "
             "reduced path expands bit-identically",
    )
    pcp.add_argument(
        "--granularity", choices=("leaf", "chunk"), default="leaf",
        help="replay granularity: leaf (default) resolves per-op "
             "events; chunk is faster but folds recompute into compute",
    )
    pcp.add_argument(
        "--faults", metavar="SCENARIO.json",
        help="analyze the critical path under a fault scenario "
             "(docs/faults.md); implies --world-ranks",
    )
    pcp.add_argument("--top", type=int, default=5,
                     help="rows in the headroom / divergence tables "
                          "(default 5)")
    pcp.add_argument("--save", metavar="DIR",
                     help="artifact directory: annotated Chrome trace "
                          "+ critpath.json")
    pcp.add_argument("--json", metavar="PATH",
                     help="save the critical-path report JSON (the "
                          "input format of `diff --critical-path`)")
    _add_diag_args(pcp)
    _add_log_args(pcp)
    pcp.set_defaults(fn=cmd_critpath)

    ps = sub.add_parser("search", help="sweep parallel strategies")
    ps.add_argument("--model", required=True)
    ps.add_argument("--system", required=True)
    ps.add_argument("--base-strategy", default="tp1_pp1_dp8_mbs1")
    ps.add_argument("--world", type=int, default=0)
    ps.add_argument("--seq-len", type=int, default=0)
    ps.add_argument("--gbs", type=int, required=True)
    ps.add_argument("--tp", default="1,2,4,8")
    ps.add_argument("--pp", default="1,2,4")
    ps.add_argument("--ep", default="1")
    ps.add_argument("--cp", default="1")
    ps.add_argument("--zero", default="1", help="zero_state levels, e.g. 1,3")
    ps.add_argument("--topk", type=int, default=5)
    ps.add_argument("--csv")
    ps.add_argument("--verbose", action="store_true")
    ps.add_argument("--dualpp", action="store_true",
                    help="add a DualPipe projection column (even-pp rows)")
    ps.add_argument(
        "--journal", metavar="PATH",
        help="checkpoint every evaluated candidate to this JSONL journal",
    )
    ps.add_argument(
        "--resume", metavar="PATH",
        help="replay a sweep journal: journaled candidates are not "
             "re-evaluated (also extends the journal unless --journal "
             "points elsewhere)",
    )
    ps.add_argument(
        "--candidate-timeout", type=float, default=None, metavar="SECONDS",
        help="per-candidate deadline; slower candidates are quarantined "
             "as status=error rows instead of stalling the sweep",
    )
    ps.add_argument(
        "--jobs", type=int, default=None, metavar="N",
        help="evaluate sweep cells across N worker processes "
             "(default: os.cpu_count(); 1 = serial)",
    )
    ps.add_argument(
        "--no-prune", action="store_true",
        help="disable the closed-form memory prune and the recording "
             "of status=pruned CSV rows; structurally impossible "
             "layouts (divisibility) are still skipped, silently, as "
             "the sweep always has",
    )
    ps.add_argument(
        "--engine", choices=("scalar", "batched"), default="scalar",
        help="candidate scoring engine: 'scalar' walks a PerfLLM per "
             "candidate; 'batched' scores whole candidate batches with "
             "the vectorized cost kernel and re-verifies the top-k "
             "rows with the scalar oracle (see docs/search.md)",
    )
    ps.add_argument(
        "--verify-topk", type=int, default=None, metavar="K",
        help="with --engine batched: how many ranked rows to re-verify "
             "with the scalar oracle (default: --topk)",
    )
    ps.add_argument(
        "--guided", action="store_true",
        help="Pareto-guided search: screen every cell with one cheap "
             "batched-kernel score, fully evaluate only the "
             "(iter_time, peak_mem, comm_fraction) frontier and its "
             "local neighborhoods, refining around the top-k — "
             "skipped cells appear as status=screened CSV rows "
             "(see docs/search.md)",
    )
    ps.add_argument(
        "--simulate-check", action="store_true",
        help="cross-check every fitting candidate with the discrete-"
             "event simulator (sim_ms CSV column); cells whose replay "
             "raises SimulationError are quarantined as status=error "
             "rows like candidate timeouts",
    )
    _add_diag_args(ps)
    _add_log_args(ps)
    _add_cache_args(ps)
    _add_trace_args(ps)
    ps.set_defaults(fn=cmd_search)

    pc = sub.add_parser(
        "calibrate", help="self-calibrate on the local TPU (miss-driven)"
    )
    pc.add_argument("--model", required=True)
    pc.add_argument("--strategy", required=True)
    pc.add_argument("--system", required=True)
    pc.add_argument("--save", help="write calibrated system config JSON")
    pc.add_argument("--max-keys", type=int, default=64)
    pc.add_argument("--bandwidth", action="store_true",
                    help="also calibrate HBM bandwidth classes")
    pc.add_argument("--collectives", action="store_true",
                    help="also sweep+fit collectives (needs >1 device)")
    _add_diag_args(pc)
    _add_log_args(pc)
    pc.set_defaults(fn=cmd_calibrate)

    pf = sub.add_parser(
        "faults",
        help="goodput prediction under a fault scenario, or seeded "
             "Monte-Carlo over sampled scenarios (docs/faults.md)",
    )
    pf.add_argument("--model", required=True)
    pf.add_argument("--strategy", required=True)
    pf.add_argument("--system", required=True)
    pf.add_argument("--scenario", metavar="SCENARIO.json",
                    help="fault-scenario JSON to predict goodput for")
    pf.add_argument("--monte-carlo", type=int, default=0, metavar="N",
                    help="sample N random scenarios instead of loading "
                         "one (seeded, deterministic)")
    pf.add_argument("--seed", type=int, default=0,
                    help="Monte-Carlo RNG seed (default 0)")
    pf.add_argument("--horizon", type=int, default=0, metavar="STEPS",
                    help="job horizon in steps (default: the scenario's "
                         "horizon_steps; 50 for --monte-carlo)")
    pf.add_argument("--ckpt-interval", type=int, default=0,
                    metavar="STEPS",
                    help="checkpoint every K steps (default: scenario "
                         "override or 50)")
    pf.add_argument("--restart-overhead", type=float, default=None,
                    metavar="SECONDS",
                    help="restart overhead per failure (default 120)")
    pf.add_argument("--granularity", choices=("chunk", "leaf"),
                    default="chunk",
                    help="step-replay granularity: 'leaf' resolves "
                         "intra-stage (tp/cp/ep) collectives so "
                         "link_degradation on those dims takes effect; "
                         "'chunk' (default) is faster and models "
                         "pp/dp_cp/edp faults exactly")
    pf.add_argument("--jobs", type=int, default=None, metavar="N",
                    help="process-parallel Monte-Carlo: fan scenarios "
                         "across N worker processes (serial == "
                         "parallel bit-for-bit; default serial)")
    pf.add_argument("--exact", action="store_true",
                    help="disable the incremental replay engine (slack "
                         "short-circuit, canonicalized step cache, "
                         "healthy-prefix fork) and run the exact "
                         "step-by-step replay — the bit-identity "
                         "reference; ~10x+ slower")
    pf.add_argument("--json", metavar="PATH",
                    help="save the full goodput report / analysis JSON")
    _add_diag_args(pf)
    _add_log_args(pf)
    pf.set_defaults(fn=cmd_faults)

    pfl = sub.add_parser(
        "fleet",
        help="multi-job fleet simulation over a job-arrival trace: "
             "fleet-wide goodput, per-job SLO attainment, and the "
             "scheduler-decision timeline (docs/fleet.md)",
    )
    pfl.add_argument(
        "--trace", required=True, metavar="TRACE.json",
        help="fleet trace (simumax-fleet-trace-v1: pods + "
             "maintenance/spot/degradation windows + templates + "
             "job arrivals)",
    )
    pfl.add_argument(
        "--elastic", action="store_true", default=None,
        help="force elastic dp-reshape on rank death (overrides the "
             "trace's scheduler.elastic)",
    )
    pfl.add_argument(
        "--no-elastic", dest="elastic", action="store_false",
        help="force rollback-restart accounting (overrides the "
             "trace's scheduler.elastic)",
    )
    pfl.add_argument(
        "--jobs", type=int, default=0, metavar="N",
        help="fan job costings across N worker processes (serial == "
             "parallel bit-for-bit; default serial)",
    )
    pfl.add_argument(
        "--naive", action="store_true",
        help="cost every job with a fresh replay context (the "
             "per-job predict_goodput loop bench_fleet.py gates "
             "against) instead of the shared per-template context",
    )
    pfl.add_argument("--top", type=int, default=12, metavar="N",
                     help="decision-timeline lines to print "
                          "(default 12)")
    pfl.add_argument(
        "--explain", action="store_true",
        help="attach the causal goodput ledger + SLO counterfactual "
             "probes (observe/fleetledger.py) and print the "
             "chip-second waterfall, top loss causes, per-pod "
             "utilization, and probe table; the base report stays "
             "byte-identical",
    )
    pfl.add_argument(
        "--chrome-trace", metavar="PATH",
        help="write the fleet timeline as a Chrome trace (pods as "
             "pids, job lanes with run/suspend/checkpoint/rollback/"
             "reshape spans, causal flow arrows, goodput/utilization "
             "counters; implies --explain)",
    )
    pfl.add_argument("--json", metavar="PATH",
                     help="save the full fleet report JSON")
    pfl.add_argument("--cache-dir", metavar="DIR",
                     help="planner cache directory override")
    pfl.add_argument("--no-cache", action="store_true",
                     help="bypass the planner cache")
    _add_log_args(pfl)
    pfl.set_defaults(fn=cmd_fleet)

    pd = sub.add_parser(
        "dualpp",
        help="DualPipe bidirectional-schedule projection (even pp)",
    )
    pd.add_argument("--model", required=True)
    pd.add_argument("--strategy", required=True)
    pd.add_argument("--system", required=True)
    pd.add_argument("--plot", help="PNG path for the F&B cell timeline")
    _add_log_args(pd)
    pd.set_defaults(fn=cmd_dualpp)

    pst = sub.add_parser(
        "straggler",
        help="world-rank simulation with per-rank slowdown injection",
    )
    pst.add_argument("--model", required=True)
    pst.add_argument("--strategy", required=True)
    pst.add_argument("--system", required=True)
    pst.add_argument(
        "--ranks", required=True,
        help="rank:multiplier list, e.g. 0:1.2,5:1.5",
    )
    _add_log_args(pst)
    pst.set_defaults(fn=cmd_straggler)

    psv = sub.add_parser(
        "serve",
        help="long-running JSON-over-HTTP planning server backed by "
             "the persistent content-addressed cache "
             "(docs/service.md): concurrent estimate/explain/search/"
             "faults/simulate/fleet queries, single-flight dedup, "
             "NDJSON sweep streaming, /healthz + /stats",
    )
    psv.add_argument("--host", default="127.0.0.1",
                     help="bind address (default 127.0.0.1)")
    psv.add_argument("--port", type=int, default=8642,
                     help="bind port (default 8642; 0 = ephemeral)")
    psv.add_argument(
        "--cache-max-mb", type=int, default=0, metavar="MB",
        help="store size budget in MiB (default: the store's 512 MiB "
             "default; LRU-evicted beyond it)",
    )
    psv.add_argument(
        "--workers", type=int, default=0, metavar="N",
        help="serve through a pool of N planner worker processes "
             "(read-only store replicas + a single parent-side "
             "writer, request coalescing, response memory cache, "
             "worker respawn/retry — docs/service.md 'Production "
             "deployment'). Default 0: the in-process threaded "
             "server",
    )
    psv.add_argument(
        "--warm", type=int, default=0, metavar="JOBS",
        help="speculatively precompute the neighbor sweep cells of "
             "each served search (one index step per swept axis) in a "
             "background warmer with a JOBS-deep bounded queue. "
             "Default 0: off",
    )
    psv.add_argument(
        "--warm-cells", type=int, default=64, metavar="N",
        help="max neighbor cells one warm job may evaluate "
             "(default 64)",
    )
    psv.add_argument(
        "--admission", type=int, default=0, metavar="BACKLOG",
        help="admission control: shed requests with 429 + Retry-After "
             "once the queued+in-flight backlog reaches BACKLOG "
             "(scaled per X-SimuMax-Priority class: low sheds at "
             "0.5x, high at 1.5x). Default 0: admit everything",
    )
    psv.add_argument(
        "--request-timeout", type=float, default=0, metavar="SEC",
        help="pooled mode: per-request SIGALRM deadline on the worker "
             "(plus the 5x+30s hard kill backstop). Default 0: no "
             "deadline",
    )
    psv.add_argument(
        "--nodes", type=int, default=0, metavar="N",
        help="fleet convenience mode: fork N-1 sibling nodes on "
             "consecutive ports from --port (this process serves "
             "node n0) joined in one consistent-hash ring — sharded "
             "store, affinity routing, fleet-wide cell coalescing "
             "(docs/service.md 'Planner fleet'). Default 0: single "
             "node",
    )
    psv.add_argument(
        "--ring", metavar="SPEC",
        help="explicit fleet membership 'id=host:port,id=host:port,"
             "...' — start every member with the same SPEC; requires "
             "--join",
    )
    psv.add_argument(
        "--join", metavar="ID",
        help="this process's node id within --ring (bind host/port "
             "come from the matching SPEC entry)",
    )
    psv.add_argument(
        "--replicate-s", type=float, default=0, metavar="SEC",
        help="fleet mode: pull read-only replicas of peer-owned "
             "store entries every SEC seconds (default 0: replicate "
             "only on POST /ring/replicate)",
    )
    psv.add_argument(
        "--probe-s", type=float, default=0, metavar="SEC",
        help="fleet mode: heartbeat every peer over /ring/ping about "
             "every SEC seconds (seeded jitter); consecutive misses "
             "mark a peer suspect then down, removing it from the "
             "live ring until it answers again (docs/service.md "
             "'Failure semantics'). Default 0: no failure detection",
    )
    psv.add_argument(
        "--probe-seed", type=int, default=0, metavar="N",
        help="seed of the failure detector's jittered probe "
             "schedule (same seed = same relative probe times; "
             "default 0)",
    )
    _add_cache_args(psv)
    _add_log_args(psv)
    _add_trace_args(psv, serve=True)
    psv.set_defaults(fn=cmd_serve)

    pca = sub.add_parser(
        "cache",
        help="inspect/maintain the persistent planner cache: stats / "
             "ls / verify (re-hash payloads, exit 1 on corruption) / "
             "clear [--namespace]",
    )
    pca.add_argument("action",
                     choices=("stats", "ls", "verify", "clear"))
    pca.add_argument(
        "--namespace", metavar="NS",
        help="restrict ls/verify/clear to one namespace "
             "(estimate, explain, sweep, profiles, des)",
    )
    pca.add_argument(
        "--drop", action="store_true",
        help="with verify: also remove the corrupt entries",
    )
    pca.add_argument("--json", metavar="PATH",
                     help="also save the structured report")
    pca.add_argument(
        "--cache-dir", metavar="DIR",
        help="cache root (default: SIMUMAX_TPU_CACHE_DIR or "
             "~/.cache/simumax-tpu)",
    )
    _add_log_args(pca)
    pca.set_defaults(fn=cmd_cache)

    args = p.parse_args(argv)
    # the process-wide reporter carries the CLI's log surface; default
    # settings keep the human output byte-identical to the bare prints
    # it replaced
    from simumax_tpu.observe.report import configure_reporter

    configure_reporter(
        level=getattr(args, "log_level", "info"),
        json_lines=getattr(args, "log_json", False),
        run_id="",
    )
    # One-line actionable messages instead of tracebacks for the whole
    # anticipated-failure taxonomy (core/errors.py). Unanticipated bugs
    # still traceback — that is the right behavior for them.
    from simumax_tpu.core.errors import (
        ConfigError,
        SimulationError,
        SimuMaxError,
        UnknownConfigError,
    )

    try:
        return args.fn(args)
    except UnknownConfigError as e:
        print(f"error: {e}", file=sys.stderr)
        print("hint: `python -m simumax_tpu list` shows every config",
              file=sys.stderr)
        sys.exit(EXIT_CONFIG)
    except ConfigError as e:
        print(f"error: invalid configuration — {e}", file=sys.stderr)
        sys.exit(EXIT_CONFIG)
    except SimulationError as e:
        # same one-line treatment as the ConfigError family: a
        # DeadlockError's multi-line state dump belongs in the
        # diagnostics report, not on stderr
        first = (str(e) or type(e).__name__).splitlines()[0]
        print(f"error: simulation failed — {type(e).__name__}: {first}",
              file=sys.stderr)
        print("hint: rerun with --diagnostics PATH for the full "
              "engine state dump", file=sys.stderr)
        sys.exit(EXIT_SIMULATION)
    except SimuMaxError as e:
        print(f"error: {type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    sys.exit(main())

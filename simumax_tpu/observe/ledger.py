"""Cost-attribution ledger (the tentpole of the observability layer).

``PerfLLM`` computes per-op FLOPs, bytes, efficiency factors and
per-collective cost terms, then aggregates them into ``CostInfo`` sums —
this module preserves that provenance instead of throwing it away:
:meth:`Ledger.collect` walks the retained symbolic module tree *after*
an estimate and materializes

* one :class:`~simumax_tpu.core.records.OpSpan` per (leaf, phase) with
  the efficiency factor used, whether it was a calibrated hit or a
  default-table miss, and the roofline regime that bound the op;
* one :class:`~simumax_tpu.core.records.CollectiveSpan` per collective
  call with its bandwidth/latency terms and exposed-vs-overlapped split;
* the **MFU-loss waterfall**: the headline step time decomposed into
  ideal compute -> compute inefficiency -> exposed comms -> pipeline
  bubble -> recompute -> DP/optimizer sync -> straggler, summing to the
  predicted iteration time (asserted to 1e-6 relative in tests).

Collection is strictly post-hoc and read-only: a run that never calls
``collect`` does zero ledger work, and a run that does gets bit-identical
predictions (the sweep therefore opts out by default and keeps its
throughput — see ``bench_sweep.py --baseline``).

Consumers: ``simumax_tpu explain`` (waterfall + top-N op table,
``--json``/``--csv``), ``simumax_tpu diff`` (:func:`diff_ledgers`), and
the analytical Chrome-trace export (``observe/trace.py``). Schema and a
worked triage example: ``docs/observability.md``.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Tuple

from simumax_tpu.core.errors import ConfigError
from simumax_tpu.core.records import (
    PHASES,
    CollectiveSpan,
    Diagnostics,
    OpSpan,
)

LEDGER_SCHEMA = "simumax-ledger-v1"

#: waterfall buckets in presentation order; they sum to the headline
#: iteration time (the bucket definitions live in docs/observability.md)
WATERFALL_ORDER = (
    "ideal_compute",
    "compute_inefficiency",
    "exposed_comm",
    "pipeline_bubble",
    "recompute",
    "dp_optimizer_sync",
    "straggler",
)

#: compact labels for the one-line sweep attribution summary
_SHORT = {
    "ideal_compute": "ideal",
    "compute_inefficiency": "ineff",
    "exposed_comm": "comm",
    "pipeline_bubble": "bubble",
    "recompute": "recomp",
    "dp_optimizer_sync": "dp+opt",
    "straggler": "strag",
}

#: goodput waterfall buckets in presentation order; they sum to the job
#: wall time (``simulator/faults.py::predict_goodput``, docs/faults.md)
GOODPUT_WATERFALL_ORDER = (
    "useful_train",
    "fault_stall",
    "checkpoint_write",
    "restore_read",
    "restart_overhead",
    "restart_replay",
    "reshape",
)

_GOODPUT_SHORT = {
    "useful_train": "useful",
    "fault_stall": "stall",
    "checkpoint_write": "ckpt",
    "restore_read": "restore",
    "restart_overhead": "restart",
    "restart_replay": "replay",
    "reshape": "reshape",
}


def build_goodput_waterfall(report) -> Dict[str, Any]:
    """Normalize a ``GoodputReport`` (or its ``to_dict()``) into the
    same ``{order, buckets, total}`` shape as the MFU-loss waterfall —
    buckets sum to the job wall time within 1e-6 by construction (the
    goodput accounting is itself the decomposition)."""
    d = report if isinstance(report, dict) else report.to_dict()
    # .get: pre-reshape persisted reports carry no "reshape" bucket
    buckets = {k: d["buckets"].get(k, 0.0)
               for k in GOODPUT_WATERFALL_ORDER}
    return {
        "order": list(GOODPUT_WATERFALL_ORDER),
        "buckets": buckets,
        "total": d["wall_time_s"],
        "goodput": d["goodput"],
        "horizon_steps": d["horizon_steps"],
        "n_restarts": d["n_restarts"],
        "n_checkpoints": d["n_checkpoints"],
    }


def goodput_waterfall_lines(report) -> List[str]:
    """Human rendering of the goodput wall-time decomposition (the
    ``faults`` subcommand's default output)."""
    wf = build_goodput_waterfall(report)
    total = wf["total"] or 1.0
    width = max(len(k) for k in wf["order"])
    lines = [
        f"== goodput waterfall: {wf['horizon_steps']} steps — wall "
        f"{total:.1f} s, goodput {100.0 * wf['goodput']:.2f}% "
        f"({wf['n_checkpoints']} checkpoints, {wf['n_restarts']} "
        f"restarts) =="
    ]
    for key in wf["order"]:
        v = wf["buckets"][key]
        pct = round(100.0 * v / total, 2) + 0.0
        lines.append(f"  {key:<{width}}  {v:12.3f} s  {pct:6.2f}%")
    lines.append(
        f"  {'= wall time':<{width}}  {total:12.3f} s  100.00%"
    )
    return lines


def goodput_attribution_line(report) -> str:
    """One-line goodput summary, e.g. ``useful 91.2% | stall 3.1% |
    ckpt 2.0% | restore 0.4% | restart 1.8% | replay 1.5%``."""
    wf = build_goodput_waterfall(report)
    total = wf["total"] or 1.0
    parts = []
    for k in GOODPUT_WATERFALL_ORDER:
        pct = round(100.0 * wf["buckets"][k] / total, 1) + 0.0
        parts.append(f"{_GOODPUT_SHORT[k]} {pct:.1f}%")
    return " | ".join(parts)


def collect_op_spans(perf) -> Tuple[List[OpSpan], List[CollectiveSpan]]:
    """Walk every called leaf of the estimate's module tree and rebuild
    each cost decision's provenance. Adopted (layer-dedup) leaves share
    their representative's info objects, so the walk yields every
    physical leaf with the values the estimate actually charged."""
    sysc = perf.system
    ops: List[OpSpan] = []
    comms: List[CollectiveSpan] = []
    for (stage, chunk), model_chunk in sorted(perf.chunks.items()):
        for leaf in model_chunk.called_leaves():
            ci, cost = leaf.compute_info, leaf.cost_info
            for phase in PHASES:
                flops = getattr(ci, f"{phase}_flops")
                accessed = getattr(ci, f"{phase}_accessed")
                if flops <= 0 and accessed <= 0:
                    continue
                op_key, shape_key = leaf.comp_key(phase)
                # the estimate's own lookup, minus the hit/miss
                # recording side effect — provenance cannot diverge
                # from what was charged
                eff, hit, spec = sysc.resolve_op_efficiency(
                    op_key, shape_key, record=False
                )
                comp_t = (
                    flops / (spec.tflops * 1e12 * eff) if flops > 0 else 0.0
                )
                mem_t = (
                    sysc.compute_mem_access_time(accessed, leaf.bw_key(phase))
                    if accessed > 0 else 0.0
                )
                regime = (
                    "memory"
                    if sysc.accelerator.mode != "compute_only"
                    and mem_t > comp_t
                    else "compute"
                )
                ops.append(OpSpan(
                    path=leaf.path_name(),
                    module_type=type(leaf).__name__,
                    category=leaf.op_category,
                    stage=stage,
                    chunk=chunk,
                    phase=phase,
                    op_key=op_key,
                    shape_key=shape_key,
                    flops=flops,
                    bytes_accessed=accessed,
                    comp_time=comp_t,
                    mem_time=mem_t,
                    time=cost.compute.get(phase),
                    efficiency=eff,
                    calibrated=hit,
                    regime=regime,
                    recompute=leaf.in_recompute,
                ))
            for call in leaf.collective_calls:
                path = perf.ctx.path(call.dim)
                bw_t, lat_t = sysc.compute_net_op_terms(
                    call.op, call.size_bytes, path
                )
                comms.append(CollectiveSpan(
                    path=leaf.path_name(),
                    stage=stage,
                    chunk=chunk,
                    phase=call.phase,
                    op=call.op,
                    dim=call.dim,
                    size_bytes=call.size_bytes,
                    time=call.time,
                    exposed_time=call.exposed_time,
                    hidden_time=call.time - call.exposed_time,
                    bw_time=bw_t,
                    lat_time=lat_t,
                    on_dcn=path.on_dcn,
                ))
    return ops, comms


def build_waterfall(perf) -> Dict[str, Any]:
    """Decompose the headline iteration time into the MFU-loss buckets.

    The decomposition is constructive along the critical path the
    estimate itself took: the barrier-binding stage's schedule end is
    split into work (compute / exposed comm / recompute, each x mbc)
    plus bubble (waiting, incl. blocking p2p); the tail adds the
    exposed DP grad reduce, optimizer, and param gather of their
    binding stages; the straggler bucket is the closed-form inflation.
    The buckets therefore sum to ``iter_time`` up to float rounding
    (~1e-15 relative — asserted at 1e-6 in tests).

    ``compute_inefficiency`` may go slightly negative when a calibrated
    per-shape efficiency exceeds 1.0 (the validator admits up to 1.05);
    the sum invariant still holds.
    """
    cost = perf.analysis_cost()
    st = perf.strategy
    mbc = st.micro_batch_num
    s_rs = cost["binding_stage_rs"]
    s_tail = cost["binding_stage_tail"]
    end_rs = cost["per_stage_end"][s_rs]
    chunks = perf.stage_chunks(s_rs)
    peak = perf.system.accelerator.op["default"].tflops * 1e12
    flops_mb = sum(c.compute_info.total_flops for c in chunks)
    compute_t = mbc * sum(c.cost_info.compute.total for c in chunks)
    net_t = mbc * sum(c.cost_info.net_exposed.total for c in chunks)
    rec_t = mbc * sum(c.cost_info.recompute_time for c in chunks)
    ideal = mbc * flops_mb / peak
    work = compute_t + net_t + rec_t
    bubble = end_rs - work
    dp_opt = (cost["exposed_rs_time"] + cost["optim_time"]
              + cost["exposed_ag_time"])
    pre_straggle = end_rs + dp_opt
    buckets = {
        "ideal_compute": ideal,
        "compute_inefficiency": compute_t - ideal,
        "exposed_comm": net_t,
        "pipeline_bubble": bubble,
        "recompute": rec_t,
        "dp_optimizer_sync": dp_opt,
        "straggler": cost["iter_time"] - pre_straggle,
    }
    return {
        "order": list(WATERFALL_ORDER),
        "buckets": buckets,
        "total": cost["iter_time"],
        "binding_stage_rs": s_rs,
        "binding_stage_tail": s_tail,
        "mfu": cost["mfu"],
        "straggle_ratio": cost["straggle_ratio"],
    }


def attribution_line(perf) -> str:
    """One-line waterfall summary for sweep CSV rows / quick scans,
    e.g. ``ideal 41.9% | ineff 22.1% | comm 3.0% | bubble 12.4% |
    recomp 0.0% | dp+opt 11.6% | strag 9.0%``."""
    wf = build_waterfall(perf)
    total = wf["total"] or 1.0
    parts = []
    for k in WATERFALL_ORDER:
        # + 0.0 folds float -0.0 (epsilon-negative buckets) into "0.0"
        pct = round(100.0 * wf["buckets"][k] / total, 1) + 0.0
        parts.append(f"{_SHORT[k]} {pct:.1f}%")
    return " | ".join(parts)


@dataclass
class Ledger:
    """The collected attribution record of one estimate."""

    meta: Dict[str, Any] = field(default_factory=dict)
    headline: Dict[str, Any] = field(default_factory=dict)
    waterfall: Dict[str, Any] = field(default_factory=dict)
    mem: Dict[str, Any] = field(default_factory=dict)
    efficiency: Dict[str, Any] = field(default_factory=dict)
    #: per-stage bucketed DP grad/param comm + pp p2p detail (charged
    #: outside the leaf collectives, so recorded at step level)
    step_comm: Dict[str, Any] = field(default_factory=dict)
    op_spans: List[OpSpan] = field(default_factory=list)
    collective_spans: List[CollectiveSpan] = field(default_factory=list)

    # -- construction ------------------------------------------------------
    @classmethod
    def collect(cls, perf) -> "Ledger":
        assert perf.ctx is not None, "call run_estimate() before collect()"
        st, m, sysc = perf.strategy, perf.model_config, perf.system
        cost = perf.analysis_cost()
        mem = perf.analysis_mem()
        identity = {
            "model": m.model_name,
            "system": sysc.sys_name,
            "system_hash": sysc.fingerprint(),
            "seq_len": st.seq_len,
            "global_batch_size": st.global_batch_size,
            "parallelism": {
                "tp": st.tp_size, "cp": st.cp_size, "pp": st.pp_size,
                "dp": st.dp_size, "ep": st.ep_size, "etp": st.etp_size,
                "vp": st.vp_size, "zero": st.zero_state,
                "mbs": st.micro_batch_size, "mbc": st.micro_batch_num,
            },
        }
        run_id = Diagnostics.identity_hash(identity)
        if not perf.diagnostics.run_id:
            # the estimate's diagnostics (and the process reporter) join
            # the ledger's run identity — also backfilling events that
            # were recorded during the estimate — so the diagnostics
            # report, --log-json lines, and this ledger all
            # cross-reference by run_id
            perf.diagnostics.set_run_identity(identity)
        ops, comms = collect_op_spans(perf)
        # step-level comm provenance: the bucketed DP grad/param comm
        # and per-microbatch pp transfer are charged outside the leaf
        # collectives, so their detail is recorded per stage here
        step_comm = {}
        for s in range(st.pp_size):
            detail = dict(perf._compute_dp_time(s))
            detail["pp_p2p_per_microbatch"] = (
                cost["stage_phase_inputs"][s]["p2p"]
            )
            for d in ("dp_cp", "edp", "pp"):
                path = perf.ctx.paths.get(d)
                if path is not None:
                    detail[f"{d}_on_dcn"] = path.on_dcn
            step_comm[f"stage{s}"] = detail
        eff = {
            "hits": {k: sorted(v) for k, v in sysc.hit_efficiency.items()},
            "misses": {k: sorted(v) for k, v in sysc.miss_efficiency.items()},
            "hit_count": sum(len(v) for v in sysc.hit_efficiency.values()),
            "miss_count": sum(len(v) for v in sysc.miss_efficiency.values()),
        }
        return cls(
            meta={"run_id": run_id, **identity,
                  "world_size": st.world_size},
            headline={
                "iter_time": cost["iter_time"],
                "iter_time_ms": cost["iter_time_ms"],
                "mfu": cost["mfu"],
                "tflops_per_chip": cost["tflops_per_chip"],
                "tgs": cost["tgs"],
                "peak_gib": mem["max_peak_gib"],
                "fits": mem["fits"],
                "straggle_ratio": cost["straggle_ratio"],
            },
            waterfall=build_waterfall(perf),
            mem={
                "max_peak_gib": mem["max_peak_gib"],
                "usable_gib": mem["usable_gib"],
                "stage_peak_gib": [s["peak_gib"] for s in mem["stages"]],
            },
            step_comm=step_comm,
            efficiency=eff,
            op_spans=ops,
            collective_spans=comms,
        )

    # -- aggregation -------------------------------------------------------
    def op_rows(self) -> List[Dict[str, Any]]:
        """Per-leaf rows (phases folded), sorted by total charged time
        descending — the `explain` top-N table. Times are per-microbatch
        seconds; ``share`` scales by mbc against the headline step time
        (an upper bound on the op's step share: ops off the binding
        stage or overlapped contribute less)."""
        rows: Dict[str, Dict[str, Any]] = {}
        for s in self.op_spans:
            r = rows.setdefault(s.path, {
                "path": s.path, "module_type": s.module_type,
                "category": s.category, "stage": s.stage, "chunk": s.chunk,
                "fwd_time": 0.0, "bwd_time": 0.0, "time": 0.0,
                "flops": 0.0, "bytes_accessed": 0.0,
                "efficiency": s.efficiency, "calibrated": s.calibrated,
                "regime": s.regime, "recompute": s.recompute,
            })
            r["time"] += s.time
            if s.phase == "fwd":
                r["fwd_time"] += s.time
            else:
                r["bwd_time"] += s.time
            r["flops"] += s.flops
            r["bytes_accessed"] += s.bytes_accessed
            # the op's weakest link is what calibration should target
            if s.efficiency < r["efficiency"]:
                r["efficiency"] = s.efficiency
            r["calibrated"] = r["calibrated"] and s.calibrated
            if s.regime == "memory":
                r["regime"] = "memory"
        for s in self.collective_spans:
            r = rows.get(s.path)
            if r is None:
                r = rows.setdefault(s.path, {
                    "path": s.path, "module_type": "", "category": "comm",
                    "stage": s.stage, "chunk": s.chunk,
                    "fwd_time": 0.0, "bwd_time": 0.0, "time": 0.0,
                    "flops": 0.0, "bytes_accessed": 0.0,
                    "efficiency": 1.0, "calibrated": True,
                    "regime": "comm", "recompute": False,
                })
            r.setdefault("net_exposed_time", 0.0)
            r.setdefault("net_hidden_time", 0.0)
            r["net_exposed_time"] += s.exposed_time
            r["net_hidden_time"] += s.hidden_time
            r["time"] += s.exposed_time
        mbc = (self.meta.get("parallelism") or {}).get("mbc", 1)
        total = self.headline.get("iter_time") or 1.0
        out = sorted(rows.values(), key=lambda r: r["time"], reverse=True)
        for r in out:
            r.setdefault("net_exposed_time", 0.0)
            r.setdefault("net_hidden_time", 0.0)
            r["share"] = mbc * r["time"] / total
        return out

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": LEDGER_SCHEMA,
            "meta": self.meta,
            "headline": self.headline,
            "waterfall": self.waterfall,
            "mem": self.mem,
            "efficiency": self.efficiency,
            "step_comm": self.step_comm,
            "ops": [s.to_dict() for s in self.op_spans],
            "collectives": [s.to_dict() for s in self.collective_spans],
        }

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1)
        return path

    @staticmethod
    def load(path: str) -> Dict[str, Any]:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        schema = data.get("schema")
        if schema != LEDGER_SCHEMA:
            raise ConfigError(
                f"{path}: not a simumax ledger (schema={schema!r}; "
                f"expected {LEDGER_SCHEMA!r} — produce one with "
                f"`simumax_tpu explain ... --json PATH`)"
            )
        return data

    # -- presentation ------------------------------------------------------
    def waterfall_lines(self) -> List[str]:
        """Human waterfall rendering (the `explain` default output)."""
        return waterfall_lines_from_dict({
            "meta": self.meta, "headline": self.headline,
            "waterfall": self.waterfall,
        })

    def top_op_lines(self, n: int = 10) -> List[str]:
        return top_op_lines_from_rows(self.op_rows(), n)


def waterfall_lines_from_dict(data: Dict[str, Any]) -> List[str]:
    """The waterfall rendering, from a ledger *dict* (``to_dict`` /
    ``load`` / a cached planner payload) — one renderer shared with the
    live :class:`Ledger`, so cached and fresh `explain` output cannot
    diverge."""
    wf = data["waterfall"]
    meta, headline = data["meta"], data["headline"]
    total = wf["total"] or 1.0
    width = max(len(k) for k in wf["order"])
    lines = [
        f"== MFU-loss waterfall: {meta['model']} on "
        f"{meta['system']} — iter "
        f"{headline['iter_time_ms']:.2f} ms, "
        f"MFU {100.0 * headline['mfu']:.2f}% =="
    ]
    for key in wf["order"]:
        v = wf["buckets"][key]
        # round-then-add-0.0 folds epsilon-negative buckets' float
        # -0.0 into plain 0.0 for display
        ms = round(v * 1e3, 3) + 0.0
        pct = round(100.0 * v / total, 2) + 0.0
        lines.append(f"  {key:<{width}}  {ms:10.3f} ms  {pct:6.2f}%")
    lines.append(
        f"  {'= step time':<{width}}  {total * 1e3:10.3f} ms  "
        f"100.00%"
    )
    return lines


def top_op_lines_from_rows(rows: List[Dict[str, Any]],
                           n: int = 10) -> List[str]:
    """The top-op table rendering, from aggregated ``op_rows``."""
    rows = rows[:n]
    if not rows:
        return []
    lines = [
        "-- top ops by charged time (per microbatch; share scales "
        "by mbc vs step) --"
    ]
    for r in rows:
        cal = "cal" if r["calibrated"] else "MISS"
        lines.append(
            f"  {r['time'] * 1e3:9.3f} ms  {r['share'] * 100:5.1f}%  "
            f"[{r['regime']:>7}|{cal:>4}|eff {r['efficiency']:.2f}]  "
            f"{r['path']} ({r['category']})"
        )
    return lines


# --------------------------------------------------------------------------
# Ledger diffing
# --------------------------------------------------------------------------


def _agg_op_times(ledger: Dict[str, Any]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for s in ledger.get("ops", []):
        out[s["path"]] = out.get(s["path"], 0.0) + s["time"]
    for s in ledger.get("collectives", []):
        out[s["path"]] = out.get(s["path"], 0.0) + s["exposed_time"]
    return out


def _category_totals(ledger: Dict[str, Any]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for s in ledger.get("ops", []):
        out[s["category"]] = out.get(s["category"], 0.0) + s["time"]
    for s in ledger.get("collectives", []):
        key = f"comm:{s['dim']}"
        out[key] = out.get(key, 0.0) + s["exposed_time"]
    return out


def diff_ledgers(a: Dict[str, Any], b: Dict[str, Any],
                 top: int = 20) -> Dict[str, Any]:
    """Compare two ledgers (two strategies, or a prediction vs a
    calibrated re-run): which buckets, op families, and individual ops
    account for the headline delta. Diffing a ledger against itself
    reports zero everywhere (``identical: True``)."""
    headline = {
        k: {
            "a": a["headline"].get(k),
            "b": b["headline"].get(k),
            "delta": (b["headline"].get(k, 0.0) or 0.0)
            - (a["headline"].get(k, 0.0) or 0.0),
        }
        for k in ("iter_time_ms", "mfu", "tgs", "peak_gib")
    }
    wf = {
        k: {
            "a": a["waterfall"]["buckets"].get(k, 0.0),
            "b": b["waterfall"]["buckets"].get(k, 0.0),
            "delta": b["waterfall"]["buckets"].get(k, 0.0)
            - a["waterfall"]["buckets"].get(k, 0.0),
        }
        for k in set(a["waterfall"]["buckets"]) | set(b["waterfall"]["buckets"])
    }
    cat_a, cat_b = _category_totals(a), _category_totals(b)
    categories = {
        k: {
            "a": cat_a.get(k, 0.0),
            "b": cat_b.get(k, 0.0),
            "delta": cat_b.get(k, 0.0) - cat_a.get(k, 0.0),
        }
        for k in set(cat_a) | set(cat_b)
    }
    ops_a, ops_b = _agg_op_times(a), _agg_op_times(b)
    deltas = [
        {"path": p, "a": ops_a.get(p, 0.0), "b": ops_b.get(p, 0.0),
         "delta": ops_b.get(p, 0.0) - ops_a.get(p, 0.0)}
        for p in set(ops_a) | set(ops_b)
    ]
    deltas.sort(key=lambda d: abs(d["delta"]), reverse=True)
    eff = {
        "miss_count": {
            "a": a["efficiency"]["miss_count"],
            "b": b["efficiency"]["miss_count"],
            "delta": b["efficiency"]["miss_count"]
            - a["efficiency"]["miss_count"],
        },
        "hit_count": {
            "a": a["efficiency"]["hit_count"],
            "b": b["efficiency"]["hit_count"],
            "delta": b["efficiency"]["hit_count"]
            - a["efficiency"]["hit_count"],
        },
    }
    identical = (
        all(v["delta"] == 0 for v in headline.values())
        and all(v["delta"] == 0 for v in wf.values())
        and all(d["delta"] == 0 for d in deltas)
        and all(v["delta"] == 0 for v in categories.values())
        and eff["miss_count"]["delta"] == 0
        and eff["hit_count"]["delta"] == 0
    )
    return {
        "schema": "simumax-ledger-diff-v1",
        "a": {"run_id": a["meta"].get("run_id"),
              "model": a["meta"].get("model"),
              "system": a["meta"].get("system")},
        "b": {"run_id": b["meta"].get("run_id"),
              "model": b["meta"].get("model"),
              "system": b["meta"].get("system")},
        "identical": identical,
        "headline": headline,
        "waterfall": wf,
        "categories": categories,
        "op_deltas": deltas[:top],
        # lists are truncated to `top`; the *_count fields carry the
        # true totals so the rendering never understates the divergence
        "ops_only_in_a": sorted(set(ops_a) - set(ops_b))[:top],
        "ops_only_in_a_count": len(set(ops_a) - set(ops_b)),
        "ops_only_in_b": sorted(set(ops_b) - set(ops_a))[:top],
        "ops_only_in_b_count": len(set(ops_b) - set(ops_a)),
        "efficiency": eff,
    }


def format_diff_lines(diff: Dict[str, Any], top: int = 10) -> List[str]:
    """Human rendering of a ledger diff."""
    lines = [
        f"== ledger diff: a={diff['a']['run_id']} "
        f"({diff['a']['model']} on {diff['a']['system']})  vs  "
        f"b={diff['b']['run_id']} "
        f"({diff['b']['model']} on {diff['b']['system']}) =="
    ]
    if diff["identical"]:
        lines.append("  identical: zero delta in every bucket and op")
        return lines
    h = diff["headline"]
    lines.append(
        f"  iter {h['iter_time_ms']['a']:.2f} -> "
        f"{h['iter_time_ms']['b']:.2f} ms "
        f"({h['iter_time_ms']['delta']:+.2f} ms)   "
        f"MFU {100 * h['mfu']['a']:.2f}% -> {100 * h['mfu']['b']:.2f}% "
        f"({100 * h['mfu']['delta']:+.2f}pp)   "
        f"peak {h['peak_gib']['a']:.2f} -> {h['peak_gib']['b']:.2f} GiB"
    )
    lines.append("  -- waterfall bucket deltas (b - a) --")
    for key in WATERFALL_ORDER:
        d = diff["waterfall"].get(key)
        if d is None:
            continue
        lines.append(
            f"    {key:<21} {d['a'] * 1e3:10.3f} -> {d['b'] * 1e3:10.3f} ms"
            f"  ({d['delta'] * 1e3:+.3f} ms)"
        )
    shown = [d for d in diff["op_deltas"] if d["delta"] != 0][:top]
    if shown:
        lines.append("  -- largest per-op deltas (per microbatch) --")
        for d in shown:
            lines.append(
                f"    {d['delta'] * 1e3:+9.3f} ms  {d['path']}"
            )
    for side, key in (("a", "ops_only_in_a"), ("b", "ops_only_in_b")):
        if diff[key]:
            count = diff.get(f"{key}_count", len(diff[key]))
            lines.append(
                f"  ops only in {side}: {count} "
                f"(e.g. {diff[key][0]})"
            )
    e = diff["efficiency"]["miss_count"]
    if e["delta"]:
        lines.append(
            f"  efficiency-table misses {e['a']} -> {e['b']} "
            f"({e['delta']:+d})"
        )
    return lines

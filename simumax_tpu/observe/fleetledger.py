"""Fleet goodput forensics (the ISSUE-18 tentpole, docs/fleet.md
"Explaining a fleet run").

Three surfaces over a finished :class:`~simumax_tpu.fleet.sim.
FleetSimulator` walk, in the established ledger discipline
(PR 3 cost ledger / PR 6 memory ledger / PR 7 critical-path blame):
collect-on == collect-off bit-identical, and every decomposition sums
to its total within 1e-6 by construction.

* **Causal goodput ledger** (:func:`build_fleet_ledger`) — re-drives
  each completed job's goodput walk through the *shared* per-template
  :class:`~simumax_tpu.simulator.faults.ReplayContext` with the walk
  observer attached (``simulator/faults.py`` / the elastic twin), so
  every perturbed step is answered from the cache the fleet walk
  already filled and the re-drive is near-free. The observer stream
  (steps, checkpoint writes, restarts, reshapes) is folded into
  per-job buckets — ``useful_train``, the ``fault_stall`` split by
  causing-event class (maintenance / degradation / suspension),
  checkpoint write, restore read, restart overhead, restart replay,
  reshape — and every bucket-second is attributed to the causing
  trace event (``maint:{wi}`` / ``link:{wi}`` / ``spot:{ri}`` /
  ``preempt:{job}`` / ``policy:checkpoint``), the causality ids the
  fleet walk records on its timeline and decisions. Roll-ups:
  chip-second-weighted fleet waterfall (the PR-3 ``{order, buckets,
  total}`` shape), per-template loss profile, per-pod utilization.
* **SLO counterfactual probes** (:func:`slo_counterfactuals`) — the
  ``memledger.whatif_probes`` pattern at fleet scale: each missed-SLO
  or starved job gets cheap counterfactuals (checkpoint interval =
  Young-Daly optimal, placement excluding degraded pods, on-demand
  instead of spot, a priority bump, elastic off) re-costed through
  the same shared context; the first SLO-recovering probe in fixed
  cheapness order is flagged ``cheapest_fix``.
* **Fleet Chrome trace** (:func:`fleet_chrome_trace`) — pods as
  pids, jobs as lanes with run / checkpoint / rollback / reshape /
  suspended spans, pod-level window lanes (maintenance, degradation,
  reclaims), flow arrows from causing event to affected job span,
  counter tracks for per-pod used chips and the running fleet
  goodput — same viewer as the pipeline traces, validated by the
  ``test_trace_validity.py`` machinery.

Everything is assembled into the report's ``explain`` key by
:func:`build_fleet_explain`; the base ``simumax-fleet-v1`` payload
stays byte-identical to an explain-off run (CI's bit-identity gate).
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

from simumax_tpu.core.errors import ConfigError

#: fleet-ledger buckets in presentation order: the goodput buckets
#: with ``fault_stall`` split by causing-event class. They sum to the
#: job's wall time within 1e-6 (same constructive accounting as
#: ``GoodputBuckets``, re-derived from the walk observer stream).
FLEET_LEDGER_ORDER = (
    "useful_train",
    "stall_maintenance",
    "stall_degradation",
    "stall_suspension",
    "stall_other",
    "checkpoint_write",
    "restore_read",
    "restart_overhead",
    "restart_replay",
    "reshape",
)

#: probe cheapness order: a config knob beats a placement change
#: beats a procurement/priority change beats a scheduling-policy flip
_PROBE_ORDER = (
    "checkpoint=young-daly",
    "placement=clean-pods",
    "spot=on-demand",
    "priority=bump",
    "elastic=off",
)

_CKPT_CAUSE = "policy:checkpoint"
_UNATTRIBUTED = "unattributed"


def _stall_bucket(cause: str) -> str:
    """Causing-event id -> stall bucket class."""
    if cause.startswith("maint:"):
        return "stall_maintenance"
    if cause.startswith("link:"):
        return "stall_degradation"
    if cause.startswith(("preempt:", "spot:")) or cause == "sched":
        return "stall_suspension"
    return "stall_other"


# --------------------------------------------------------------------------
# Per-job attribution: fold the walk-observer stream into causes
# --------------------------------------------------------------------------


class _JobAttribution:
    """State machine mirroring the goodput walk's commit/rollback
    accounting, fed by the walk observer. ``pending`` holds committed
    but uncheckpointed step rows exactly like the walk's
    ``uncommitted`` list: a checkpoint finalizes them into
    useful/stall, a restart converts them into ``restart_replay``
    attributed to the killing event."""

    def __init__(self, windows: List[tuple], deaths: List[tuple],
                 reshape_causes: List[str]):
        #: (t0_s, t1_s, weight_rate, cause) stall-bearing windows
        self.windows = windows
        #: (t_s, cause) rank-death events
        self.deaths = deaths
        self.reshape_causes = reshape_causes
        self.buckets = {k: 0.0 for k in FLEET_LEDGER_ORDER}
        #: cause -> bucket -> seconds
        self.causes: Dict[str, Dict[str, float]] = {}
        #: (healthy_s, stall_s, {(cause, bucket): s}) rows since the
        #: last successful checkpoint
        self.pending: List[tuple] = []
        self.spans: List[dict] = []
        self._run_start: Optional[float] = None
        self._n_reshapes = 0
        self.wall_end = 0.0

    def _charge(self, cause: str, bucket: str, seconds: float):
        if seconds == 0.0:
            return
        self.buckets[bucket] += seconds
        per = self.causes.setdefault(cause, {})
        per[bucket] = per.get(bucket, 0.0) + seconds

    def _split_stall(self, t0: float, t1: float,
                     stall: float) -> Dict[Tuple[str, str], float]:
        """Attribute a step's stall across the scenario windows
        overlapping ``[t0, t1)``, weighted by overlap x stall rate
        (1.0 for a freeze, ``multiplier - 1`` for a degradation).
        No overlapping window -> the unattributed stall bucket."""
        if stall <= 0.0:
            return {}
        weights: Dict[Tuple[str, str], float] = {}
        total = 0.0
        for (w0, w1, rate, cause) in self.windows:
            ov = min(t1, w1) - max(t0, w0)
            if ov <= 0.0 or rate <= 0.0:
                continue
            key = (cause, _stall_bucket(cause))
            weights[key] = weights.get(key, 0.0) + ov * rate
            total += ov * rate
        if total <= 0.0:
            return {(_UNATTRIBUTED, "stall_other"): stall}
        return {k: stall * w / total for k, w in weights.items()}

    def _death_cause(self, abort_s: float) -> str:
        if not self.deaths:
            return _UNATTRIBUTED
        t, cause = min(self.deaths,
                       key=lambda d: (abs(d[0] - abort_s), d[0]))
        return cause

    def _close_run(self, end_s: float):
        if self._run_start is not None and end_s > self._run_start:
            self.spans.append({"name": "run", "t0_s": self._run_start,
                               "dur_s": end_s - self._run_start})
        self._run_start = None

    def _commit_pending(self):
        for (h, stall, attr) in self.pending:
            self.buckets["useful_train"] += h
            for (cause, bucket), s in attr.items():
                self._charge(cause, bucket, s)
            # useful time has no causing event; count it explicitly
            # so per-cause totals + useful sum back to wall
            per = self.causes.setdefault("useful", {})
            per["useful_train"] = per.get("useful_train", 0.0) + h
        self.pending = []

    def feed(self, rec: tuple):
        kind = rec[0]
        if kind == "step":
            _, wall, h, dur = rec
            if self._run_start is None:
                self._run_start = wall
            attr = self._split_stall(wall, wall + dur, dur - h)
            self.pending.append((h, dur - h, attr))
            self.wall_end = wall + dur
        elif kind == "checkpoint":
            _, wall, write_s = rec
            self._commit_pending()
            self._charge(_CKPT_CAUSE, "checkpoint_write", write_s)
            self._close_run(wall)
            self.spans.append({"name": "checkpoint", "t0_s": wall,
                               "dur_s": write_s,
                               "cause": _CKPT_CAUSE})
            self.wall_end = wall + write_s
        elif kind == "restart":
            _, abort, extra, overhead, read_s = rec
            cause = self._death_cause(abort)
            for (h, stall, _attr) in self.pending:
                self._charge(cause, "restart_replay", h + stall)
            self.pending = []
            self._charge(cause, "restart_replay", extra)
            self._charge(cause, "restart_overhead", overhead)
            self._charge(cause, "restore_read", read_s)
            self._close_run(abort)
            self.spans.append({"name": "rollback", "t0_s": abort,
                               "dur_s": overhead + read_s,
                               "cause": cause})
            self.wall_end = abort + overhead + read_s
        elif kind == "reshape":
            _, wall, partial, cost, level = rec
            cause = (self.reshape_causes[self._n_reshapes]
                     if self._n_reshapes < len(self.reshape_causes)
                     else _UNATTRIBUTED)
            self._n_reshapes += 1
            self._charge(cause, "reshape", partial + cost)
            self._close_run(wall)
            self.spans.append({"name": "reshape", "t0_s": wall,
                               "dur_s": partial + cost,
                               "cause": cause, "level": level})
            self.wall_end = wall + partial + cost

    def finish(self, wall_s: float):
        self._commit_pending()
        self._close_run(wall_s)
        self.wall_end = wall_s


def _job_windows_and_deaths(scenario, causes: List[str]):
    """Scenario events + causality ids -> the attribution inputs:
    stall-bearing windows (freezes at rate 1, degradations at rate
    ``multiplier - 1``) and rank-death instants, in job-relative
    seconds."""
    windows: List[tuple] = []
    deaths: List[tuple] = []
    for ev, cause in zip(scenario.events, causes):
        t0 = ev.start_ms * 1e-3
        if ev.kind == "rank_death":
            deaths.append((t0, cause))
            continue
        t1 = t0 + (ev.duration_ms or 0.0) * 1e-3
        rate = 1.0
        if ev.kind == "link_degradation":
            rate = max(0.0, (ev.multiplier or 1.0) - 1.0)
        elif ev.kind == "slowdown":
            rate = max(0.0, (ev.multiplier or 1.0) - 1.0)
        windows.append((t0, t1, rate, cause))
    return windows, deaths


def attribute_job(sim, job) -> Optional[Dict[str, Any]]:
    """One completed job's causal ledger record: re-drive its goodput
    walk through the shared template context with the observer
    attached and fold the stream. Returns ``None`` for jobs without a
    report (starved/suspended at trace end — nothing to decompose)."""
    if job.report is None:
        return None
    from simumax_tpu.fleet.sim import elastic_goodput_walk
    from simumax_tpu.simulator.faults import predict_goodput

    rt = sim._runtimes[job.spec.template]
    scenario, causes = sim._materialize(job, with_causes=True)
    windows, deaths = _job_windows_and_deaths(scenario, causes)
    attr = _JobAttribution(windows, deaths,
                           list(job.reshape_causes))
    if job.reshapes:
        levels = sim._job_levels(job, rt)
        report = elastic_goodput_walk(
            rt.ctx, scenario, rt.ctx.resolve_spec(scenario),
            list(job.reshapes), levels, observer=attr.feed,
        )
    else:
        report = predict_goodput(
            rt.perf, scenario, granularity=rt.granularity,
            _ctx=rt.ctx, observer=attr.feed,
        )
    attr.finish(report.wall_time_s)
    # suspension freezes (scheduler wait after a preemption/reclaim
    # kill) become explicit job-lane spans; maintenance/degradation
    # windows already render on the pod lane
    for (w0, w1, _rate, cause) in windows:
        if _stall_bucket(cause) == "stall_suspension":
            attr.spans.append({"name": "suspended", "t0_s": w0,
                               "dur_s": w1 - w0, "cause": cause})
    start = job.start_s or 0.0
    cause_rows = sorted(
        (
            {"cause": c, "total_s": round(sum(b.values()), 9),
             "buckets": {k: round(v, 9) for k, v in sorted(b.items())}}
            for c, b in attr.causes.items()
        ),
        key=lambda r: (-r["total_s"], r["cause"]),
    )
    rec = {
        "name": job.spec.name,
        "template": job.spec.template,
        "state": job.state,
        "chips": rt.world_size,
        "start_s": start,
        "wall_time_s": report.wall_time_s,
        "queue_wait_s": job.queue_wait_s,
        "goodput": report.goodput,
        "buckets": {k: round(attr.buckets[k], 9)
                    for k in FLEET_LEDGER_ORDER},
        "causes": cause_rows,
        "spans": [
            dict(s, t0_s=round(s["t0_s"] + start, 9),
                 dur_s=round(s["dur_s"], 9))
            for s in attr.spans
        ],
    }
    if job.spec.slo_goodput is not None:
        rec["slo_goodput"] = job.spec.slo_goodput
        rec["slo_attained"] = (job.state == "done"
                               and report.goodput
                               >= job.spec.slo_goodput)
    return rec


# --------------------------------------------------------------------------
# Causality-id resolution (id -> the causing trace event)
# --------------------------------------------------------------------------


def resolve_causes(sim) -> Dict[str, Dict[str, Any]]:
    """Every causality id the walk can mint, resolved to the fleet
    trace event it names — the ledger's foreign keys. The golden test
    asserts every id the ledger used resolves here."""
    out: Dict[str, Dict[str, Any]] = {
        _CKPT_CAUSE: {"kind": "checkpoint_policy"},
        _UNATTRIBUTED: {"kind": "unattributed"},
        "useful": {"kind": "useful_train"},
        "sched": {"kind": "scheduler"},
    }
    for wi, w in enumerate(sim.fleet.maintenance):
        out[f"maint:{wi}"] = {
            "kind": "maintenance", "pod": w.pod,
            "start_s": w.start_s, "end_s": w.end_s,
        }
    for wi, w in enumerate(sim.fleet.link_degradations):
        out[f"link:{wi}"] = {
            "kind": "link_degradation", "pod": w.pod, "dim": w.dim,
            "multiplier": w.multiplier,
            "start_s": w.start_s, "end_s": w.end_s,
        }
    for ri, rec in enumerate(sim.fleet.materialize_spot()):
        out[f"spot:{ri}"] = {
            "kind": "spot_reclaim", "pod": rec.pod,
            "start_s": rec.start_s, "chips": rec.chips,
        }
    for job in sim._jobs:
        out[f"preempt:{job.spec.name}"] = {
            "kind": "priority_preemption", "by": job.spec.name,
            "priority": job.spec.priority,
        }
    return out


# --------------------------------------------------------------------------
# Per-pod utilization from the walk's occupancy deltas
# --------------------------------------------------------------------------


def _pod_utilization(sim, makespan: float) -> Dict[str, Any]:
    """Integrate the walk's chip-occupancy deltas per pod over
    ``[0, makespan]``: used and capacity chip-seconds, the
    utilization ratio, and the (t, used_chips) step samples the
    Chrome counter tracks render."""
    out: Dict[str, Any] = {}
    horizon = max(makespan, 0.0)
    for p in sim._pods:
        deltas = sorted(
            (e for e in sim.occupancy if e["pod"] == p.name),
            key=lambda e: e["t"],
        )
        used = 0
        cap = p.chips
        t_prev = 0.0
        used_s = cap_s = 0.0
        samples: List[List[float]] = [[0.0, 0]]
        for e in deltas:
            t = min(max(e["t"], 0.0), horizon)
            used_s += used * (t - t_prev)
            cap_s += cap * (t - t_prev)
            t_prev = t
            used += e.get("used", 0)
            cap += e.get("cap", 0)
            if samples[-1][0] == t:
                samples[-1][1] = used
            else:
                samples.append([round(t, 6), used])
        used_s += used * (horizon - t_prev)
        cap_s += cap * (horizon - t_prev)
        if samples[-1][0] != horizon:
            samples.append([round(horizon, 6), used])
        out[p.name] = {
            "capacity_chips": p.chips,
            "used_chip_s": round(used_s, 6),
            "capacity_chip_s": round(cap_s, 6),
            "utilization": (used_s / cap_s) if cap_s else 0.0,
            "samples": samples,
        }
    return out


# --------------------------------------------------------------------------
# SLO counterfactual probes
# --------------------------------------------------------------------------


def _recost(rt, scenario, spec, reshapes, levels):
    from simumax_tpu.fleet.sim import elastic_goodput_walk
    from simumax_tpu.simulator.faults import predict_goodput

    if reshapes:
        return elastic_goodput_walk(rt.ctx, scenario, spec,
                                    reshapes, levels)
    return predict_goodput(rt.perf, scenario, spec=spec,
                           granularity=rt.granularity, _ctx=rt.ctx)


def _drop_events(scenario, causes, keep):
    """A scenario with only the (event, cause) pairs ``keep`` admits;
    the surviving causes ride along."""
    from simumax_tpu.simulator.faults import FaultScenario

    kept = [(e, c) for e, c in zip(scenario.events, causes)
            if keep(e, c)]
    return FaultScenario(
        events=[e for e, _c in kept],
        horizon_steps=scenario.horizon_steps,
        checkpoint=scenario.checkpoint,
    ), [c for _e, c in kept]


def _probe_bound(rec: Dict[str, Any], change: str) -> Optional[float]:
    """Upper bound on the goodput a probe can reach: useful time is
    invariant under every intervention, and an intervention can at
    best delete the wall-seconds the ledger attributes to what it
    changes. ``None`` = no usable bound (always re-cost)."""
    if rec is None:
        return None
    removable = 0.0
    if change == "checkpoint=young-daly":
        removable = (rec["buckets"]["checkpoint_write"]
                     + rec["buckets"]["restart_replay"])
    elif change == "placement=clean-pods":
        removable = sum(r["total_s"] for r in rec["causes"]
                        if r["cause"].startswith("link:"))
    elif change == "spot=on-demand":
        removable = sum(r["total_s"] for r in rec["causes"]
                        if r["cause"].startswith("spot:"))
    elif change == "priority=bump":
        removable = sum(r["total_s"] for r in rec["causes"]
                        if r["cause"].startswith("preempt:"))
    else:
        return None
    useful = rec["buckets"]["useful_train"]
    denom = rec["wall_time_s"] - removable
    return (useful / denom) if denom > 0 else 1.0


def slo_counterfactuals(sim, jobs=None,
                        attribution: Optional[Dict[str, dict]] = None
                        ) -> List[Dict[str, Any]]:
    """The what-if probe table for SLO-missing jobs: re-cost cheap
    counterfactual policy changes through the shared per-template
    replay context (cache-hot, so each probe is near-free) and flag
    the first probe in cheapness order that recovers the SLO as
    ``cheapest_fix``. Starved jobs (never completed) get a probe row
    naming the admission-side fix instead of a re-cost.

    ``attribution`` (``{job_name: per-job ledger record}``, supplied
    by :func:`build_fleet_explain`) enables bound pruning: a probe
    whose :func:`_probe_bound` upper bound is already below the SLO
    is reported with ``goodput_bound`` instead of paying a re-cost —
    the bound is exact ("useful time is invariant; at best the probe
    deletes its own attributed seconds"), so pruned probes are
    provably non-recovering.

    Probe failures from genuinely infeasible counterfactuals
    (``SimuMaxError`` family, ``ValueError``) become rows with an
    ``error`` field; ``AssertionError`` stays loud (estimator-bug
    policy, same as ``memledger.whatif_probes``)."""
    from simumax_tpu.core.errors import SimuMaxError
    from simumax_tpu.observe.telemetry import get_registry
    from simumax_tpu.simulator.faults import FaultEvent, FaultScenario

    reg = get_registry()
    probes: List[Dict[str, Any]] = []
    for job in (jobs if jobs is not None else sim._jobs):
        slo = job.spec.slo_goodput
        if slo is None:
            continue
        if (job.state == "done" and job.report is not None
                and job.report["goodput"] >= slo):
            continue
        if job.report is None or job.state != "done":
            probes.append({
                "job": job.spec.name, "slo": slo,
                "change": "priority=bump", "recovers": None,
                "error": f"starved (state={job.state}): never "
                         "completed, nothing to re-cost — admission "
                         "or priority is the lever",
            })
            reg.counter("fleet_probes_total", outcome="starved").inc()
            continue
        rt = sim._runtimes[job.spec.template]
        scenario, causes = sim._materialize(job, with_causes=True)
        reshapes = list(job.reshapes)
        levels = sim._job_levels(job, rt)
        spec = rt.ctx.resolve_spec(scenario)
        base_goodput = job.report["goodput"]
        h = job.report["healthy_step_s"]
        ckpt = job.report["checkpoint"]
        candidates: List[tuple] = []
        # 1. checkpoint interval = Young-Daly optimal from the job's
        #    OBSERVED failure rate (PR-5's closed form; zero observed
        #    restarts means MTBF -> inf, i.e. no mid-run writes)
        n_restarts = job.report["n_restarts"]
        if h > 0:
            if n_restarts > 0:
                mtbf = job.report["wall_time_s"] / n_restarts
                yd = max(1, int(round(
                    math.sqrt(2.0 * ckpt["write_s"] * mtbf) / h)))
            else:
                yd = scenario.horizon_steps
            if yd != spec.interval_steps:
                import dataclasses as _dc

                spec_yd = _dc.replace(spec, interval_steps=yd)
                candidates.append((
                    "checkpoint=young-daly",
                    f"interval {spec.interval_steps} -> {yd} steps",
                    scenario, causes, spec_yd, reshapes, levels,
                ))
        # 2. placement excluding degraded pods: the job's
        #    link-degradation windows vanish
        if any(e.kind == "link_degradation" for e in scenario.events):
            sc2, c2 = _drop_events(
                scenario, causes,
                lambda e, c: e.kind != "link_degradation")
            candidates.append((
                "placement=clean-pods",
                "drop all link-degradation windows",
                sc2, c2, spec, reshapes, levels,
            ))
        # 3. on-demand instead of spot: every spot-reclaim
        #    consequence (kills, freezes, reshapes) vanishes
        spot_reshapes = any(c.startswith("spot:")
                            for c in job.reshape_causes)
        if (any(c.startswith("spot:") for c in causes)
                or spot_reshapes):
            sc3, c3 = _drop_events(
                scenario, causes,
                lambda e, c: not c.startswith("spot:"))
            rs3 = [] if spot_reshapes else reshapes
            lv3 = {} if spot_reshapes else levels
            candidates.append((
                "spot=on-demand",
                "drop all spot-reclaim consequences",
                sc3, c3, spec, rs3, lv3,
            ))
        # 4. priority bump: preemption kills + suspension waits by
        #    higher-priority arrivals vanish
        if any(c.startswith("preempt:") for c in causes):
            sc4, c4 = _drop_events(
                scenario, causes,
                lambda e, c: not c.startswith("preempt:"))
            candidates.append((
                "priority=bump",
                "drop all priority-preemption consequences",
                sc4, c4, spec, reshapes, levels,
            ))
        # 5. elastic off: each reshape becomes a rank death at the
        #    same instant and the job walks the rollback-restart
        #    path (documented approximation: the dead rank is the
        #    base-world rank 0 of the dropped replica set)
        if reshapes:
            ev5 = list(scenario.events) + [
                FaultEvent("rank_death", start_ms=t_r * 1e3, rank=0)
                for (t_r, _reps) in reshapes
            ]
            order = sorted(range(len(ev5)),
                           key=lambda i: ev5[i].start_ms)
            sc5 = FaultScenario(
                events=[ev5[i] for i in order],
                horizon_steps=scenario.horizon_steps,
                checkpoint=scenario.checkpoint,
            )
            candidates.append((
                "elastic=off",
                "rollback-restart instead of dp shrink",
                sc5, None, spec, [], {},
            ))
        candidates.sort(key=lambda c: _PROBE_ORDER.index(c[0]))
        rec = (attribution or {}).get(job.spec.name)
        for (change, detail, sc, _c, sp, rs, lv) in candidates:
            row: Dict[str, Any] = {
                "job": job.spec.name, "slo": slo, "change": change,
                "detail": detail,
                "baseline_goodput": base_goodput,
            }
            bound = _probe_bound(rec, change)
            if bound is not None and bound < slo:
                row["goodput_bound"] = bound
                row["recovers"] = False
                reg.counter("fleet_probes_total",
                            outcome="no").inc()
                probes.append(row)
                continue
            try:
                rep = _recost(rt, sc, sp, rs, lv)
                row["goodput"] = rep.goodput
                row["recovers"] = rep.goodput >= slo
                reg.counter(
                    "fleet_probes_total",
                    outcome="recovers" if row["recovers"] else "no",
                ).inc()
            except (SimuMaxError, ValueError) as exc:
                row["recovers"] = False
                row["error"] = f"{type(exc).__name__}: {exc}"
                reg.counter("fleet_probes_total",
                            outcome="error").inc()
            probes.append(row)
            if row["recovers"]:
                # candidates run cheapest-first, so the first
                # recovering probe IS the answer; pricier
                # interventions are moot and never re-costed
                row["cheapest_fix"] = True
                break
    return probes


# --------------------------------------------------------------------------
# The explain payload
# --------------------------------------------------------------------------


def build_fleet_ledger(sim) -> Dict[str, Any]:
    """The causal goodput ledger of a finished fleet walk: per-job
    attribution records plus chip-second-weighted fleet roll-ups
    (waterfall, per-template loss profile, per-pod utilization,
    per-cause totals)."""
    from simumax_tpu.observe.telemetry import get_registry

    reg = get_registry()
    per_job: List[Dict[str, Any]] = []
    fleet_buckets = {k: 0.0 for k in FLEET_LEDGER_ORDER}
    fleet_causes: Dict[str, Dict[str, float]] = {}
    per_template: Dict[str, Dict[str, Any]] = {}
    total_chip_s = 0.0
    makespan = 0.0
    for job in sim._jobs:
        rec = attribute_job(sim, job)
        if rec is None:
            rt = sim._runtimes.get(job.spec.template)
            per_job.append({
                "name": job.spec.name,
                "template": job.spec.template,
                "state": job.state,
                "chips": rt.world_size if rt else 0,
                "wall_time_s": 0.0,
                "queue_wait_s": job.queue_wait_s,
                "buckets": {k: 0.0 for k in FLEET_LEDGER_ORDER},
                "causes": [], "spans": [],
            })
            continue
        per_job.append(rec)
        reg.counter("fleet_explain_jobs_total").inc()
        if job.state != "done":
            continue
        chips = rec["chips"]
        makespan = max(makespan,
                       rec["start_s"] + rec["wall_time_s"])
        total_chip_s += rec["wall_time_s"] * chips
        tpl = per_template.setdefault(rec["template"], {
            "jobs": 0, "chip_s": 0.0,
            "buckets": {k: 0.0 for k in FLEET_LEDGER_ORDER},
        })
        tpl["jobs"] += 1
        tpl["chip_s"] += rec["wall_time_s"] * chips
        for k, v in rec["buckets"].items():
            fleet_buckets[k] += v * chips
            tpl["buckets"][k] += v * chips
        for row in rec["causes"]:
            per = fleet_causes.setdefault(row["cause"], {})
            for k, v in row["buckets"].items():
                per[k] = per.get(k, 0.0) + v * chips
    events = resolve_causes(sim)
    cause_rows = sorted(
        (
            {
                "cause": c,
                "event": events.get(c, {"kind": "unknown"}),
                "chip_s": round(sum(b.values()), 6),
                "buckets": {k: round(v, 6)
                            for k, v in sorted(b.items())},
            }
            for c, b in fleet_causes.items()
        ),
        key=lambda r: (-r["chip_s"], r["cause"]),
    )
    for tpl in per_template.values():
        tpl["chip_s"] = round(tpl["chip_s"], 6)
        tpl["buckets"] = {k: round(v, 6)
                          for k, v in tpl["buckets"].items()}
    return {
        # the PR-3 waterfall shape, chip-second weighted
        "order": list(FLEET_LEDGER_ORDER),
        "buckets": {k: round(fleet_buckets[k], 6)
                    for k in FLEET_LEDGER_ORDER},
        "total_chip_s": round(total_chip_s, 6),
        "makespan_s": makespan,
        "per_job": per_job,
        "per_template": dict(sorted(per_template.items())),
        "per_pod": _pod_utilization(sim, makespan),
        "causes": cause_rows,
    }


def build_fleet_explain(sim) -> Dict[str, Any]:
    """The report's ``explain`` payload: ledger + probe table + the
    causality-id resolution table. Computed strictly AFTER the walk
    from state the walk records unconditionally, so the base payload
    cannot depend on whether explain ran."""
    if sim.report is None:
        raise ConfigError(
            "build_fleet_explain needs a finished walk: call run() "
            "first", phase="fleet",
        )
    ledger = build_fleet_ledger(sim)
    attribution = {r["name"]: r for r in ledger["per_job"]
                   if r.get("wall_time_s")}
    return {
        "schema": "simumax-fleet-explain-v1",
        "ledger": ledger,
        "probes": slo_counterfactuals(sim, attribution=attribution),
        "events": resolve_causes(sim),
    }


# --------------------------------------------------------------------------
# Rendering
# --------------------------------------------------------------------------


def fleet_waterfall_lines(ledger: Dict[str, Any]) -> List[str]:
    """Chip-second-weighted fleet waterfall (the PR-3 rendering
    idiom over ``FLEET_LEDGER_ORDER``)."""
    total = ledger["total_chip_s"] or 1.0
    width = max(len(k) for k in ledger["order"])
    lines = [
        f"== fleet goodput waterfall: {total:.0f} chip-seconds over "
        f"{sum(1 for j in ledger['per_job'] if j['state'] == 'done')}"
        f" completed jobs =="
    ]
    for key in ledger["order"]:
        v = ledger["buckets"][key]
        pct = round(100.0 * v / total, 2) + 0.0
        lines.append(
            f"  {key:<{width}}  {v:14.1f} chip-s  {pct:6.2f}%"
        )
    lines.append(
        f"  {'= occupied':<{width}}  {total:14.1f} chip-s  100.00%"
    )
    return lines


def _describe_event(ev: Dict[str, Any]) -> str:
    kind = ev.get("kind", "unknown")
    if kind == "maintenance":
        return (f"maintenance {ev['pod']} "
                f"[{ev['start_s']:.0f}, {ev['end_s']:.0f})s")
    if kind == "link_degradation":
        return (f"degradation {ev['pod']} {ev['dim']} "
                f"x{ev['multiplier']:.2f} "
                f"[{ev['start_s']:.0f}, {ev['end_s']:.0f})s")
    if kind == "spot_reclaim":
        return (f"reclaim {ev['pod']} -{ev['chips']} chips "
                f"@{ev['start_s']:.0f}s")
    if kind == "priority_preemption":
        return f"preemption by {ev['by']}"
    if kind == "checkpoint_policy":
        return "checkpoint policy (periodic writes)"
    return kind


def fleet_explain_lines(report: Dict[str, Any],
                        top_causes: int = 8,
                        top_probes: int = 12) -> List[str]:
    """Human rendering of the explain payload: the chip-second
    waterfall, the top causes table, per-pod utilization, and the
    SLO counterfactual probe table."""
    explain = report.get("explain")
    if not explain:
        raise ConfigError(
            "report has no 'explain' payload (run the fleet walk "
            "with explain=True / --explain)", phase="fleet",
        )
    ledger = explain["ledger"]
    lines = fleet_waterfall_lines(ledger)
    loss = [r for r in ledger["causes"] if r["cause"] != "useful"]
    if loss:
        lines.append(f"  -- top loss causes ({len(loss)} events) --")
        for r in loss[:top_causes]:
            worst = max(r["buckets"], key=lambda k: r["buckets"][k])
            lines.append(
                f"  {r['chip_s']:12.1f} chip-s  {r['cause']:<16} "
                f"{_describe_event(r['event'])} (mostly {worst})"
            )
    lines.append("  -- per-pod utilization --")
    for pod, u in ledger["per_pod"].items():
        lines.append(
            f"  {pod}: {100.0 * u['utilization']:6.2f}% of "
            f"{u['capacity_chip_s']:.0f} chip-s"
        )
    probes = explain["probes"]
    if probes:
        lines.append(
            "  -- SLO counterfactual probes (shared-context "
            "re-costs) --"
        )
        for p in probes[:top_probes]:
            if "error" in p:
                lines.append(
                    f"    {p['job']}: {p['change']:<22} "
                    f"{p['error']}"
                )
                continue
            if "goodput_bound" in p:
                lines.append(
                    f"    {p['job']}: {p['change']:<22} pruned — "
                    f"upper bound {100.0 * p['goodput_bound']:.2f}% "
                    f"< SLO {100.0 * p['slo']:.0f}% (cannot recover)"
                )
                continue
            star = ("  <- cheapest SLO fix"
                    if p.get("cheapest_fix") else "")
            verdict = "recovers" if p["recovers"] else "still miss"
            lines.append(
                f"    {p['job']}: {p['change']:<22} goodput "
                f"{100.0 * p['baseline_goodput']:.2f}% -> "
                f"{100.0 * p['goodput']:.2f}% vs SLO "
                f"{100.0 * p['slo']:.0f}% ({verdict}){star}"
            )
        if len(probes) > top_probes:
            lines.append(f"    ... {len(probes) - top_probes} more")
    return lines


# --------------------------------------------------------------------------
# Fleet report diffing
# --------------------------------------------------------------------------


def diff_fleet_reports(a: Dict[str, Any], b: Dict[str, Any],
                       top: int = 10) -> Dict[str, Any]:
    """Structured diff of two ``simumax-fleet-v1`` reports (A -> B):
    headline deltas, per-job goodput movers, and — when both carry an
    explain payload — the fleet-bucket chip-second deltas."""
    for name, r in (("A", a), ("B", b)):
        if r.get("schema") != "simumax-fleet-v1":
            raise ConfigError(
                f"diff input {name} is not a simumax-fleet-v1 "
                f"report (schema={r.get('schema')!r})", phase="fleet",
            )
    headline = {
        k: {"a": a[k], "b": b[k], "delta": b[k] - a[k]}
        for k in ("fleet_goodput", "chip_utilization", "makespan_s")
    }
    headline["slo_fraction"] = {
        "a": a["slo"]["fraction"], "b": b["slo"]["fraction"],
        "delta": b["slo"]["fraction"] - a["slo"]["fraction"],
    }
    ja = {j["name"]: j for j in a["jobs"]}
    jb = {j["name"]: j for j in b["jobs"]}
    movers = []
    for name in sorted(set(ja) & set(jb)):
        ga = (ja[name]["report"] or {}).get("goodput")
        gb = (jb[name]["report"] or {}).get("goodput")
        if ga is None and gb is None:
            continue
        movers.append({
            "job": name, "a": ga, "b": gb,
            "delta": (gb or 0.0) - (ga or 0.0),
        })
    movers.sort(key=lambda m: (-abs(m["delta"]), m["job"]))
    out: Dict[str, Any] = {
        "headline": headline,
        "jobs": movers[:top],
        "only_a": sorted(set(ja) - set(jb)),
        "only_b": sorted(set(jb) - set(ja)),
    }
    la = (a.get("explain") or {}).get("ledger")
    lb = (b.get("explain") or {}).get("ledger")
    if la and lb:
        out["buckets"] = {
            k: {
                "a": la["buckets"].get(k, 0.0),
                "b": lb["buckets"].get(k, 0.0),
                "delta": (lb["buckets"].get(k, 0.0)
                          - la["buckets"].get(k, 0.0)),
            }
            for k in FLEET_LEDGER_ORDER
        }
    return out


def format_fleet_diff_lines(diff: Dict[str, Any],
                            top: int = 10) -> List[str]:
    """Human rendering of :func:`diff_fleet_reports`."""
    h = diff["headline"]
    lines = [
        "== fleet diff (A -> B) ==",
        f"  fleet goodput {100.0 * h['fleet_goodput']['a']:.2f}% -> "
        f"{100.0 * h['fleet_goodput']['b']:.2f}% "
        f"({100.0 * h['fleet_goodput']['delta']:+.2f}pp)",
        f"  chip utilization "
        f"{100.0 * h['chip_utilization']['a']:.2f}% -> "
        f"{100.0 * h['chip_utilization']['b']:.2f}% "
        f"({100.0 * h['chip_utilization']['delta']:+.2f}pp)",
        f"  makespan {h['makespan_s']['a']:.1f}s -> "
        f"{h['makespan_s']['b']:.1f}s "
        f"({h['makespan_s']['delta']:+.1f}s)",
        f"  SLO attainment "
        f"{100.0 * h['slo_fraction']['a']:.1f}% -> "
        f"{100.0 * h['slo_fraction']['b']:.1f}% "
        f"({100.0 * h['slo_fraction']['delta']:+.1f}pp)",
    ]
    if diff.get("buckets"):
        lines.append("  -- fleet bucket deltas (chip-s) --")
        for k, d in diff["buckets"].items():
            if abs(d["delta"]) < 1e-9:
                continue
            lines.append(
                f"  {k:<18} {d['a']:12.1f} -> {d['b']:12.1f} "
                f"({d['delta']:+12.1f})"
            )
    if diff["jobs"]:
        lines.append("  -- top per-job goodput movers --")
        for m in diff["jobs"][:top]:
            fa = (f"{100.0 * m['a']:.2f}%" if m["a"] is not None
                  else "n/a")
            fb = (f"{100.0 * m['b']:.2f}%" if m["b"] is not None
                  else "n/a")
            lines.append(
                f"  {m['job']:<20} {fa:>8} -> {fb:>8} "
                f"({100.0 * m['delta']:+.2f}pp)"
            )
    for side, names in (("A", diff["only_a"]), ("B", diff["only_b"])):
        if names:
            lines.append(f"  only in {side}: {', '.join(names)}")
    return lines


# --------------------------------------------------------------------------
# Fleet Chrome-trace export
# --------------------------------------------------------------------------

_SPAN_COLORS = {
    "run": "good",
    "checkpoint": "thread_state_runnable",
    "rollback": "terrible",
    "reshape": "thread_state_iowait",
    "suspended": "bad",
    "maintenance": "bad",
    "degradation": "thread_state_iowait",
    "reclaim": "terrible",
}


def fleet_chrome_trace(report: Dict[str, Any]) -> dict:
    """Fleet timeline in the Chrome trace-event format (the same
    viewer as the pipeline traces): one pid per pod (lane 0 shows the
    pod's maintenance/degradation/reclaim windows, one lane per job
    homed there), job spans from the attribution ledger, flow arrows
    from each causing window to the rollback/reshape/checkpoint span
    it produced, per-pod used-chip counter tracks and the running
    fleet-goodput counter. Requires the report's ``explain``
    payload (built from its span records alone, so cached explain
    payloads re-export identically)."""
    explain = report.get("explain")
    if not explain:
        raise ConfigError(
            "fleet_chrome_trace needs the report's 'explain' payload "
            "(simulate_fleet(..., explain=True) / fleet --explain)",
            phase="fleet",
        )
    ledger = explain["ledger"]
    events_tbl = explain["events"]
    pods = sorted(ledger["per_pod"])
    pod_pid = {name: i for i, name in enumerate(pods)}
    fleet_pid = len(pods)
    out: List[dict] = []
    for name in pods:
        out.append({"ph": "M", "pid": pod_pid[name],
                    "name": "process_name",
                    "args": {"name": f"pod {name}"}})
        out.append({"ph": "M", "pid": pod_pid[name], "tid": 0,
                    "name": "thread_name",
                    "args": {"name": "fleet events"}})
    out.append({"ph": "M", "pid": fleet_pid, "name": "process_name",
                "args": {"name": "fleet"}})
    # pod window spans (the flow-arrow sources), keyed by cause id
    window_span: Dict[str, tuple] = {}
    for cause, ev in sorted(events_tbl.items()):
        kind = ev.get("kind")
        if kind == "maintenance":
            pid, t0 = pod_pid[ev["pod"]], ev["start_s"]
            dur, name = ev["end_s"] - t0, f"maintenance [{cause}]"
            color = _SPAN_COLORS["maintenance"]
        elif kind == "link_degradation":
            pid, t0 = pod_pid[ev["pod"]], ev["start_s"]
            dur = ev["end_s"] - t0
            name = (f"degradation {ev['dim']} "
                    f"x{ev['multiplier']:.2f} [{cause}]")
            color = _SPAN_COLORS["degradation"]
        elif kind == "spot_reclaim":
            pid, t0 = pod_pid[ev["pod"]], ev["start_s"]
            dur = 0.0
            name = f"reclaim -{ev['chips']} chips [{cause}]"
            color = _SPAN_COLORS["reclaim"]
        else:
            continue
        window_span[cause] = (pid, 0, t0)
        out.append({
            "ph": "X", "pid": pid, "tid": 0, "name": name,
            "ts": t0 * 1e6, "dur": max(dur, 0.0) * 1e6,
            "cname": color, "args": {"cause": cause},
        })
    # job lanes: homed on the first pod of the admission placement
    job_home: Dict[str, str] = {}
    for d in report["decisions"]:
        if d["event"] == "admitted" and d.get("pods"):
            job_home.setdefault(d["job"], d["pods"][0])
    lane_counter = {name: 0 for name in pods}
    job_lane: Dict[str, tuple] = {}
    for j in ledger["per_job"]:
        home = job_home.get(j["name"])
        if home is None:
            continue  # never admitted: no lane
        lane_counter[home] += 1
        tid = lane_counter[home]
        job_lane[j["name"]] = (pod_pid[home], tid)
        out.append({"ph": "M", "pid": pod_pid[home], "tid": tid,
                    "name": "thread_name",
                    "args": {"name": f"job {j['name']}"}})
    flow_id = 0
    for j in ledger["per_job"]:
        lane = job_lane.get(j["name"])
        if lane is None:
            continue
        pid, tid = lane
        for s in j["spans"]:
            args = {"job": j["name"]}
            if s.get("cause"):
                args["cause"] = s["cause"]
            out.append({
                "ph": "X", "pid": pid, "tid": tid, "name": s["name"],
                "ts": s["t0_s"] * 1e6,
                "dur": max(s["dur_s"], 0.0) * 1e6,
                "cname": _SPAN_COLORS.get(s["name"]),
                "args": args,
            })
            cause = s.get("cause", "")
            src = window_span.get(cause)
            if src is None and cause.startswith("preempt:"):
                # preemptions have no pod window: the arrow starts on
                # the preemptor job's own lane at the instant it hits
                pl = job_lane.get(cause[len("preempt:"):])
                if pl is not None:
                    src = (pl[0], pl[1], s["t0_s"])
            if src is not None and s["name"] != "run":
                flow_id += 1
                spid, stid, st0 = src
                out.append({"ph": "s", "pid": spid, "tid": stid,
                            "id": flow_id, "name": "cause",
                            "cat": "cause", "ts": st0 * 1e6})
                out.append({"ph": "f", "pid": pid, "tid": tid,
                            "id": flow_id, "name": "cause",
                            "cat": "cause", "ts": s["t0_s"] * 1e6,
                            "bp": "e"})
    # per-pod used-chip counters
    for name in pods:
        for (t, used) in ledger["per_pod"][name]["samples"]:
            out.append({
                "ph": "C", "pid": pod_pid[name], "name": "used_chips",
                "ts": t * 1e6, "args": {"chips": max(used, 0)},
            })
    # running fleet goodput: cumulative chip-weighted over completions
    done = sorted(
        (j for j in report["jobs"]
         if j["state"] == "done" and j["report"] is not None),
        key=lambda j: (j["completed_s"], j["name"]),
    )
    useful = wall = 0.0
    out.append({"ph": "C", "pid": fleet_pid, "name": "fleet_goodput_pct",
                "ts": 0.0, "args": {"pct": 0.0}})
    for j in done:
        useful += j["report"]["useful_time_s"] * j["chips"]
        wall += j["report"]["wall_time_s"] * j["chips"]
        out.append({
            "ph": "C", "pid": fleet_pid, "name": "fleet_goodput_pct",
            "ts": j["completed_s"] * 1e6,
            "args": {"pct": round(100.0 * useful / wall, 4)
                     if wall else 0.0},
        })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_fleet_trace(report: Dict[str, Any], path: str) -> str:
    import json

    with open(path, "w", encoding="utf-8") as f:
        json.dump(fleet_chrome_trace(report), f)
    return path


__all__ = [
    "FLEET_LEDGER_ORDER",
    "attribute_job",
    "build_fleet_ledger",
    "build_fleet_explain",
    "slo_counterfactuals",
    "resolve_causes",
    "fleet_waterfall_lines",
    "fleet_explain_lines",
    "diff_fleet_reports",
    "format_fleet_diff_lines",
    "fleet_chrome_trace",
    "write_fleet_trace",
]

"""Critical-path engine for the discrete-event simulator (the tentpole
of ISSUE 7): slack, blame, and sim-vs-analytical divergence.

The DES (``simulator/engine.py``) emits a makespan and a Chrome trace
with no account of *which* events determined it. This module closes
that gap with classic critical-path analysis of the event-dependency
graph — the technique behind Holistic Trace Analysis and Chrome's
tracing blame model:

* :class:`DependencySkeleton` — the engine's optional ``dep_recorder``:
  a compact, bounded record of the event-dependency graph built while
  the run streams (program order per rank, rendezvous joins, p2p
  send -> recv edges, async-stream joins, fault perturbations). It
  retains only flat scalar arrays + predecessor id tuples, so it works
  unchanged under ``StreamingTraceWriter`` (trace events leave the
  process; the skeleton stays).
* :func:`analyze` — the post-pass: per-event slack (how much an event
  could stretch before the makespan moves), the cross-rank critical
  path (binding-predecessor walk from the makespan rank's final
  event), and the **simulated waterfall** — the reference (binding)
  stage's timeline blame-decomposed into compute / recompute /
  exposed comm per dim / pipeline bubble / DP+optimizer sync / fault
  / straggler, summing to the reported DES makespan within 1e-6 (the
  simulated twin of ``observe/ledger.py::build_waterfall``, sharing
  its anchor-stage semantics; blocked gaps are blamed through the
  binding dependency, HTA-style).
* :func:`diverge` — aligns the simulated waterfall bucket-by-bucket
  with the analytical one and names the top disagreeing
  ops/collectives: divergence localizes which efficiency-table entries
  or comm terms drift (the calibration-drift signal for ROADMAP item
  5's device-free calibration).
* :func:`diff_critpath` / :func:`format_critpath_diff_lines` — compare
  two saved reports (two strategies, or healthy vs fault scenario).

Graph model. Every recorded node ``j`` carries its observed ``start``
/ ``end`` and the predecessor set that determined it. With
``S_j = max(end of preds)`` (the join) and ``W_j = end_j - S_j`` (own
work beyond the binding dependency), delaying a predecessor by ``d``
moves ``j`` iff the delayed end exceeds ``S_j`` — the max-plus
semantics of rendezvous. The backward pass computes the latest
allowed end ``L_j`` (``L = makespan`` at the sinks;
``L_p = min(L_j - W_j)`` over successors ``j``) and
``slack_j = L_j - end_j``. Walking binding predecessors from the
makespan rank's final event telescopes exactly: consecutive path
nodes satisfy ``end_j = end_pred + W_j``, so the path works sum to
the makespan up to float reassociation.

Under rank-symmetry reduction (``simulator/reduce.py``) the skeleton
is recorded over class representatives; expansion maps engine ranks to
representative global ranks (class reps are each class's smallest
member, and binding ties break toward smaller ranks, so the reduced
path expands bit-identically to the exact full-world path — asserted
on the parity grid in ``tests/test_critpath.py``).

Consumers: ``simumax_tpu critical-path``, ``perf --simulate
--critical-path``, ``diff --critical-path``; schema and a worked
triage example in ``docs/observability.md``.
"""

from __future__ import annotations

import json
import math
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

from simumax_tpu.core.errors import ConfigError
from simumax_tpu.core.records import CritSegment

CRITPATH_SCHEMA = "simumax-critpath-v1"

#: fixed (non-comm) simulated-waterfall buckets, in presentation order;
#: ``comm:<dim>`` buckets sort between ``recompute`` and
#: ``pipeline_bubble`` (see :func:`_waterfall_order`)
_FIXED_HEAD = ("compute", "recompute")
_FIXED_TAIL = ("pipeline_bubble", "dp_optimizer_sync", "fault", "straggler")

#: step-tail event names charged to the DP/optimizer bucket (the
#: simulated twin of the analytical ``dp_optimizer_sync``)
_DP_NAMES = ("adam_step", "optimizer_barrier", "tied_embedding_grad")
_DP_PREFIXES = ("grad_rs_", "param_ag_")


_KEY_DIM = None


def _dim_of(key) -> Optional[str]:
    global _KEY_DIM
    if _KEY_DIM is None:  # lazy: avoids an import-machinery hit per call
        from simumax_tpu.simulator.faults import key_dim

        _KEY_DIM = key_dim
    return _KEY_DIM(key)


class DependencySkeleton:
    """Bounded event-dependency recorder, plugged into the engine as
    ``dep_recorder``. Purely observational: recorder-on and
    recorder-off runs are bit-identical (asserted in tests).

    Nodes live in flat parallel lists; predecessor ids always precede
    the node (creation order is a topological order), so the backward
    pass is a single reverse sweep. ``emit_idx`` mirrors the engine's
    per-rank emitted-event counter (-1 for non-emitted bookkeeping
    nodes such as clock advances and stream joins), which is what lets
    a post-pass annotate Chrome-trace events by ``(rank, emit index)``
    without retaining the events themselves."""

    def __init__(self):
        self.rank: List[int] = []
        self.name: List[str] = []
        self.kind: List[str] = []  # compute|comm|p2p|wait|fault|advance|join|trace
        self.lane: List[str] = []
        self.start: List[float] = []
        self.end: List[float] = []
        self.extra: List[float] = []  # fault-injected seconds within the span
        self.dim: List[Optional[str]] = []
        self.link: List[Optional[Tuple[int, int]]] = []  # p2p (src, dst)
        self.emit_idx: List[int] = []
        self.preds: List[tuple] = []
        self.adv: List[bool] = []  # clock-advancing (tail-chain) node
        #: program-order frontier per rank (last clock-advancing node)
        self._tail: Dict[int, int] = {}
        self._emit_count: Dict[int, int] = {}
        # transient join bookkeeping (deleted as soon as consumed —
        # the bounded-memory contract mirrors the engine's own)
        self._coll_arrivals: Dict[tuple, Dict[int, int]] = {}
        self._send_nodes: Dict[tuple, int] = {}
        self._recv_posts: Dict[tuple, int] = {}
        self._async_posts: Dict[tuple, Dict[int, int]] = {}
        self._async_tmp: Dict[tuple, Tuple[tuple, List[int]]] = {}
        self._chain_prev: Dict[tuple, int] = {}
        self._pending_async: Dict[int, List[int]] = {}

    def __len__(self) -> int:
        return len(self.rank)

    # -- node construction -------------------------------------------------
    def _node(self, rank: int, name: str, kind: str, lane: str,
              start: float, end: float, preds, *, emitted: bool,
              advance_tail: bool, extra: float = 0.0,
              dim: Optional[str] = None,
              link: Optional[Tuple[int, int]] = None) -> int:
        i = len(self.rank)
        self.rank.append(rank)
        self.name.append(sys.intern(name))
        self.kind.append(kind)  # call sites pass literals (interned)
        self.lane.append(lane)
        self.start.append(start)
        self.end.append(end)
        self.extra.append(extra)
        self.dim.append(sys.intern(dim) if dim else None)
        self.link.append(link)
        if emitted:
            c = self._emit_count.get(rank, 0)
            self.emit_idx.append(c)
            self._emit_count[rank] = c + 1
        else:
            self.emit_idx.append(-1)
        ps = []
        for p in preds:
            if p is not None and p >= 0:
                ps.append(p)
        self.preds.append(tuple(ps))
        self.adv.append(advance_tail)
        if advance_tail:
            self._tail[rank] = i
        return i

    def _t(self, rank: int) -> int:
        return self._tail.get(rank, -1)

    # -- engine hooks (call order mirrors engine emission order) -----------
    def on_compute(self, rank, name, lane, start, end, extra):
        # the hottest hook (every leaf fwd/bwd span lands here):
        # hand-inlined _node, measured at ~2x the generic path
        i = len(self.rank)
        self.rank.append(rank)
        self.name.append(sys.intern(name))
        self.kind.append("compute")
        self.lane.append(lane)
        self.start.append(start)
        self.end.append(end)
        self.extra.append(extra)
        self.dim.append(None)
        self.link.append(None)
        c = self._emit_count.get(rank, 0)
        self.emit_idx.append(c)
        self._emit_count[rank] = c + 1
        t = self._tail.get(rank, -1)
        self.preds.append((t,) if t >= 0 else ())
        self.adv.append(True)
        self._tail[rank] = i

    def on_advance(self, rank, start, end):
        self._node(rank, "advance", "advance", "comp", start, end,
                   (self._t(rank),), emitted=False, advance_tail=True)

    def on_trace(self, rank, name, start, end):
        # zero-advance visibility span: no successors, never on the
        # path, excluded from the backward pass (its end may exceed
        # the rank's clock by design)
        self._node(rank, name, "trace", "comm", start, end,
                   (self._t(rank),), emitted=True, advance_tail=False)

    def on_coll_arrive(self, ckey, rank):
        self._coll_arrivals.setdefault(ckey, {})[rank] = self._t(rank)

    def on_coll_serve(self, ckey, key, rank, name, start, end, extra,
                      dead_peers):
        arrivals = self._coll_arrivals.get(ckey, {})
        preds = list(arrivals.values())
        for p in dead_peers:
            preds.append(self._t(p))  # the dead peer's death node
        if rank not in arrivals:
            preds.append(self._t(rank))
        self._node(rank, name, "comm", "comm", start, end, preds,
                   emitted=True, advance_tail=True, extra=extra,
                   dim=_dim_of(key))

    def on_coll_done(self, ckey):
        self._coll_arrivals.pop(ckey, None)

    def on_send(self, skey, rank, name, lane, start, end, extra,
                advance_tail, rendezvous):
        preds = [self._t(rank)]
        if rendezvous:
            preds.append(self._recv_posts.get(skey))
        node = self._node(rank, name, "p2p", lane, start, end, preds,
                          emitted=True, advance_tail=advance_tail,
                          extra=extra, dim="pp", link=(skey[0], skey[1]))
        self._send_nodes[skey] = node

    def on_recv_post(self, skey, rank):
        self._recv_posts[skey] = self._t(rank)

    def on_recv_serve(self, skey, rank, name, start, end, emitted):
        preds = (self._t(rank), self._send_nodes.pop(skey, None))
        self._recv_posts.pop(skey, None)
        self._node(rank, f"wait_{name}", "wait", "wait", start, end,
                   preds, emitted=emitted, advance_tail=True,
                   dim="pp", link=(skey[0], skey[1]))

    def on_sendrecv_serve(self, rank, name, start, end, in_key, out_key,
                          emitted):
        preds = [self._t(rank)]
        link = None
        if in_key is not None:
            preds.append(self._send_nodes.pop(in_key, None))
            self._recv_posts.pop(in_key, None)
            link = (in_key[0], in_key[1])
        if out_key is not None:
            # own outbound publish + the peer's recv-post marker (the
            # rendezvous half of a send-only batched pair)
            preds.append(self._send_nodes.get(out_key))
            preds.append(self._recv_posts.get(out_key))
            if link is None:
                link = (out_key[0], out_key[1])
        self._node(rank, name, "wait", "wait", start, end, preds,
                   emitted=emitted, advance_tail=True, dim="pp",
                   link=link)

    def on_async_post(self, ckey, rank):
        self._async_posts.setdefault(ckey, {})[rank] = self._t(rank)

    def on_async_finish_peer(self, ckey, chain_key, name, start, end,
                             peer, extra):
        preds = list(self._async_posts.get(ckey, {}).values())
        prev = self._chain_prev.get(chain_key)
        if prev is not None:
            preds.append(prev)
        node = self._node(peer, name, "comm", "comm", start, end, preds,
                          emitted=True, advance_tail=False, extra=extra,
                          dim=_dim_of(chain_key[0]))
        self._pending_async.setdefault(peer, []).append(node)
        self._async_tmp.setdefault(ckey, (chain_key, []))[1].append(node)

    def on_async_done(self, ckey):
        tmp = self._async_tmp.pop(ckey, None)
        if tmp is not None and tmp[1]:
            self._chain_prev[tmp[0]] = tmp[1][0]
        self._async_posts.pop(ckey, None)

    def on_wait_comm(self, rank, start, end):
        preds = [self._t(rank)] + self._pending_async.pop(rank, [])
        self._node(rank, "wait_comm", "join", "comp", start, end, preds,
                   emitted=False, advance_tail=True)

    def on_death(self, rank, t):
        self._node(rank, "rank_death", "fault", "comp", t, t,
                   (self._t(rank),), emitted=True, advance_tail=True)

    def on_fault_span(self, rank, name, start, end):
        self._node(rank, name, "fault", "comp", start, end,
                   (self._t(rank),), emitted=True, advance_tail=True,
                   extra=end - start)


# --------------------------------------------------------------------------
# Post-pass: slack, critical path, simulated waterfall
# --------------------------------------------------------------------------


def _joins_and_work(skel: DependencySkeleton):
    """Per-node join time ``S`` (max predecessor end; own start for
    sources) and own work ``W = end - S`` (clamped at 0 for float
    safety)."""
    end = skel.end
    start = skel.start
    all_preds = skel.preds
    n = len(end)
    S: List[float] = [0.0] * n
    W: List[float] = [0.0] * n
    for j in range(n):
        preds = all_preds[j]
        if preds:
            s = end[preds[0]]
            for p in preds:
                e = end[p]
                if e > s:
                    s = e
        else:
            s = start[j]
        S[j] = s
        w = end[j] - s
        W[j] = w if w > 0.0 else 0.0
    return S, W


def _slack(skel: DependencySkeleton, W: List[float],
           makespan: float) -> List[float]:
    """Latest-allowed-end backward pass: ``slack_j = L_j - end_j``.
    Zero-slack nodes form the critical paths; ``math.inf`` marks
    trace-only visibility spans (no timing successors by design)."""
    n = len(skel.end)
    L = [makespan] * n
    kind = skel.kind
    all_preds = skel.preds
    end = skel.end
    for j in range(n - 1, -1, -1):
        if kind[j] == "trace":
            continue
        allowed = L[j] - W[j]
        for p in all_preds[j]:
            if allowed < L[p]:
                L[p] = allowed
    inf = math.inf
    out = [0.0] * n
    for j in range(n):
        if kind[j] == "trace":
            out[j] = inf
        else:
            s = L[j] - end[j]
            out[j] = s if s > 0.0 else 0.0
    return out


def _sink(skel: DependencySkeleton) -> Optional[int]:
    """The makespan rank's final node (max end; ties -> smallest rank
    — the determinism contract shared with the engine's heap)."""
    best = None
    for rank in sorted(skel._tail):
        j = skel._tail[rank]
        if best is None or skel.end[j] > skel.end[best]:
            best = j
    return best


def _walk_path(skel: DependencySkeleton, sink: int) -> List[int]:
    """Binding-predecessor walk from the sink: at each node pick the
    predecessor with the maximum end (ties -> smallest rank, then
    smallest id — expands bit-identically under symmetry reduction
    because class representatives are each class's smallest member)."""
    path = [sink]
    cur = sink
    while skel.preds[cur]:
        cur = _binding_pred(skel, cur)
        path.append(cur)
    path.reverse()
    return path


def _bucket_of(skel: DependencySkeleton, j: int, ref_ranks) -> str:
    """Blame bucket of one path node — the simulated twin of the
    analytical waterfall's buckets (docs/observability.md). Compute on
    a reference-stage rank is ``compute``; path time spent on other
    stages' work while the reference stage waits is the pipeline
    bubble, exactly the analytical decomposition's anchor."""
    name, kind = skel.name[j], skel.kind[j]
    if kind == "fault":
        return "fault"
    if name in _DP_NAMES or name.startswith(_DP_PREFIXES):
        return "dp_optimizer_sync"
    if kind in ("p2p", "wait", "advance"):
        return "comm:pp"
    if kind == "comm" or skel.lane[j] == "comm":
        dim = skel.dim[j]
        return f"comm:{dim}" if dim else "comm:intra"
    if ".recompute#" in name:
        return "recompute"
    if kind == "join":
        return "dp_optimizer_sync"  # stream join residue (rare, ~0)
    return "compute" if skel.rank[j] in ref_ranks else "pipeline_bubble"


def _waterfall_order(buckets: Dict[str, float]) -> List[str]:
    comm = sorted(k for k in buckets if k.startswith("comm:"))
    return [k for k in _FIXED_HEAD if k in buckets] + comm + [
        k for k in _FIXED_TAIL if k in buckets
    ]


def _binding_pred(skel: DependencySkeleton, j: int) -> Optional[int]:
    """The predecessor whose end determined node ``j``'s join (max end;
    ties -> smallest rank, then smallest id — the shared determinism
    contract that makes reduced and exact walks expand identically)."""
    best = None
    end, rank = skel.end, skel.rank
    for p in skel.preds[j]:
        if best is None or end[p] > end[best] or (
            end[p] == end[best] and (rank[p], p) < (rank[best], best)
        ):
            best = p
    return best


def _timeline_waterfall(skel: DependencySkeleton, S, W,
                        ref_rank: int, ref_ranks, makespan: float):
    """Blame-decompose the reference rank's timeline ``[0, makespan]``
    — the simulated twin of ``build_waterfall``'s constructive
    decomposition of the binding stage's schedule end.

    Each clock-advancing node contributes its own work ``W`` to its op
    bucket; the gap before it (time the rank sat blocked) is blamed via
    the binding dependency: p2p waits split into transfer (``comm:pp``,
    bounded by the binding send's wire time) + ``pipeline_bubble``,
    rendezvous skew folds into the op's own bucket (waiting for the DP
    group IS DP sync), fault-stretched spans and fault-delayed binding
    deps land in ``fault``. The residual after the reference rank's
    final clock (the tail-binding stage's longer optimizer tail) lands
    in ``dp_optimizer_sync``. Contributions telescope, so the buckets
    sum to the makespan up to float reassociation."""
    buckets: Dict[str, float] = {}

    def add(b: str, v: float):
        if v > 0:
            buckets[b] = buckets.get(b, 0.0) + v

    prev_end = 0.0
    for j in range(len(skel)):
        if skel.rank[j] != ref_rank or not skel.adv[j]:
            continue
        gap = max(0.0, S[j] - prev_end)
        w = W[j]
        fx = min(skel.extra[j], w)
        if fx > 0:
            add("fault", fx)
            w -= fx
        b = _bucket_of(skel, j, ref_ranks)
        if gap > 0:
            bp = _binding_pred(skel, j)
            if bp is not None:
                gfx = min(gap, skel.extra[bp])
                if skel.kind[bp] == "fault":
                    gfx = gap  # waiting out a dead/aborted partner
                if gfx > 0:
                    add("fault", gfx)
                    gap -= gfx
            if b == "comm:pp":
                transfer = 0.0
                if bp is not None and skel.kind[bp] == "p2p":
                    transfer = min(gap, W[bp])
                add("comm:pp", transfer)
                add("pipeline_bubble", gap - transfer)
            elif b in ("compute", "pipeline_bubble", "recompute", "fault"):
                add("pipeline_bubble", gap)
            else:
                add(b, gap)  # rendezvous skew folds into the op bucket
        add(b, w)
        prev_end = skel.end[j]
    # tail skew: the makespan rank's optimizer tail outlasting ours
    add("dp_optimizer_sync", makespan - prev_end)
    return buckets


def _segments(skel, path, W, ref_ranks, rank_map, stage_of):
    """Merge consecutive path nodes with one (rank, bucket) into
    :class:`CritSegment` rows (readable path summary; works sum to the
    engine makespan exactly like the raw node walk)."""
    segs: List[CritSegment] = []
    for j in path:
        b = _bucket_of(skel, j, ref_ranks)
        r = skel.rank[j]
        g = rank_map[r] if rank_map is not None else r
        if segs and segs[-1].rank == g and segs[-1].bucket == b:
            s = segs[-1]
            s.end = skel.end[j]
            s.work += W[j]
            s.events += 1
            s.fault_extra += min(skel.extra[j], W[j])
            continue
        segs.append(CritSegment(
            rank=g, stage=stage_of(r) if stage_of else 0, bucket=b,
            name=skel.name[j], start=skel.start[j], end=skel.end[j],
            work=W[j], events=1,
            fault_extra=min(skel.extra[j], W[j]),
        ))
    return segs


def _headroom(work: Dict[Any, float], slack: Dict[Any, float]):
    """Tolerable uniform-slowdown bound per entity: a slowdown adding
    total delay ``D <= min_slack`` cannot move the makespan (any path
    accumulates at most ``D``, and every path's float is at least its
    minimum node slack), so ``min_slack / work`` is a sound headroom
    fraction."""
    out = []
    for k in sorted(work, key=repr):
        w = work[k]
        s = slack.get(k, math.inf)
        pct = None
        if w > 0 and math.isfinite(s):
            pct = 100.0 * s / w
        out.append({
            "key": k, "work_ms": w * 1e3,
            "min_slack_us": None if not math.isfinite(s) else s * 1e6,
            "tolerates_slowdown_pct": pct,
        })
    out.sort(key=lambda e: (
        e["tolerates_slowdown_pct"] is None,
        e["tolerates_slowdown_pct"] if e["tolerates_slowdown_pct"]
        is not None else 0.0,
    ))
    return out


def analyze(skel: DependencySkeleton, makespan: float,
            straggle_ratio: float = 1.0,
            rank_map: Optional[List[int]] = None,
            weights: Optional[List[int]] = None,
            stage_of=None, meta: Optional[Dict[str, Any]] = None,
            ref_stage: Optional[int] = None):
    """Full post-pass over a recorded skeleton.

    ``makespan`` is the engine's raw virtual end time (pre-straggler);
    the report's waterfall adds a ``straggler`` bucket of
    ``makespan * (ratio - 1)`` so buckets sum to the *reported* DES
    ``end_time`` — mirroring the analytical ``build_waterfall``.

    ``rank_map`` (class representative -> global rank) and ``weights``
    expand a symmetry-reduced skeleton; ``stage_of(engine_rank)``
    labels segments with pipeline stages.

    ``ref_stage`` anchors the compute-vs-bubble split (path work on the
    reference stage's ranks is ``compute``, other stages' work is the
    bubble). The runner passes the analytical ``binding_stage_rs`` so
    the simulated and analytical waterfalls share one anchor and their
    divergence measures model drift, not anchor mismatch; default is
    the makespan rank's own stage.

    Returns ``(report, annotations)`` where ``annotations`` maps
    ``(engine_rank, per-rank emit index) -> (slack_seconds, on_path)``
    for Chrome-trace args."""
    report: Dict[str, Any] = {
        "schema": CRITPATH_SCHEMA,
        "meta": dict(meta or {}),
        "makespan_ms": makespan * 1e3,
        "end_time_ms": makespan * straggle_ratio * 1e3,
        "straggle_ratio": straggle_ratio,
        "n_nodes": len(skel),
    }
    if not len(skel):
        report.update({
            "waterfall": {"order": [], "buckets": {}, "total": 0.0},
            "path": [], "slack": {}, "per_rank_headroom": [],
            "per_link_headroom": [],
            "slack_index": {"mode": (meta or {}).get("mode"),
                            "buckets": 0, "makespan_s": 0.0,
                            "ranks": [], "links": [],
                            "rank_buckets": [], "link_buckets": []},
        })
        return report, {}
    S, W = _joins_and_work(skel)
    slack = _slack(skel, W, makespan)
    sink = _sink(skel)
    path = _walk_path(skel, sink)
    on_path = set(path)
    if ref_stage is None:
        ref_stage = (stage_of(skel.rank[sink]) if stage_of
                     else skel.rank[sink])
    all_ranks = sorted(skel._tail)
    ref_ranks = frozenset(
        r for r in all_ranks
        if (stage_of(r) if stage_of else r) == ref_stage
    ) or frozenset({skel.rank[sink]})
    ref_rank = min(ref_ranks)

    buckets = _timeline_waterfall(skel, S, W, ref_rank, ref_ranks,
                                  makespan)
    if straggle_ratio != 1.0:
        buckets["straggler"] = makespan * (straggle_ratio - 1.0)
    segs = _segments(skel, path, W, ref_ranks, rank_map, stage_of)
    report["waterfall"] = {
        "order": _waterfall_order(buckets),
        "buckets": buckets,
        "total": makespan * straggle_ratio,
    }
    # merged segments; capped for pod-size leaf paths with the true
    # total recorded (no silent truncation — the waterfall above is
    # always complete)
    report["path"] = [s.to_dict() for s in segs[:20000]]
    report["path_segments"] = len(segs)
    report["path_truncated"] = len(segs) > 20000
    report["ref_rank"] = (
        rank_map[ref_rank] if rank_map is not None else ref_rank
    )
    report["ref_stage"] = ref_stage
    report["makespan_rank"] = (
        rank_map[skel.rank[sink]] if rank_map is not None
        else skel.rank[sink]
    )

    # one fused pass over the nodes: slack distribution, per-rank /
    # per-link headroom sources, Chrome annotations, per-op work on
    # the reference rank (bench_simulate gates this post-pass at
    # <= 15% events/s overhead, so the O(n) passes stay merged)
    n = len(skel)
    kinds, ranks_l, links, dims = skel.kind, skel.rank, skel.link, skel.dim
    emit_idxs, names = skel.emit_idx, skel.name
    finite: List[float] = []
    zero_count = 0
    rank_work: Dict[int, float] = {}
    rank_slack: Dict[int, float] = {}
    link_work: Dict[str, float] = {}
    link_slack: Dict[str, float] = {}
    #: class-weighted link/dim work: total wire+exposed seconds across
    #: the EXACT world (a reduced node stands for ``weights[r]``
    #: symmetric copies) — the fault-replay slack gate bounds the
    #: worst-case injected delay of a dim-wide degradation with it
    link_wwork: Dict[str, float] = {}
    # time-bucketed slack/work: a fault window mid-step only touches
    # the nodes it overlaps, so the replay gate needs min-slack/work
    # restricted to the window — whole-step minima are ~always zero
    # (the optimizer barrier alone puts a zero-slack node on every
    # rank). A node spanning several buckets contributes its full work
    # to each (overcount; the gate's delay bound stays conservative).
    n_buckets = 48
    bscale = (n_buckets / makespan) if makespan > 0 else 0.0
    rank_bwork: Dict[int, List[float]] = {}
    rank_bslack: Dict[int, List[float]] = {}
    link_bwork: Dict[str, List[float]] = {}
    link_bslack: Dict[str, List[float]] = {}

    def _bucket_span(lo_t: float, hi_t: float):
        lo = int(lo_t * bscale)
        hi = int(hi_t * bscale)
        lo = 0 if lo < 0 else (n_buckets - 1 if lo >= n_buckets else lo)
        hi = lo if hi < lo else (n_buckets - 1 if hi >= n_buckets
                                 else hi)
        return lo, hi
    annotations: Dict[tuple, tuple] = {}
    emitted: List[int] = []
    op_work: Dict[str, float] = {}
    inf = math.inf
    for j in range(n):
        k = kinds[j]
        sj = slack[j]
        idx = emit_idxs[j]
        r = ranks_l[j]
        if idx >= 0:
            annotations[(r, idx)] = (sj, j in on_path)
            if sj != inf:
                emitted.append(j)
        if k == "trace":
            continue
        finite.append(sj)
        if sj <= 1e-12:
            zero_count += 1
        w = W[j]
        rank_work[r] = rank_work.get(r, 0.0) + w
        if sj < rank_slack.get(r, inf):
            rank_slack[r] = sj
        blo, bhi = _bucket_span(S[j], skel.end[j])
        bw = rank_bwork.get(r)
        if bw is None:
            bw = rank_bwork[r] = [0.0] * n_buckets
            rank_bslack[r] = [inf] * n_buckets
        bs = rank_bslack[r]
        for b in range(blo, bhi + 1):
            bw[b] += w
            if sj < bs[b]:
                bs[b] = sj
        lk = links[j]
        if lk is not None:
            a, b2 = lk
            if rank_map is not None:
                a, b2 = rank_map[a], rank_map[b2]
            key = f"pp:{a}->{b2}"
        elif dims[j]:
            key = f"dim:{dims[j]}"
        else:
            key = None
        if key is not None:
            ww = w * (weights[r] if weights is not None else 1)
            link_work[key] = link_work.get(key, 0.0) + w
            link_wwork[key] = link_wwork.get(key, 0.0) + ww
            if sj < link_slack.get(key, inf):
                link_slack[key] = sj
            lbw = link_bwork.get(key)
            if lbw is None:
                lbw = link_bwork[key] = [0.0] * n_buckets
                link_bslack[key] = [inf] * n_buckets
            lbs = link_bslack[key]
            for b in range(blo, bhi + 1):
                lbw[b] += ww
                if sj < lbs[b]:
                    lbs[b] = sj
        if r == ref_rank and w > 0 and k not in ("join", "advance"):
            op = _base_op(names[j])
            op_work[op] = op_work.get(op, 0.0) + w
    finite.sort()

    def _pct(q):
        if not finite:
            return 0.0
        return finite[min(len(finite) - 1, int(q * len(finite)))]

    report["slack"] = {
        "events": len(finite),
        "zero_slack_events": zero_count,
        "p50_us": _pct(0.50) * 1e6,
        "p90_us": _pct(0.90) * 1e6,
        "max_us": (finite[-1] if finite else 0.0) * 1e6,
    }
    # deterministic per-event samples: the tightest and loosest emitted
    # events, addressable as engine (rank, emit index) — the exact key
    # the engine's ``event_delays`` perturbation hook takes, which is
    # what the slack-correctness property test replays
    emitted.sort(key=lambda j: (slack[j], ranks_l[j], j))

    def _sample(j):
        return {
            "engine_rank": ranks_l[j], "emit_idx": emit_idxs[j],
            "name": names[j], "slack_us": slack[j] * 1e6,
        }

    report["slack_samples"] = {
        "tightest": [_sample(j) for j in emitted[:32]],
        "loosest": [_sample(j) for j in emitted[-32:][::-1]],
    }
    per_rank = _headroom(rank_work, rank_slack)
    for e in per_rank:
        r = e.pop("key")
        e["rank"] = rank_map[r] if rank_map is not None else r
        e["members"] = weights[r] if weights is not None else 1
        if stage_of:
            e["stage"] = stage_of(r)
    # lists are tightest-first and capped for pod-size worlds; the
    # *_count fields carry the true totals (no silent truncation)
    report["per_rank_headroom"] = per_rank[:64]
    report["per_rank_count"] = len(per_rank)
    per_link = _headroom(link_work, link_slack)
    for e in per_link:
        e["link"] = e.pop("key")
    report["per_link_headroom"] = per_link[:64]
    report["per_link_count"] = len(per_link)

    # machine-facing slack index (the fault-replay slack gate,
    # ``simulator/faults.py``): UNtruncated, raw engine seconds.
    # Rank rows are keyed by representative global rank (class members
    # behave bit-identically, so they share the rep's row); link rows
    # carry class-weighted work so a dim-wide perturbation's delay
    # bound covers every symmetric copy in the exact world. ``None``
    # slack = unbounded (no timing successor observed).
    def _bs_out(arr: List[float]) -> List[Optional[float]]:
        return [v if math.isfinite(v) else None for v in arr]

    report["slack_index"] = {
        "mode": report["meta"].get("mode"),
        "buckets": n_buckets,
        "makespan_s": makespan,
        "ranks": [
            [rank_map[r] if rank_map is not None else r,
             rank_work.get(r, 0.0),
             (rank_slack[r]
              if math.isfinite(rank_slack.get(r, inf)) else None)]
            for r in sorted(rank_work)
        ],
        "links": [
            [k, link_wwork[k],
             (link_slack[k]
              if math.isfinite(link_slack.get(k, inf)) else None)]
            for k in sorted(link_wwork)
        ],
        "rank_buckets": [
            [rank_map[r] if rank_map is not None else r,
             rank_bwork[r], _bs_out(rank_bslack[r])]
            for r in sorted(rank_bwork)
        ],
        "link_buckets": [
            [k, link_bwork[k], _bs_out(link_bslack[k])]
            for k in sorted(link_bwork)
        ],
    }

    report["sim_ops"] = op_work
    return report, annotations


_MB_RE = re.compile(r"(?:#|_)mb\d+$")


def _base_op(name: str) -> str:
    """Collapse an engine event name to its op identity: strip the
    ``#mb<k>`` / ``_mb<k>`` instance suffix and the phase tail, so
    events aggregate per op across microbatches
    (``layer0.mlp.up.fwd#mb3`` -> ``layer0.mlp.up``, chunk-granularity
    ``fwd_mb3`` -> ``fwd``)."""
    base = _MB_RE.sub("", name)
    for suffix in (".fwd", ".bwd", ".recompute"):
        if base.endswith(suffix):
            return base[: -len(suffix)]
    return base


# --------------------------------------------------------------------------
# Sim-vs-analytical divergence
# --------------------------------------------------------------------------

#: analytical bucket -> simulated buckets alignment (see
#: docs/observability.md: the analytical ``pipeline_bubble`` includes
#: blocking p2p stalls, so ``comm:pp`` folds into it on the sim side)
_ALIGN = (
    ("ideal_compute + compute_inefficiency",
     ("ideal_compute", "compute_inefficiency"), ("compute",)),
    ("exposed_comm", ("exposed_comm",), ("comm:*",)),
    ("pipeline_bubble", ("pipeline_bubble",),
     ("pipeline_bubble", "comm:pp")),
    ("recompute", ("recompute",), ("recompute",)),
    ("dp_optimizer_sync", ("dp_optimizer_sync",), ("dp_optimizer_sync",)),
    ("straggler", ("straggler",), ("straggler",)),
    ("fault", (), ("fault",)),
)


def diverge(perf, report: Dict[str, Any], top: int = 10) -> Dict[str, Any]:
    """Align the simulated waterfall bucket-by-bucket against the
    analytical ``build_waterfall`` and name the top disagreeing
    ops/collectives (per-op analytical charge x mbc on the reference
    stage vs realized work on the reference rank's timeline).

    Bucket divergence localizes model drift: a ``compute`` gap points
    at efficiency-table entries (ROADMAP item 5's calibration-drift
    signal), an ``exposed_comm`` gap at collective bw/lat terms, a
    ``pipeline_bubble`` gap at the schedule model itself."""
    from simumax_tpu.observe.ledger import build_waterfall

    awf = build_waterfall(perf)
    sim = report["waterfall"]["buckets"]

    def _sum_sim(keys):
        total = 0.0
        for k in keys:
            if k == "comm:*":
                total += sum(v for b, v in sim.items()
                             if b.startswith("comm:") and b != "comm:pp")
            else:
                total += sim.get(k, 0.0)
        return total

    rows = []
    for label, akeys, skeys in _ALIGN:
        a = sum(awf["buckets"].get(k, 0.0) for k in akeys)
        s = _sum_sim(skeys)
        rows.append({
            "bucket": label,
            "analytical_ms": a * 1e3,
            "simulated_ms": s * 1e3,
            "delta_ms": (s - a) * 1e3,
        })
    # per-op disagreement on the reference stage — leaf granularity
    # only: chunk-granularity events are whole-microbatch aggregates
    # with no per-op identity to align against the analytical spans
    st = perf.strategy
    mbc = st.micro_batch_num
    ref_stage = report.get("ref_stage", 0)
    if report.get("meta", {}).get("granularity") != "leaf":
        return {
            "schema": "simumax-critpath-divergence-v1",
            "analytical_total_ms": awf["total"] * 1e3,
            "simulated_total_ms": report["waterfall"]["total"] * 1e3,
            "delta_ms": (report["waterfall"]["total"]
                         - awf["total"]) * 1e3,
            "buckets": rows,
            "ref_stage": ref_stage,
            "top_op_deltas": [],
            "note": "per-op divergence needs granularity=leaf",
        }
    analytical_ops: Dict[str, float] = {}
    for (stage, _chunk), chunk in sorted(perf.chunks.items()):
        if stage != ref_stage:
            continue
        for leaf in chunk.called_leaves():
            key = leaf.path_name().split(".", 1)[-1]
            analytical_ops[key] = (
                analytical_ops.get(key, 0.0)
                + mbc * (leaf.cost_info.compute.total
                         + leaf.cost_info.net_exposed.total)
            )
    sim_ops = report.get("sim_ops", {})
    # sim op keys carry per-leaf suffixes the analytical side charges on
    # the leaf itself (".all_gather[tp]", ".fwd_comm"): fold onto the
    # longest analytical key that prefixes them
    folded: Dict[str, float] = {}
    akeys_sorted = sorted(analytical_ops, key=len, reverse=True)
    for k, v in sim_ops.items():
        target = k
        if k not in analytical_ops:
            for ak in akeys_sorted:
                if k.startswith(ak + "."):
                    target = ak
                    break
        folded[target] = folded.get(target, 0.0) + v
    deltas = [
        {"op": p, "analytical_ms": analytical_ops.get(p, 0.0) * 1e3,
         "simulated_ms": folded.get(p, 0.0) * 1e3,
         "delta_ms": (folded.get(p, 0.0)
                      - analytical_ops.get(p, 0.0)) * 1e3}
        for p in set(analytical_ops) | set(folded)
    ]
    deltas.sort(key=lambda d: abs(d["delta_ms"]), reverse=True)
    return {
        "schema": "simumax-critpath-divergence-v1",
        "analytical_total_ms": awf["total"] * 1e3,
        "simulated_total_ms": report["waterfall"]["total"] * 1e3,
        "delta_ms": (report["waterfall"]["total"] - awf["total"]) * 1e3,
        "buckets": rows,
        "ref_stage": ref_stage,
        "top_op_deltas": deltas[:top],
    }


# --------------------------------------------------------------------------
# Presentation + persistence
# --------------------------------------------------------------------------


def waterfall_lines(report: Dict[str, Any]) -> List[str]:
    """Human rendering of the simulated waterfall (the
    ``critical-path`` subcommand's default output)."""
    wf = report["waterfall"]
    total = wf["total"] or 1.0
    if not wf["order"]:
        return ["== simulated waterfall: empty run =="]
    width = max(len(k) for k in wf["order"])
    lines = [
        f"== simulated critical-path waterfall — DES makespan "
        f"{report['end_time_ms']:.2f} ms "
        f"({report['n_nodes']} dependency nodes, ref rank "
        f"{report.get('ref_rank', 0)} / stage "
        f"{report.get('ref_stage', 0)}) =="
    ]
    for key in wf["order"]:
        v = wf["buckets"][key]
        ms = round(v * 1e3, 3) + 0.0
        pct = round(100.0 * v / total, 2) + 0.0
        lines.append(f"  {key:<{width}}  {ms:10.3f} ms  {pct:6.2f}%")
    lines.append(
        f"  {'= makespan':<{width}}  {total * 1e3:10.3f} ms  100.00%"
    )
    return lines


def headroom_lines(report: Dict[str, Any], top: int = 5) -> List[str]:
    lines = []
    tight = [e for e in report.get("per_rank_headroom", [])
             if e.get("tolerates_slowdown_pct") is not None][:top]
    if tight:
        lines.append("-- tightest ranks (tolerable uniform slowdown "
                     "before step time moves) --")
        for e in tight:
            members = (f" (x{e['members']} symmetric ranks)"
                       if e.get("members", 1) > 1 else "")
            lines.append(
                f"  rank {e['rank']} (stage {e.get('stage', '?')}): "
                f"{e['tolerates_slowdown_pct']:.2f}% "
                f"(min slack {e['min_slack_us']:.1f} us over "
                f"{e['work_ms']:.1f} ms work){members}"
            )
    tight = [e for e in report.get("per_link_headroom", [])
             if e.get("tolerates_slowdown_pct") is not None][:top]
    if tight:
        lines.append("-- tightest links/dims --")
        for e in tight:
            lines.append(
                f"  {e['link']}: {e['tolerates_slowdown_pct']:.2f}% "
                f"(min slack {e['min_slack_us']:.1f} us over "
                f"{e['work_ms']:.1f} ms comm)"
            )
    return lines


def divergence_lines(div: Dict[str, Any], top: int = 5) -> List[str]:
    lines = [
        f"-- sim vs analytical: {div['simulated_total_ms']:.2f} ms vs "
        f"{div['analytical_total_ms']:.2f} ms "
        f"({div['delta_ms']:+.2f} ms) --"
    ]
    width = max(len(r["bucket"]) for r in div["buckets"])
    for r in div["buckets"]:
        lines.append(
            f"  {r['bucket']:<{width}}  {r['analytical_ms']:10.3f} -> "
            f"{r['simulated_ms']:10.3f} ms  ({r['delta_ms']:+.3f} ms)"
        )
    shown = [d for d in div["top_op_deltas"] if d["delta_ms"] != 0][:top]
    if shown:
        lines.append("  -- top disagreeing ops/collectives "
                     "(ref stage, x mbc) --")
        for d in shown:
            lines.append(
                f"    {d['delta_ms']:+9.3f} ms  {d['op']}"
            )
    return lines


def save_report(report: Dict[str, Any], path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, default=str)
    return path


def load_report(path: str) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    schema = data.get("schema")
    if schema != CRITPATH_SCHEMA:
        raise ConfigError(
            f"{path}: not a simumax critical-path report "
            f"(schema={schema!r}; expected {CRITPATH_SCHEMA!r} — produce "
            f"one with `simumax_tpu critical-path ... --json PATH`)"
        )
    return data


def diff_critpath(a: Dict[str, Any], b: Dict[str, Any],
                  top: int = 10) -> Dict[str, Any]:
    """Compare two critical-path reports (two strategies, or a healthy
    run vs a fault scenario): makespan movement, per-bucket waterfall
    deltas, and headroom shifts on the tightest ranks."""
    keys = set(a["waterfall"]["buckets"]) | set(b["waterfall"]["buckets"])
    wf = {
        k: {
            "a": a["waterfall"]["buckets"].get(k, 0.0),
            "b": b["waterfall"]["buckets"].get(k, 0.0),
            "delta": b["waterfall"]["buckets"].get(k, 0.0)
            - a["waterfall"]["buckets"].get(k, 0.0),
        }
        for k in keys
    }

    def _rank_headroom(rep):
        return {
            e["rank"]: e.get("tolerates_slowdown_pct")
            for e in rep.get("per_rank_headroom", [])
        }

    ha, hb = _rank_headroom(a), _rank_headroom(b)
    # compare only ranks present on BOTH sides: the per-rank lists are
    # capped tightest-first, so a rank merely entering/leaving the
    # window is a list artifact, not a headroom change
    headroom = [
        {"rank": r, "a_pct": ha[r], "b_pct": hb[r]}
        for r in sorted(set(ha) & set(hb))
        if ha[r] != hb[r]
    ]
    identical = (
        a["end_time_ms"] == b["end_time_ms"]
        and all(v["delta"] == 0 for v in wf.values())
        and not headroom
    )
    return {
        "schema": "simumax-critpath-diff-v1",
        "identical": identical,
        "end_time_ms": {
            "a": a["end_time_ms"], "b": b["end_time_ms"],
            "delta": b["end_time_ms"] - a["end_time_ms"],
        },
        "waterfall": wf,
        "headroom_changes": headroom[:top],
        "ref_rank": {"a": a.get("ref_rank"), "b": b.get("ref_rank")},
    }


def format_critpath_diff_lines(diff: Dict[str, Any],
                               top: int = 10) -> List[str]:
    lines = [
        f"== critical-path diff: {diff['end_time_ms']['a']:.2f} -> "
        f"{diff['end_time_ms']['b']:.2f} ms "
        f"({diff['end_time_ms']['delta']:+.2f} ms) =="
    ]
    if diff["identical"]:
        lines.append("  identical: zero delta in every bucket")
        return lines
    order = _waterfall_order({k: 1 for k in diff["waterfall"]})
    width = max(len(k) for k in order) if order else 1
    for k in order:
        d = diff["waterfall"][k]
        if d["a"] == 0 and d["b"] == 0:
            continue
        lines.append(
            f"  {k:<{width}}  {d['a'] * 1e3:10.3f} -> "
            f"{d['b'] * 1e3:10.3f} ms  ({d['delta'] * 1e3:+.3f} ms)"
        )
    shown = diff.get("headroom_changes", [])[:top]
    if shown:
        lines.append("  -- slack-headroom changes --")
        for h in shown:
            fa = ("-" if h["a_pct"] is None else f"{h['a_pct']:.2f}%")
            fb = ("-" if h["b_pct"] is None else f"{h['b_pct']:.2f}%")
            lines.append(f"    rank {h['rank']}: {fa} -> {fb}")
    return lines

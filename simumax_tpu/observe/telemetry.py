"""Unified telemetry (L12): one process-wide measurement plane.

SimuMax predicts distributed training *before* you run it; this module
makes the predictor itself measurable. Two halves, both dependency-free
(stdlib only) and both strictly observe-only — telemetry-on and
telemetry-off runs produce bit-identical payloads:

**Metrics.** A :class:`MetricsRegistry` of labelled counters, gauges,
and histograms. Every previously ad-hoc counting surface — the HTTP
server's request/latency accounting, ``ContentStore.counters``,
``Planner`` single-flight/hit counters, ``Diagnostics.counters``, the
DES progress heartbeat — mirrors into the registry, which renders as
either a JSON snapshot (:meth:`MetricsRegistry.snapshot`) or Prometheus
text exposition (:func:`render_prometheus`, served by ``GET /metrics``).
Histograms keep exact count/sum/min/max plus a **bounded quantile
reservoir** (deterministic stride decimation, never a full-stream
sort), so snapshotting is O(reservoir) regardless of traffic.

Metric names are a closed catalogue: :data:`METRICS` declares every
legal name with its type and help text, the registry rejects unknown
names at runtime, and staticcheck ``SIM007`` enforces the same contract
statically (every literal ``registry.counter/gauge/histogram(...)``
name must appear here, documented). Dynamic dimensions travel in
labels, never in names.

**Traces.** A :class:`Tracer` of nested :class:`SpanRecord`s with
contextvar-propagated ``trace_id``/``span_id``: the HTTP server opens
one trace per request (echoed in ``X-SimuMax-Trace``), the planner,
store, sweep, and DES layers annotate their phases with
:meth:`Tracer.span`, ``Reporter --log-json`` lines carry the active
ids, and finished traces export as Chrome-trace events
(:func:`chrome_trace`) so a planner request's internals render in the
same viewer as the pipeline traces. Id propagation is always on (the
header must correlate even when nothing records); span *records* are
kept only while :attr:`Tracer.enabled` (``--trace-requests``), in a
bounded per-trace buffer.

See ``docs/observability.md`` ("Unified telemetry") for the catalogue
and the span model, and ``docs/service.md`` ("Monitoring the server")
for the scrape config.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import os
import random
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

from simumax_tpu.core.errors import ConfigError

# --------------------------------------------------------------------------
# Metric catalogue
# --------------------------------------------------------------------------

#: the closed catalogue of legal metric names: name -> {type, help}.
#: Every ``registry.counter/gauge/histogram(...)`` call site must use a
#: literal name declared (and documented) here — enforced at runtime by
#: the registry and statically by staticcheck SIM007. Dynamic
#: dimensions (endpoint, op, counter name) are labels, not names.
METRICS: Dict[str, Dict[str, str]] = {
    "http_requests_total": {
        "type": "counter",
        "help": "HTTP requests served by the planning server, "
                "by endpoint.",
    },
    "http_errors_total": {
        "type": "counter",
        "help": "HTTP requests that ended in an error, by endpoint.",
    },
    "http_request_seconds": {
        "type": "histogram",
        "help": "HTTP request wall time in seconds, by endpoint.",
    },
    "store_ops_total": {
        "type": "counter",
        "help": "Content-addressed store operations, by op "
                "(hits/misses/puts/evictions/corrupt_dropped).",
    },
    "planner_ops_total": {
        "type": "counter",
        "help": "Planner facade operations, by op (evaluations/hits/"
                "misses/singleflight_waits/put_errors).",
    },
    "diag_counter": {
        "type": "gauge",
        "help": "Latest value of a free-form Diagnostics counter "
                "(sweep cell accounting etc.), by counter name.",
    },
    "des_events_served": {
        "type": "gauge",
        "help": "Trace events emitted so far by the running "
                "discrete-event simulation (progress heartbeat).",
    },
    "des_blocked_ranks": {
        "type": "gauge",
        "help": "Ranks currently blocked on a rendezvous in the "
                "running discrete-event simulation.",
    },
    "des_clock_seconds": {
        "type": "gauge",
        "help": "Virtual clock of the running discrete-event "
                "simulation, in simulated seconds.",
    },
    "trace_spans_dropped_total": {
        "type": "counter",
        "help": "Span records dropped because a trace exceeded the "
                "tracer's per-trace buffer bound.",
    },
    "pool_workers": {
        "type": "gauge",
        "help": "Live planner worker processes in the serving pool.",
    },
    "pool_queue_depth": {
        "type": "gauge",
        "help": "Requests queued in the worker pool awaiting a "
                "worker, by priority class.",
    },
    "pool_requests_total": {
        "type": "counter",
        "help": "Requests executed by pool workers, by outcome "
                "(ok/error/timeout).",
    },
    "pool_queue_wait_seconds": {
        "type": "histogram",
        "help": "Wall time a pooled request spent between submission "
                "and a worker picking it up.",
    },
    "pool_worker_restarts_total": {
        "type": "counter",
        "help": "Worker processes respawned after dying or being "
                "killed by the request hard-deadline.",
    },
    "pool_retries_total": {
        "type": "counter",
        "help": "Requests retried on another worker after their "
                "assigned worker died mid-query.",
    },
    "pool_coalesced_total": {
        "type": "counter",
        "help": "Requests coalesced onto an identical in-flight "
                "request instead of dispatching to a worker.",
    },
    "pool_memcache_hits_total": {
        "type": "counter",
        "help": "Requests served from the in-memory response cache "
                "(dependency-validated canonical bytes).",
    },
    "pool_memcache_entries": {
        "type": "gauge",
        "help": "Entries currently held by the in-memory response "
                "cache.",
    },
    "coalesce_cells_total": {
        "type": "counter",
        "help": "Sweep-cell flight-table events, by role "
                "(leader/follower/abandoned).",
    },
    "warmer_jobs_total": {
        "type": "counter",
        "help": "Speculative cache-warming jobs, by outcome (warmed/"
                "duplicate/dropped/skipped_headroom/skipped_remote/"
                "skipped_degraded/error).",
    },
    "ring_nodes": {
        "type": "gauge",
        "help": "Fleet members in this node's consistent-hash ring "
                "view.",
    },
    "router_forwards_total": {
        "type": "counter",
        "help": "Requests relayed to their ring owner, by destination "
                "node.",
    },
    "router_local_hits_total": {
        "type": "counter",
        "help": "Requests whose route key this node already owned "
                "(served locally, no fleet hop).",
    },
    "coalesce_remote_follows_total": {
        "type": "counter",
        "help": "Sweep cells served by following another node's "
                "in-flight evaluation over the wire instead of "
                "re-evaluating.",
    },
    "replica_pulls_total": {
        "type": "counter",
        "help": "Store entries copied from a peer's shard by the "
                "read-only replica pull loop.",
    },
    "warmer_cells_total": {
        "type": "counter",
        "help": "Neighbor sweep cells precomputed into the store by "
                "the speculative warmer.",
    },
    "admission_rejected_total": {
        "type": "counter",
        "help": "Requests shed with 429 by admission control, by "
                "priority class.",
    },
    "ring_epoch": {
        "type": "gauge",
        "help": "Membership version of this node's live ring view "
                "(bumped on every failure-detector remove/rejoin).",
    },
    "ring_member_state": {
        "type": "gauge",
        "help": "Failure-detector verdict per fleet peer "
                "(0=up, 1=suspect, 2=down), by node.",
    },
    "router_hop_timeouts_total": {
        "type": "counter",
        "help": "Forward hops abandoned because the peer accepted "
                "the connection but exceeded the per-hop read "
                "deadline, by destination node.",
    },
    "hedged_requests_total": {
        "type": "counter",
        "help": "Hedged second sends for slow read-only forwards, by "
                "outcome (won/lost/failed).",
    },
    "store_quarantined_total": {
        "type": "counter",
        "help": "Corrupt/torn store entries moved into .quarantine/ "
                "(read-path drops, verify --drop, and the start-time "
                "recovery sweep all route here).",
    },
    "chaos_injections_total": {
        "type": "counter",
        "help": "Fault injections fired by the chaos harness, by "
                "kind (kill/stop/drop/delay/corrupt).",
    },
    "faults_scenarios_total": {
        "type": "counter",
        "help": "Fault scenarios walked by predict_goodput (one per "
                "goodput prediction).",
    },
    "faults_step_cache_hits_total": {
        "type": "counter",
        "help": "Perturbed-step simulations answered from the replay "
                "step cache, by kind (exact/canonical signature).",
    },
    "faults_slack_shortcircuits_total": {
        "type": "counter",
        "help": "Perturbed steps proven makespan-neutral by the "
                "critical-path slack gate and answered without a "
                "replay.",
    },
    "faults_prefix_forks_total": {
        "type": "counter",
        "help": "Perturbed-step replays resumed from a forked "
                "healthy-prefix engine snapshot instead of replaying "
                "the step from t=0.",
    },
    "replay_batched_total": {
        "type": "counter",
        "help": "Perturbed-step cache misses replayed through the "
                "batched vmapped array program, by backend.",
    },
    "replay_batch_fallbacks_total": {
        "type": "counter",
        "help": "Batch-round cache misses that fell back to the "
                "scalar engine, by counted reason (deaths/sendrecv/"
                "unknown_kind/no_streams/lowering_error/"
                "jax_unavailable/small_batch/backend_numpy).",
    },
    "fleet_jobs_total": {
        "type": "counter",
        "help": "Fleet-simulation job events, by event (admitted/"
                "queued/resumed/preempted/reclaimed/reshaped/"
                "restarted/frozen/completed/starved).",
    },
    "fleet_template_ctx_total": {
        "type": "counter",
        "help": "Fleet job costings by template replay-context fate: "
                "kind=built paid a fresh healthy-step DES + replay "
                "state, kind=shared reused another job's context — "
                "the cross-job amortization the fleet bench gates.",
    },
    "fleet_slo_attainment": {
        "type": "gauge",
        "help": "Fraction of SLO-carrying jobs meeting their goodput "
                "SLO in the most recent fleet trace walk.",
    },
    "replay_compile_cache_shapes": {
        "type": "gauge",
        "help": "Distinct (backend, shape) array programs currently "
                "held by the batched-replay compile cache.",
    },
    "replay_compile_cache_capacity": {
        "type": "gauge",
        "help": "Entry bound of the batched-replay compile cache "
                "(the cache is cleared when it would be exceeded).",
    },
    "fleet_explain_jobs_total": {
        "type": "counter",
        "help": "Completed jobs attributed by the fleet goodput "
                "ledger (one observer re-drive each, "
                "observe/fleetledger.py).",
    },
    "fleet_probes_total": {
        "type": "counter",
        "help": "SLO counterfactual probes re-costed by the fleet "
                "ledger, by outcome (recovers/no/error/starved).",
    },
}

#: default bounded-reservoir size for histograms: big enough for stable
#: p50/p99, small enough that a snapshot sort is microseconds
DEFAULT_RESERVOIR = 512


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


# --------------------------------------------------------------------------
# Instruments
# --------------------------------------------------------------------------


class Counter:
    """Monotonic labelled counter."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0):
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Labelled gauge: set to the latest value (or inc/dec)."""

    __slots__ = ("name", "labels", "_lock", "_value")

    def __init__(self, name: str, labels: Dict[str, str]):
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float):
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0):
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Labelled histogram with exact count/sum/min/max and a bounded
    quantile reservoir.

    The reservoir is filled by **deterministic stride decimation**:
    every observation is kept until the buffer reaches its bound, then
    the buffer is halved (every second sample dropped) and the keep
    stride doubles. The retained sample is a uniform systematic
    subsample of the arrival sequence — deterministic in the
    observation order, never random — and quantiles are nearest-rank
    over the sorted reservoir, so :meth:`quantile` (and any snapshot)
    is O(reservoir), independent of how many observations were made.
    """

    __slots__ = ("name", "labels", "_lock", "_count", "_sum", "_min",
                 "_max", "_reservoir", "_bound", "_stride", "_seen")

    def __init__(self, name: str, labels: Dict[str, str],
                 reservoir: int = DEFAULT_RESERVOIR):
        if reservoir < 2:
            raise ConfigError(
                f"histogram reservoir must be >= 2, got {reservoir}",
                metric=name,
            )
        self.name = name
        self.labels = dict(labels)
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None
        self._reservoir: List[float] = []
        self._bound = int(reservoir)
        self._stride = 1
        self._seen = 0  # observations since the last kept sample

    def observe(self, v: float):
        v = float(v)
        with self._lock:
            self._count += 1
            self._sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v
            # systematic subsample: keep every stride-th observation
            if self._seen % self._stride == 0:
                self._reservoir.append(v)
                if len(self._reservoir) >= self._bound:
                    # decimate: halve the buffer, double the stride
                    self._reservoir = self._reservoir[::2]
                    self._stride *= 2
            self._seen += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _snapshot_values(self) -> Tuple[int, float, Optional[float],
                                        Optional[float], List[float]]:
        with self._lock:
            return (self._count, self._sum, self._min, self._max,
                    sorted(self._reservoir))

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile over the bounded reservoir (0.0 when
        nothing was observed) — same rank convention as the server's
        ``percentile`` helper, applied to the subsample."""
        _, _, _, _, vals = self._snapshot_values()
        if not vals:
            return 0.0
        i = min(len(vals) - 1,
                max(0, int(round(q * (len(vals) - 1)))))
        return vals[i]

    def to_dict(self) -> Dict[str, Any]:
        count, total, vmin, vmax, vals = self._snapshot_values()

        def rank(q: float) -> float:
            if not vals:
                return 0.0
            return vals[min(len(vals) - 1,
                            max(0, int(round(q * (len(vals) - 1)))))]

        return {
            "count": count,
            "sum": total,
            "min": vmin if vmin is not None else 0.0,
            "max": vmax if vmax is not None else 0.0,
            "reservoir_size": len(vals),
            "p50": rank(0.50),
            "p90": rank(0.90),
            "p99": rank(0.99),
        }


# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------


class MetricsRegistry:
    """Thread-safe get-or-create registry of labelled instruments.

    Instruments are keyed by ``(name, sorted labels)``; the same call
    from two threads returns the same object. Names must be declared in
    :data:`METRICS` with the matching type — unknown names raise
    :class:`ConfigError` (the runtime half of the SIM007 contract).
    Tests that need isolation construct their own registry; library
    code defaults to the process-wide one (:func:`get_registry`).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, tuple], Any] = {}

    def _get(self, name: str, kind: str, labels: Dict[str, str],
             factory):
        spec = METRICS.get(name)
        if spec is None:
            raise ConfigError(
                f"unknown metric name {name!r}: declare it in "
                f"telemetry.METRICS (the SIM007 catalogue) before use",
                metric=name,
            )
        if spec["type"] != kind:
            raise ConfigError(
                f"metric {name!r} is declared as a {spec['type']}, "
                f"not a {kind}",
                metric=name,
            )
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._metrics.get(key)
            if inst is None:
                inst = factory()
                self._metrics[key] = inst
            return inst

    def counter(self, name: str, /, **labels: str) -> Counter:
        return self._get(name, "counter", labels,
                         lambda: Counter(name, labels))

    def gauge(self, name: str, /, **labels: str) -> Gauge:
        return self._get(name, "gauge", labels,
                         lambda: Gauge(name, labels))

    def histogram(self, name: str, /, *,
                  reservoir: int = DEFAULT_RESERVOIR,
                  **labels: str) -> Histogram:
        return self._get(name, "histogram", labels,
                         lambda: Histogram(name, labels, reservoir))

    def instruments(self) -> List[Any]:
        """All registered instruments, sorted by (name, labels) for
        deterministic rendering."""
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe snapshot of every instrument: ``{name: [{labels,
        value | histogram fields}, ...]}``."""
        out: Dict[str, List[Dict[str, Any]]] = {}
        for inst in self.instruments():
            entry: Dict[str, Any] = {"labels": dict(inst.labels)}
            if isinstance(inst, Histogram):
                entry.update(inst.to_dict())
            else:
                entry["value"] = inst.value
            out.setdefault(inst.name, []).append(entry)
        return out


_REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (``/metrics`` of a default
    ``serve`` renders this one)."""
    return _REGISTRY


# --------------------------------------------------------------------------
# Prometheus text exposition
# --------------------------------------------------------------------------

#: content type of the text exposition format (version 0.0.4)
PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _labels_text(labels: Dict[str, str],
                 extra: Optional[Dict[str, str]] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    body = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(merged.items())
    )
    return "{" + body + "}"


def _fmt(v: float) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def render_prometheus(registry: Optional[MetricsRegistry] = None) -> str:
    """Render a registry in the Prometheus text exposition format
    (v0.0.4): ``# HELP`` / ``# TYPE`` per family, one sample line per
    labelled instrument; histograms render as summaries (quantile
    samples from the bounded reservoir plus ``_sum`` / ``_count``)."""
    registry = registry or get_registry()
    by_name: Dict[str, List[Any]] = {}
    for inst in registry.instruments():
        by_name.setdefault(inst.name, []).append(inst)
    lines: List[str] = []
    for name in sorted(by_name):
        spec = METRICS[name]
        ptype = "summary" if spec["type"] == "histogram" else spec["type"]
        lines.append(f"# HELP {name} {spec['help']}")
        lines.append(f"# TYPE {name} {ptype}")
        for inst in by_name[name]:
            if isinstance(inst, Histogram):
                d = inst.to_dict()
                for q, key in (("0.5", "p50"), ("0.9", "p90"),
                               ("0.99", "p99")):
                    lines.append(
                        f"{name}"
                        f"{_labels_text(inst.labels, {'quantile': q})} "
                        f"{_fmt(d[key])}"
                    )
                lines.append(
                    f"{name}_sum{_labels_text(inst.labels)} "
                    f"{_fmt(d['sum'])}"
                )
                lines.append(
                    f"{name}_count{_labels_text(inst.labels)} "
                    f"{_fmt(d['count'])}"
                )
            else:
                lines.append(
                    f"{name}{_labels_text(inst.labels)} "
                    f"{_fmt(inst.value)}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


# --------------------------------------------------------------------------
# Tracing
# --------------------------------------------------------------------------

#: (trace_id, span_id) of the active span — contextvars give correct
#: propagation per thread (each HTTP request thread gets its own copy)
_CTX: contextvars.ContextVar[Optional[Tuple[str, str]]] = \
    contextvars.ContextVar("simumax_trace", default=None)


class SpanRecord:
    """One finished span: ids, name, wall bounds (perf_counter
    seconds), and free-form attributes."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "end", "attrs", "thread")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, start: float,
                 end: float, attrs: Dict[str, Any], thread: str):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = start
        self.end = end
        self.attrs = attrs
        self.thread = thread

    @property
    def duration(self) -> float:
        return self.end - self.start

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "name": self.name,
            "start_s": self.start,
            "duration_s": self.duration,
            "thread": self.thread,
            "attrs": self.attrs,
        }


#: per-thread PRNG for id generation: ids must be cheap (they are
#: minted on every served request) and unique, not cryptographic —
#: uuid4 costs ~25us/call on entropy-starved hosts, getrandbits ~0.5us.
#: Seeded per thread from urandom once; thread-local so no lock and no
#: cross-thread sequence coupling
_ID_RNG = threading.local()


def _rng() -> "random.Random":
    rng = getattr(_ID_RNG, "rng", None)
    if rng is None:
        rng = _ID_RNG.rng = random.Random(
            int.from_bytes(os.urandom(8), "big")
            ^ threading.get_ident()
        )
    return rng


def new_trace_id() -> str:
    return f"{_rng().getrandbits(64):016x}"


def new_span_id() -> str:
    # 64-bit like trace ids: span_tree() keys nodes by span_id alone,
    # and a maximal 4096-span trace has a ~0.2% birthday collision at
    # 32 bits — enough to silently corrupt 1 in ~500 large artifacts
    return f"{_rng().getrandbits(64):016x}"


class Tracer:
    """Contextvar-propagated span tracer with bounded retention.

    Id propagation is unconditional once a trace is opened (the HTTP
    server needs ``X-SimuMax-Trace`` and Reporter correlation whether
    or not anyone is recording); :class:`SpanRecord` retention is
    gated on :attr:`enabled` and bounded per trace
    (``max_spans_per_trace``) and across traces (``max_traces``,
    oldest-finished-first eviction)."""

    def __init__(self, max_traces: int = 256,
                 max_spans_per_trace: int = 4096,
                 registry: Optional[MetricsRegistry] = None):
        self.enabled = False
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._lock = threading.Lock()
        #: finished spans per trace id, in completion order
        self._spans: Dict[str, List[SpanRecord]] = {}
        #: trace ids in creation order (for bounded eviction)
        self._order: List[str] = []
        self._registry = registry

    def configure(self, enabled: Optional[bool] = None,
                  registry: Optional[MetricsRegistry] = None) -> "Tracer":
        if enabled is not None:
            self.enabled = bool(enabled)
        if registry is not None:
            self._registry = registry
        return self

    # -- context -----------------------------------------------------------
    @staticmethod
    def current_ids() -> Optional[Tuple[str, str]]:
        """(trace_id, span_id) of the active span, or None."""
        return _CTX.get()

    @staticmethod
    def current_trace_id() -> Optional[str]:
        ids = _CTX.get()
        return ids[0] if ids else None

    @contextlib.contextmanager
    def trace(self, name: str, trace_id: Optional[str] = None,
              **attrs: Any) -> Iterator[str]:
        """Open a root span (a new trace); yields the trace id. Always
        propagates ids; records spans only while :attr:`enabled`."""
        tid = trace_id or new_trace_id()
        sid = new_span_id()
        token = _CTX.set((tid, sid))
        start = time.perf_counter()
        try:
            yield tid
        finally:
            end = time.perf_counter()
            _CTX.reset(token)
            if self.enabled:
                self._record(SpanRecord(
                    tid, sid, None, name, start, end, dict(attrs),
                    threading.current_thread().name,
                ))

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[Optional[str]]:
        """Open a child span under the active trace. A no-op (yields
        None) when no trace is active — library code can annotate
        unconditionally without paying for id generation outside a
        traced request."""
        ids = _CTX.get()
        if ids is None:
            yield None
            return
        tid, parent = ids
        sid = new_span_id()
        token = _CTX.set((tid, sid))
        start = time.perf_counter()
        try:
            yield sid
        finally:
            end = time.perf_counter()
            _CTX.reset(token)
            if self.enabled:
                self._record(SpanRecord(
                    tid, sid, parent, name, start, end, dict(attrs),
                    threading.current_thread().name,
                ))

    # -- retention ---------------------------------------------------------
    def _record(self, rec: SpanRecord):
        with self._lock:
            spans = self._spans.get(rec.trace_id)
            if spans is None:
                spans = self._spans[rec.trace_id] = []
                self._order.append(rec.trace_id)
                while len(self._order) > self.max_traces:
                    evicted = self._order.pop(0)
                    self._spans.pop(evicted, None)
            if len(spans) >= self.max_spans_per_trace:
                if self._registry is not None:
                    self._registry.counter(
                        "trace_spans_dropped_total").inc()
                return
            spans.append(rec)

    def pop_trace(self, trace_id: str) -> List[SpanRecord]:
        """Remove and return one trace's finished spans (completion
        order) — the per-request artifact path."""
        with self._lock:
            spans = self._spans.pop(trace_id, [])
            if trace_id in self._order:
                self._order.remove(trace_id)
            return spans

    def drain(self) -> List[SpanRecord]:
        """Remove and return every finished span (trace creation
        order) — the end-of-command artifact path."""
        with self._lock:
            out: List[SpanRecord] = []
            for tid in self._order:
                out.extend(self._spans.get(tid, []))
            self._spans.clear()
            self._order.clear()
            return out


_TRACER = Tracer(registry=_REGISTRY)


def get_tracer() -> Tracer:
    return _TRACER


def current_ids() -> Optional[Tuple[str, str]]:
    """(trace_id, span_id) of the active span, or None — the Reporter's
    correlation hook."""
    return _CTX.get()


# --------------------------------------------------------------------------
# Span export
# --------------------------------------------------------------------------


def span_tree(spans: List[SpanRecord]) -> List[Dict[str, Any]]:
    """Nest finished spans into parent->children trees (one root per
    trace), each node a ``to_dict`` record plus ``children``."""
    nodes: Dict[str, Dict[str, Any]] = {}
    for s in spans:
        d = s.to_dict()
        d["children"] = []
        nodes[s.span_id] = d
    roots: List[Dict[str, Any]] = []
    for s in spans:
        node = nodes[s.span_id]
        parent = nodes.get(s.parent_id) if s.parent_id else None
        if parent is not None:
            parent["children"].append(node)
        else:
            roots.append(node)
    for node in nodes.values():
        node["children"].sort(key=lambda n: n["start_s"])
    roots.sort(key=lambda n: n["start_s"])
    return roots


def chrome_trace(spans: List[SpanRecord]) -> Dict[str, Any]:
    """Lay finished spans out as Chrome-trace complete events (``ph:
    "X"``), one tid lane per thread — loadable in the same trace viewer
    (Perfetto / chrome://tracing) as the pipeline-schedule traces."""
    if spans:
        t0 = min(s.start for s in spans)
    else:
        t0 = 0.0
    threads = sorted({s.thread for s in spans})
    tid_of = {t: i for i, t in enumerate(threads)}
    events: List[Dict[str, Any]] = [
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "simumax_tpu request tracing"}},
    ]
    for t in threads:
        events.append({
            "name": "thread_name", "ph": "M", "pid": 0,
            "tid": tid_of[t], "args": {"name": t},
        })
    for s in sorted(spans, key=lambda s: (s.start, s.span_id)):
        args: Dict[str, Any] = {
            "trace_id": s.trace_id, "span_id": s.span_id,
        }
        if s.parent_id:
            args["parent_id"] = s.parent_id
        args.update(s.attrs)
        events.append({
            "name": s.name, "ph": "X", "pid": 0, "tid": tid_of[s.thread],
            "ts": (s.start - t0) * 1e6, "dur": s.duration * 1e6,
            "cat": "span", "args": args,
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(spans: List[SpanRecord], path: str) -> str:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(chrome_trace(spans), f)
    return path

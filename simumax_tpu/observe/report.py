"""Shared structured reporter: the one place library code is allowed to
write user-facing progress/report lines (``tests/test_no_bare_print.py``
enforces this — ``print(`` is forbidden in ``simumax_tpu/`` outside this
module and the CLI).

Two output modes, switched by the CLI's ``--log-json`` flag:

* **human** (default): each call prints exactly its ``msg`` string —
  byte-identical to the bare ``print(...)`` calls it replaced, so
  existing scripts/tests that parse stdout keep working;
* **json** (``--log-json``): one JSON object per line with ``ts``
  (epoch seconds), ``level``, ``run_id``, ``msg``, plus any structured
  fields the call site attached — machine-ingestable run logs that
  merge/attribute across processes via the run identity. Lines emitted
  inside an active telemetry trace (a served HTTP request, a
  ``--trace-requests`` command) additionally carry ``trace_id`` /
  ``span_id``, so run logs cross-reference span trees and the
  ``X-SimuMax-Trace`` response header.

``--log-level`` filters: a call below the threshold emits nothing in
either mode. ``debug`` lines only appear with ``--log-level debug``.
"""

from __future__ import annotations

import json
import sys
import time
import uuid
from typing import Any, Optional, TextIO

from simumax_tpu.core.errors import ConfigError
from simumax_tpu.observe.telemetry import current_ids as telemetry_ids

LEVELS = {"debug": 10, "info": 20, "warning": 30, "error": 40}


class Reporter:
    """Leveled line reporter with human/JSON dual output.

    ``stream=None`` resolves ``sys.stdout`` at emit time (so pytest's
    capsys and CLI redirection both see the output)."""

    def __init__(self, level: str = "info", json_lines: bool = False,
                 run_id: str = "", stream: Optional[TextIO] = None):
        self.configure(level=level, json_lines=json_lines, run_id=run_id,
                       stream=stream)

    def configure(self, level: Optional[str] = None,
                  json_lines: Optional[bool] = None,
                  run_id: Optional[str] = None,
                  stream: Optional[TextIO] = None) -> "Reporter":
        if level is not None:
            if level not in LEVELS:
                raise ConfigError(
                    f"unknown log level {level!r}: expected one of "
                    f"{sorted(LEVELS)}"
                )
            self.level = level
            self.threshold = LEVELS[level]
        if json_lines is not None:
            self.json_lines = json_lines
        if run_id is not None:
            self.run_id = run_id or uuid.uuid4().hex[:12]
        if stream is not None:
            self.stream = stream
        elif not hasattr(self, "stream"):
            self.stream = None
        return self

    # -- emission ----------------------------------------------------------
    def log(self, level: str, msg: str, **fields: Any):
        if LEVELS[level] < self.threshold:
            return
        out = self.stream if self.stream is not None else sys.stdout
        if self.json_lines:
            record = {
                "ts": time.time(),
                "level": level,
                "run_id": self.run_id,
                "msg": msg,
            }
            ids = telemetry_ids()
            if ids is not None:
                record["trace_id"], record["span_id"] = ids
            record.update(fields)
            out.write(json.dumps(record, default=str) + "\n")
        else:
            # byte-identical to the print(...) calls this replaced
            out.write(msg + "\n")

    def debug(self, msg: str, **fields: Any):
        self.log("debug", msg, **fields)

    def info(self, msg: str, **fields: Any):
        self.log("info", msg, **fields)

    def warning(self, msg: str, **fields: Any):
        self.log("warning", msg, **fields)

    def error(self, msg: str, **fields: Any):
        self.log("error", msg, **fields)


#: process-wide reporter; the CLI reconfigures it from --log-level /
#: --log-json, library code fetches it via get_reporter()
_REPORTER = Reporter()


def get_reporter() -> Reporter:
    return _REPORTER


def configure_reporter(level: Optional[str] = None,
                       json_lines: Optional[bool] = None,
                       run_id: Optional[str] = None,
                       stream: Optional[TextIO] = None) -> Reporter:
    """Reconfigure the process-wide reporter (the CLI boundary calls
    this once, before any command body runs)."""
    return _REPORTER.configure(level=level, json_lines=json_lines,
                               run_id=run_id, stream=stream)

"""Per-tensor HBM ledger, peak-memory waterfall, and OOM forensics —
the memory-side twin of the cost-attribution ledger (``observe/ledger``).

``analysis_mem`` predicts each stage's peak HBM as one scalar; this
module keeps the provenance behind that scalar. :meth:`MemoryLedger.
collect` replays the same analytical schedules the estimate used (the
1F1B / interleaved replay paths in ``perf.py`` and the per-chunk
activation walk in ``models/llm.py``) and materializes the **full live
set at each stage's predicted peak**: every allocation as a
:class:`~simumax_tpu.core.records.MemSpan` with module path, best-effort
shape, dtype, and sharding provenance, bucketed into a **peak-HBM
waterfall** (params / grads / optimizer states / activation cache /
recompute working set / workspace / comm buffers / MoE routing / MLA
latent-KV) whose buckets sum to ``analysis_mem()["max_peak_bytes"]``
within 1e-6 relative (asserted in tests across dense/MoE/MLA x
pp{1,2,4} x recompute).

Collection is post-hoc and read-only: ledger-on and ledger-off headline
predictions are bit-identical, and sweeps never collect (their rows
carry only the one-line :func:`memory_attribution_line`, derived from
the already-cached ``analysis_mem``).

Three more surfaces ride on the same data:

* **analytical memory timeline** — :func:`analytical_memory_trackers`
  drives a :class:`~simumax_tpu.simulator.memory.SimuMemoryTracker`
  per stage from the schedule replay, so the analytical prediction
  ships the *same* artifacts as the discrete-event simulator (JSON
  snapshot schema, torch memory-viz pickle, Chrome counter tracks) and
  the two can be diffed directly;
* **analytical-vs-DES cross-check** — :func:`mem_crosscheck` compares
  per-stage peaks against a ``simulate(track_memory=True)`` run, the
  memory analog of the sweep's ``sim_vs_analytical`` column;
* **OOM forensics** — :func:`oom_forensics` reports the top holders at
  the binding stage's peak plus :func:`whatif_probes`: re-costed
  candidate fixes (halved micro-batch via the existing ``rebatch()``
  build-reuse fast path, recompute escalation, the next ZeRO stage),
  ranked so the *cheapest fitting change* is named explicitly.

CLI: ``simumax_tpu explain --memory`` and ``simumax_tpu diff --memory``
(see ``docs/observability.md``).
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from simumax_tpu.core.config import GiB
from simumax_tpu.core.errors import ConfigError
from simumax_tpu.core.records import Diagnostics, MemSpan

MEM_LEDGER_SCHEMA = "simumax-memledger-v1"

#: peak-HBM waterfall buckets in presentation order; they sum to the
#: stage's ``analysis_mem`` ``peak_bytes`` (definitions in
#: docs/observability.md). ``recompute_working_set`` may go slightly
#: negative when a peak lands mid-replay with the saved segment input
#: reuse outweighing the re-materialized raw caches.
MEM_WATERFALL_ORDER = (
    "params",
    "grads",
    "optimizer_states",
    "activation_cache",
    "recompute_working_set",
    "workspace",
    "comm_buffers",
    "moe_routing",
    "mla_latent_kv",
)

_MEM_SHORT = {
    "params": "wt",
    "grads": "grad",
    "optimizer_states": "opt",
    "activation_cache": "act",
    "recompute_working_set": "recomp",
    "workspace": "wksp",
    "comm_buffers": "comm",
    "moe_routing": "moe",
    "mla_latent_kv": "kv",
}

#: leaf op categories whose activation state is routing bookkeeping
#: (dispatch/combine indices, router logits) rather than generic caches
_MOE_ROUTING_CATEGORIES = frozenset({"router", "moe_dispatch"})
#: MLA down-projections cache the compressed latent the runtime would
#: keep as the KV cache — surfaced as their own bucket so the latent-KV
#: saving of MLA (ROADMAP item 4's serving workload) is visible
_MLA_LATENT_CATEGORIES = frozenset({"mla_down_proj"})

#: transient probe kinds -> waterfall bucket
_TRANSIENT_BUCKET = {
    "fwd_temp": "workspace",
    "bwd_temp": "workspace",
    "grad_flight": "comm_buffers",
    "saved_input_reuse": "recompute_working_set",
    "recompute_cache": "recompute_working_set",
}


def _cache_bucket(leaf) -> str:
    cat = getattr(leaf, "op_category", "other")
    if cat in _MOE_ROUTING_CATEGORIES:
        return "moe_routing"
    if cat in _MLA_LATENT_CATEGORIES:
        return "mla_latent_kv"
    return "activation_cache"


def _holder_bucket(leaf, kind: str) -> str:
    if kind == "act_cache":
        return _cache_bucket(leaf)
    return _TRANSIENT_BUCKET[kind]


def _param_shape(leaf) -> Optional[str]:
    """Best-effort parameter shape: GEMM leaves expose their (k, n) via
    ``gemm_mnk``; the embedding its (vocab, hidden); norms their width."""
    if hasattr(leaf, "gemm_mnk") and leaf.outputs:
        b, _, k, n = leaf.gemm_mnk("fwd")
        return f"({b}, {k}, {n})" if b > 1 else f"({k}, {n})"
    if hasattr(leaf, "vocab") and hasattr(leaf, "hidden"):
        return f"({leaf.vocab}, {leaf.hidden})"
    if hasattr(leaf, "hidden"):
        return f"({leaf.hidden},)"
    return None


def _act_shape_dtype(leaf) -> Tuple[Optional[str], str]:
    """Indicative shape/dtype of a leaf's cached activation (the module
    input it saves for backward)."""
    if leaf.inputs:
        t = leaf.inputs[0]
        return str(list(t.shape)), t.dtype
    return None, ""


def _param_sharding(st, kind: str, moe: bool) -> str:
    """Provenance string: which ZeRO stage shards this tensor family and
    over which data-parallel group (mirrors ``make_param_info``)."""
    dim = "edp" if moe else "dp_cp"
    group = st.edp_size if moe else st.dp_size * st.cp_size
    sharded_from = {"weight": 3, "grad": 2, "opt_state": 1}[kind]
    z = st.zero_state
    verb = "sharded" if z >= sharded_from and group > 1 else "replicated"
    return f"zero{z}: {verb} over {dim}{group}"


def _act_sharding(st) -> str:
    parts = [f"cp{st.cp_size}"]
    if st.enable_sequence_parallel and st.tp_size > 1:
        parts.append(f"sp{st.tp_size}")
    return "seq " + "x".join(parts)


# --------------------------------------------------------------------------
# Peak live-set materialization
# --------------------------------------------------------------------------


def replay_peak_holders(chunk) -> Tuple[float, List[Tuple[Any, str, float]]]:
    """Fold one chunk's ``activation_events()`` walk (the exact stream
    ``compute_activations`` folds to the scalar ``peak_point``) and
    materialize the live set at the winning probe.

    Returns ``(peak_bytes, holders)`` where ``holders`` is a list of
    ``(leaf, kind, bytes)`` summing to ``peak_bytes`` (up to float
    association); ``peak_bytes`` equals ``chunk.peak_point.bytes``.

    Two passes: the first locates the winning probe (the same
    ``cand > peak`` fold as ``compute_activations``), the second
    materializes holders only up to that probe — no per-probe copies.
    """
    # pass 1: locate the winning probe index
    live = 0.0
    peak_bytes = 0.0
    peak_idx = -1
    for idx, ev in enumerate(chunk.activation_events()):
        op = ev[0]
        if op == "alloc":
            live += ev[3]
        elif op == "free":
            live -= ev[3]
        else:
            cand = live
            for _, extra in ev[3]:
                cand += extra
            if cand > peak_bytes:
                peak_bytes, peak_idx = cand, idx
    if peak_idx < 0:
        return 0.0, []
    # pass 2: materialize the live set at that probe
    holders: Dict[Tuple[int, str], List] = {}
    for idx, ev in enumerate(chunk.activation_events()):
        op = ev[0]
        if op == "alloc":
            h = holders.setdefault((id(ev[1]), ev[2]), [ev[1], ev[2], 0.0])
            h[2] += ev[3]
        elif op == "free":
            h = holders.get((id(ev[1]), ev[2]))
            if h is not None:
                h[2] -= ev[3]
                if h[2] == 0.0:
                    del holders[(id(ev[1]), ev[2])]
        elif idx == peak_idx:
            out = [(l, k, b) for l, k, b in
                   (tuple(h) for h in holders.values()) if b]
            out.extend(
                (ev[1], kind, extra) for kind, extra in ev[3] if extra
            )
            return peak_bytes, out
    raise AssertionError("activation walk changed between passes")


def _interleaved_peak_state(perf, stage: int):
    """The interleaved schedule-position replay of one stage — the
    SHARED fold (``perf.interleaved_stage_peak``, the one
    ``_analysis_mem_interleaved`` itself uses) with the holder-side
    outputs kept: ``(counts, active_chunk)`` where ``counts`` maps
    chunk_idx -> number of full per-microbatch caches held at the peak
    (the active chunk's own microbatch already excluded — its partial
    state is the chunk walk's holder set) and ``active_chunk`` is None
    when the plain outstanding-cache sum won the max."""
    from simumax_tpu.parallel.pipeline import interleaved_order
    from simumax_tpu.perf import interleaved_stage_peak

    st = perf.strategy
    order = interleaved_order(
        st.pp_size, stage, st.micro_batch_num, st.vp_size,
        st.vpp_group_size,
    )
    chunks = perf.stage_chunks(stage)
    cache = {ch.chunk_idx: ch.act_info.cache_bytes for ch in chunks}
    peakpt = {
        ch.chunk_idx: ch.peak_point.bytes if ch.peak_point else 0.0
        for ch in chunks
    }
    _, _, peak_counts, peak_active = interleaved_stage_peak(
        order, cache, peakpt
    )
    return peak_counts, peak_active


def _param_spans(perf, stage: int) -> List[MemSpan]:
    st = perf.strategy
    spans: List[MemSpan] = []
    for chunk in perf.stage_chunks(stage):
        for leaf in chunk.called_leaves():
            pi = leaf.param_info
            if not pi.total_bytes:
                continue
            shape = _param_shape(leaf)
            for moe in (False, True):
                fam = (
                    (("weight", pi.moe_weight_bytes, "params", st.dtype),
                     ("grad", pi.moe_grad_bytes, "grads",
                      "fp32" if st.grad_element_size == 4 else st.dtype),
                     ("opt_state", pi.moe_state_bytes,
                      "optimizer_states", "fp32"))
                    if moe else
                    (("weight", pi.weight_bytes, "params", st.dtype),
                     ("grad", pi.grad_bytes, "grads",
                      "fp32" if st.grad_element_size == 4 else st.dtype),
                     ("opt_state", pi.state_bytes,
                      "optimizer_states", "fp32"))
                )
                for kind, nbytes, bucket, dtype in fam:
                    if not nbytes:
                        continue
                    spans.append(MemSpan(
                        path=leaf.path_name(),
                        module_type=type(leaf).__name__,
                        category=leaf.op_category,
                        stage=stage,
                        chunk=chunk.chunk_idx,
                        bucket=bucket,
                        kind=kind,
                        bytes=nbytes,
                        count=1,
                        shape=shape,
                        dtype=dtype,
                        sharding=_param_sharding(st, kind, moe),
                    ))
    return spans


def collect_stage_spans(perf, stage: int) -> List[MemSpan]:
    """The full live set at ``stage``'s predicted peak, as MemSpans that
    sum to ``analysis_mem()["stages"][stage]["peak_bytes"]`` within 1e-6
    relative (param spans + one activation cache per outstanding
    microbatch + the active chunk's internal-walk holders, mirroring the
    exact arithmetic ``analysis_mem`` used)."""
    st = perf.strategy
    spans = _param_spans(perf, stage)
    chunks = perf.stage_chunks(stage)
    act_shard = _act_sharding(st)

    if st.vp_size > 1:
        counts, active = _interleaved_peak_state(perf, stage)
        active_chunks = [c for c in chunks if c.chunk_idx == active]
    else:
        # the stage's in-flight count comes from analysis_mem itself
        # (the stable schema's live_microbatches), not a re-derived
        # formula — one source, so the ledger cannot drift from the
        # headline's admission model
        live = perf.analysis_mem()["stages"][stage]["live_microbatches"]
        out = max(live - 1, 0)
        counts = {c.chunk_idx: out for c in chunks}
        # vp=1 has one chunk per stage; its internal walk peak always
        # rides on top of the outstanding caches (analysis_mem adds
        # replay_peak unconditionally)
        active_chunks = (
            [max(chunks, key=lambda c:
                 c.peak_point.bytes if c.peak_point else 0.0)]
            if chunks else []
        )

    # one full per-microbatch activation cache per outstanding microbatch
    for chunk in chunks:
        n = counts.get(chunk.chunk_idx, 0)
        if n <= 0:
            continue
        for leaf in chunk.called_leaves():
            cb = leaf.act_info.cache_bytes
            if not cb:
                continue
            shape, dtype = _act_shape_dtype(leaf)
            spans.append(MemSpan(
                path=leaf.path_name(),
                module_type=type(leaf).__name__,
                category=leaf.op_category,
                stage=stage,
                chunk=chunk.chunk_idx,
                bucket=_cache_bucket(leaf),
                kind="act_cache",
                bytes=cb * n,
                count=n,
                shape=shape,
                dtype=dtype,
                sharding=act_shard,
            ))

    # the active chunk's internal activation walk at ITS peak: building
    # caches, recompute raw caches, fwd/bwd workspace, grads in flight
    for chunk in active_chunks:
        _, holders = replay_peak_holders(chunk)
        for leaf, kind, nbytes in holders:
            shape, dtype = _act_shape_dtype(leaf)
            spans.append(MemSpan(
                path=leaf.path_name(),
                module_type=type(leaf).__name__,
                category=leaf.op_category,
                stage=stage,
                chunk=chunk.chunk_idx,
                bucket=_holder_bucket(leaf, kind),
                kind=kind,
                bytes=nbytes,
                count=1,
                shape=shape,
                dtype=dtype,
                sharding=act_shard,
            ))
    return spans


def _bucket_sums(spans: List[MemSpan]) -> Dict[str, float]:
    buckets = {k: 0.0 for k in MEM_WATERFALL_ORDER}
    for s in spans:
        buckets[s.bucket] += s.bytes
    return buckets


def build_memory_waterfall(perf, spans_by_stage=None) -> Dict[str, Any]:
    """Decompose the headline peak-HBM prediction into the memory
    buckets. ``buckets`` belong to the binding (max-peak) stage and sum
    to ``analysis_mem()["max_peak_bytes"]`` within 1e-6 relative;
    ``per_stage`` carries every stage's decomposition.

    ``spans_by_stage`` (stage -> span list) reuses an already-collected
    live set instead of re-walking every chunk — ``MemoryLedger.
    collect`` passes its own so each stage is materialized once."""
    mem = perf.analysis_mem()
    if spans_by_stage is None:
        spans_by_stage = {
            s: collect_stage_spans(perf, s)
            for s in range(len(mem["stages"]))
        }
    per_stage = []
    for s, entry in enumerate(mem["stages"]):
        buckets = _bucket_sums(spans_by_stage[s])
        per_stage.append({
            "stage": s,
            "buckets": buckets,
            "total": entry["peak_bytes"],
            "fits_margin_bytes": entry["fits_margin_bytes"],
        })
    binding = mem["binding_stage"]
    return {
        "order": list(MEM_WATERFALL_ORDER),
        "buckets": per_stage[binding]["buckets"],
        "total": mem["max_peak_bytes"],
        "binding_stage": binding,
        "per_stage": per_stage,
        "usable_bytes": mem["usable_bytes"],
        "fits": mem["fits"],
    }


def memory_attribution_line(perf) -> str:
    """One-line peak-memory summary for sweep CSV rows, e.g.
    ``wt 21.3% | grad 10.7% | opt 32.0% | act 36.0%``. Derived from the
    already-cached ``analysis_mem`` only — no ledger walk, so sweeps
    stay on the zero-cost path (``act`` folds every activation-side
    bucket; the full split is ``explain --memory``)."""
    mem = perf.analysis_mem()
    entry = mem["stages"][mem["binding_stage"]]
    peak = entry["peak_bytes"] or 1.0
    act = entry["peak_bytes"] - entry["model_bytes"]
    parts = []
    for tag, v in (("wt", entry["weight_bytes"]),
                   ("grad", entry["grad_bytes"]),
                   ("opt", entry["optimizer_state_bytes"]),
                   ("act", act)):
        pct = round(100.0 * v / peak, 1) + 0.0
        parts.append(f"{tag} {pct:.1f}%")
    return " | ".join(parts)


# --------------------------------------------------------------------------
# Analytical memory timeline (SimuMemoryTracker schema)
# --------------------------------------------------------------------------


def analytical_memory_trackers(perf, record_events: bool = True) -> list:
    """Drive one :class:`~simumax_tpu.simulator.memory.SimuMemoryTracker`
    per stage from the analytical schedule replay (``_schedule_events``
    — the exact intervals the headline time came from): static = the
    stage's model bytes, one activation-cache token per (microbatch,
    chunk) allocated at its forward's end and freed at its backward's
    end. Token naming (``mb{i}:c{chunk}``) matches the discrete-event
    simulator's chunk granularity, so snapshots/pickles from the two
    predictors diff directly. This is also the single source of the
    analytical ``hbm_bytes`` counter tracks in ``observe/trace.py``
    (which passes ``record_events=False`` to skip the per-event viz
    trace it does not serialize)."""
    from simumax_tpu.simulator.memory import SimuMemoryTracker

    perf.analysis_cost()  # ensures the schedule replay ran (cached)
    st = perf.strategy
    trackers = []
    for s in range(st.pp_size):
        chunks = perf.stage_chunks(s)
        static = sum(c.param_info.total_bytes for c in chunks)
        cache = {c.chunk_idx: c.act_info.cache_bytes for c in chunks}
        tr = SimuMemoryTracker(s, static_bytes=static,
                               record_events=record_events,
                               source="analytical")
        stage_events = sorted(
            (e for e in perf._schedule_events if e[0] == s),
            key=lambda e: (e[4], e[5]),
        )
        for (_, kind, c, mb, _, end) in stage_events:
            nbytes = cache.get(c, 0.0)
            if not nbytes:
                continue
            token = f"mb{mb}:c{c}"
            if kind == "F":
                tr.alloc(end, nbytes, token, "act")
            else:
                tr.free(end, token=token, tag="act")
        trackers.append(tr)
    return trackers


def export_analytical_memory(perf, save_path: str) -> Dict[str, str]:
    """Write the analytical memory timeline in the simulator's artifact
    formats: the JSON snapshot (``simumax_tpu_memory_snapshot_v1``), the
    torch memory-viz pickle (binding stage), and a Chrome trace of the
    per-stage ``hbm_bytes`` counter tracks."""
    from simumax_tpu.simulator.memory import export_memory_viz
    from simumax_tpu.simulator.trace import write_chrome_trace

    os.makedirs(save_path, exist_ok=True)
    trackers = analytical_memory_trackers(perf)
    paths = {}
    snap_path = os.path.join(save_path, "analytical_memory_snapshot.json")
    with open(snap_path, "w", encoding="utf-8") as f:
        json.dump([t.snapshot() for t in trackers], f)
    paths["snapshot"] = snap_path
    # the stage analysis_mem calls binding, not the tracker-peak argmax:
    # tracker timelines carry only whole-microbatch caches, so their
    # peaks can rank stages differently from the headline (which adds
    # the replay transient) — all artifacts of one run must agree on
    # which stage is binding
    binding = perf.analysis_mem()["binding_stage"]
    paths["memory_viz"] = export_memory_viz(
        trackers[binding],
        os.path.join(save_path, "analytical_memory_viz.pickle"),
    )
    paths["counters"] = write_chrome_trace(
        os.path.join(save_path, "analytical_memory_counters.json"),
        [], trackers,
    )
    return paths


def mem_crosscheck(perf, granularity: str = "leaf") -> Dict[str, Any]:
    """Per-stage analytical-vs-DES peak cross-check (the memory analog
    of the sweep's ``sim_vs_analytical`` time column): run the
    discrete-event simulator with memory tracking (one representative
    rank per stage) and compare each stage's simulated peak against
    ``analysis_mem``'s prediction. ``leaf`` granularity replays temps /
    recompute / grad-flight like the analytical walk; ``chunk`` only
    tracks whole-microbatch caches, so its peaks sit below the
    analytical number by the transient working set."""
    mem = perf.analysis_mem()
    sim = perf.simulate(None, granularity=granularity, track_memory=True)
    stages = []
    for s, summ in enumerate(sim["memory"]):
        ana = mem["stages"][s]["peak_bytes"]
        des = summ["peak_bytes"]
        stages.append({
            "stage": s,
            "analytical_peak_gib": ana / GiB,
            "des_peak_gib": des / GiB,
            "des_vs_analytical": (des / ana) if ana else None,
        })
    ratios = [r["des_vs_analytical"] for r in stages
              if r["des_vs_analytical"] is not None]
    return {
        "granularity": granularity,
        "stages": stages,
        "min_ratio": min(ratios) if ratios else None,
        "max_ratio": max(ratios) if ratios else None,
    }


# --------------------------------------------------------------------------
# OOM forensics / what-if probes
# --------------------------------------------------------------------------


def whatif_probes(perf) -> List[Dict[str, Any]]:
    """Re-cost candidate memory-saving changes against this estimate and
    report each one's feasibility and step-time cost. Probes:

    * ``halve_mbs`` — micro_batch_size/2, micro_batch_num*2 (same GBS),
      evaluated through the existing ``rebatch()`` build-reuse fast path
      on a copy of the built graph;
    * ``recompute=selective`` / ``recompute=full_block`` — escalate the
      recompute family (fresh build);
    * ``zero=N`` — the next ZeRO stage (fresh build).

    Never mutates ``perf``; probe failures from genuinely infeasible
    configs (``SimuMaxError`` family, ``rebatch``'s ``ValueError``) are
    reported as rows with an ``error`` field instead of aborting.
    ``AssertionError`` is deliberately NOT caught: an internal
    invariant violation (conservation/schedule checks) is an estimator
    bug and must stay loud — the same policy the sweep's
    ``evaluate_strategy`` documents."""
    import copy as _copy

    from simumax_tpu.core.errors import SimuMaxError

    st = perf.strategy
    base_iter = perf.analysis_cost()["iter_time_ms"]
    # the schema's own threshold, not a re-derivation — probe margins
    # must use the same usable-HBM number the headline fits verdict did
    cap = perf.analysis_mem()["usable_bytes"]
    probes: List[Dict[str, Any]] = []

    def record(change: str, perf2):
        mem2 = perf2.analysis_mem()
        cost2 = perf2.analysis_cost()
        probes.append({
            "change": change,
            "fits": mem2["fits"],
            "peak_gib": mem2["max_peak_gib"],
            "mem_margin_gib": (cap - mem2["max_peak_bytes"]) / GiB,
            "iter_time_ms": cost2["iter_time_ms"],
            "iter_penalty_pct": (
                100.0 * (cost2["iter_time_ms"] - base_iter) / base_iter
                if base_iter else 0.0
            ),
        })

    def fail(change: str, exc: Exception):
        probes.append({"change": change, "fits": False,
                       "error": f"{type(exc).__name__}: {exc}"})

    if st.micro_batch_size > 1 and st.micro_batch_size % 2 == 0:
        change = (f"mbs {st.micro_batch_size} -> "
                  f"{st.micro_batch_size // 2} (mbc x2)")
        st2 = _copy.deepcopy(st)
        st2.micro_batch_size //= 2
        st2.micro_batch_num *= 2
        probe = _copy.deepcopy(perf)
        probe.diagnostics = Diagnostics()
        try:
            probe.rebatch(st2)
            record(change, probe)
        except (SimuMaxError, ValueError) as exc:
            fail(change, exc)

    rc = st.recompute
    rebuilds: List[Tuple[str, Dict[str, Any]]] = []
    if not rc.enabled:
        rebuilds.append(("recompute=selective(sdp)", dict(
            enable_recompute=True, recompute_granularity="selective",
            recompute_layer_num=-1, sdp_recompute=True,
        )))
    if rc.granularity != "full_block":
        rebuilds.append(("recompute=full_block", dict(
            enable_recompute=True, recompute_granularity="full_block",
            recompute_layer_num=-1,
        )))
    if st.zero_state < 3 and st.dp_size * st.cp_size > 1:
        rebuilds.append((f"zero={st.zero_state + 1}", dict(
            zero_state=st.zero_state + 1,
        )))
    for change, fields in rebuilds:
        st2 = _copy.deepcopy(st)
        for k, v in fields.items():
            setattr(st2, k, v)
        try:
            st2.__post_init__()
            from simumax_tpu.perf import PerfLLM

            p2 = PerfLLM()
            p2.diagnostics = Diagnostics()
            p2.configure(st2, _copy.deepcopy(perf.model_config),
                         _copy.deepcopy(perf.system))
            p2.run_estimate()
            record(change, p2)
        except (SimuMaxError, ValueError) as exc:
            fail(change, exc)
    fitting = [p for p in probes if p.get("fits")]
    if fitting:
        cheapest = min(fitting, key=lambda p: p["iter_time_ms"])
        cheapest["cheapest_fit"] = True
    return probes


def oom_forensics(perf, top: int = 8, probes: bool = True,
                  spans: Optional[List[MemSpan]] = None) -> Dict[str, Any]:
    """Forensic report for a config's HBM verdict: the binding stage,
    deficit vs usable HBM, the top holders of its peak live set, and
    (optionally) the what-if probe table naming the cheapest fitting
    change. Useful for fits=True configs too (headroom audit), but built
    for the ``fits=False`` triage loop.

    ``spans`` reuses an already-collected span list (e.g. a
    ``MemoryLedger``'s) instead of re-walking the binding stage."""
    mem = perf.analysis_mem()
    binding = mem["binding_stage"]
    if spans is None:
        spans = collect_stage_spans(perf, binding)
    holders = sorted((s for s in spans if s.stage == binding),
                     key=lambda s: s.bytes, reverse=True)
    return {
        "fits": mem["fits"],
        "binding_stage": binding,
        "peak_gib": mem["max_peak_gib"],
        "usable_gib": mem["usable_gib"],
        "deficit_gib": max(0.0, -mem["fits_margin_bytes"]) / GiB,
        "top_holders": [s.to_dict() for s in holders[:top]],
        "what_if": whatif_probes(perf) if probes else [],
    }


def oom_forensic_lines(report: Dict[str, Any]) -> List[str]:
    """Human rendering of an OOM forensics report."""
    verdict = "fits" if report["fits"] else "OOM"
    lines = [
        f"== memory forensics: stage {report['binding_stage']} peaks at "
        f"{report['peak_gib']:.2f} GiB / {report['usable_gib']:.2f} GiB "
        f"usable ({verdict}"
        + (f", deficit {report['deficit_gib']:.2f} GiB" if not report["fits"]
           else "")
        + ") =="
    ]
    if report["top_holders"]:
        lines.append("  -- top holders at the peak --")
        for h in report["top_holders"]:
            n = f" x{h['count']}" if h["count"] > 1 else ""
            shape = f" {h['shape']}" if h["shape"] else ""
            lines.append(
                f"  {h['bytes'] / GiB:8.3f} GiB  [{h['bucket']}] "
                f"{h['path']} ({h['kind']}{n}{shape}, {h['sharding']})"
            )
    if report["what_if"]:
        lines.append("  -- what-if probes (same GBS) --")
        for p in report["what_if"]:
            if "error" in p:
                lines.append(f"    {p['change']:<28} infeasible: "
                             f"{p['error']}")
                continue
            tag = "fits" if p["fits"] else "OOM "
            star = "  <- cheapest fit" if p.get("cheapest_fit") else ""
            lines.append(
                f"    {p['change']:<28} {tag} peak {p['peak_gib']:7.2f} "
                f"GiB  iter {p['iter_time_ms']:9.2f} ms "
                f"({p['iter_penalty_pct']:+.1f}%){star}"
            )
    return lines


# --------------------------------------------------------------------------
# The ledger object
# --------------------------------------------------------------------------


@dataclass
class MemoryLedger:
    """The collected memory-attribution record of one estimate."""

    meta: Dict[str, Any] = field(default_factory=dict)
    headline: Dict[str, Any] = field(default_factory=dict)
    waterfall: Dict[str, Any] = field(default_factory=dict)
    spans: List[MemSpan] = field(default_factory=list)
    #: per-stage analytical timeline in the simulator's snapshot schema
    timeline: List[Dict[str, Any]] = field(default_factory=list)

    @classmethod
    def collect(cls, perf, timeline: bool = True) -> "MemoryLedger":
        assert perf.ctx is not None, "call run_estimate() before collect()"
        st, m, sysc = perf.strategy, perf.model_config, perf.system
        mem = perf.analysis_mem()
        identity = {
            "model": m.model_name,
            "system": sysc.sys_name,
            "system_hash": sysc.fingerprint(),
            "seq_len": st.seq_len,
            "global_batch_size": st.global_batch_size,
            "parallelism": {
                "tp": st.tp_size, "cp": st.cp_size, "pp": st.pp_size,
                "dp": st.dp_size, "ep": st.ep_size, "etp": st.etp_size,
                "vp": st.vp_size, "zero": st.zero_state,
                "mbs": st.micro_batch_size, "mbc": st.micro_batch_num,
            },
            # memory-relevant knobs the time ledger's identity omits:
            # two runs differing only in recompute wiring have
            # different peaks and must not share a run_id. Explicit
            # fields (not asdict) so the hash stays stable: the
            # frozenset tail_modules would stringify in hash-seed order
            "recompute": {
                "granularity": st.recompute.granularity,
                "layer_num": st.recompute.recompute_layer_num,
                "attn": st.recompute.attn_recompute,
                "attn_norm": st.recompute.attn_norm_recompute,
                "mlp": st.recompute.mlp_recompute,
                "mlp_norm": st.recompute.mlp_norm_recompute,
                "sdp": st.recompute.sdp_recompute,
                "moe_act": st.recompute.moe_act_recompute,
                "mla_up_proj": st.recompute.mla_up_proj_recompute,
                "variance": st.recompute.variance,
                "tail_modules": sorted(st.recompute.tail_modules),
            },
            "mem_factor": st.mem_factor,
        }
        run_id = Diagnostics.identity_hash(identity)
        if not perf.diagnostics.run_id:
            perf.diagnostics.set_run_identity(identity)
        # one walk per stage: the waterfall and the span list are two
        # views of the same collected live sets
        spans_by_stage = {
            s: collect_stage_spans(perf, s) for s in range(st.pp_size)
        }
        wf = build_memory_waterfall(perf, spans_by_stage=spans_by_stage)
        spans = [
            span
            for s in range(st.pp_size)
            for span in spans_by_stage[s]
        ]
        return cls(
            meta={"run_id": run_id, **identity,
                  "world_size": st.world_size},
            headline={
                "max_peak_gib": mem["max_peak_gib"],
                "usable_gib": mem["usable_gib"],
                "hbm_capacity_gib": mem["hbm_capacity_gib"],
                "fits": mem["fits"],
                "mem_margin_gib": mem["fits_margin_bytes"] / GiB,
                "stage_peak_gib": [s["peak_gib"] for s in mem["stages"]],
                "stage_margin_gib": [
                    s["fits_margin_bytes"] / GiB for s in mem["stages"]
                ],
            },
            waterfall=wf,
            spans=spans,
            # snapshot() never serializes the per-event viz trace, so
            # skip recording it (export_analytical_memory builds its
            # own event-recording trackers for the pickle)
            timeline=(
                [t.snapshot() for t in
                 analytical_memory_trackers(perf, record_events=False)]
                if timeline else []
            ),
        )

    # -- aggregation -------------------------------------------------------
    def span_rows(self, stage: Optional[int] = None) -> List[Dict[str, Any]]:
        """Per-path rows (kinds folded) for one stage (default: the
        binding stage), sorted by bytes held at the peak descending —
        the `explain --memory` top-holders table."""
        if stage is None:
            stage = self.waterfall.get("binding_stage", 0)
        rows: Dict[Tuple[str, str], Dict[str, Any]] = {}
        for s in self.spans:
            if s.stage != stage:
                continue
            r = rows.setdefault((s.path, s.bucket), {
                "path": s.path, "module_type": s.module_type,
                "category": s.category, "stage": s.stage,
                "chunk": s.chunk, "bucket": s.bucket, "kinds": [],
                "bytes": 0.0, "count": 0, "shape": s.shape,
                "dtype": s.dtype, "sharding": s.sharding,
            })
            r["bytes"] += s.bytes
            # additive: total instances folded into ``bytes`` (e.g. 3
            # outstanding full caches + the active microbatch's partial
            # one -> count 4), keeping bytes/count a true average
            r["count"] += s.count
            if s.kind not in r["kinds"]:
                r["kinds"].append(s.kind)
        out = sorted(rows.values(), key=lambda r: r["bytes"], reverse=True)
        # share is of the REQUESTED stage's own peak, not the binding
        # stage's — rows of any stage sum to ~1
        per_stage = self.waterfall.get("per_stage") or []
        total = (
            per_stage[stage]["total"] if stage < len(per_stage)
            else self.waterfall.get("total")
        ) or 1.0
        for r in out:
            r["share"] = r["bytes"] / total
            r["kinds"] = ",".join(r["kinds"])
        return out

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": MEM_LEDGER_SCHEMA,
            "meta": self.meta,
            "headline": self.headline,
            "waterfall": self.waterfall,
            "spans": [s.to_dict() for s in self.spans],
            "timeline": self.timeline,
        }

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=1)
        return path

    @staticmethod
    def load(path: str) -> Dict[str, Any]:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        schema = data.get("schema")
        if schema != MEM_LEDGER_SCHEMA:
            raise ConfigError(
                f"{path}: not a simumax memory ledger (schema={schema!r}; "
                f"expected {MEM_LEDGER_SCHEMA!r} — produce one with "
                f"`simumax_tpu explain ... --memory --json PATH`)"
            )
        return data

    # -- presentation ------------------------------------------------------
    def waterfall_lines(self) -> List[str]:
        """Human peak-HBM waterfall rendering (the `explain --memory`
        default output)."""
        wf = self.waterfall
        total = wf["total"] or 1.0
        width = max(len(k) for k in wf["order"])
        verdict = "fits" if self.headline["fits"] else "OOM"
        lines = [
            f"== peak-HBM waterfall: {self.meta['model']} on "
            f"{self.meta['system']} — stage {wf['binding_stage']} peaks "
            f"at {self.headline['max_peak_gib']:.2f} GiB / "
            f"{self.headline['usable_gib']:.2f} GiB usable "
            f"({verdict}, margin "
            f"{self.headline['mem_margin_gib']:+.2f} GiB) =="
        ]
        for key in wf["order"]:
            v = wf["buckets"][key]
            if v == 0.0:
                continue
            gib = round(v / GiB, 3) + 0.0
            pct = round(100.0 * v / total, 2) + 0.0
            lines.append(f"  {key:<{width}}  {gib:10.3f} GiB  {pct:6.2f}%")
        lines.append(
            f"  {'= peak HBM':<{width}}  {total / GiB:10.3f} GiB  100.00%"
        )
        return lines

    def top_holder_lines(self, n: int = 10) -> List[str]:
        rows = self.span_rows()[:n]
        if not rows:
            return []
        lines = [
            f"-- top holders at stage "
            f"{self.waterfall['binding_stage']}'s peak --"
        ]
        for r in rows:
            cnt = f" x{r['count']}" if r["count"] > 1 else ""
            shape = f" {r['shape']}" if r["shape"] else ""
            lines.append(
                f"  {r['bytes'] / GiB:8.3f} GiB  {r['share'] * 100:5.1f}%  "
                f"[{r['bucket']}]  {r['path']} "
                f"({r['kinds']}{cnt}{shape}, {r['sharding']})"
            )
        return lines


# --------------------------------------------------------------------------
# Memory-ledger diffing
# --------------------------------------------------------------------------


def _span_totals(ledger: Dict[str, Any]) -> Dict[str, float]:
    """Per-path byte totals at the binding stage's peak."""
    binding = ledger["waterfall"].get("binding_stage", 0)
    out: Dict[str, float] = {}
    for s in ledger.get("spans", []):
        if s["stage"] != binding:
            continue
        out[s["path"]] = out.get(s["path"], 0.0) + s["bytes"]
    return out


def diff_memory_ledgers(a: Dict[str, Any], b: Dict[str, Any],
                        top: int = 20) -> Dict[str, Any]:
    """Compare two memory ledgers (two strategies, or before/after a
    model change): which buckets and which tensors account for the peak
    delta. Diffing a ledger against itself reports zero everywhere."""
    headline = {
        k: {
            "a": a["headline"].get(k),
            "b": b["headline"].get(k),
            "delta": (b["headline"].get(k) or 0.0)
            - (a["headline"].get(k) or 0.0),
        }
        for k in ("max_peak_gib", "mem_margin_gib")
    }
    wf = {
        k: {
            "a": a["waterfall"]["buckets"].get(k, 0.0),
            "b": b["waterfall"]["buckets"].get(k, 0.0),
            "delta": b["waterfall"]["buckets"].get(k, 0.0)
            - a["waterfall"]["buckets"].get(k, 0.0),
        }
        for k in set(a["waterfall"]["buckets"]) | set(b["waterfall"]["buckets"])
    }
    spans_a, spans_b = _span_totals(a), _span_totals(b)
    deltas = [
        {"path": p, "a": spans_a.get(p, 0.0), "b": spans_b.get(p, 0.0),
         "delta": spans_b.get(p, 0.0) - spans_a.get(p, 0.0)}
        for p in set(spans_a) | set(spans_b)
    ]
    deltas.sort(key=lambda d: abs(d["delta"]), reverse=True)
    # per-stage peaks: a change confined to a NON-binding stage moves
    # none of the binding-stage numbers above, but it is still a real
    # memory delta and must not read as "identical"
    peaks_a = a["headline"].get("stage_peak_gib") or []
    peaks_b = b["headline"].get("stage_peak_gib") or []
    n_stages = max(len(peaks_a), len(peaks_b))
    stage_peaks = [
        {"stage": s,
         "a": peaks_a[s] if s < len(peaks_a) else None,
         "b": peaks_b[s] if s < len(peaks_b) else None,
         "delta": (peaks_b[s] if s < len(peaks_b) else 0.0)
         - (peaks_a[s] if s < len(peaks_a) else 0.0)}
        for s in range(n_stages)
    ]
    identical = (
        all(v["delta"] == 0 for v in headline.values())
        and all(v["delta"] == 0 for v in wf.values())
        and all(d["delta"] == 0 for d in deltas)
        and len(peaks_a) == len(peaks_b)
        and all(s["delta"] == 0 for s in stage_peaks)
        and a["headline"].get("fits") == b["headline"].get("fits")
    )
    return {
        "schema": "simumax-memledger-diff-v1",
        "a": {"run_id": a["meta"].get("run_id"),
              "model": a["meta"].get("model"),
              "system": a["meta"].get("system"),
              "fits": a["headline"].get("fits"),
              "binding_stage": a["waterfall"].get("binding_stage", 0)},
        "b": {"run_id": b["meta"].get("run_id"),
              "model": b["meta"].get("model"),
              "system": b["meta"].get("system"),
              "fits": b["headline"].get("fits"),
              "binding_stage": b["waterfall"].get("binding_stage", 0)},
        "identical": identical,
        "headline": headline,
        "stage_peaks": stage_peaks,
        "waterfall": wf,
        "span_deltas": deltas[:top],
        "spans_only_in_a": sorted(set(spans_a) - set(spans_b))[:top],
        "spans_only_in_a_count": len(set(spans_a) - set(spans_b)),
        "spans_only_in_b": sorted(set(spans_b) - set(spans_a))[:top],
        "spans_only_in_b_count": len(set(spans_b) - set(spans_a)),
    }


def format_memory_diff_lines(diff: Dict[str, Any],
                             top: int = 10) -> List[str]:
    """Human rendering of a memory-ledger diff."""
    lines = [
        f"== memory-ledger diff: a={diff['a']['run_id']} "
        f"({diff['a']['model']} on {diff['a']['system']})  vs  "
        f"b={diff['b']['run_id']} "
        f"({diff['b']['model']} on {diff['b']['system']}) =="
    ]
    if diff["identical"]:
        lines.append("  identical: zero delta in every bucket and span")
        return lines
    h = diff["headline"]
    fits = {True: "fits", False: "OOM", None: "?"}
    lines.append(
        f"  peak {h['max_peak_gib']['a']:.2f} -> "
        f"{h['max_peak_gib']['b']:.2f} GiB "
        f"({h['max_peak_gib']['delta']:+.2f} GiB)   "
        f"margin {h['mem_margin_gib']['a']:+.2f} -> "
        f"{h['mem_margin_gib']['b']:+.2f} GiB   "
        f"[{fits[diff['a']['fits']]} -> {fits[diff['b']['fits']]}]"
    )
    if diff["a"].get("binding_stage") != diff["b"].get("binding_stage"):
        # each ledger's buckets and span totals describe its OWN binding
        # stage, so when the peak moved stages every section below
        # compares different stages' live sets — say so up front
        lines.append(
            f"  note: binding stage moved "
            f"{diff['a']['binding_stage']} -> {diff['b']['binding_stage']}"
            f" — the bucket and per-tensor sections below compare "
            f"different stages' live sets"
        )
    moved = [s for s in diff.get("stage_peaks", []) if s["delta"] != 0]
    if moved:
        lines.append("  -- per-stage peak deltas (b - a) --")
        for s in moved:
            a_gib = s["a"] if s["a"] is not None else 0.0
            b_gib = s["b"] if s["b"] is not None else 0.0
            lines.append(
                f"    stage {s['stage']}: {a_gib:8.2f} -> {b_gib:8.2f} "
                f"GiB  ({s['delta']:+.2f} GiB)"
            )
    lines.append("  -- waterfall bucket deltas (b - a) --")
    for key in MEM_WATERFALL_ORDER:
        d = diff["waterfall"].get(key)
        if d is None or (d["a"] == 0.0 and d["b"] == 0.0):
            continue
        lines.append(
            f"    {key:<22} {d['a'] / GiB:9.3f} -> {d['b'] / GiB:9.3f} GiB"
            f"  ({d['delta'] / GiB:+.3f} GiB)"
        )
    shown = [d for d in diff["span_deltas"] if d["delta"] != 0][:top]
    if shown:
        lines.append("  -- largest per-tensor deltas (binding stage) --")
        for d in shown:
            lines.append(
                f"    {d['delta'] / GiB:+9.3f} GiB  {d['path']}"
            )
    for side, key in (("a", "spans_only_in_a"), ("b", "spans_only_in_b")):
        if diff[key]:
            count = diff.get(f"{key}_count", len(diff[key]))
            lines.append(
                f"  tensors only in {side}: {count} (e.g. {diff[key][0]})"
            )
    return lines

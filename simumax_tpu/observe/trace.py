"""Chrome/Perfetto trace export for the *analytical* path.

``simulate()`` already exports its discrete-event timeline via
``simulator/trace.py``; this module lays out the analytical estimate's
schedule replay (``PerfLLM.calculate_1f1b_bubble`` /
``calculate_interleaved_schedule`` — the exact intervals the headline
time was derived from) in the same Chrome-trace conventions, so a
``perf`` run is inspectable in the same UI as a ``simulate()`` run:

* pid = pipeline stage, tid lanes ``comp`` / ``comm`` (reusing
  ``simulator.trace.to_chrome_trace`` — the batch writer built on the
  same ``_meta_dicts`` / ``_x_dict`` / ``_counter_dicts`` helpers as
  the engine's streaming ``StreamingTraceWriter`` sink, so both UIs
  stay byte-compatible — for metadata, lane order, colors and
  ``displayTimeUnit``);
* per-microbatch F/B slices on the comp lane, the exposed DP grad
  reduce-scatter / optimizer / param all-gather tail after each stage's
  last backward;
* an ``hbm_bytes`` counter track reconstructed from the schedule
  (model bytes + one activation cache per in-flight microbatch), the
  analytical analog of ``analysis_mem``'s live-microbatch accounting.

Times are pre-straggler seconds (the schedule's own clock); the
straggler inflation is a scalar on top and is recorded in the result.
"""

from __future__ import annotations

from typing import List, Tuple

from simumax_tpu.simulator.engine import TraceEvent
from simumax_tpu.simulator.memory import MemSample, SimuMemoryTracker
from simumax_tpu.simulator.trace import to_chrome_trace


def analytical_trace_events(perf) -> Tuple[List[TraceEvent], List[SimuMemoryTracker]]:
    """Build TraceEvents + per-stage memory counter tracks from the last
    ``analysis_cost()`` schedule replay. The counter tracks ARE the
    memory ledger's analytical timeline trackers
    (``observe/memledger.py::analytical_memory_trackers`` — one replay,
    two consumers), extended with a flat ``step_end`` sample covering
    the exposed optimizer tail this trace additionally lays out."""
    from simumax_tpu.observe.memledger import analytical_memory_trackers

    perf.analysis_cost()  # ensures the replay ran (cached)
    st = perf.strategy
    pp, vp = st.pp_size, st.vp_size
    events: List[TraceEvent] = []
    trackers = analytical_memory_trackers(perf, record_events=False)
    by_stage: List[List[tuple]] = [[] for _ in range(pp)]
    for ev in perf._schedule_events:
        by_stage[ev[0]].append(ev)
    for s in range(pp):
        for (_, kind, c, mb, start, end) in sorted(
            by_stage[s], key=lambda e: e[4]
        ):
            name = f"{'fwd' if kind == 'F' else 'bwd'} mb{mb}"
            if vp > 1:
                name += f" chunk{c}"
            events.append(TraceEvent(
                rank=s, lane="comp", name=name, start=start, end=end,
                kind="compute",
            ))
        # exposed step tail: grad reduce-scatter -> optimizer -> param
        # gather (the analytical max-path components, laid out serially
        # the way analysis_cost charges them)
        t = max((e[5] for e in by_stage[s]), default=0.0)
        dp = perf._compute_dp_time(s)
        optim = perf._compute_optim_time(s)
        for name, dur, lane, kind in (
            ("grad_reduce_scatter", dp["exposed_rs"], "comm", "comm"),
            ("optimizer", optim, "comp", "compute"),
            ("param_all_gather", dp["exposed_ag"], "comm", "comm"),
        ):
            if dur <= 0:
                continue
            events.append(TraceEvent(
                rank=s, lane=lane, name=name, start=t, end=t + dur,
                kind=kind,
            ))
            t += dur
        trackers[s].timeline.append(
            MemSample(t, trackers[s].static_bytes, "step_end")
        )
    return events, trackers


def analytical_chrome_trace(perf) -> dict:
    events, trackers = analytical_trace_events(perf)
    trace = to_chrome_trace(events, trackers)
    trace["otherData"] = {
        "source": "simumax_tpu analytical estimate",
        "straggle_ratio": perf.analysis_cost()["straggle_ratio"],
        "time_base": "pre-straggler schedule seconds (exported as us)",
    }
    return trace


def write_analytical_trace(perf, path: str) -> str:
    import json

    with open(path, "w", encoding="utf-8") as f:
        json.dump(analytical_chrome_trace(perf), f)
    return path

"""Observability layer (L8-adjacent): the cost-attribution ledger, the
MFU-loss waterfall, ledger diffing, the analytical Chrome-trace export,
and the shared structured reporter.

See ``docs/observability.md`` for the ledger schema, the waterfall
bucket definitions, and a worked misprediction-triage example.
"""

from simumax_tpu.observe.ledger import Ledger, attribution_line, build_waterfall, diff_ledgers
from simumax_tpu.observe.report import Reporter, configure_reporter, get_reporter

__all__ = [
    "Ledger",
    "Reporter",
    "attribution_line",
    "build_waterfall",
    "configure_reporter",
    "diff_ledgers",
    "get_reporter",
]

"""Observability layer (L8-adjacent): the cost-attribution ledger, the
MFU-loss waterfall, the per-tensor HBM memory ledger with its
peak-memory waterfall and OOM forensics, the discrete-event
critical-path engine (slack, blame, simulated waterfall,
sim-vs-analytical divergence), ledger diffing, the analytical
Chrome-trace / memory-timeline exports, and the shared structured
reporter.

See ``docs/observability.md`` for the ledger schemas, the waterfall
bucket definitions, and worked triage examples.
"""

from simumax_tpu.observe.critpath import (
    DependencySkeleton,
    diff_critpath,
    diverge,
)
from simumax_tpu.observe.fleetledger import (
    build_fleet_explain,
    build_fleet_ledger,
    diff_fleet_reports,
    fleet_chrome_trace,
    fleet_explain_lines,
    format_fleet_diff_lines,
    slo_counterfactuals,
)
from simumax_tpu.observe.ledger import Ledger, attribution_line, build_waterfall, diff_ledgers
from simumax_tpu.observe.memledger import (
    MemoryLedger,
    build_memory_waterfall,
    diff_memory_ledgers,
    mem_crosscheck,
    memory_attribution_line,
    oom_forensics,
)
from simumax_tpu.observe.report import Reporter, configure_reporter, get_reporter

__all__ = [
    "DependencySkeleton",
    "Ledger",
    "MemoryLedger",
    "Reporter",
    "attribution_line",
    "build_fleet_explain",
    "build_fleet_ledger",
    "build_memory_waterfall",
    "build_waterfall",
    "diff_fleet_reports",
    "fleet_chrome_trace",
    "fleet_explain_lines",
    "format_fleet_diff_lines",
    "slo_counterfactuals",
    "configure_reporter",
    "diff_critpath",
    "diff_ledgers",
    "diff_memory_ledgers",
    "diverge",
    "get_reporter",
    "mem_crosscheck",
    "memory_attribution_line",
    "oom_forensics",
]

"""Per-stage job generators for the event simulator (L5).

Reference: ``simumax/core/transformer/pipeline_schedule.py``
(``PpSchedule.prefill_batch:717-959`` non-interleaved 1F1B,
``OptimizerSimulator:30-87``) + the per-leaf job factories scattered
through the reference's leaf modules (``prefill_fwd/prefill_bwd``).

Redesign: leaves carry no job-construction code — the generator walks
each chunk's called leaves and replays their recorded cost/activation
info as engine requests, with the memory tracker driven inline. One
simulated rank per PP stage (the reference's ``merge_lanes`` mode):
intra-stage collectives (tp/cp/ep/etp) are charged as local comm-lane
time; PP p2p and the optimizer barrier are true cross-rank rendezvous.
"""

from __future__ import annotations

from typing import Generator, List, Optional

from simumax_tpu.core.utils import dp_comm_buckets
from simumax_tpu.parallel.pipeline import one_f_one_b_order
from simumax_tpu.simulator.memory import SimuMemoryTracker


def _leaf_calls(leaf, phase: str, point: str):
    return [
        c for c in leaf.collective_calls
        if c.phase == phase and c.point == point and c.exposed_time > 0
    ]


class StageProcess:
    """Builds the generator coroutine for one PP stage."""

    #: model-equivalence pin (docs/simulation.md "Blocking-send
    #: model"): when True, non-interleaved blocking 1F1B issues its
    #: steady-state sends as true Megatron batched isend/irecv pairs
    #: (engine ``sendrecv``, the send batched with the next op's recv
    #: — ``send_forward_recv_backward`` semantics) instead of the
    #: default async-send + sender transfer-stall approximation. On a
    #: symmetric schedule the two are timing-identical; the regression
    #: test ``tests/test_critpath.py::TestSteadyStateSendrecvParity``
    #: pins that equivalence across the blocking parity grid, which is
    #: why the lean default model is sound.
    _steady_sendrecv = False

    def __init__(
        self,
        perf,
        stage: int,
        tracker: Optional[SimuMemoryTracker] = None,
        granularity: str = "leaf",
        rank: Optional[int] = None,
        perturb: float = 1.0,
        groups: Optional[dict] = None,
        dp_cp_group: Optional[list] = None,
        bucket_groups: Optional[dict] = None,
        neighbor_map: Optional[dict] = None,
        barrier_group: Optional[list] = None,
    ):
        self.perf = perf
        self.stage = stage
        self.st = perf.strategy
        self.tracker = tracker
        self.granularity = granularity
        self.chunks = perf.stage_chunks(stage)
        self.pp = self.st.pp_size
        #: world-rank mode: this process IS global rank ``rank``; exposed
        #: intra-stage collectives become true rendezvous among the
        #: rank's groups, and ``perturb`` scales its compute (straggler
        #: injection). Under symmetry reduction ``rank`` is an *engine*
        #: rank (one per class) and ``groups`` / ``neighbor_map`` /
        #: ``barrier_group`` arrive pre-mapped onto class reps — the
        #: process itself never needs global coordinates then.
        self.rank = rank
        self.perturb = perturb
        self._groups = groups or {}
        self._dp_cp_group = dp_cp_group
        #: pre-computed dp_cp/edp grad-stream rendezvous groups (the
        #: runner builds them once for the whole world — the lazy
        #: ``group_of`` fallback below is O(world) per rank, quadratic
        #: at pod scale)
        self._bucket_groups = bucket_groups or {}
        self._neighbor_map = neighbor_map
        self._barrier_group = barrier_group
        if rank is not None and not self._groups:
            from simumax_tpu.parallel.mesh import group_of

            for dim in ("tp", "cp", "ep", "etp"):
                if getattr(self.st, f"{dim}_size") > 1:
                    self._groups[dim] = group_of(rank, self.st, dim)
        path = perf.ctx.path("pp")
        self.p2p_time = (
            perf.system.compute_net_op_time(
                "p2p", self.chunks[0].boundary_bytes(), path
            )
            if self.pp > 1
            else 0.0
        )
        # independent DP-comm model (NOT perf._compute_dp_time): bucket
        # plan from this stage's own params; overlap emerges from the
        # engine's async comm streams rather than a closed-form min()
        self._dp = self._dp_plan()
        self._rs_cursor = {d: 0 for d in self._dp["rs"]}
        self._grad_acc = {d: 0.0 for d in self._dp["rs"]}
        self._rs_active = False
        self._dp_groups: dict = {}

    # -- DP comm plan (independent of the analytical path) -----------------
    def _dp_plan(self) -> dict:
        """Per-stream grad reduce / param gather bucket schedules.

        Streams: dense grads over ``dp_cp``, MoE grads over ``edp`` —
        modeled as parallel comm channels (Megatron uses separate
        process groups / NCCL streams for the two).
        """
        st, sysc, perf = self.st, self.perf.system, self.perf
        dense = sum(c.param_info.dense_numel for c in self.chunks)
        moe = sum(c.param_info.moe_numel for c in self.chunks)
        g_el = 2.0 if st.grad_reduce_in_bf16 else 4.0
        p_el = st.element_size
        plan = {"rs": {}, "ag": {}, "bounds": {}, "tied": 0.0}
        specs = []
        if st.dp_size * st.cp_size > 1 and dense > 0 and st.zero_state < 3:
            specs.append(("dp_cp", dense, st.dp_size * st.cp_size))
        if st.edp_size > 1 and moe > 0 and st.zero_state < 3:
            specs.append(("edp", moe, st.edp_size))
        for dim, numel, group in specs:
            path = perf.ctx.path(dim)
            op = "reduce_scatter" if st.zero_state >= 1 else "all_reduce"
            sizes = dp_comm_buckets(numel, group)
            plan["rs"][dim] = [
                sysc.compute_net_op_time(op, nb * g_el, path) for nb in sizes
            ]
            bounds, acc = [], 0.0
            for nb in sizes:
                acc += nb
                bounds.append(acc)
            plan["bounds"][dim] = bounds
            if st.zero_state >= 1:
                plan["ag"][dim] = [
                    sysc.compute_net_op_time("all_gather", nb * p_el, path)
                    for nb in sizes
                ]
        if (
            st.pp_size > 1
            and not perf.model_config.untie_embeddings
            and self.stage in (0, self.pp - 1)
        ):
            m = perf.model_config
            emb_grad = (
                m.padded_vocab_size * m.hidden_size / st.tp_size
                * st.grad_element_size
            )
            plan["tied"] = 2 * sysc.compute_net_op_time(
                "p2p", emb_grad, perf.ctx.path("pp")
            )
        return plan

    def _dim_group(self, dim: str):
        """dp_cp / edp rendezvous group of this world rank (None in
        merged mode: the group's members are represented by one rank).
        Computed once per StageProcess; pre-mapped groups passed by the
        runner (full-world precompute or symmetry reduction) win."""
        if self.rank is None:
            return None
        if dim in self._bucket_groups:
            return self._bucket_groups[dim]
        if dim in self._dp_groups:
            return self._dp_groups[dim]
        from simumax_tpu.parallel.mesh import group_of, rank_coords

        st = self.st
        if dim == "dp_cp":
            group = self._dp_cp_group
            if not group:
                mine = rank_coords(self.rank, st)
                group = sorted(
                    r for r in range(st.world_size)
                    if rank_coords(r, st)["tp"] == mine["tp"]
                    and rank_coords(r, st)["pp"] == mine["pp"]
                )
        else:
            group = group_of(self.rank, st, dim)
        self._dp_groups[dim] = group
        return group

    def _engine_rank(self) -> int:
        return self.stage if self.rank is None else self.rank

    def _async_bucket(self, dim: str, idx: int, dur: float, tag: str):
        group = self._dim_group(dim)
        peers = group if group else [self._engine_rank()]
        return (
            "async_collective", f"{tag}:{dim}", dur,
            f"{tag}_{dim}_b{idx}", list(peers),
        )

    def _grad_ready(self, leaf) -> Generator:
        """Post grad-reduce buckets whose parameters have all produced
        grads (called after each leaf backward while overlap is active)."""
        if not self._rs_active:
            return
        ready = {
            "dp_cp": leaf.param_info.dense_numel,
            "edp": leaf.param_info.moe_numel,
        }
        for dim, buckets in self._dp["rs"].items():
            self._grad_acc[dim] += ready.get(dim, 0.0)
            bounds = self._dp["bounds"][dim]
            while (
                self._rs_cursor[dim] < len(buckets)
                and self._grad_acc[dim] >= bounds[self._rs_cursor[dim]] - 1e-6
            ):
                i = self._rs_cursor[dim]
                self._rs_cursor[dim] = i + 1
                yield self._async_bucket(dim, i, buckets[i], "grad_rs")

    def _begin_rs_window(self):
        self._rs_active = True
        self._rs_cursor = {d: 0 for d in self._dp["rs"]}
        self._grad_acc = {d: 0.0 for d in self._dp["rs"]}

    def _flush_rs_window(self) -> Generator:
        """End of an overlapped backward window: post any bucket not yet
        posted (chunk-granularity walks never post inline)."""
        if not self._rs_active:
            return
        for dim, buckets in self._dp["rs"].items():
            while self._rs_cursor[dim] < len(buckets):
                i = self._rs_cursor[dim]
                self._rs_cursor[dim] = i + 1
                yield self._async_bucket(dim, i, buckets[i], "grad_rs")
        self._rs_active = False

    def _pp_stride(self) -> int:
        st = self.st
        return st.tp_size * st.cp_size * st.dp_size

    def _neighbor(self, stage: int) -> int:
        """Engine rank id of the same position at another pp stage."""
        if self.rank is None:
            return stage
        if self._neighbor_map is not None:
            return self._neighbor_map[stage]
        return self.rank + (stage - self.stage) * self._pp_stride()

    def _comm_events(self, leaf, phase: str, point: str):
        """Yield exposed-comm engine requests for one leaf phase/point:
        lumped local time in merged mode; true per-group rendezvous in
        world-rank mode. Overlapped (hidden) collective time is emitted
        as zero-advance trace spans so traces show the async comm."""
        name = leaf.path_name().split(".", 1)[-1]
        hidden = sum(
            c.time - c.exposed_time
            for c in leaf.collective_calls
            if c.phase == phase and c.point == point
            and c.time > c.exposed_time
        )
        if hidden > 0:
            yield ("trace", hidden, f"{name}.{phase}_comm_async", "comm")
        if self.rank is None:
            total = sum(c.exposed_time for c in _leaf_calls(leaf, phase, point))
            if total:
                yield ("compute", total, f"{name}.{phase}_comm", "comm")
            return
        for c in _leaf_calls(leaf, phase, point):
            group = self._groups.get(c.dim)
            if group is None:
                if c.exposed_time:
                    yield ("compute", c.exposed_time, f"{name}.{c.op}", "comm")
                continue
            yield (
                "collective",
                (c.dim, tuple(group)),
                c.exposed_time,
                f"{name}.{c.op}[{c.dim}]",
                list(group),
            )

    # -- memory helpers ----------------------------------------------------
    @staticmethod
    def _token(mb, leaf, prefix=""):
        """Cache-token id: readable leaf path for peak attribution plus
        the object id for uniqueness (two leaves may share a path name,
        and backward frees in reverse order — a shared FIFO would pop
        the wrong size)."""
        name = leaf.path_name().split(".", 1)[-1]
        return f"mb{mb}:{prefix}{name}#{id(leaf)}"

    def _alloc(self, t, nbytes, token=None, tag=""):
        if self.tracker is not None and nbytes:
            self.tracker.alloc(t, nbytes, token, tag)

    def _free(self, t, nbytes=0.0, token=None, tag=""):
        if self.tracker is not None:
            self.tracker.free(t, nbytes, token, tag)

    # -- one microbatch forward / backward ---------------------------------
    def _fwd(self, mb: int, clock: List[float], chunks=None) -> Generator:
        for chunk in (chunks if chunks is not None else self.chunks):
            if self.granularity == "chunk":
                dur = (chunk.cost_info.compute.fwd * self.perturb
                       + chunk.cost_info.net_exposed.fwd)
                t = yield ("compute", dur, f"fwd_mb{mb}", "comp")
                clock[0] = t
                self._alloc(t, chunk.act_info.cache_bytes,
                            f"mb{mb}:c{chunk.chunk_idx}", "act")
                continue
            for leaf in chunk.called_leaves():
                comp = leaf.cost_info.compute.fwd * self.perturb
                name = leaf.path_name().split(".", 1)[-1]
                for ev in self._comm_events(leaf, "fwd", "pre"):
                    t = yield ev
                    clock[0] = t
                self._alloc(clock[0], leaf.raw_act_info.fwd_temp_bytes,
                            tag="temp")
                if comp:
                    t = yield ("compute", comp, f"{name}.fwd#mb{mb}", "comp")
                    clock[0] = t
                self._free(clock[0], leaf.raw_act_info.fwd_temp_bytes,
                           tag="temp")
                if leaf.act_info.cache_bytes:
                    self._alloc(
                        clock[0], leaf.act_info.cache_bytes,
                        self._token(mb, leaf), "act",
                    )
                for ev in self._comm_events(leaf, "fwd", "post"):
                    t = yield ev
                    clock[0] = t

    def _bwd(self, mb: int, clock: List[float], chunks=None) -> Generator:
        for chunk in reversed(chunks if chunks is not None else self.chunks):
            if self.granularity == "chunk":
                dur = (
                    chunk.cost_info.compute.bwd * self.perturb
                    + chunk.cost_info.recompute_time * self.perturb
                    + chunk.cost_info.net_exposed.bwd_act
                    + chunk.cost_info.net_exposed.bwd_w
                )
                t = yield ("compute", dur, f"bwd_mb{mb}", "comp")
                clock[0] = t
                self._free(t, token=f"mb{mb}:c{chunk.chunk_idx}", tag="act")
                continue
            leaves = chunk.called_leaves()
            done = set()
            i = len(leaves) - 1
            while i >= 0:
                leaf = leaves[i]
                if id(leaf) in done:
                    i -= 1
                    continue
                seg = getattr(leaf, "recompute_segment", None)
                if leaf.in_recompute and seg is not None:
                    seg_leaves = [
                        l for l in leaves
                        if getattr(l, "recompute_segment", None) is seg
                    ]
                    # variance-tail leaves are not replayed (reference
                    # ``base_struct.py:444-451``): no replay time, no
                    # re-materialised cache; a single-leaf segment keeps
                    # its saved input live until its own backward.
                    replay = sum(
                        sl.cost_info.compute.fwd * self.perturb
                        + sl.cost_info.net_exposed.fwd
                        for sl in seg_leaves
                        if not sl.variance_tail
                    )
                    name = seg.path_name().split(".", 1)[-1]
                    saved = seg_leaves[0].act_info.cache_bytes
                    t = yield ("compute", replay, f"{name}.recompute#mb{mb}",
                               "comp")
                    clock[0] = t
                    for sl in seg_leaves:
                        if sl.raw_act_info.cache_bytes and not sl.variance_tail:
                            self._alloc(t, sl.raw_act_info.cache_bytes,
                                        self._token(mb, sl, "r:"), "recompute")
                    if saved and not seg_leaves[0].variance_tail:
                        self._free(t, token=self._token(mb, seg_leaves[0]),
                                   tag="act")
                    for sl in reversed(seg_leaves):
                        dur = (
                            sl.cost_info.compute.bwd * self.perturb
                            + sl.cost_info.net_exposed.bwd_act
                            + sl.cost_info.net_exposed.bwd_w
                        )
                        lname = sl.path_name().split(".", 1)[-1]
                        flight = (sl.raw_act_info.bwd_temp_bytes
                                  + sl.raw_act_info.grad_flight_bytes)
                        self._alloc(clock[0], flight, tag="temp")
                        if dur:
                            t = yield ("compute", dur, f"{lname}.bwd#mb{mb}",
                                       "comp")
                            clock[0] = t
                        self._free(clock[0], flight, tag="temp")
                        if sl.variance_tail:
                            if sl is seg_leaves[0] and saved:
                                self._free(clock[0],
                                           token=self._token(mb, sl),
                                           tag="act")
                        elif sl.raw_act_info.cache_bytes:
                            self._free(clock[0], token=self._token(mb, sl, "r:"),
                                       tag="recompute")
                        done.add(id(sl))
                        for ev in self._grad_ready(sl):
                            t = yield ev
                            clock[0] = t
                    i -= 1
                    continue
                comp_a = leaf.cost_info.compute.bwd_act * self.perturb
                comp_w = leaf.cost_info.compute.bwd_w * self.perturb
                name = leaf.path_name().split(".", 1)[-1]
                for phase in ("bwd_act", "bwd_w"):
                    for point in ("pre", "post"):
                        for ev in self._comm_events(leaf, phase, point):
                            t = yield ev
                            clock[0] = t
                # grad-in-flight: incoming output-grad + outgoing
                # input-grad live while the bwd op runs
                flight = (leaf.raw_act_info.bwd_temp_bytes
                          + leaf.raw_act_info.grad_flight_bytes)
                self._alloc(clock[0], flight, tag="temp")
                if comp_a + comp_w:
                    t = yield ("compute", comp_a + comp_w,
                               f"{name}.bwd#mb{mb}", "comp")
                    clock[0] = t
                self._free(clock[0], flight, tag="temp")
                if leaf.act_info.cache_bytes:
                    self._free(clock[0], token=self._token(mb, leaf),
                               tag="act")
                done.add(id(leaf))
                for ev in self._grad_ready(leaf):
                    t = yield ev
                    clock[0] = t
                i -= 1

    # -- optimizer tail (reference ``OptimizerSimulator``) -----------------
    def _optimizer(self, clock: List[float]) -> Generator:
        st = self.st
        if st.overlap_grad_reduce:
            # buckets were posted asynchronously during the backward;
            # join the comm streams before touching the grads
            t = yield ("wait_comm",)
            clock[0] = t
        else:
            repeat = st.micro_batch_num if st.zero_state == 2 else 1
            for _ in range(repeat):
                for dim, buckets in self._dp["rs"].items():
                    group = self._dim_group(dim)
                    for i, dur in enumerate(buckets):
                        if group:
                            t = yield (
                                "collective", (f"grad_rs:{dim}", tuple(group)),
                                dur, f"grad_rs_{dim}_b{i}", group,
                            )
                        else:
                            t = yield ("compute", dur, f"grad_rs_{dim}_b{i}",
                                       "comm")
                        clock[0] = t
        if self._dp["tied"]:
            t = yield ("compute", self._dp["tied"], "tied_embedding_grad",
                       "comm")
            clock[0] = t
        # world barrier before the step (rerun_state_machine analog)
        if self._barrier_group is not None:
            barrier = list(self._barrier_group)
        else:
            barrier = list(range(self.pp if self.rank is None
                                  else st.world_size))
        t = yield (
            "collective",
            "optimizer_barrier",
            0.0,
            "optimizer_barrier",
            barrier,
        )
        clock[0] = t
        t = yield ("compute",
                   self.perf._compute_optim_time(self.stage) * self.perturb,
                   "adam_step", "comp")
        clock[0] = t
        # param all-gather: when overlapped it belongs to the NEXT
        # iteration's first forward — in this steady-state model it was
        # posted at schedule start and joined after the first
        # microbatch's forward, so nothing is charged here
        if not st.overlap_param_gather:
            for dim, buckets in self._dp["ag"].items():
                group = self._dim_group(dim)
                for i, dur in enumerate(buckets):
                    if group:
                        t = yield (
                            "collective", (f"param_ag:{dim}", tuple(group)),
                            dur, f"param_ag_{dim}_b{i}", group,
                        )
                    else:
                        t = yield ("compute", dur, f"param_ag_{dim}_b{i}",
                                   "comm")
                    clock[0] = t

    def _post_param_gathers(self) -> Generator:
        """Steady state with ``overlap_param_gather``: the previous
        iteration's param all-gathers overlap this iteration's warmup
        forward — post them on the comm streams at schedule start."""
        for dim, buckets in self._dp["ag"].items():
            for i, dur in enumerate(buckets):
                yield self._async_bucket(dim, i, dur, "param_ag")

    # -- full schedule ------------------------------------------------------
    def process(self) -> Generator:
        if self.st.vp_size > 1:
            yield from self._process_interleaved()
            return
        st, stage, pp = self.st, self.stage, self.pp
        mbc = st.micro_batch_num
        clock = [0.0]
        ag_join_pending = False
        if st.overlap_param_gather and self._dp["ag"]:
            yield from self._post_param_gathers()
            ag_join_pending = True
        b_seen = 0
        f_seen = 0
        # blocking-pipeline send semantics: warmup forward sends and
        # cooldown backward sends have a peer in a recv-only phase, so a
        # true rendezvous (send_sync) is cycle-free there; steady-state
        # sends use the async-send + sender transfer-stall
        # approximation, which is timing-identical to Megatron's real
        # batched isend/irecv pairs on a symmetric schedule — pinned by
        # the ``_steady_sendrecv`` variant below + the parity
        # regression test (docs/simulation.md "Blocking-send model";
        # unfused blocking sends would deadlock the warmup ring, which
        # is exactly why Megatron fuses them).
        warmup = pp - 1 - stage
        order = list(one_f_one_b_order(pp, stage, mbc))

        def recv_spec(op):
            """(peer, tag, name, lane) of one schedule op's inbound
            p2p, or None (boundary stages)."""
            kind, mb = op
            if kind == "F":
                if stage == 0:
                    return None
                return (self._neighbor(stage - 1), f"fwd{mb}",
                        f"recv_fwd{mb}", "pp_fwd")
            if stage == pp - 1:
                return None
            return (self._neighbor(stage + 1), f"bwd{mb}",
                    f"recv_bwd{mb}", "pp_bwd")

        def steady_send(dst, tag, name, lane, i):
            """Steady-state blocking send: batched with the next op's
            recv when ``_steady_sendrecv`` (true Megatron pairing),
            else async publish + sender transfer stall."""
            if self._steady_sendrecv:
                nxt = recv_spec(order[i + 1]) if i + 1 < len(order) else None
                if nxt is not None:
                    t = yield ("sendrecv", dst, tag, self.p2p_time,
                               nxt[0], nxt[1], f"{name}+{nxt[2]}", lane)
                    clock[0] = t
                    return True
                t = yield ("sendrecv", dst, tag, self.p2p_time,
                           None, None, name, lane)
                clock[0] = t
                return False
            t = yield ("send", dst, tag, self.p2p_time, name, lane)
            clock[0] = t
            yield ("advance", clock[0] + self.p2p_time)
            return False

        recv_batched = False  # next op's input already received by a pair
        for i, (kind, mb) in enumerate(order):
            if kind == "F":
                f_seen += 1
                if stage > 0 and not recv_batched:
                    t = yield ("recv", self._neighbor(stage - 1), f"fwd{mb}",
                               f"recv_fwd{mb}", "pp_fwd")
                    clock[0] = t
                recv_batched = False
                yield from self._fwd(mb, clock)
                if ag_join_pending:
                    # params must be resident once the first microbatch's
                    # forward has consumed them: join the gather streams
                    t = yield ("wait_comm",)
                    clock[0] = t
                    ag_join_pending = False
                if stage < pp - 1:
                    if st.pp_comm_async:
                        t = yield ("send", self._neighbor(stage + 1),
                                   f"fwd{mb}", self.p2p_time,
                                   f"send_fwd{mb}", "pp_fwd")
                        clock[0] = t
                    elif f_seen <= warmup:
                        t = yield ("send_sync", self._neighbor(stage + 1),
                                   f"fwd{mb}", self.p2p_time,
                                   f"send_fwd{mb}", "pp_fwd")
                        clock[0] = t
                    else:
                        recv_batched = yield from steady_send(
                            self._neighbor(stage + 1), f"fwd{mb}",
                            f"send_fwd{mb}", "pp_fwd", i,
                        )
            else:
                b_seen += 1
                if st.overlap_grad_reduce and (
                    st.zero_state == 2 or b_seen == mbc
                ):
                    self._begin_rs_window()
                if stage < pp - 1 and not recv_batched:
                    t = yield ("recv", self._neighbor(stage + 1), f"bwd{mb}",
                               f"recv_bwd{mb}", "pp_bwd")
                    clock[0] = t
                recv_batched = False
                yield from self._bwd(mb, clock)
                yield from self._flush_rs_window()
                if stage > 0:
                    if st.pp_comm_async:
                        t = yield ("send", self._neighbor(stage - 1),
                                   f"bwd{mb}", self.p2p_time,
                                   f"send_bwd{mb}", "pp_bwd")
                        clock[0] = t
                    elif b_seen > mbc - warmup:
                        t = yield ("send_sync", self._neighbor(stage - 1),
                                   f"bwd{mb}", self.p2p_time,
                                   f"send_bwd{mb}", "pp_bwd")
                        clock[0] = t
                    else:
                        recv_batched = yield from steady_send(
                            self._neighbor(stage - 1), f"bwd{mb}",
                            f"send_bwd{mb}", "pp_bwd", i,
                        )
        yield from self._optimizer(clock)

    def _process_interleaved(self) -> Generator:
        """Interleaved (VPP) schedule: chunk c's forward on the last
        stage feeds chunk c+1 on stage 0; backward wraps the other way
        (Megatron interleaved 1F1B, reference
        ``pipeline_schedule.py:97-715``)."""
        from simumax_tpu.parallel.pipeline import interleaved_order

        st, stage, pp = self.st, self.stage, self.pp
        vp, mbc = st.vp_size, st.micro_batch_num
        group = st.vpp_group_size
        by_chunk = {c.chunk_idx: [c] for c in self.chunks}
        clock = [0.0]
        order = interleaved_order(pp, stage, mbc, vp, group)
        n_b = sum(1 for op in order if op[0] == "B")
        ag_join_pending = False
        if st.overlap_param_gather and self._dp["ag"]:
            yield from self._post_param_gathers()
            ag_join_pending = True
        b_seen = 0
        rs_begun: set = set()

        def specs(op):
            """(recv, send) p2p specs of one schedule op; each is
            ``(peer, tag, name, lane)`` or None."""
            kind, c, mb = op
            if kind == "F":
                recv = None
                if not (stage == 0 and c == 0):
                    src = self._neighbor(stage - 1 if stage > 0 else pp - 1)
                    recv = (src, f"fwd_c{c}_mb{mb}",
                            f"recv_fwd_c{c}_mb{mb}", "pp_fwd")
                send = None
                if not (stage == pp - 1 and c == vp - 1):
                    dst = self._neighbor(stage + 1 if stage < pp - 1 else 0)
                    rc = c if stage < pp - 1 else c + 1
                    send = (dst, f"fwd_c{rc}_mb{mb}",
                            f"send_fwd_c{rc}_mb{mb}", "pp_fwd")
                return recv, send
            recv = None
            if not (stage == pp - 1 and c == vp - 1):
                src = self._neighbor(stage + 1 if stage < pp - 1 else 0)
                recv = (src, f"bwd_c{c}_mb{mb}",
                        f"recv_bwd_c{c}_mb{mb}", "pp_bwd")
            send = None
            if not (stage == 0 and c == 0):
                dst = self._neighbor(stage - 1 if stage > 0 else pp - 1)
                rc = c if stage > 0 else c - 1
                send = (dst, f"bwd_c{rc}_mb{mb}",
                        f"send_bwd_c{rc}_mb{mb}", "pp_bwd")
            return recv, send

        recv_batched = False  # next op's input already received by a pair
        for i, op in enumerate(order):
            kind, c, mb = op
            recv, send = specs(op)
            if kind == "B":
                b_seen += 1
                # grad-reduce windows (interleaved): ZeRO-2 reduces each
                # microbatch's grads — its window spans that mb's chunk
                # backwards (chunk vp-1 first, chunk 0 last); otherwise
                # grads are final only on the last microbatch, whose
                # window spans its B ops until the schedule's final B
                if st.overlap_grad_reduce:
                    if st.zero_state == 2:
                        if mb not in rs_begun:
                            yield from self._flush_rs_window()
                            rs_begun.add(mb)
                            self._begin_rs_window()
                    elif mb == mbc - 1 and not self._rs_active:
                        self._begin_rs_window()
            if recv is not None and not recv_batched:
                t = yield ("recv", recv[0], recv[1], recv[2], recv[3])
                clock[0] = t
            recv_batched = False
            if kind == "F":
                yield from self._fwd(mb, clock, by_chunk[c])
                if ag_join_pending:
                    t = yield ("wait_comm",)
                    clock[0] = t
                    ag_join_pending = False
            else:
                yield from self._bwd(mb, clock, by_chunk[c])
                if st.overlap_grad_reduce and (
                    (st.zero_state == 2 and c == 0) or b_seen == n_b
                ):
                    yield from self._flush_rs_window()
            if send is not None:
                if st.pp_comm_async:
                    t = yield ("send", send[0], send[1], self.p2p_time,
                               send[2], send[3])
                    clock[0] = t
                else:
                    # Megatron blocking interleaved: the send is batched
                    # with the NEXT op's recv in one batch_isend_irecv
                    # call (reference pipeline_schedule.py:344-592) —
                    # publish-then-pair semantics, so warmup rings of
                    # mutual sends cannot deadlock (engine "sendrecv")
                    nxt = specs(order[i + 1])[0] if i + 1 < len(order) else None
                    if nxt is not None:
                        t = yield ("sendrecv", send[0], send[1],
                                   self.p2p_time, nxt[0], nxt[1],
                                   f"{send[2]}+{nxt[2]}", send[3])
                        clock[0] = t
                        recv_batched = True
                    else:
                        t = yield ("sendrecv", send[0], send[1],
                                   self.p2p_time, None, None, send[2],
                                   send[3])
                        clock[0] = t
        yield from self._optimizer(clock)

"""Per-stage job generators for the event simulator (L5).

Reference: ``simumax/core/transformer/pipeline_schedule.py``
(``PpSchedule.prefill_batch:717-959`` non-interleaved 1F1B,
``OptimizerSimulator:30-87``) + the per-leaf job factories scattered
through the reference's leaf modules (``prefill_fwd/prefill_bwd``).

Redesign: leaves carry no job-construction code — the generator walks
each chunk's called leaves and replays their recorded cost/activation
info as engine requests, with the memory tracker driven inline. One
simulated rank per PP stage (the reference's ``merge_lanes`` mode):
intra-stage collectives (tp/cp/ep/etp) are charged as local comm-lane
time; PP p2p and the optimizer barrier are true cross-rank rendezvous.
"""

from __future__ import annotations

from typing import Generator, List, Optional

from simumax_tpu.parallel.pipeline import one_f_one_b_order
from simumax_tpu.simulator.memory import SimuMemoryTracker


def _leaf_events(leaf, phase: str):
    """(pre_comm, compute, post_comm) exposed seconds for one leaf/phase
    (partial exposure of overlapped collectives included)."""
    pre = post = 0.0
    for c in leaf.collective_calls:
        if c.phase != phase or c.exposed_time <= 0:
            continue
        if c.point == "pre":
            pre += c.exposed_time
        else:
            post += c.exposed_time
    return pre, leaf.cost_info.compute.get(phase), post


class StageProcess:
    """Builds the generator coroutine for one PP stage."""

    def __init__(
        self,
        perf,
        stage: int,
        tracker: Optional[SimuMemoryTracker] = None,
        granularity: str = "leaf",
    ):
        self.perf = perf
        self.stage = stage
        self.st = perf.strategy
        self.tracker = tracker
        self.granularity = granularity
        self.chunks = perf.stage_chunks(stage)
        self.pp = self.st.pp_size
        path = perf.ctx.path("pp")
        self.p2p_time = (
            perf.system.compute_net_op_time(
                "p2p", self.chunks[0].boundary_bytes(), path
            )
            if self.pp > 1
            else 0.0
        )

    # -- memory helpers ----------------------------------------------------
    def _alloc(self, t, nbytes, token=None, tag=""):
        if self.tracker is not None and nbytes:
            self.tracker.alloc(t, nbytes, token, tag)

    def _free(self, t, nbytes=0.0, token=None, tag=""):
        if self.tracker is not None:
            self.tracker.free(t, nbytes, token, tag)

    # -- one microbatch forward / backward ---------------------------------
    def _fwd(self, mb: int, clock: List[float], chunks=None) -> Generator:
        for chunk in (chunks if chunks is not None else self.chunks):
            leaves = chunk.called_leaves()
            if self.granularity == "chunk":
                dur = chunk.cost_info.fwd_time
                t = yield ("compute", dur, f"fwd_mb{mb}", "comp")
                clock[0] = t
                self._alloc(t, chunk.act_info.cache_bytes,
                            f"mb{mb}:c{chunk.chunk_idx}", "act")
                continue
            for leaf in leaves:
                pre, comp, post = _leaf_events(leaf, "fwd")
                name = leaf.path_name().split(".", 1)[-1]
                if pre:
                    t = yield ("compute", pre, f"{name}.fwd_comm", "comm")
                    clock[0] = t
                self._alloc(clock[0], leaf.raw_act_info.fwd_temp_bytes,
                            tag="temp")
                if comp:
                    t = yield ("compute", comp, f"{name}.fwd#mb{mb}", "comp")
                    clock[0] = t
                self._free(clock[0], leaf.raw_act_info.fwd_temp_bytes,
                           tag="temp")
                if leaf.act_info.cache_bytes:
                    self._alloc(
                        clock[0], leaf.act_info.cache_bytes,
                        f"mb{mb}:{id(leaf)}", "act",
                    )
                if post:
                    t = yield ("compute", post, f"{name}.fwd_comm", "comm")
                    clock[0] = t

    def _bwd(self, mb: int, clock: List[float], chunks=None) -> Generator:
        for chunk in reversed(chunks if chunks is not None else self.chunks):
            leaves = chunk.called_leaves()
            if self.granularity == "chunk":
                dur = chunk.cost_info.bwd_time
                t = yield ("compute", dur, f"bwd_mb{mb}", "comp")
                clock[0] = t
                self._free(t, token=f"mb{mb}:c{chunk.chunk_idx}", tag="act")
                continue
            done = set()
            i = len(leaves) - 1
            while i >= 0:
                leaf = leaves[i]
                if id(leaf) in done:
                    i -= 1
                    continue
                seg = getattr(leaf, "recompute_segment", None)
                if leaf.in_recompute and seg is not None:
                    seg_leaves = [
                        l for l in leaves
                        if getattr(l, "recompute_segment", None) is seg
                    ]
                    replay = sum(
                        sl.cost_info.compute.fwd
                        + sl.cost_info.net_exposed.fwd
                        for sl in seg_leaves
                    )
                    name = seg.path_name().split(".", 1)[-1]
                    saved = seg_leaves[0].act_info.cache_bytes
                    t = yield ("compute", replay, f"{name}.recompute#mb{mb}",
                               "comp")
                    clock[0] = t
                    for sl in seg_leaves:
                        if sl.raw_act_info.cache_bytes:
                            self._alloc(t, sl.raw_act_info.cache_bytes,
                                        f"mb{mb}:r{id(sl)}", "recompute")
                    if saved:
                        self._free(t, token=f"mb{mb}:{id(seg_leaves[0])}",
                                   tag="act")
                    for sl in reversed(seg_leaves):
                        dur = (
                            sl.cost_info.phase_time("bwd_act")
                            + sl.cost_info.phase_time("bwd_w")
                        )
                        lname = sl.path_name().split(".", 1)[-1]
                        flight = (sl.raw_act_info.bwd_temp_bytes
                                  + sl.raw_act_info.grad_flight_bytes)
                        self._alloc(clock[0], flight, tag="temp")
                        if dur:
                            t = yield ("compute", dur, f"{lname}.bwd#mb{mb}",
                                       "comp")
                            clock[0] = t
                        self._free(clock[0], flight, tag="temp")
                        if sl.raw_act_info.cache_bytes:
                            self._free(clock[0], token=f"mb{mb}:r{id(sl)}",
                                       tag="recompute")
                        done.add(id(sl))
                    i -= 1
                    continue
                pre_a, comp_a, post_a = _leaf_events(leaf, "bwd_act")
                pre_w, comp_w, post_w = _leaf_events(leaf, "bwd_w")
                name = leaf.path_name().split(".", 1)[-1]
                dur_comm = pre_a + post_a + pre_w + post_w
                if dur_comm:
                    t = yield ("compute", dur_comm, f"{name}.bwd_comm", "comm")
                    clock[0] = t
                # grad-in-flight: incoming output-grad + outgoing
                # input-grad live while the bwd op runs
                flight = (leaf.raw_act_info.bwd_temp_bytes
                          + leaf.raw_act_info.grad_flight_bytes)
                self._alloc(clock[0], flight, tag="temp")
                if comp_a + comp_w:
                    t = yield ("compute", comp_a + comp_w,
                               f"{name}.bwd#mb{mb}", "comp")
                    clock[0] = t
                self._free(clock[0], flight, tag="temp")
                if leaf.act_info.cache_bytes:
                    self._free(clock[0], token=f"mb{mb}:{id(leaf)}",
                               tag="act")
                done.add(id(leaf))
                i -= 1

    # -- optimizer tail (reference ``OptimizerSimulator``) -----------------
    def _optimizer(self, clock: List[float]) -> Generator:
        perf = self.perf
        dp = perf._compute_dp_time()
        # grad reduce-scatter (dense + moe)
        rs = dp.get("dense_grad_rs_time", 0.0) + dp.get("moe_grad_rs_time", 0.0)
        ag = dp.get("dense_param_ag_time", 0.0) + dp.get("moe_param_ag_time", 0.0)
        if rs:
            t = yield ("compute", rs, "grad_reduce_scatter", "comm")
            clock[0] = t
        # world barrier before the step (rerun_state_machine analog)
        t = yield (
            "collective",
            "optimizer_barrier",
            0.0,
            "optimizer_barrier",
            list(range(self.pp)),
        )
        clock[0] = t
        t = yield ("compute", perf._compute_optim_time(), "adam_step", "comp")
        clock[0] = t
        if ag:
            t = yield ("compute", ag, "param_all_gather", "comm")
            clock[0] = t

    # -- full schedule ------------------------------------------------------
    def process(self) -> Generator:
        if self.st.vp_size > 1:
            yield from self._process_interleaved()
            return
        st, stage, pp = self.st, self.stage, self.pp
        mbc = st.micro_batch_num
        clock = [0.0]
        for kind, mb in one_f_one_b_order(pp, stage, mbc):
            if kind == "F":
                if stage > 0:
                    t = yield ("recv", stage - 1, f"fwd{mb}",
                               f"recv_fwd{mb}", "pp_fwd")
                    clock[0] = t
                yield from self._fwd(mb, clock)
                if stage < pp - 1:
                    t = yield (
                        "send", stage + 1, f"fwd{mb}", self.p2p_time,
                        f"send_fwd{mb}", "pp_fwd",
                    )
                    clock[0] = t
                    if not st.pp_comm_async:
                        # blocking isend approximation: sender stalls for
                        # the transfer. True rendezvous needs fused
                        # send/recv pairs (Megatron batch_isend_irecv) —
                        # unfused blocking sends deadlock in warmup.
                        yield ("advance", clock[0] + self.p2p_time)
            else:
                if stage < pp - 1:
                    t = yield ("recv", stage + 1, f"bwd{mb}",
                               f"recv_bwd{mb}", "pp_bwd")
                    clock[0] = t
                yield from self._bwd(mb, clock)
                if stage > 0:
                    t = yield (
                        "send", stage - 1, f"bwd{mb}", self.p2p_time,
                        f"send_bwd{mb}", "pp_bwd",
                    )
                    clock[0] = t
                    if not st.pp_comm_async:
                        yield ("advance", clock[0] + self.p2p_time)
        yield from self._optimizer(clock)

    def _process_interleaved(self) -> Generator:
        """Interleaved (VPP) schedule: chunk c's forward on the last
        stage feeds chunk c+1 on stage 0; backward wraps the other way
        (Megatron interleaved 1F1B, reference
        ``pipeline_schedule.py:97-715``)."""
        from simumax_tpu.parallel.pipeline import interleaved_order

        st, stage, pp = self.st, self.stage, self.pp
        vp, mbc = st.vp_size, st.micro_batch_num
        group = st.vpp_group_size
        by_chunk = {c.chunk_idx: [c] for c in self.chunks}
        clock = [0.0]
        for kind, c, mb in interleaved_order(pp, stage, mbc, vp, group):
            if kind == "F":
                if not (stage == 0 and c == 0):
                    src = stage - 1 if stage > 0 else pp - 1
                    t = yield ("recv", src, f"fwd_c{c}_mb{mb}",
                               f"recv_fwd_c{c}_mb{mb}", "pp_fwd")
                    clock[0] = t
                yield from self._fwd(mb, clock, by_chunk[c])
                if not (stage == pp - 1 and c == vp - 1):
                    dst = stage + 1 if stage < pp - 1 else 0
                    rc = c if stage < pp - 1 else c + 1
                    t = yield ("send", dst, f"fwd_c{rc}_mb{mb}",
                               self.p2p_time, f"send_fwd_c{rc}_mb{mb}",
                               "pp_fwd")
                    clock[0] = t
                    if not st.pp_comm_async:
                        yield ("advance", clock[0] + self.p2p_time)
            else:
                if not (stage == pp - 1 and c == vp - 1):
                    src = stage + 1 if stage < pp - 1 else 0
                    t = yield ("recv", src, f"bwd_c{c}_mb{mb}",
                               f"recv_bwd_c{c}_mb{mb}", "pp_bwd")
                    clock[0] = t
                yield from self._bwd(mb, clock, by_chunk[c])
                if not (stage == 0 and c == 0):
                    dst = stage - 1 if stage > 0 else pp - 1
                    rc = c if stage > 0 else c - 1
                    t = yield ("send", dst, f"bwd_c{rc}_mb{mb}",
                               self.p2p_time, f"send_bwd_c{rc}_mb{mb}",
                               "pp_bwd")
                    clock[0] = t
                    if not st.pp_comm_async:
                        yield ("advance", clock[0] + self.p2p_time)
        yield from self._optimizer(clock)

"""Per-stage job generators for the event simulator (L5).

Reference: ``simumax/core/transformer/pipeline_schedule.py``
(``PpSchedule.prefill_batch:717-959`` non-interleaved 1F1B,
``OptimizerSimulator:30-87``) + the per-leaf job factories scattered
through the reference's leaf modules (``prefill_fwd/prefill_bwd``).

Redesign: leaves carry no job-construction code — the generator walks
each chunk's called leaves and replays their recorded cost/activation
info as engine requests, with the memory tracker driven inline. One
simulated rank per PP stage (the reference's ``merge_lanes`` mode):
intra-stage collectives (tp/cp/ep/etp) are charged as local comm-lane
time; PP p2p and the optimizer barrier are true cross-rank rendezvous.
"""

from __future__ import annotations

from typing import Generator, List, Optional

from simumax_tpu.parallel.pipeline import one_f_one_b_order
from simumax_tpu.simulator.memory import SimuMemoryTracker


def _leaf_calls(leaf, phase: str, point: str):
    return [
        c for c in leaf.collective_calls
        if c.phase == phase and c.point == point and c.exposed_time > 0
    ]


class StageProcess:
    """Builds the generator coroutine for one PP stage."""

    def __init__(
        self,
        perf,
        stage: int,
        tracker: Optional[SimuMemoryTracker] = None,
        granularity: str = "leaf",
        rank: Optional[int] = None,
        perturb: float = 1.0,
        groups: Optional[dict] = None,
        dp_cp_group: Optional[list] = None,
    ):
        self.perf = perf
        self.stage = stage
        self.st = perf.strategy
        self.tracker = tracker
        self.granularity = granularity
        self.chunks = perf.stage_chunks(stage)
        self.pp = self.st.pp_size
        #: world-rank mode: this process IS global rank ``rank``; exposed
        #: intra-stage collectives become true rendezvous among the
        #: rank's groups, and ``perturb`` scales its compute (straggler
        #: injection)
        self.rank = rank
        self.perturb = perturb
        self._groups = groups or {}
        self._dp_cp_group = dp_cp_group
        if rank is not None and not self._groups:
            from simumax_tpu.parallel.mesh import group_of

            for dim in ("tp", "cp", "ep", "etp"):
                if getattr(self.st, f"{dim}_size") > 1:
                    self._groups[dim] = group_of(rank, self.st, dim)
        path = perf.ctx.path("pp")
        self.p2p_time = (
            perf.system.compute_net_op_time(
                "p2p", self.chunks[0].boundary_bytes(), path
            )
            if self.pp > 1
            else 0.0
        )

    def _pp_stride(self) -> int:
        st = self.st
        return st.tp_size * st.cp_size * st.dp_size

    def _neighbor(self, stage: int) -> int:
        """Engine rank id of the same position at another pp stage."""
        if self.rank is None:
            return stage
        return self.rank + (stage - self.stage) * self._pp_stride()

    def _comm_events(self, leaf, phase: str, point: str):
        """Yield exposed-comm engine requests for one leaf phase/point:
        lumped local time in merged mode; true per-group rendezvous in
        world-rank mode. Overlapped (hidden) collective time is emitted
        as zero-advance trace spans so traces show the async comm."""
        name = leaf.path_name().split(".", 1)[-1]
        hidden = sum(
            c.time - c.exposed_time
            for c in leaf.collective_calls
            if c.phase == phase and c.point == point
            and c.time > c.exposed_time
        )
        if hidden > 0:
            yield ("trace", hidden, f"{name}.{phase}_comm_async", "comm")
        if self.rank is None:
            total = sum(c.exposed_time for c in _leaf_calls(leaf, phase, point))
            if total:
                yield ("compute", total, f"{name}.{phase}_comm", "comm")
            return
        for c in _leaf_calls(leaf, phase, point):
            group = self._groups.get(c.dim)
            if group is None:
                if c.exposed_time:
                    yield ("compute", c.exposed_time, f"{name}.{c.op}", "comm")
                continue
            yield (
                "collective",
                (c.dim, tuple(group)),
                c.exposed_time,
                f"{name}.{c.op}[{c.dim}]",
                list(group),
            )

    # -- memory helpers ----------------------------------------------------
    def _alloc(self, t, nbytes, token=None, tag=""):
        if self.tracker is not None and nbytes:
            self.tracker.alloc(t, nbytes, token, tag)

    def _free(self, t, nbytes=0.0, token=None, tag=""):
        if self.tracker is not None:
            self.tracker.free(t, nbytes, token, tag)

    # -- one microbatch forward / backward ---------------------------------
    def _fwd(self, mb: int, clock: List[float], chunks=None) -> Generator:
        for chunk in (chunks if chunks is not None else self.chunks):
            leaves = chunk.called_leaves()
            if self.granularity == "chunk":
                dur = (chunk.cost_info.compute.fwd * self.perturb
                       + chunk.cost_info.net_exposed.fwd)
                t = yield ("compute", dur, f"fwd_mb{mb}", "comp")
                clock[0] = t
                self._alloc(t, chunk.act_info.cache_bytes,
                            f"mb{mb}:c{chunk.chunk_idx}", "act")
                continue
            for leaf in leaves:
                comp = leaf.cost_info.compute.fwd * self.perturb
                name = leaf.path_name().split(".", 1)[-1]
                for ev in self._comm_events(leaf, "fwd", "pre"):
                    t = yield ev
                    clock[0] = t
                self._alloc(clock[0], leaf.raw_act_info.fwd_temp_bytes,
                            tag="temp")
                if comp:
                    t = yield ("compute", comp, f"{name}.fwd#mb{mb}", "comp")
                    clock[0] = t
                self._free(clock[0], leaf.raw_act_info.fwd_temp_bytes,
                           tag="temp")
                if leaf.act_info.cache_bytes:
                    self._alloc(
                        clock[0], leaf.act_info.cache_bytes,
                        f"mb{mb}:{id(leaf)}", "act",
                    )
                for ev in self._comm_events(leaf, "fwd", "post"):
                    t = yield ev
                    clock[0] = t

    def _bwd(self, mb: int, clock: List[float], chunks=None) -> Generator:
        for chunk in reversed(chunks if chunks is not None else self.chunks):
            leaves = chunk.called_leaves()
            if self.granularity == "chunk":
                dur = (
                    chunk.cost_info.compute.bwd * self.perturb
                    + chunk.cost_info.recompute_time * self.perturb
                    + chunk.cost_info.net_exposed.bwd_act
                    + chunk.cost_info.net_exposed.bwd_w
                )
                t = yield ("compute", dur, f"bwd_mb{mb}", "comp")
                clock[0] = t
                self._free(t, token=f"mb{mb}:c{chunk.chunk_idx}", tag="act")
                continue
            done = set()
            i = len(leaves) - 1
            while i >= 0:
                leaf = leaves[i]
                if id(leaf) in done:
                    i -= 1
                    continue
                seg = getattr(leaf, "recompute_segment", None)
                if leaf.in_recompute and seg is not None:
                    seg_leaves = [
                        l for l in leaves
                        if getattr(l, "recompute_segment", None) is seg
                    ]
                    replay = sum(
                        sl.cost_info.compute.fwd * self.perturb
                        + sl.cost_info.net_exposed.fwd
                        for sl in seg_leaves
                    )
                    name = seg.path_name().split(".", 1)[-1]
                    saved = seg_leaves[0].act_info.cache_bytes
                    t = yield ("compute", replay, f"{name}.recompute#mb{mb}",
                               "comp")
                    clock[0] = t
                    for sl in seg_leaves:
                        if sl.raw_act_info.cache_bytes:
                            self._alloc(t, sl.raw_act_info.cache_bytes,
                                        f"mb{mb}:r{id(sl)}", "recompute")
                    if saved:
                        self._free(t, token=f"mb{mb}:{id(seg_leaves[0])}",
                                   tag="act")
                    for sl in reversed(seg_leaves):
                        dur = (
                            sl.cost_info.compute.bwd * self.perturb
                            + sl.cost_info.net_exposed.bwd_act
                            + sl.cost_info.net_exposed.bwd_w
                        )
                        lname = sl.path_name().split(".", 1)[-1]
                        flight = (sl.raw_act_info.bwd_temp_bytes
                                  + sl.raw_act_info.grad_flight_bytes)
                        self._alloc(clock[0], flight, tag="temp")
                        if dur:
                            t = yield ("compute", dur, f"{lname}.bwd#mb{mb}",
                                       "comp")
                            clock[0] = t
                        self._free(clock[0], flight, tag="temp")
                        if sl.raw_act_info.cache_bytes:
                            self._free(clock[0], token=f"mb{mb}:r{id(sl)}",
                                       tag="recompute")
                        done.add(id(sl))
                    i -= 1
                    continue
                comp_a = leaf.cost_info.compute.bwd_act * self.perturb
                comp_w = leaf.cost_info.compute.bwd_w * self.perturb
                name = leaf.path_name().split(".", 1)[-1]
                for phase in ("bwd_act", "bwd_w"):
                    for point in ("pre", "post"):
                        for ev in self._comm_events(leaf, phase, point):
                            t = yield ev
                            clock[0] = t
                # grad-in-flight: incoming output-grad + outgoing
                # input-grad live while the bwd op runs
                flight = (leaf.raw_act_info.bwd_temp_bytes
                          + leaf.raw_act_info.grad_flight_bytes)
                self._alloc(clock[0], flight, tag="temp")
                if comp_a + comp_w:
                    t = yield ("compute", comp_a + comp_w,
                               f"{name}.bwd#mb{mb}", "comp")
                    clock[0] = t
                self._free(clock[0], flight, tag="temp")
                if leaf.act_info.cache_bytes:
                    self._free(clock[0], token=f"mb{mb}:{id(leaf)}",
                               tag="act")
                done.add(id(leaf))
                i -= 1

    # -- optimizer tail (reference ``OptimizerSimulator``) -----------------
    def _optimizer(self, clock: List[float]) -> Generator:
        perf = self.perf
        dp = perf._compute_dp_time()
        # grad reduce-scatter (dense + moe)
        rs = dp.get("dense_grad_rs_time", 0.0) + dp.get("moe_grad_rs_time", 0.0)
        ag = dp.get("dense_param_ag_time", 0.0) + dp.get("moe_param_ag_time", 0.0)
        st = self.st
        group = self._dp_cp_group
        if group is None and self.rank is not None and st.dp_size * st.cp_size > 1:
            from simumax_tpu.parallel.mesh import rank_coords

            mine = rank_coords(self.rank, st)
            group = sorted(
                r
                for r in range(st.world_size)
                if rank_coords(r, st)["tp"] == mine["tp"]
                and rank_coords(r, st)["pp"] == mine["pp"]
            )
        if self.rank is not None and group:
            if rs:
                t = yield ("collective", ("dp_cp_rs", tuple(group)), rs,
                           "grad_reduce_scatter", group)
                clock[0] = t
        elif rs:
            t = yield ("compute", rs, "grad_reduce_scatter", "comm")
            clock[0] = t
        # world barrier before the step (rerun_state_machine analog)
        n_ranks = self.pp if self.rank is None else st.world_size
        t = yield (
            "collective",
            "optimizer_barrier",
            0.0,
            "optimizer_barrier",
            list(range(n_ranks)),
        )
        clock[0] = t
        t = yield ("compute", perf._compute_optim_time() * self.perturb,
                   "adam_step", "comp")
        clock[0] = t
        if self.rank is not None and group and ag:
            t = yield ("collective", ("dp_cp_ag", tuple(group)), ag,
                       "param_all_gather", group)
            clock[0] = t
        elif ag:
            t = yield ("compute", ag, "param_all_gather", "comm")
            clock[0] = t

    # -- full schedule ------------------------------------------------------
    def process(self) -> Generator:
        if self.st.vp_size > 1:
            yield from self._process_interleaved()
            return
        st, stage, pp = self.st, self.stage, self.pp
        mbc = st.micro_batch_num
        clock = [0.0]
        for kind, mb in one_f_one_b_order(pp, stage, mbc):
            if kind == "F":
                if stage > 0:
                    t = yield ("recv", self._neighbor(stage - 1), f"fwd{mb}",
                               f"recv_fwd{mb}", "pp_fwd")
                    clock[0] = t
                yield from self._fwd(mb, clock)
                if stage < pp - 1:
                    t = yield (
                        "send", self._neighbor(stage + 1), f"fwd{mb}",
                        self.p2p_time, f"send_fwd{mb}", "pp_fwd",
                    )
                    clock[0] = t
                    if not st.pp_comm_async:
                        # blocking isend approximation: sender stalls for
                        # the transfer. True rendezvous needs fused
                        # send/recv pairs (Megatron batch_isend_irecv) —
                        # unfused blocking sends deadlock in warmup.
                        yield ("advance", clock[0] + self.p2p_time)
            else:
                if stage < pp - 1:
                    t = yield ("recv", self._neighbor(stage + 1), f"bwd{mb}",
                               f"recv_bwd{mb}", "pp_bwd")
                    clock[0] = t
                yield from self._bwd(mb, clock)
                if stage > 0:
                    t = yield (
                        "send", self._neighbor(stage - 1), f"bwd{mb}",
                        self.p2p_time, f"send_bwd{mb}", "pp_bwd",
                    )
                    clock[0] = t
                    if not st.pp_comm_async:
                        yield ("advance", clock[0] + self.p2p_time)
        yield from self._optimizer(clock)

    def _process_interleaved(self) -> Generator:
        """Interleaved (VPP) schedule: chunk c's forward on the last
        stage feeds chunk c+1 on stage 0; backward wraps the other way
        (Megatron interleaved 1F1B, reference
        ``pipeline_schedule.py:97-715``)."""
        from simumax_tpu.parallel.pipeline import interleaved_order

        st, stage, pp = self.st, self.stage, self.pp
        vp, mbc = st.vp_size, st.micro_batch_num
        group = st.vpp_group_size
        by_chunk = {c.chunk_idx: [c] for c in self.chunks}
        clock = [0.0]
        for kind, c, mb in interleaved_order(pp, stage, mbc, vp, group):
            if kind == "F":
                if not (stage == 0 and c == 0):
                    src = self._neighbor(stage - 1 if stage > 0 else pp - 1)
                    t = yield ("recv", src, f"fwd_c{c}_mb{mb}",
                               f"recv_fwd_c{c}_mb{mb}", "pp_fwd")
                    clock[0] = t
                yield from self._fwd(mb, clock, by_chunk[c])
                if not (stage == pp - 1 and c == vp - 1):
                    dst = self._neighbor(stage + 1 if stage < pp - 1 else 0)
                    rc = c if stage < pp - 1 else c + 1
                    t = yield ("send", dst, f"fwd_c{rc}_mb{mb}",
                               self.p2p_time, f"send_fwd_c{rc}_mb{mb}",
                               "pp_fwd")
                    clock[0] = t
                    if not st.pp_comm_async:
                        yield ("advance", clock[0] + self.p2p_time)
            else:
                if not (stage == pp - 1 and c == vp - 1):
                    src = self._neighbor(stage + 1 if stage < pp - 1 else 0)
                    t = yield ("recv", src, f"bwd_c{c}_mb{mb}",
                               f"recv_bwd_c{c}_mb{mb}", "pp_bwd")
                    clock[0] = t
                yield from self._bwd(mb, clock, by_chunk[c])
                if not (stage == 0 and c == 0):
                    dst = self._neighbor(stage - 1 if stage > 0 else pp - 1)
                    rc = c if stage > 0 else c - 1
                    t = yield ("send", dst, f"bwd_c{rc}_mb{mb}",
                               self.p2p_time, f"send_bwd_c{rc}_mb{mb}",
                               "pp_bwd")
                    clock[0] = t
                    if not st.pp_comm_async:
                        yield ("advance", clock[0] + self.p2p_time)
        yield from self._optimizer(clock)

"""simulate() entry point (L5 top).

Reference: ``simumax/core/simu_runner.py:22-94`` (``run_simulation``:
one simulated rank per PP stage, memory tracker wiring, trace +
memory-artifact export).

Pod-scale additions on top of the reference shape:

* ``world_ranks=True`` simulates every global rank; with
  ``reduce="auto"`` (default) the world is first partitioned into
  rank-symmetry classes (:mod:`simumax_tpu.simulator.reduce`) and one
  representative per class is simulated — bit-identical results at a
  fraction of the work, falling back to exact full-world simulation
  wherever a ``perturbation`` entry breaks the symmetry.
* ``stream_trace=True`` (with ``save_path``) streams Chrome-trace
  events to disk while the engine runs instead of retaining them, so
  peak RSS is bounded regardless of event count.
"""

from __future__ import annotations

import json
import os
from typing import Optional

from simumax_tpu.simulator.engine import SimuEngine
from simumax_tpu.simulator.memory import SimuMemoryTracker
from simumax_tpu.simulator.schedule import StageProcess
from simumax_tpu.simulator.trace import StreamingTraceWriter, write_chrome_trace


def _diag(perf):
    diag = getattr(perf, "diagnostics", None)
    if diag is None:
        from simumax_tpu.core.records import Diagnostics

        diag = Diagnostics.active()
    return diag


def _world_memberships(st) -> dict:
    """Rendezvous-group membership per parallel dim, computed once for
    the whole world (the per-rank ``group_of`` fallback inside
    ``StageProcess`` is O(world) per rank — quadratic at pod scale)."""
    from simumax_tpu.parallel.mesh import rank_coords, rank_groups

    memberships = {}
    for dim in ("tp", "cp", "ep", "etp"):
        if getattr(st, f"{dim}_size") > 1:
            by_rank = {}
            for g in rank_groups(st, dim):
                for r in g:
                    by_rank[r] = g
            memberships[dim] = by_rank
    buckets: dict = {}
    if st.dp_size * st.cp_size > 1:
        for r in range(st.world_size):
            c = rank_coords(r, st)
            buckets.setdefault((c["tp"], c["pp"]), []).append(r)
        by_rank = {}
        for g in buckets.values():
            g = sorted(g)
            for r in g:
                by_rank[r] = g
        memberships["dp_cp"] = by_rank
    if st.edp_size > 1:
        by_rank = {}
        for g in rank_groups(st, "edp"):
            for r in g:
                by_rank[r] = g
        memberships["edp"] = by_rank
    return memberships


def build_reduced_engine(perf, plan, granularity: str,
                         fault_model=None, engine_kw: Optional[dict] = None,
                         wrap_proc=None, drop_events: bool = False):
    """Engine + one ``StageProcess`` coroutine per symmetry class of
    ``plan`` — the world-rank construction shared by
    :func:`run_simulation` and the incremental fault-replay engine
    (``simulator/faults.py``), so the two can never drift.

    ``wrap_proc(engine_rank, gen) -> proc`` wraps each coroutine (the
    replay engine passes a ``RecordingProc`` to capture request
    streams); ``drop_events=True`` keeps event counters without
    constructing trace records (replays need only makespan + deaths).
    """
    k = plan.n_classes
    engine = SimuEngine(k, fault_model=fault_model,
                        drop_events=drop_events, **(engine_kw or {}))
    barrier = list(range(k))
    for i in range(k):
        groups = {
            d: g for d, g in plan.groups[i].items()
            if d in ("tp", "cp", "ep", "etp")
        }
        buckets = {
            d: g for d, g in plan.groups[i].items()
            if d in ("dp_cp", "edp")
        }
        proc = StageProcess(
            perf, plan.stages[i], tracker=None,
            granularity=granularity,
            rank=i, perturb=plan.perturbs[i],
            groups=groups, bucket_groups=buckets,
            neighbor_map=plan.neighbor_maps[i] or None,
            barrier_group=barrier,
        ).process()
        if wrap_proc is not None:
            proc = wrap_proc(i, proc)
        engine.add_rank(i, proc)
    return engine


def run_simulation(
    perf,
    save_path: Optional[str] = None,
    granularity: str = "leaf",
    track_memory: Optional[bool] = None,
    world_ranks: bool = False,
    perturbation: Optional[dict] = None,
    reduce="auto",
    stream_trace: bool = False,
    faults=None,
    critical_path: bool = False,
    progress_every: int = 200_000,
    event_delays: Optional[dict] = None,
) -> dict:
    """Discrete-event replay of one training iteration. ``perf`` must
    have completed ``run_estimate()``.

    ``world_ranks=True`` simulates every global rank (instead of one
    representative per pipeline stage): intra-stage collectives become
    true rendezvous among each rank's tp/cp/ep groups and the optimizer
    syncs over real dp groups — enabling per-rank straggler injection
    via ``perturbation`` ({rank: compute-time multiplier}). The
    reference only approximates stragglers with a closed-form inflation
    (perf_llm.py:255-291); here the slowdown propagates through the
    actual collective dependency graph.

    ``reduce`` controls world-rank symmetry reduction: ``"auto"``
    (default) simulates one rank per symmetry class when that is
    cheaper, ``True`` forces the reduced path, ``False`` forces exact
    full-world simulation. Reduced results are expanded back to
    full-world shape (``per_rank_end_ms``, event counts) and carry a
    ``reduction`` summary block.

    Memory tracking is a per-representative-stage feature and is
    disabled in world mode (result carries no 'memory' key); passing
    ``track_memory=True`` together with ``world_ranks=True`` records a
    Diagnostics warning instead of silently ignoring the request.

    ``stream_trace=True`` with ``save_path`` writes ``trace.json``
    incrementally while the engine runs (bounded peak RSS); without
    ``save_path`` it is ignored with a Diagnostics warning.

    ``faults`` injects a :class:`~simumax_tpu.simulator.faults.
    FaultScenario` (or a path to its JSON): timed rank slowdowns,
    preemptions, link degradation, and rank deaths, consulted by the
    engine at event-service time (``docs/faults.md``). Requires
    ``world_ranks=True`` when non-empty; an empty scenario is
    bit-identical to no scenario at all. The result then carries a
    structured ``"faults"`` outcome block — a rank death degrades
    gracefully (partners resolve via the fault model) instead of
    deadlocking.

    ``critical_path=True`` records the event-dependency skeleton during
    the run and attaches a ``"critical_path"`` report
    (``observe/critpath.py``): per-event slack, the cross-rank critical
    path, a simulated waterfall whose buckets sum to ``end_time``
    within 1e-6, sim-vs-analytical ``divergence``, and per-rank /
    per-link slack-headroom summaries. Recording is observational —
    on vs off makespans are bit-identical. With ``save_path`` the
    report lands in ``critpath.json`` and (batch-trace mode) the Chrome
    trace gains ``on_critical_path`` / ``slack_us`` args; under
    ``stream_trace`` only the bounded skeleton is retained, so the
    streamed trace is not annotated (the report still is).

    ``progress_every`` drives the progress heartbeat every N served
    engine events: the ``des_events_served`` / ``des_blocked_ranks`` /
    ``des_clock_seconds`` registry gauges (``observe/telemetry.py`` —
    scrapeable from ``GET /metrics`` while the run is in flight) are
    always updated, and a debug-level Reporter line (events/s, virtual
    clock, blocked-rank count) is additionally emitted at ``--log-level
    debug``; 0 disables both. Default stdout is byte-identical (debug
    lines are suppressed at the default log level; gauges are
    observe-only). The gauges are process-wide and unlabelled —
    deliberately, so a long-lived server never accumulates per-run
    label cardinality — which makes them last-writer-wins: concurrent
    ``/v1/simulate`` runs interleave their heartbeats, so treat them
    as "a simulation is alive and progressing", not as a per-run
    series (per-run numbers live in the request's span tree).

    ``event_delays`` ({(engine rank, per-rank emit index): extra
    seconds}) perturbs single events at service time — the
    slack-correctness test hook."""
    from simumax_tpu.core.errors import ConfigError

    if not perf.chunks:
        raise ConfigError(
            "simulate() needs a completed estimate: call run_estimate() "
            "first", phase="simulate",
        )
    st = perf.strategy
    pp = st.pp_size
    perturbation = perturbation or {}
    diag = _diag(perf)
    if isinstance(faults, str):
        from simumax_tpu.simulator.faults import FaultScenario

        faults = FaultScenario.from_json(faults)
    if faults is not None:
        faults.validate(st.world_size)
        if faults.empty:
            # the empty scenario must be bit-identical to a run with no
            # scenario at all: drop it before it can touch anything
            faults = None
        elif not world_ranks:
            raise ConfigError(
                "fault scenarios need world_ranks=True: rank-scoped "
                "faults are meaningless when one simulated rank stands "
                "for a whole pipeline stage",
                phase="simulate", world_size=st.world_size,
            )
    if world_ranks and track_memory:
        # memory tracking is per-representative-stage; world mode is for
        # timing/straggler analysis (satellite of ISSUE 4: surface the
        # silent downgrade)
        if diag is not None:
            diag.warn(
                "simulate",
                "track_memory=True is ignored with world_ranks=True: "
                "memory tracking is per-representative-stage; run "
                "simulate() without world_ranks for memory analysis",
                world_size=st.world_size,
            )
    do_memory = bool(track_memory is None or track_memory) and not world_ranks
    sink = None
    if stream_trace:
        if save_path:
            os.makedirs(save_path, exist_ok=True)
            sink = StreamingTraceWriter(os.path.join(save_path, "trace.json"))
        elif diag is not None:
            diag.warn(
                "simulate",
                "stream_trace=True needs save_path to stream to; ignored",
            )

    rec = None
    if critical_path:
        from simumax_tpu.observe.critpath import DependencySkeleton

        rec = DependencySkeleton()
    progress = None
    if progress_every:
        from simumax_tpu.observe.report import LEVELS, get_reporter
        from simumax_tpu.observe.telemetry import get_registry

        _rep = get_reporter()
        # registry gauges are updated at every heartbeat regardless of
        # log level (a long pod-scale run stays observable from
        # ``GET /metrics`` while it runs); the debug *line* is still
        # emitted only when the reporter would show it
        _emit_lines = _rep.threshold <= LEVELS["debug"]
        _reg = get_registry()
        _g_events = _reg.gauge("des_events_served")
        _g_blocked = _reg.gauge("des_blocked_ranks")
        _g_clock = _reg.gauge("des_clock_seconds")

        def progress(served, events, clock_s, blocked_ranks,
                     elapsed_s):
            _g_events.set(events)
            _g_blocked.set(blocked_ranks)
            _g_clock.set(clock_s)
            if not _emit_lines:
                return
            # rate in emitted trace events/s — the same unit as
            # num_events and bench_simulate's events/s metric (a
            # served request emits 0-2 trace events)
            rate = events / elapsed_s if elapsed_s else 0.0
            _rep.debug(
                f"[simulate] {events} events emitted "
                f"({rate:,.0f} ev/s), clock "
                f"{clock_s * 1e3:.1f} ms, {blocked_ranks} ranks "
                f"blocked",
                event="sim_progress", served=served, events=events,
                clock_ms=clock_s * 1e3,
                blocked_ranks=blocked_ranks, events_per_sec=rate,
            )

    engine_kw = dict(
        dep_recorder=rec,
        event_delays=event_delays,
        progress=progress,
        progress_every=progress_every,
    )
    plan = None
    trackers = []
    fault_model = None
    if world_ranks:
        n = st.world_size
        bad = [r for r in perturbation if not 0 <= r < n]
        if bad:
            # a typed error, not an assert: rank validation must
            # survive `python -O`, and the CLI turns ConfigError into
            # an actionable one-liner
            raise ConfigError(
                f"perturbation for nonexistent ranks {bad} "
                f"(world {n})",
                phase="simulate", world_size=n, bad_ranks=bad,
            )
        if reduce:
            from simumax_tpu.simulator.reduce import build_reduction

            plan = build_reduction(
                st, perturbation,
                signatures=faults.rank_signatures() if faults else None,
            )
            if reduce == "auto" and plan.n_classes >= n:
                plan = None  # no symmetry to exploit: exact path
        if faults is not None:
            from simumax_tpu.simulator.faults import StepFaultModel

            fault_model = StepFaultModel(
                faults, rank_map=plan.reps if plan is not None else None
            )
        if plan is not None:
            engine = build_reduced_engine(
                perf, plan, granularity, fault_model=fault_model,
                engine_kw=dict(event_sink=sink, **engine_kw),
            )
        else:
            from simumax_tpu.parallel.mesh import rank_coords

            memberships = _world_memberships(st)
            engine = SimuEngine(n, event_sink=sink,
                                fault_model=fault_model, **engine_kw)
            for r in range(n):
                stage = rank_coords(r, st)["pp"]
                proc = StageProcess(
                    perf, stage, tracker=None, granularity=granularity,
                    rank=r, perturb=perturbation.get(r, 1.0),
                    groups={
                        d: m[r] for d, m in memberships.items()
                        if d in ("tp", "cp", "ep", "etp") and r in m
                    },
                    bucket_groups={
                        d: m[r] for d, m in memberships.items()
                        if d in ("dp_cp", "edp") and r in m
                    },
                )
                engine.add_rank(r, proc.process())
    else:
        engine = SimuEngine(pp, event_sink=sink, **engine_kw)
        for s in range(pp):
            static = sum(
                c.param_info.total_bytes for c in perf.stage_chunks(s)
            )
            tracker = (
                SimuMemoryTracker(s, static_bytes=static,
                                  record_events=save_path is not None)
                if do_memory
                else None
            )
            trackers.append(tracker)
            proc = StageProcess(
                perf, s, tracker=tracker, granularity=granularity
            )
            engine.add_rank(s, proc.process())
    try:
        end_time = engine.run()
    except BaseException:
        if sink is not None:
            # finalize what streamed so far: a valid (partial) trace is
            # exactly what's needed to debug the deadlocked schedule
            sink.close(trackers if do_memory else None)
        raise
    # machine-variance inflation, same as the analytical path
    # (perf-vs-simulator agreement must survive the straggler model)
    ratio = perf.straggler_ratio()
    raw_end = end_time
    end_time *= ratio

    if plan is not None:
        per_rank_ms = [
            engine.clock[plan.class_of[r]] * 1e3
            for r in range(plan.world_size)
        ]
        num_events = sum(
            w * c for w, c in zip(plan.weights, engine.events_by_rank)
        )
        num_comm = sum(
            w * c for w, c in zip(plan.weights, engine.comm_events_by_rank)
        )
    else:
        per_rank_ms = [t * 1e3 for t in engine.clock]
        num_events = engine.num_events
        num_comm = sum(engine.comm_events_by_rank)

    result = {
        "end_time": end_time,
        "end_time_ms": end_time * 1e3,
        "straggle_ratio": ratio,
        "per_rank_end_ms": per_rank_ms,
        "num_events": num_events,
        "num_comm_events": num_comm,
    }
    if fault_model is not None:
        from simumax_tpu.simulator.faults import FaultOutcome

        deaths = []
        for (r, t) in engine.deaths:
            # a dead class rep stands for every member (a death that
            # leaves ranks symmetric — e.g. whole-world kill — keeps
            # them in one class); sort so reduced == exact regardless
            # of engine kill order. Times carry the same straggler
            # inflation as end_time so the result dict has one wall
            # time base.
            members = plan.classes[r] if plan is not None else [r]
            deaths.extend(
                {"rank": g, "time_ms": t * ratio * 1e3} for g in members
            )
        deaths.sort(key=lambda d: (d["time_ms"], d["rank"]))
        result["faults"] = FaultOutcome(
            applied_events=len(faults.events),
            completed=not deaths,
            deaths=deaths,
        ).to_dict()
    if plan is not None:
        result["reduction"] = {
            "world_size": plan.world_size,
            "n_classes": plan.n_classes,
            "engine_events": engine.num_events,
            "max_class_size": max(plan.weights),
        }
    annotations = None
    if rec is not None:
        from simumax_tpu.observe.critpath import analyze, diverge

        if plan is not None:
            rank_map = plan.reps
            weights = plan.weights
            stages = plan.stages

            def stage_of(r):
                return stages[r]
        elif world_ranks:
            from simumax_tpu.parallel.mesh import rank_coords

            world_stages = [
                rank_coords(r, st)["pp"] for r in range(st.world_size)
            ]
            rank_map = weights = None

            def stage_of(r):
                return world_stages[r]
        else:
            rank_map = weights = None

            def stage_of(r):
                return r  # merged mode: one engine rank per pp stage
        report, annotations = analyze(
            rec, raw_end, straggle_ratio=ratio, rank_map=rank_map,
            weights=weights, stage_of=stage_of,
            # share the analytical anchor stage so the two waterfalls'
            # compute-vs-bubble split diverges only on model drift
            ref_stage=perf.analysis_cost()["binding_stage_rs"],
            meta={
                "model": perf.model_config.model_name,
                "system": perf.system.sys_name,
                "world_size": st.world_size,
                "mode": ("reduced" if plan is not None
                         else "world" if world_ranks else "merged"),
                "granularity": granularity,
                "faulted": fault_model is not None,
            },
        )
        # top=32 matches the slack-sample depth so the CLI's --top can
        # go deeper than diverge()'s display default without the saved
        # report silently capping the op table
        report["divergence"] = diverge(perf, report, top=32)
        result["critical_path"] = report
    if do_memory:
        result["memory"] = [t.summary() for t in trackers]
        for t in trackers:
            leftover = t.outstanding_tokens()
            assert not leftover, (
                f"stage {t.rank}: unfreed activation tokens {leftover}"
            )
    if save_path:
        os.makedirs(save_path, exist_ok=True)
        trace_path = os.path.join(save_path, "trace.json")
        if sink is not None:
            # streamed events already left the process: the trace stays
            # un-annotated (the critpath report still lands below —
            # only the bounded skeleton was retained)
            sink.close(trackers if do_memory else None)
        else:
            write_chrome_trace(
                trace_path, engine.events, trackers if do_memory else None,
                annotations=annotations,
            )
        result["trace_path"] = trace_path
        if rec is not None:
            from simumax_tpu.observe.critpath import save_report

            result["critical_path_path"] = save_report(
                result["critical_path"],
                os.path.join(save_path, "critpath.json"),
            )
        if do_memory:
            snaps = [t.snapshot() for t in trackers]
            with open(
                os.path.join(save_path, "simu_memory_snapshot.json"), "w"
            ) as f:
                json.dump(snaps, f)
            # torch memory-viz parity artifact (pytorch.org/memory_viz):
            # rank 0's per-op alloc/free trace (reference
            # simu_memory.py:212-556 pickle analog)
            from simumax_tpu.simulator.memory import export_memory_viz

            result["memory_viz_path"] = export_memory_viz(
                trackers[0],
                os.path.join(save_path, "memory_viz_snapshot.pickle"),
            )
            try:
                from simumax_tpu.simulator.plot import plot_memory_timeline

                result["memory_plot"] = plot_memory_timeline(
                    snaps,
                    os.path.join(save_path, "memory_timeline.png"),
                    hbm_gib=perf.system.accelerator.mem_gbs,
                )
            except ImportError:
                pass
    if save_path:
        with open(os.path.join(save_path, "simu_result.json"), "w") as f:
            json.dump(result, f, indent=2)
    return result


def analyze_stragglers(
    perf,
    slow_ranks: dict,
    granularity: str = "chunk",
    reduce="auto",
) -> dict:
    """Quantify the iteration-time impact of per-rank slowdowns
    ({rank: multiplier}) by replaying the schedule with every global
    rank simulated. Returns baseline/perturbed times, the realized
    inflation, and the reference-style closed-form ratio for
    comparison. Symmetry reduction (``reduce``) applies to both runs —
    the perturbed run automatically shatters only the classes the
    stragglers touch."""
    base = run_simulation(
        perf, None, granularity=granularity, world_ranks=True, reduce=reduce
    )
    slow = run_simulation(
        perf, None, granularity=granularity, world_ranks=True,
        perturbation=slow_ranks, reduce=reduce,
    )
    return {
        "baseline_ms": base["end_time_ms"],
        "perturbed_ms": slow["end_time_ms"],
        "inflation": slow["end_time"] / base["end_time"],
        #: naive serial expectation: the worst single multiplier (what
        #: you'd get if the slow rank gated everything); the simulated
        #: inflation shows how much the schedule actually absorbs
        "worst_multiplier": max(slow_ranks.values(), default=1.0),
        "slow_ranks": slow_ranks,
    }

"""simulate() entry point (L5 top).

Reference: ``simumax/core/simu_runner.py:22-94`` (``run_simulation``:
one simulated rank per PP stage, memory tracker wiring, trace +
memory-artifact export).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from simumax_tpu.simulator.engine import SimuEngine
from simumax_tpu.simulator.memory import SimuMemoryTracker
from simumax_tpu.simulator.schedule import StageProcess
from simumax_tpu.simulator.trace import write_chrome_trace


def run_simulation(
    perf,
    save_path: Optional[str] = None,
    granularity: str = "leaf",
    track_memory: bool = True,
    world_ranks: bool = False,
    perturbation: Optional[dict] = None,
) -> dict:
    """Discrete-event replay of one training iteration. ``perf`` must
    have completed ``run_estimate()``.

    ``world_ranks=True`` simulates every global rank (instead of one
    representative per pipeline stage): intra-stage collectives become
    true rendezvous among each rank's tp/cp/ep groups and the optimizer
    syncs over real dp groups — enabling per-rank straggler injection
    via ``perturbation`` ({rank: compute-time multiplier}). The
    reference only approximates stragglers with a closed-form inflation
    (perf_llm.py:255-291); here the slowdown propagates through the
    actual collective dependency graph. Memory tracking is a
    per-representative-stage feature and is disabled in world mode
    (result carries no 'memory' key)."""
    assert perf.chunks, "call run_estimate() before simulate()"
    st = perf.strategy
    pp = st.pp_size
    perturbation = perturbation or {}
    if world_ranks:
        from simumax_tpu.parallel.mesh import rank_coords, rank_groups

        n = st.world_size
        bad = [r for r in perturbation if not 0 <= r < n]
        assert not bad, f"perturbation for nonexistent ranks {bad} (world {n})"
        # memory tracking is per-representative-stage; world mode is for
        # timing/straggler analysis
        track_memory = False
        # group membership computed once per dim, shared by all ranks
        memberships = {}
        for dim in ("tp", "cp", "ep", "etp"):
            if getattr(st, f"{dim}_size") > 1:
                by_rank = {}
                for g in rank_groups(st, dim):
                    for r in g:
                        by_rank[r] = g
                memberships[dim] = by_rank
        dp_groups = {}
        if st.dp_size * st.cp_size > 1:
            from collections import defaultdict

            buckets = defaultdict(list)
            for r in range(n):
                c = rank_coords(r, st)
                buckets[(c["tp"], c["pp"])].append(r)
            for g in buckets.values():
                for r in g:
                    dp_groups[r] = sorted(g)
        engine = SimuEngine(n)
        trackers = []
        for r in range(n):
            stage = rank_coords(r, st)["pp"]
            proc = StageProcess(
                perf, stage, tracker=None, granularity=granularity,
                rank=r, perturb=perturbation.get(r, 1.0),
                groups={d: m[r] for d, m in memberships.items() if r in m},
                dp_cp_group=dp_groups.get(r),
            )
            engine.add_rank(r, proc.process())
    else:
        engine = SimuEngine(pp)
        trackers = []
        for s in range(pp):
            static = sum(
                c.param_info.total_bytes for c in perf.stage_chunks(s)
            )
            tracker = (
                SimuMemoryTracker(s, static_bytes=static,
                                  record_events=save_path is not None)
                if track_memory
                else None
            )
            trackers.append(tracker)
            proc = StageProcess(
                perf, s, tracker=tracker, granularity=granularity
            )
            engine.add_rank(s, proc.process())
    end_time = engine.run()
    # machine-variance inflation, same as the analytical path
    # (perf-vs-simulator agreement must survive the straggler model)
    ratio = perf.straggler_ratio()
    end_time *= ratio

    result = {
        "end_time": end_time,
        "end_time_ms": end_time * 1e3,
        "straggle_ratio": ratio,
        "per_rank_end_ms": [t * 1e3 for t in engine.clock],
        "num_events": len(engine.events),
    }
    if track_memory:
        result["memory"] = [t.summary() for t in trackers]
        for t in trackers:
            leftover = t.outstanding_tokens()
            assert not leftover, (
                f"stage {t.rank}: unfreed activation tokens {leftover}"
            )
    if save_path:
        os.makedirs(save_path, exist_ok=True)
        trace_path = os.path.join(save_path, "trace.json")
        write_chrome_trace(
            trace_path, engine.events, trackers if track_memory else None
        )
        result["trace_path"] = trace_path
        if track_memory:
            snaps = [t.snapshot() for t in trackers]
            with open(
                os.path.join(save_path, "simu_memory_snapshot.json"), "w"
            ) as f:
                json.dump(snaps, f)
            # torch memory-viz parity artifact (pytorch.org/memory_viz):
            # rank 0's per-op alloc/free trace (reference
            # simu_memory.py:212-556 pickle analog)
            from simumax_tpu.simulator.memory import export_memory_viz

            result["memory_viz_path"] = export_memory_viz(
                trackers[0],
                os.path.join(save_path, "memory_viz_snapshot.pickle"),
            )
            try:
                from simumax_tpu.simulator.plot import plot_memory_timeline

                result["memory_plot"] = plot_memory_timeline(
                    snaps,
                    os.path.join(save_path, "memory_timeline.png"),
                    hbm_gib=perf.system.accelerator.mem_gbs,
                )
            except ImportError:
                pass
    if save_path:
        with open(os.path.join(save_path, "simu_result.json"), "w") as f:
            json.dump(result, f, indent=2)
    return result


def analyze_stragglers(
    perf,
    slow_ranks: dict,
    granularity: str = "chunk",
) -> dict:
    """Quantify the iteration-time impact of per-rank slowdowns
    ({rank: multiplier}) by replaying the schedule with every global
    rank simulated. Returns baseline/perturbed times, the realized
    inflation, and the reference-style closed-form ratio for
    comparison."""
    base = run_simulation(
        perf, None, granularity=granularity, world_ranks=True
    )
    slow = run_simulation(
        perf, None, granularity=granularity, world_ranks=True,
        perturbation=slow_ranks,
    )
    return {
        "baseline_ms": base["end_time_ms"],
        "perturbed_ms": slow["end_time_ms"],
        "inflation": slow["end_time"] / base["end_time"],
        #: naive serial expectation: the worst single multiplier (what
        #: you'd get if the slow rank gated everything); the simulated
        #: inflation shows how much the schedule actually absorbs
        "worst_multiplier": max(slow_ranks.values(), default=1.0),
        "slow_ranks": slow_ranks,
    }

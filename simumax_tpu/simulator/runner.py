"""simulate() entry point (L5 top).

Reference: ``simumax/core/simu_runner.py:22-94`` (``run_simulation``:
one simulated rank per PP stage, memory tracker wiring, trace +
memory-artifact export).
"""

from __future__ import annotations

import json
import os
from typing import Optional

from simumax_tpu.simulator.engine import SimuEngine
from simumax_tpu.simulator.memory import SimuMemoryTracker
from simumax_tpu.simulator.schedule import StageProcess
from simumax_tpu.simulator.trace import write_chrome_trace


def run_simulation(
    perf,
    save_path: Optional[str] = None,
    granularity: str = "leaf",
    track_memory: bool = True,
) -> dict:
    """Discrete-event replay of one training iteration. ``perf`` must
    have completed ``run_estimate()``."""
    assert perf.chunks, "call run_estimate() before simulate()"
    st = perf.strategy
    pp = st.pp_size
    engine = SimuEngine(pp)
    trackers = []
    for s in range(pp):
        static = sum(c.param_info.total_bytes for c in perf.stage_chunks(s))
        tracker = (
            SimuMemoryTracker(s, static_bytes=static) if track_memory else None
        )
        trackers.append(tracker)
        proc = StageProcess(perf, s, tracker=tracker, granularity=granularity)
        engine.add_rank(s, proc.process())
    end_time = engine.run()
    # machine-variance inflation, same as the analytical path
    # (perf-vs-simulator agreement must survive the straggler model)
    ratio = perf.straggler_ratio()
    end_time *= ratio

    result = {
        "end_time": end_time,
        "end_time_ms": end_time * 1e3,
        "straggle_ratio": ratio,
        "per_rank_end_ms": [t * 1e3 for t in engine.clock],
        "num_events": len(engine.events),
    }
    if track_memory:
        result["memory"] = [t.summary() for t in trackers]
        for t in trackers:
            leftover = t.outstanding_tokens()
            assert not leftover, (
                f"stage {t.rank}: unfreed activation tokens {leftover}"
            )
    if save_path:
        os.makedirs(save_path, exist_ok=True)
        trace_path = os.path.join(save_path, "trace.json")
        write_chrome_trace(
            trace_path, engine.events, trackers if track_memory else None
        )
        result["trace_path"] = trace_path
        if track_memory:
            snaps = [t.snapshot() for t in trackers]
            with open(
                os.path.join(save_path, "simu_memory_snapshot.json"), "w"
            ) as f:
                json.dump(snaps, f)
            try:
                from simumax_tpu.simulator.plot import plot_memory_timeline

                result["memory_plot"] = plot_memory_timeline(
                    snaps,
                    os.path.join(save_path, "memory_timeline.png"),
                    hbm_gib=perf.system.accelerator.mem_gbs,
                )
            except ImportError:
                pass
    if save_path:
        with open(os.path.join(save_path, "simu_result.json"), "w") as f:
            json.dump(result, f, indent=2)
    return result

"""Rank-symmetry reduction for world-rank simulation (L5).

At pod scale (256 v5e chips, thousands of v5p chips) almost every
global rank is interchangeable with hundreds of others: ranks whose
(pp stage, tp/cp/ep/etp group roles, dp/edp group roles, perturbation
multiplier) signatures are identical execute bit-identical event
sequences, because every engine request they issue — compute durations,
collective rendezvous, p2p tags, async buckets — is derived from
exactly those signatures. Analytical pod-scale models (Calculon) and
event-driven simulators (ASTRA-sim) exploit the same symmetry; here it
is computed exactly, not assumed.

Classes are found by color refinement (the 1-dimensional
Weisfeiler-Leman fixpoint): start from ``(stage, perturb)`` colors and
iteratively split ranks whose *relational* position differs — the color
tuple of their tp/cp/ep/etp group peers (in group order), of their
dp_cp/edp bucket peers, and of their pipeline neighbours. A
``perturbation`` entry therefore shatters exactly the classes whose
symmetry it breaks: untouched regions stay merged, and in the worst
case the refinement degenerates to one-rank classes, which *is* the
exact full-world simulation (the automatic fallback — reduced and full
are the same algorithm, reduction just deduplicates proven-identical
coroutines).

The reduced engine runs one representative per class; rendezvous
groups, pipeline neighbours and the optimizer barrier are mapped onto
class representatives (class-weighted rendezvous: ``max`` over one
arrival per class equals ``max`` over all members because members are
bit-identical). Results are expanded back to full-world shape by
:mod:`simumax_tpu.simulator.runner`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

from simumax_tpu.parallel.mesh import rank_coords, rank_groups


@dataclass
class ReductionPlan:
    """Everything the runner needs to simulate one rank per symmetry
    class and expand the result to full-world shape."""

    world_size: int
    #: global members of each class, ascending; class index == engine rank
    classes: List[List[int]]
    #: class index of every global rank
    class_of: List[int]
    #: pp stage / perturbation multiplier per class
    stages: List[int]
    perturbs: List[float]
    #: per-class rendezvous groups, mapped to engine ranks: keys are the
    #: dims StageProcess consults (tp/cp/ep/etp plus dp_cp/edp buckets)
    groups: List[Dict[str, List[int]]]
    #: per-class {pp stage -> engine rank} for p2p neighbours
    neighbor_maps: List[Dict[int, int]]

    @property
    def n_classes(self) -> int:
        return len(self.classes)

    @property
    def reps(self) -> List[int]:
        """Representative global rank per engine (class) rank — each
        class's smallest member. Critical-path expansion maps path
        nodes through this list (``observe/critpath.py``): binding
        ties break toward smaller ranks in both the reduced and the
        exact engine, and every representative is its class's minimum,
        so the reduced path expands bit-identically to the exact
        full-world path."""
        return [members[0] for members in self.classes]

    @property
    def weights(self) -> List[int]:
        return [len(members) for members in self.classes]


def _dense_dp_cp_groups(st) -> List[List[int]]:
    """dp_cp bucket membership exactly as the world-rank runner builds
    it: ranks sharing (tp, pp) coords (cp folds into the data-parallel
    grad stream)."""
    buckets: Dict[tuple, List[int]] = {}
    for r in range(st.world_size):
        c = rank_coords(r, st)
        buckets.setdefault((c["tp"], c["pp"]), []).append(r)
    return [sorted(g) for g in buckets.values()]


def _membership(groups: List[List[int]]) -> Dict[int, List[int]]:
    by_rank: Dict[int, List[int]] = {}
    for g in groups:
        for r in g:
            by_rank[r] = g
    return by_rank


def canonical_class_order(plan: ReductionPlan,
                          seeds: List[tuple]) -> List[int]:
    """A structure-canonical ordering of a plan's classes, used by the
    fault-replay step cache (``simulator/faults.py``) to relabel two
    plans that differ only in *which* symmetric ranks a scenario
    touched into one byte-equal cache key.

    Runs the same color-refinement idiom as :func:`build_reduction`,
    but over *classes*: initial colors are ``(stage, perturb, class
    size, seed)`` — ``seeds[i]`` carries the class's fault timeline —
    refined by the color tuples of each class's rendezvous-group peers
    (in group order) and pipeline neighbours until stable. Classes are
    then ordered by final color, ties broken by original class index.

    The ordering is only a *relabeling recipe*: the cache key built
    from it re-serializes the full engine problem in the new
    numbering, so an imperfect canonicalization can cost cache hits
    but never correctness (byte-equal keys are byte-equal problems).
    """
    k = plan.n_classes
    color: List[tuple] = [
        (plan.stages[i], plan.perturbs[i], len(plan.classes[i]), seeds[i])
        for i in range(k)
    ]
    canon: Dict[tuple, int] = {}
    out: List[int] = [0] * k
    n_colors = 0
    while True:
        canon.clear()
        for i in range(k):
            sig = [color[i]]
            for dim in sorted(plan.groups[i]):
                sig.append(
                    (dim, tuple(color[p] for p in plan.groups[i][dim]))
                )
            sig.append(tuple(sorted(
                (s, color[p]) for s, p in plan.neighbor_maps[i].items()
            )))
            key = tuple(sig)
            c = canon.get(key)
            if c is None:
                c = canon[key] = len(canon)
            out[i] = c
        if len(canon) == n_colors:
            break
        n_colors = len(canon)
        color = [(c,) for c in out]
    return sorted(range(k), key=lambda i: (out[i], i))


def orbit_of(plan: ReductionPlan, rank: int) -> int:
    """The symmetry-orbit (class) index of a global rank under a
    reduction plan. The fleet scheduler annotates placement decisions
    with the orbits its fault events land in: two events whose target
    ranks share an orbit of the *healthy* plan are the same abstract
    event up to relabeling, so the fault-replay step cache answers the
    second from the first's replay (``faults.ReplayContext``'s
    canonical keying) — the cross-job amortization the fleet bench
    measures."""
    return plan.class_of[rank]


def reduction_structure(st) -> tuple:
    """The world's relational structure — group memberships, pipeline
    stages and neighbours — computed once and reusable across
    :func:`build_reduction` calls on the same strategy (the
    fault-replay engine builds one plan per scenario partition, and at
    pod scale this precompute dominates the refinement itself)."""
    n = st.world_size
    pp = st.pp_size
    stride = st.tp_size * st.cp_size * st.dp_size  # == StageProcess._pp_stride

    memberships: Dict[str, Dict[int, List[int]]] = {}
    for dim in ("tp", "cp", "ep", "etp"):
        if getattr(st, f"{dim}_size") > 1:
            memberships[dim] = _membership(rank_groups(st, dim))
    if st.dp_size * st.cp_size > 1:
        memberships["dp_cp"] = _membership(_dense_dp_cp_groups(st))
    if st.edp_size > 1:
        memberships["edp"] = _membership(rank_groups(st, "edp"))
    stages = [rank_coords(r, st)["pp"] for r in range(n)]

    def pp_next(r: int) -> Optional[int]:
        if pp <= 1:
            return None
        s = stages[r]
        # interleaved schedules wrap stage pp-1 -> 0 (chunk handoff)
        return r + stride if s < pp - 1 else r - (pp - 1) * stride

    def pp_prev(r: int) -> Optional[int]:
        if pp <= 1:
            return None
        s = stages[r]
        return r - stride if s > 0 else r + (pp - 1) * stride

    nxt = [pp_next(r) for r in range(n)]
    prv = [pp_prev(r) for r in range(n)]
    dims = sorted(memberships)
    return memberships, stages, nxt, prv, dims


def build_reduction(st, perturbation: Optional[dict] = None,
                    signatures: Optional[dict] = None,
                    structure: Optional[tuple] = None) -> ReductionPlan:
    """Partition the world into symmetry classes and map the simulated
    structures onto class representatives. Deterministic: classes are
    numbered by their smallest member.

    ``signatures`` maps rank -> extra hashable identity folded into the
    initial colors: a fault scenario's per-rank event signature
    (``faults.py::FaultScenario.rank_signatures``) shatters exactly the
    classes its rank-scoped events touch, the same way a straggler
    ``perturbation`` does. Signature *values* reach the refinement only
    through equality, so any renaming that preserves the induced
    partition yields the same plan — seeding them with the healthy
    class ids (as the fault-replay engine does) additionally makes the
    refinement converge from the already-stable healthy partition.

    ``structure`` reuses a precomputed :func:`reduction_structure`."""
    perturbation = perturbation or {}
    signatures = signatures or {}
    n = st.world_size
    pp = st.pp_size

    stride = st.tp_size * st.cp_size * st.dp_size
    if structure is None:
        structure = reduction_structure(st)
    memberships, stages, nxt, prv, dims = structure

    # color refinement to fixpoint, vectorized. Color ids reach the
    # next iteration only through EQUALITY (the final plan groups by
    # partition and orders classes by smallest member), so any id
    # labeling that induces the same partition yields the same plan —
    # np.unique's sorted labeling is as good as first-occurrence, and
    # the partition sequence (hence the stop iteration and the final
    # partition) is identical to the scalar refinement's.
    #
    # Structure prep (per call, not per iteration): each dim becomes a
    # per-rank group index plus a padded member matrix; a group's color
    # signature is the row of member colors in group order, padded with
    # -2 (never a color id), so ragged groups can't collide.
    init: Dict[tuple, int] = {}
    color = np.empty(n, dtype=np.int64)
    for r in range(n):
        key = (stages[r], float(perturbation.get(r, 1.0)),
               signatures.get(r))
        c = init.get(key)
        if c is None:
            c = init[key] = len(init)
        color[r] = c
    dim_gids: List[np.ndarray] = []
    dim_members: List[np.ndarray] = []
    for dim in dims:
        byrank = memberships[dim]
        gid = np.full(n, -1, dtype=np.int64)
        groups_seen: Dict[int, int] = {}
        rows: List[List[int]] = []
        for r in range(n):
            grp = byrank.get(r)
            if grp is None:
                continue
            g = groups_seen.get(id(grp))
            if g is None:
                g = groups_seen[id(grp)] = len(rows)
                rows.append(grp)
            gid[r] = g
        lmax = max((len(g) for g in rows), default=1)
        members = np.full((max(len(rows), 1), lmax), n, dtype=np.int64)
        for g, grp in enumerate(rows):
            members[g, : len(grp)] = grp
        dim_gids.append(gid)
        dim_members.append(members)
    nxt_a = np.asarray(nxt, dtype=np.int64) if pp > 1 else None
    prv_a = np.asarray(prv, dtype=np.int64) if pp > 1 else None

    n_colors = 0
    while True:
        cols = [color]
        color_ext = np.append(color, -2)  # pad slot n -> sentinel
        for gid, members in zip(dim_gids, dim_members):
            _, guid = np.unique(color_ext[members], axis=0,
                                return_inverse=True)
            # rank not in any group of this dim -> -1 (never equal to
            # a group id), matching the scalar refinement's None
            cols.append(np.append(guid.ravel(), -1)[gid])
        if pp > 1:
            cols.append(color[nxt_a])
            cols.append(color[prv_a])
        sig = np.stack(cols, axis=1)
        uniq, inv = np.unique(sig, axis=0, return_inverse=True)
        colors_out = inv.ravel()
        if len(uniq) == n_colors:
            break
        n_colors = len(uniq)
        color = colors_out

    # classes ordered by smallest member (deterministic representative)
    members_by_color: Dict[int, List[int]] = {}
    for r in range(n):
        members_by_color.setdefault(color[r], []).append(r)
    classes = sorted(members_by_color.values(), key=lambda m: m[0])
    class_of = [0] * n
    for idx, members in enumerate(classes):
        for r in members:
            class_of[r] = idx

    def map_group(grp: List[int]) -> List[int]:
        return sorted({class_of[p] for p in grp})

    plan_groups: List[Dict[str, List[int]]] = []
    neighbor_maps: List[Dict[int, int]] = []
    for members in classes:
        rep = members[0]
        g: Dict[str, List[int]] = {}
        for dim in dims:
            grp = memberships[dim].get(rep)
            if grp is not None:
                g[dim] = map_group(grp)
        plan_groups.append(g)
        nmap: Dict[int, int] = {}
        if pp > 1:
            s = stages[rep]
            for s2 in range(pp):
                # same arithmetic as StageProcess._neighbor; stages the
                # schedule never addresses may fall outside the world
                peer = rep + (s2 - s) * stride
                if 0 <= peer < n:
                    nmap[s2] = class_of[peer]
        neighbor_maps.append(nmap)

    return ReductionPlan(
        world_size=n,
        classes=classes,
        class_of=class_of,
        stages=[stages[m[0]] for m in classes],
        perturbs=[float(perturbation.get(m[0], 1.0)) for m in classes],
        groups=plan_groups,
        neighbor_maps=neighbor_maps,
    )

"""Fault injection, checkpoint/restore cost model, goodput prediction.

SimuMax predicts MFU for a *healthy* job; at pod scale a real TPU
training run also spends wall-clock on preemptions, slow hosts,
degraded links, and checkpoint/restore — the gap between MFU and
*goodput* that resilient-training systems (Bamboo, Oobleck) exist to
close. This module makes failure a first-class, simulatable input:

* :class:`FaultEvent` / :class:`FaultScenario` — a declarative,
  JSON-loadable timeline of faults: per-rank compute-slowdown windows,
  ICI/DCN link-bandwidth degradation scoped to specific collective
  groups, host preemptions (a rank frozen for a window), and rank
  deaths followed by restart-from-checkpoint.
* :class:`StepFaultModel` — the discrete-event engine's view of one
  training step: piecewise compute-rate multipliers integrated at
  event-service time, comm-time multipliers per collective dim, and
  death times. A dead rank no longer deadlocks the world: its
  collective partners resolve against the fault model
  (``SimuEngine`` consults it, see ``simulator/engine.py``) and the
  run returns a structured :class:`FaultOutcome` instead of crashing.
* :class:`CheckpointCostModel` — checkpoint write / restore read times
  derived from :class:`~simumax_tpu.core.config.SystemConfig`'s
  HBM→host→storage chain (``SystemConfig.host``) and the per-rank
  weight + optimizer-state bytes of the estimate.
* :func:`predict_goodput` — composes perturbed step simulations,
  periodic checkpoint writes, and death→restart→replay sequences into
  a wall-time decomposition (:class:`GoodputBuckets`) whose buckets
  sum to the wall time exactly; ``goodput = useful_train / wall``.
* :func:`analyze_faults` — seeded Monte-Carlo over sampled scenarios:
  goodput distribution plus the empirically optimal checkpoint
  interval (cross-checked against the Young–Daly closed form).
* :class:`ReplayContext` — the incremental fault-replay engine
  (ISSUE 14): per-estimate memoized state that makes the Monte-Carlo
  hot path ~free with **bit-identical** reports. Four independent,
  individually toggleable optimizations (:class:`ReplayOptions`):

  1. *slack-gated short-circuit* — a perturbed step whose fault
     timeline provably fits inside the healthy step's critical-path
     slack headroom (``observe/critpath.py`` ``slack_index``) moves
     the makespan by zero, so it is answered as the healthy step
     without simulating;
  2. *symmetry-canonicalized step cache* — sub-scenario cache keys are
     normalized through ``reduce.py``'s color-refinement classes, so
     two scenarios hitting symmetric ranks share one replay;
  3. *healthy-prefix fork* — each scenario partition's step program is
     recorded once (``RecordingProc``) and replayed (``ReplayProc``);
     the engine is paused at the first fault onset and the paused
     state forked into a snapshot ladder, so later scenarios replay
     only the suffix after their onset;
  4. *process-parallel Monte-Carlo* — ``analyze_faults(jobs=N)`` fans
     scenarios across a worker pool with the PR-2 executor discipline
     (worker-main-thread SIGALRM deadlines, canonical-cache
     merge-back, serial == parallel bit-for-bit).

All scenario times are **milliseconds relative to the simulated
window** (one step for ``simulate(faults=...)``; job wall-clock for
:func:`predict_goodput`, which re-bases events per step itself).
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import random
import threading
import weakref
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from simumax_tpu.core.errors import ConfigError, SimulationError
from simumax_tpu.core.records import GoodputBuckets

EVENT_KINDS = ("slowdown", "link_degradation", "preemption", "rank_death")

#: dims a link_degradation may target: the collective-group dims the
#: schedule issues rendezvous on, plus "pp" (p2p) and "*" (every comm op)
LINK_DIMS = ("tp", "cp", "ep", "etp", "dp_cp", "edp", "pp", "*")

#: canonical-cache probes tolerated without a single hit before the
#: layer goes dormant for the context's lifetime (probing serializes
#: the whole engine problem — the costliest key in the pipeline)
CANON_PROBE_LIMIT = 512


# --------------------------------------------------------------------------
# Scenario schema
# --------------------------------------------------------------------------


@dataclass
class FaultEvent:
    """One timed fault. Field use per ``kind``:

    * ``slowdown`` — ``rank``'s compute takes ``multiplier``× longer
      during ``[start_ms, start_ms + duration_ms)`` (``duration_ms``
      None = until the end of the window).
    * ``preemption`` — ``rank`` is frozen (makes no progress) for
      ``duration_ms`` starting at ``start_ms``; collective partners
      stall on its late arrivals.
    * ``link_degradation`` — comm ops on ``dim`` take ``multiplier``×
      longer while active; ``ranks`` (optional) scopes it to ops whose
      rendezvous involves at least one listed rank.
    * ``rank_death`` — ``rank`` dies at ``start_ms`` and never
      returns; the job must restart from the last checkpoint
      (:func:`predict_goodput` accounts the restart).

    ``slowdown`` / ``preemption`` / ``rank_death`` may target a
    ``ranks`` *list* instead of a single ``rank`` — exactly equivalent
    to (and bit-identical with) one single-rank event per listed rank,
    but O(ranks) cheaper to window and replay. The fleet simulator
    leans on this: a maintenance window freezing a 128-chip pod is one
    event, not 128 (``fleet/sim.py``).
    """

    kind: str
    start_ms: float = 0.0
    duration_ms: Optional[float] = None
    rank: Optional[int] = None
    multiplier: float = 1.0
    dim: Optional[str] = None
    ranks: Optional[List[int]] = None

    @property
    def end_ms(self) -> float:
        if self.kind == "rank_death":
            return math.inf
        if self.duration_ms is None:
            return math.inf
        return self.start_ms + self.duration_ms

    def targets(self) -> Tuple[int, ...]:
        """The perturbed ranks: ``rank`` or the ``ranks`` list (for
        ``link_degradation`` the list is a *scope*, not a target —
        this returns () there)."""
        if self.kind == "link_degradation":
            return ()
        if self.rank is not None:
            return (self.rank,)
        if self.ranks is not None:
            return tuple(self.ranks)
        return ()

    def validate(self, world_size: Optional[int] = None) -> "FaultEvent":
        def bad(msg):
            raise ConfigError(
                f"fault event {self.to_dict()}: {msg}",
                phase="simulate", fault_kind=self.kind,
            )

        if self.kind not in EVENT_KINDS:
            bad(f"unknown kind (expected one of {EVENT_KINDS})")
        if not (isinstance(self.start_ms, (int, float))
                and math.isfinite(self.start_ms) and self.start_ms >= 0):
            bad("start_ms must be a finite non-negative number")
        if self.duration_ms is not None and not (
            isinstance(self.duration_ms, (int, float))
            and math.isfinite(self.duration_ms) and self.duration_ms > 0
        ):
            bad("duration_ms must be a finite positive number")
        if self.kind in ("slowdown", "preemption", "rank_death"):
            if self.rank is None and not self.ranks:
                bad("needs a target rank (or a ranks list)")
            if self.rank is not None and self.ranks is not None:
                bad("rank and ranks are mutually exclusive")
            if world_size is not None:
                oob = [r for r in self.targets()
                       if not 0 <= r < world_size]
                if oob:
                    bad(f"rank {oob[0]} outside world "
                        f"[0, {world_size})")
        if self.kind == "preemption" and self.duration_ms is None:
            bad("preemption needs a finite duration_ms")
        if self.kind in ("slowdown", "link_degradation"):
            if not (math.isfinite(self.multiplier) and self.multiplier >= 1.0):
                bad("multiplier must be finite and >= 1.0")
        if self.kind == "link_degradation":
            if self.dim not in LINK_DIMS:
                bad(f"dim {self.dim!r} not one of {LINK_DIMS}")
            if self.ranks is not None and world_size is not None:
                oob = [r for r in self.ranks
                       if not 0 <= r < world_size]
                if oob:
                    bad(f"scope ranks {oob} outside world "
                        f"[0, {world_size})")
        return self

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"kind": self.kind, "start_ms": self.start_ms}
        if self.duration_ms is not None:
            d["duration_ms"] = self.duration_ms
        if self.rank is not None:
            d["rank"] = self.rank
        if self.kind in ("slowdown", "link_degradation"):
            d["multiplier"] = self.multiplier
        if self.dim is not None:
            d["dim"] = self.dim
        if self.ranks is not None:
            d["ranks"] = list(self.ranks)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultEvent":
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        extra = set(d) - known
        if extra:
            raise ConfigError(
                f"fault event has unknown fields {sorted(extra)} "
                f"(known: {sorted(known)})", phase="simulate",
            )
        return cls(**d)

    def signature(self) -> tuple:
        """Hashable identity used for symmetry-reduction coloring."""
        return (self.kind, self.start_ms, self.duration_ms,
                self.multiplier, self.dim)


@dataclass
class FaultScenario:
    """A declarative fault timeline plus the job-level knobs goodput
    prediction needs (horizon length, checkpoint overrides)."""

    events: List[FaultEvent] = field(default_factory=list)
    #: job horizon for goodput prediction (training steps)
    horizon_steps: int = 100
    #: optional :class:`CheckpointSpec` field overrides
    checkpoint: Optional[Dict[str, Any]] = None
    #: provenance when sampled by :func:`sample_scenario`
    seed: Optional[int] = None

    @property
    def empty(self) -> bool:
        return not self.events

    def validate(self, world_size: Optional[int] = None) -> "FaultScenario":
        if not isinstance(self.horizon_steps, int) or self.horizon_steps < 1:
            raise ConfigError(
                f"horizon_steps must be a positive int, got "
                f"{self.horizon_steps!r}", phase="simulate",
            )
        for ev in self.events:
            ev.validate(world_size)
        return self

    # -- (de)serialization -------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "schema": "simumax-fault-scenario-v1",
            "horizon_steps": self.horizon_steps,
            "events": [e.to_dict() for e in self.events],
        }
        if self.checkpoint:
            d["checkpoint"] = dict(self.checkpoint)
        if self.seed is not None:
            d["seed"] = self.seed
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultScenario":
        schema = d.get("schema", "simumax-fault-scenario-v1")
        if schema != "simumax-fault-scenario-v1":
            raise ConfigError(
                f"unknown fault-scenario schema {schema!r}",
                phase="simulate",
            )
        events = [
            e if isinstance(e, FaultEvent) else FaultEvent.from_dict(e)
            for e in d.get("events", [])
        ]
        return cls(
            events=events,
            horizon_steps=int(d.get("horizon_steps", 100)),
            checkpoint=d.get("checkpoint"),
            seed=d.get("seed"),
        )

    @classmethod
    def from_json(cls, path: str) -> "FaultScenario":
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            raise ConfigError(
                f"cannot load fault scenario {path}: {exc}",
                phase="simulate", path=path,
            )
        return cls.from_dict(data)

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2)
        return path

    # -- step windowing / reduction support --------------------------------
    def shifted(self, offset_ms: float, span_ms: float) -> "FaultScenario":
        """The sub-scenario active inside ``[offset, offset + span)``,
        with event times re-based to the window start (clamped at 0 —
        an event already in progress is active from the window start,
        with its remaining duration)."""
        out: List[FaultEvent] = []
        for ev in self.events:
            if ev.kind == "rank_death":
                if offset_ms <= ev.start_ms < offset_ms + span_ms:
                    out.append(FaultEvent(
                        "rank_death", start_ms=ev.start_ms - offset_ms,
                        rank=ev.rank,
                        ranks=list(ev.ranks)
                        if ev.ranks is not None else None,
                    ))
                continue
            if ev.end_ms <= offset_ms or ev.start_ms >= offset_ms + span_ms:
                continue
            start = max(ev.start_ms - offset_ms, 0.0)
            dur = None
            if ev.duration_ms is not None:
                dur = ev.end_ms - offset_ms - start
            out.append(FaultEvent(
                ev.kind, start_ms=start, duration_ms=dur, rank=ev.rank,
                multiplier=ev.multiplier, dim=ev.dim,
                ranks=list(ev.ranks) if ev.ranks is not None else None,
            ))
        return FaultScenario(events=out, horizon_steps=self.horizon_steps,
                             checkpoint=self.checkpoint, seed=self.seed)

    def signature(self) -> tuple:
        """Hashable identity of the event set (step-result caching)."""
        return tuple(
            ev.signature() + (ev.rank, tuple(ev.ranks) if ev.ranks else None)
            for ev in self.events
        )

    def rank_signatures(self) -> Dict[int, tuple]:
        """Per-rank fault signature for rank-symmetry reduction: two
        ranks with different signatures must land in different classes
        (``simulator/reduce.py`` colors on this), so a fault shatters
        exactly the symmetry it breaks — globally-scoped link events
        perturb every group of a dim identically and shatter nothing."""
        sigs: Dict[int, List[tuple]] = {}
        for ev in self.events:
            targets: Sequence[int] = ev.targets()
            if ev.kind == "link_degradation" and ev.ranks is not None:
                targets = ev.ranks
            for r in targets:
                sigs.setdefault(r, []).append(ev.signature())
        return {r: tuple(sorted(s)) for r, s in sigs.items()}


# --------------------------------------------------------------------------
# Engine-facing fault model (one step window, times in SECONDS)
# --------------------------------------------------------------------------


def key_dim(key) -> Optional[str]:
    """Collective dim of an engine rendezvous key. Keys are either
    ``(dim, group)`` tuples (leaf collectives), strings like
    ``"grad_rs:dp_cp"`` / ``"param_ag:edp"`` (bucketed DP streams and
    their async-stream names), or ``"optimizer_barrier"``. Shared with
    the critical-path engine (``observe/critpath.py``), which blames
    exposed rendezvous time onto the same dims the fault model scales."""
    if isinstance(key, tuple):
        key = key[0]
    if not isinstance(key, str):
        return None
    return key.rsplit(":", 1)[-1] if ":" in key else key


#: backwards-compatible private alias (pre-critpath internal name)
_key_dim = key_dim


class StepFaultModel:
    """The engine's consult-at-service-time view of a scenario, scoped
    to one simulated step. All times are seconds relative to the step
    start. ``rank_map`` translates engine ranks to global ranks when
    the engine runs one representative per symmetry class."""

    def __init__(self, scenario: FaultScenario,
                 rank_map: Optional[Sequence[int]] = None):
        self.scenario = scenario
        self._map = list(rank_map) if rank_map is not None else None
        #: global rank -> [(start_s, end_s, multiplier)]; multiplier
        #: math.inf encodes a preemption freeze (progress rate 0)
        self._slow: Dict[int, List[Tuple[float, float, float]]] = {}
        #: (dim, start_s, end_s, multiplier, scope frozenset | None)
        self._links: List[Tuple[str, float, float, float,
                                Optional[frozenset]]] = []
        #: global rank -> earliest death time (s)
        self._deaths: Dict[int, float] = {}
        for ev in scenario.events:
            s = ev.start_ms * 1e-3
            e = ev.end_ms * 1e-3 if math.isfinite(ev.end_ms) else math.inf
            if ev.kind == "slowdown":
                if ev.multiplier == 1.0:
                    # a 1.0x slowdown is the identity by definition —
                    # keep it out of the piecewise integration, whose
                    # float re-association at window edges would
                    # otherwise drift span ends by an ulp (the slack
                    # gate proves such events delay nothing and must
                    # agree with the engine to the bit)
                    continue
                for r in ev.targets():
                    self._slow.setdefault(r, []).append(
                        (s, e, ev.multiplier)
                    )
            elif ev.kind == "preemption":
                for r in ev.targets():
                    self._slow.setdefault(r, []).append(
                        (s, e, math.inf)
                    )
            elif ev.kind == "link_degradation":
                scope = (frozenset(ev.ranks)
                         if ev.ranks is not None else None)
                self._links.append((ev.dim, s, e, ev.multiplier, scope))
            elif ev.kind == "rank_death":
                for r in ev.targets():
                    prev = self._deaths.get(r)
                    self._deaths[r] = s if prev is None \
                        else min(prev, s)
        for wins in self._slow.values():
            wins.sort()

    def _g(self, engine_rank: int) -> int:
        return self._map[engine_rank] if self._map is not None \
            else engine_rank

    def death_time(self, engine_rank: int) -> Optional[float]:
        return self._deaths.get(self._g(engine_rank))

    def has_slow(self, engine_rank: int) -> bool:
        """Whether any slowdown/preemption window targets this rank —
        the engine's per-run fast path (untouched ranks skip the
        ``compute_end`` piecewise integration entirely)."""
        return self._g(engine_rank) in self._slow

    @property
    def has_deaths(self) -> bool:
        return bool(self._deaths)

    def compute_end(self, engine_rank: int, start: float,
                    duration: float) -> float:
        """Wall end time of ``duration`` seconds of work starting at
        ``start`` under this rank's piecewise slowdown windows
        (progress rate ``1/Π multipliers`` of the active windows, 0
        while preempted)."""
        wins = self._slow.get(self._g(engine_rank))
        if not wins or duration <= 0:
            return start + duration
        edges = sorted({x for w in wins for x in w[:2]
                        if math.isfinite(x) and x > start})
        t, work = start, duration
        ei = 0
        while True:
            mult = 1.0
            for (s, e, m) in wins:
                if s <= t < e:
                    mult = math.inf if m == math.inf else mult * m
            while ei < len(edges) and edges[ei] <= t:
                ei += 1
            nxt = edges[ei] if ei < len(edges) else math.inf
            if mult == math.inf:
                # frozen: no progress until the window closes (finite
                # by validation)
                t = nxt
                continue
            need = work * mult
            if t + need <= nxt:
                return t + need
            work -= (nxt - t) / mult
            t = nxt

    def comm_scale(self, key, engine_peers: Sequence[int],
                   t: float) -> float:
        """Comm-time multiplier of one rendezvous/p2p op at service
        time ``t``: the product of active link windows matching its dim
        whose scope (if any) intersects the participating ranks."""
        if not self._links:
            return 1.0
        dim = _key_dim(key)
        m = 1.0
        for (d, s, e, mult, scope) in self._links:
            if not s <= t < e:
                continue
            if d != "*" and d != dim:
                continue
            if scope is not None and not any(
                self._g(p) in scope for p in engine_peers
            ):
                continue
            m *= mult
        return m


@dataclass
class FaultOutcome:
    """Structured result of a faulted simulation: whether the step
    completed, who died when, how much was injected."""

    applied_events: int
    completed: bool
    deaths: List[Dict[str, float]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": "simumax-fault-outcome-v1",
            "applied_events": self.applied_events,
            "completed": self.completed,
            "deaths": list(self.deaths),
        }


# --------------------------------------------------------------------------
# Checkpoint / restore cost model
# --------------------------------------------------------------------------


@dataclass
class CheckpointSpec:
    """Checkpointing policy knobs (overridable per scenario via
    ``FaultScenario.checkpoint``)."""

    #: write a checkpoint every N committed steps
    interval_steps: int = 50
    #: failure detection + rescheduling + process restart + re-init,
    #: before the restore read begins
    restart_overhead_s: float = 120.0
    #: bandwidth overrides (GB/s per chip); None = derive from
    #: ``SystemConfig.host``
    write_gbps: Optional[float] = None
    read_gbps: Optional[float] = None

    @classmethod
    def from_overrides(cls, overrides: Optional[Dict[str, Any]],
                       base: Optional["CheckpointSpec"] = None
                       ) -> "CheckpointSpec":
        spec = base or cls()
        if not overrides:
            return spec
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        extra = set(overrides) - known
        if extra:
            raise ConfigError(
                f"unknown checkpoint fields {sorted(extra)} "
                f"(known: {sorted(known)})", phase="simulate",
            )
        kw = {f: getattr(spec, f) for f in known}
        kw.update(overrides)
        out = cls(**kw)
        if out.interval_steps < 1:
            raise ConfigError(
                f"checkpoint interval_steps must be >= 1, got "
                f"{out.interval_steps}", phase="simulate",
            )
        return out


@dataclass
class CheckpointCostModel:
    """Per-rank checkpoint write / restore read times.

    The checkpointed state per rank is its weights + optimizer state
    (gradients are not checkpointed). The write streams HBM → host
    (``host.d2h_gbps``) → persistent storage / DCN
    (``host.ckpt_write_gbps``); pipelined streaming is bound by the
    slowest stage of the chain (HBM read bandwidth included for
    completeness — it never binds on real parts), plus a fixed
    commit/barrier latency. Restore is the reverse chain with the read
    bandwidths."""

    bytes_per_rank: float
    write_s: float
    read_s: float
    spec: CheckpointSpec

    @classmethod
    def from_perf(cls, perf,
                  spec: Optional[CheckpointSpec] = None
                  ) -> "CheckpointCostModel":
        spec = spec or CheckpointSpec()
        mem = perf.analysis_mem()
        nbytes = max(
            s["weight_bytes"] + s["optimizer_state_bytes"]
            for s in mem["stages"]
        )
        host = perf.system.host
        hbm = perf.system.accelerator.bandwidth["default"].gbps
        write_bw = spec.write_gbps or min(
            hbm, host.d2h_gbps, host.ckpt_write_gbps
        )
        read_bw = spec.read_gbps or min(
            hbm, host.d2h_gbps, host.ckpt_read_gbps
        )
        return cls(
            bytes_per_rank=nbytes,
            write_s=nbytes / (write_bw * 1e9) + host.latency_s,
            read_s=nbytes / (read_bw * 1e9) + host.latency_s,
            spec=spec,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "bytes_per_rank": self.bytes_per_rank,
            "write_s": self.write_s,
            "read_s": self.read_s,
            "interval_steps": self.spec.interval_steps,
            "restart_overhead_s": self.spec.restart_overhead_s,
        }


# --------------------------------------------------------------------------
# Goodput prediction
# --------------------------------------------------------------------------


@dataclass
class GoodputReport:
    """Wall-time decomposition of a scenario over ``horizon_steps``
    training steps. ``buckets`` sum to ``wall_time_s`` exactly (the
    accounting is constructive); ``goodput = useful_train / wall``."""

    goodput: float
    wall_time_s: float
    useful_time_s: float
    healthy_step_s: float
    horizon_steps: int
    n_checkpoints: int
    n_restarts: int
    steps_replayed: int
    buckets: GoodputBuckets
    deaths: List[Dict[str, float]]
    checkpoint: Dict[str, Any]
    truncated: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": "simumax-goodput-v1",
            "goodput": self.goodput,
            "wall_time_s": self.wall_time_s,
            "useful_time_s": self.useful_time_s,
            "healthy_step_s": self.healthy_step_s,
            "horizon_steps": self.horizon_steps,
            "n_checkpoints": self.n_checkpoints,
            "n_restarts": self.n_restarts,
            "steps_replayed": self.steps_replayed,
            "buckets": self.buckets.to_dict(),
            "deaths": list(self.deaths),
            "checkpoint": dict(self.checkpoint),
            "truncated": self.truncated,
        }


def _simulate_step(perf, sub: FaultScenario,
                   cache: Dict[tuple, Tuple[float, Optional[float]]],
                   granularity: str, reduce) -> Tuple[float, Optional[float]]:
    """(wall duration, death time | None) of one step under the
    re-based sub-scenario ``sub``; death times arrive in the same
    straggler-inflated wall base as ``end_time``."""
    from simumax_tpu.simulator.runner import run_simulation

    key = sub.signature()
    hit = cache.get(key)
    if hit is not None:
        return hit
    res = run_simulation(
        perf, None, granularity=granularity, world_ranks=True,
        reduce=reduce, faults=sub,
    )
    deaths = res["faults"]["deaths"]
    if deaths:
        t_death = min(d["time_ms"] for d in deaths) * 1e-3
        out = (t_death, t_death)
    else:
        out = (res["end_time"], None)
    cache[key] = out
    return out


def _batched_replay():
    """Lazy import of the batched-replay lowering (keeps faults.py
    importable without jax/numpy on the path until a batch dispatch
    actually needs them)."""
    from simumax_tpu.simulator import batched_replay

    return batched_replay


# --------------------------------------------------------------------------
# Incremental fault replay (ISSUE 14 tentpole)
# --------------------------------------------------------------------------


@dataclass
class ReplayOptions:
    """Per-optimization toggles for the incremental replay engine.
    Every switch is independently disableable, and every combination
    is bit-identical to the exact path — enforced by the
    incremental-vs-exact sweep in ``tests/test_faults.py``."""

    #: answer provably makespan-neutral steps from the healthy step's
    #: critical-path slack headroom, without simulating
    short_circuit: bool = True
    #: share one replay between scenarios perturbing symmetric ranks
    #: (step cache additionally keyed by the canonicalized problem)
    canonical_cache: bool = True
    #: record step request streams once per scenario partition, replay
    #: them, and resume from forked healthy-prefix snapshots
    prefix_fork: bool = True
    #: treat fault windows that outlast the step's realized end as
    #: open-ended in the step-cache keys (validity-checked against the
    #: realized end), so every interior step of a long-running fault —
    #: and its interval-grid wall shifts — shares one replay
    horizon_clamp: bool = True
    #: fork-ladder bound: snapshots retained per step-program family
    max_snapshots: int = 16
    #: miss-replay backend: ``"numpy"`` keeps every miss on the scalar
    #: engine walk; ``"jax"`` lowers miss batches to the vmapped array
    #: program (``simulator/batched_replay.py``) whenever the family
    #: can lower; ``"auto"`` dispatches jax only when it is importable
    #: and the miss batch is large enough to amortize dispatch —
    #: per-scenario scalar fallback with a counted reason otherwise,
    #: never a whole-batch downgrade
    replay_backend: str = "auto"
    #: auto-dispatch floor for ``replay_backend="auto"`` (0 = use
    #: ``batched_replay.JIT_BATCH_MIN``)
    jit_batch_min: int = 0


@dataclass
class _StepFamily:
    """Replay state shared by every sub-scenario with one touched-rank
    partition: the faulted reduction plan, the recorded per-class
    request streams, and the fork ladder of paused engine snapshots
    (``(pause time, engine with no fault model attached)``)."""

    plan: Any
    streams: Optional[List[list]] = None
    ladder: List[Tuple[float, Any]] = field(default_factory=list)


def _union_len(wins: List[Tuple[float, float]]) -> float:
    """Total length of the union of ``[start, end)`` windows
    (``math.inf`` if any window is unbounded)."""
    total = 0.0
    cur_s = cur_e = None
    for s, e in sorted(wins):
        if cur_e is None or s > cur_e:
            if cur_e is not None:
                total += cur_e - cur_s
            cur_s, cur_e = s, e
        elif e > cur_e:
            cur_e = e
    if cur_e is not None:
        total += cur_e - cur_s
    return total


@contextlib.contextmanager
def _deadline(seconds: Optional[float], label: str):
    """Per-scenario SIGALRM deadline (the PR-2 executor discipline:
    armed on the running thread only when it is a process main thread,
    which in pool mode is the worker's main thread). No timeout, or a
    non-main thread, is a no-op."""
    if (not seconds or seconds <= 0
            or threading.current_thread() is not threading.main_thread()):
        yield
        return
    import signal

    def _alarm(signum, frame):
        raise SimulationError(
            f"goodput scenario exceeded its {seconds:g}s deadline: "
            f"{label}",
            phase="simulate", scenario=label,
        )

    old = signal.signal(signal.SIGALRM, _alarm)
    signal.setitimer(signal.ITIMER_REAL, seconds)
    try:
        yield
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0.0)
        signal.signal(signal.SIGALRM, old)


class ReplayContext:
    """Memoized incremental-replay state shared across
    :func:`predict_goodput` / :func:`analyze_faults` calls on one
    completed estimate.

    Everything is lazy: the fault-free step (recorded with the
    critical-path skeleton when the slack gate is on), the checkpoint
    cost chain, step-program families (recorded request streams + fork
    ladders per touched-rank partition), and the perturbed-step cache
    in two keyings — the exact event signature and the
    symmetry-canonicalized engine problem. Cached values are
    bit-identical to what the exact path computes; the context only
    removes duplicated work, never changes a number.

    ``stats`` is observational (cache hits, short-circuits, forks…)
    and mirrored into the telemetry registry counters
    (``faults_*_total``); it is deliberately NOT part of any analysis
    result, because parallel scheduling makes hit counts
    non-deterministic while the results stay bit-identical.
    """

    def __init__(self, perf, granularity: str = "chunk", reduce="auto",
                 options: Optional[ReplayOptions] = None):
        if reduce is False:
            raise ConfigError(
                "ReplayContext replays through symmetry-reduction "
                "plans; reduce=False requests the exact unreduced "
                "path — call predict_goodput/analyze_faults with "
                "incremental=False instead",
                phase="simulate",
            )
        self.perf = perf
        self.granularity = granularity
        self.reduce = reduce
        self.options = options or ReplayOptions()
        self.stats: Dict[str, int] = {k: 0 for k in (
            "scenarios", "steps", "sims", "recordings", "replays",
            "forks", "shortcircuits", "cache_hits", "canon_hits",
            "clamp_hits", "batched",
        )}
        from simumax_tpu.observe.telemetry import get_registry

        _reg = get_registry()
        self._registry = _reg
        self._c_scenarios = _reg.counter("faults_scenarios_total")
        self._c_hits = _reg.counter("faults_step_cache_hits_total",
                                    kind="exact")
        self._c_canon = _reg.counter("faults_step_cache_hits_total",
                                     kind="canonical")
        self._c_clamp = _reg.counter("faults_step_cache_hits_total",
                                     kind="clamped")
        self._c_gate = _reg.counter("faults_slack_shortcircuits_total")
        self._c_forks = _reg.counter("faults_prefix_forks_total")
        self._c_batched = _reg.counter("replay_batched_total",
                                       backend="jax")
        #: reason -> counter, filled lazily from the closed catalogue
        self._c_fallbacks: Dict[str, Any] = {}
        self._healthy: Optional[dict] = None
        self._slack: Optional[tuple] = None
        self._structure = None  # memoized reduction relations
        self._healthy_classes: Optional[List[int]] = None
        self._families: Dict[tuple, _StepFamily] = {}
        #: stage -> (recorded stream, its plan, its engine rank): the
        #: remap source shared by every family (a step program is a
        #: pure function of stage + rendezvous structure)
        self._stage_sources: Dict[int, Tuple[list, Any, int]] = {}
        self._cache: Dict[tuple, Tuple[float, Optional[float]]] = {}
        #: id -> weakref of scenarios already validated against this
        #: estimate's world — the fleet walk re-costs one scenario
        #: object many times against a shared context, and validation
        #: is O(events)/call. (id-keyed because dataclass equality
        #: makes FaultScenario unhashable; the weakref guards against
        #: id reuse after collection.)
        self._validated: Dict[int, Any] = {}
        #: checkpoint-override dict -> resolved CheckpointSpec
        self._specs: Dict[Optional[tuple], CheckpointSpec] = {}
        #: clamped / canonical entries additionally carry the realized
        #: raw end (`raw_limit`) their open-ended windows must cover
        self._clamped: Dict[tuple, Tuple[float, Optional[float],
                                         float]] = {}
        self._canon: Dict[tuple, Tuple[float, Optional[float],
                                       float]] = {}
        self._ckpt: Dict[tuple, CheckpointCostModel] = {}
        #: id(fam) -> LoweredProgram | fallback-reason str (fams are
        #: owned by self._families, so ids are stable for our lifetime)
        self._lowerings: Dict[int, Any] = {}
        #: (id(plan), rank_events) -> canonical class order — the
        #: refinement in reduce.canonical_class_order is a pure
        #: function of both, and Monte-Carlo rounds re-ask it for the
        #: same few event patterns thousands of times
        self._canon_orders: Dict[tuple, Any] = {}
        #: adaptive canonical probing: key serialization is the most
        #: expensive cache layer, and a workload whose scenarios never
        #: relabel onto each other pays it for nothing. After
        #: CANON_PROBE_LIMIT misses with zero hits the layer goes
        #: dormant (cache-speed only: a canon hit returns the same
        #: bytes a fresh sim would, so skipping can't change results)
        self._canon_misses = 0

    # -- hoisted per-call prologue (satellite of ISSUE 15) -----------------
    def validate_scenario(self, scenario: FaultScenario):
        """``scenario.validate(world_size)`` hoisted to once per
        scenario *object* per context. Scenarios are immutable once
        handed to a prediction (the step cache already keys on event
        identity), so re-validating the same object on every
        ``predict_goodput`` call — thousands of times per template in
        the fleet walk — only re-pays an O(events) walk. The
        single-call path (no shared context) still validates every
        time, unchanged."""
        key = id(scenario)
        ref = self._validated.get(key)
        if ref is not None and ref() is scenario:
            return
        scenario.validate(self.perf.strategy.world_size)
        self._validated[key] = weakref.ref(
            scenario,
            lambda _r, k=key, m=self._validated: m.pop(k, None),
        )

    def resolve_spec(self, scenario: FaultScenario) -> CheckpointSpec:
        """``CheckpointSpec.from_overrides(scenario.checkpoint)``
        memoized on the override values — byte-identical resolution,
        one dataclass build per distinct override set instead of one
        per call."""
        ck = scenario.checkpoint
        key = tuple(sorted(ck.items())) if ck else None
        spec = self._specs.get(key)
        if spec is None:
            spec = CheckpointSpec.from_overrides(ck)
            self._specs[key] = spec
        return spec

    # -- memoized healthy step + checkpoint chain --------------------------
    def healthy(self) -> dict:
        """The fault-free step, simulated once per context. With the
        slack gate enabled the same run records the critical-path
        skeleton (recorder-on is bit-identical to recorder-off — the
        PR-7 contract), so the gate tables come for free."""
        if self._healthy is None:
            from simumax_tpu.simulator.runner import run_simulation

            self._healthy = run_simulation(
                self.perf, None, granularity=self.granularity,
                world_ranks=True, reduce=self.reduce,
                critical_path=self.options.short_circuit,
            )
        return self._healthy

    def checkpoint_model(self, spec: CheckpointSpec) -> CheckpointCostModel:
        """``CheckpointCostModel.from_perf`` memoized on the bandwidth
        overrides (the bytes/chain analysis is spec-independent)."""
        key = (spec.write_gbps, spec.read_gbps)
        base = self._ckpt.get(key)
        if base is None:
            base = CheckpointCostModel.from_perf(self.perf, spec)
            self._ckpt[key] = base
        if base.spec is spec:
            return base
        return CheckpointCostModel(
            bytes_per_rank=base.bytes_per_rank, write_s=base.write_s,
            read_s=base.read_s, spec=spec,
        )

    def _healthy_reduction(self) -> List[int]:
        """Healthy (fault-free) symmetry classes + memoized relational
        structure — shared by the slack gate's rank mapping and every
        step family's plan build."""
        if self._healthy_classes is None:
            from simumax_tpu.simulator.reduce import (
                build_reduction,
                reduction_structure,
            )

            self._structure = reduction_structure(self.perf.strategy)
            plan = build_reduction(self.perf.strategy, {},
                                   structure=self._structure)
            self._healthy_classes = plan.class_of
            self._healthy_rep_of = [
                plan.reps[plan.class_of[r]]
                for r in range(plan.world_size)
            ]
        return self._healthy_classes

    # -- (a) slack-gated short-circuit -------------------------------------
    def _gate_tables(self):
        if self._slack is None:
            report = self.healthy().get("critical_path") or {}
            idx = report.get("slack_index") or {}

            def _fin(arr):
                return [math.inf if v is None else v for v in arr]

            ranks = {
                int(r): (w, math.inf if s is None else s)
                for (r, w, s) in idx.get("ranks", [])
            }
            links = {
                k: (w, math.inf if s is None else s)
                for (k, w, s) in idx.get("links", [])
            }
            rank_b = {
                int(r): (bw, _fin(bs))
                for (r, bw, bs) in idx.get("rank_buckets", [])
            }
            link_b = {
                k: (bw, _fin(bs))
                for (k, bw, bs) in idx.get("link_buckets", [])
            }
            n_b = int(idx.get("buckets") or 0)
            mk = float(idx.get("makespan_s") or 0.0)
            rep_of = None
            if idx.get("mode") == "reduced":
                self._healthy_reduction()
                rep_of = self._healthy_rep_of
            self._slack = (ranks, links, rank_b, link_b, n_b, mk,
                           rep_of)
        return self._slack

    def _gate(self, sub: FaultScenario) -> bool:
        """Sound makespan-neutrality proof for one re-based
        sub-scenario against the healthy step's slack tables.

        Model every fault as added delay on the events it touches and
        bound the total, ``D``:

        * slowdowns on rank ``r`` with combined multiplier ``M`` (the
          product — overlapping windows compose multiplicatively in
          ``compute_end``): ``D_r <= min(U * (1 - 1/M),
          (M - 1) * work_r)`` where ``U`` is the union length of the
          windows (progress deficit accrues only inside them, at rate
          at most ``1 - 1/M``) and ``work_r`` the rank's healthy work
          overlapping the windows (each second of work stretches at
          most ``M``-fold);
        * a preemption freezes progress, so its rank's deficit is at
          most the union length of all its windows (deficit rate <= 1);
        * link degradations scale a comm op's whole duration by the
          product of matching windows at its start, so per slack-index
          key ``D_k <= (M_k - 1) * work_k`` with ``work_k`` the
          class-weighted wire+exposed seconds on that key overlapping
          the windows (scoped events are treated as unscoped —
          conservative).

        If ``sum(D) <= min slack over every touched node`` the
        makespan provably cannot move: any dependency path accumulates
        at most ``sum(D)`` of delay, and a path through a touched node
        has float at least that node's slack (``slack_j`` is the
        minimum float over paths through ``j``).

        Touched nodes are window-local, so work and the slack
        threshold are evaluated over the slack index's *time buckets*:
        a fault only touches nodes overlapping its window inflated
        left by the coarse whole-step delay bound from pass 1 (delays
        only shift nodes right, by at most the total delay), and the
        threshold is the minimum bucket slack over the covered buckets
        — whole-step minima are ~always zero (the optimizer barrier
        alone puts a zero-slack node on every rank), but mid-step
        windows routinely clear. Deaths never gate. Replay-verified by
        the slack-soundness property test, mirroring PR 7's slack
        soundness tests."""
        (ranks, links, rank_b, link_b, n_b, mk,
         rep_of) = self._gate_tables()
        if not ranks or not n_b or mk <= 0.0:
            return False
        by_rank: Dict[int, list] = {}
        link_events: List[Tuple[str, float, float, float]] = []
        for ev in sub.events:
            if ev.kind == "rank_death":
                return False
            s = ev.start_ms * 1e-3
            e = (ev.end_ms * 1e-3 if math.isfinite(ev.end_ms)
                 else math.inf)
            if ev.kind == "link_degradation":
                link_events.append((ev.dim, ev.multiplier, s, e))
                continue
            for r in ev.targets():
                entry = by_rank.setdefault(r, [1.0, [], False])
                entry[1].append((s, e))
                if ev.kind == "preemption":
                    entry[2] = True
                else:
                    entry[0] *= ev.multiplier

        def _link_mult_wins(key):
            m, wins = 1.0, []
            for (dim, mult, s, e) in link_events:
                if (dim == "*" or key == f"dim:{dim}"
                        or (dim == "pp" and key.startswith("pp:"))):
                    m *= mult
                    wins.append((s, e))
            return m, wins

        # pass 1 — coarse whole-step delay bound (how far any node can
        # shift right), used to inflate the windows in pass 2
        coarse = 0.0
        for r, (mult, wins, preempt) in by_rank.items():
            g = rep_of[r] if rep_of is not None else r
            ent = ranks.get(g)
            if ent is None:
                return False
            work, _ = ent
            union = _union_len(wins)
            if preempt:
                d = union
            else:
                d = (mult - 1.0) * work
                if math.isfinite(union):
                    d = min(d, union * (1.0 - 1.0 / mult))
            if not math.isfinite(d):
                return False
            coarse += d
        touched_links = []
        for key, (work, _) in links.items():
            m, wins = _link_mult_wins(key)
            if m == 1.0 or work <= 0.0:
                continue
            touched_links.append((key, m, wins))
            coarse += (m - 1.0) * work

        # pass 2 — windowed work bound + windowed slack threshold
        scale = n_b / mk

        def _covered(wins):
            bset = set()
            for (s, e) in wins:
                lo = int((s - coarse) * scale)
                lo = 0 if lo < 0 else min(lo, n_b - 1)
                hi = (n_b - 1 if not math.isfinite(e)
                      else max(lo, min(int(e * scale), n_b - 1)))
                bset.update(range(lo, hi + 1))
            return bset

        total = 0.0
        min_slack = math.inf
        for r, (mult, wins, preempt) in by_rank.items():
            g = rep_of[r] if rep_of is not None else r
            ent = rank_b.get(g)
            if ent is None:
                return False
            bwork, bslack = ent
            bset = _covered(wins)
            union = _union_len(wins)
            if preempt:
                d = union
            else:
                d = (mult - 1.0) * sum(bwork[b] for b in bset)
                if math.isfinite(union):
                    d = min(d, union * (1.0 - 1.0 / mult))
            if not math.isfinite(d):
                return False
            total += d
            for b in bset:
                if bslack[b] < min_slack:
                    min_slack = bslack[b]
        for key, m, wins in touched_links:
            ent = link_b.get(key)
            if ent is None:
                return False
            bwork, bslack = ent
            bset = _covered(wins)
            total += (m - 1.0) * sum(bwork[b] for b in bset)
            for b in bset:
                if bslack[b] < min_slack:
                    min_slack = bslack[b]
        return total <= min_slack

    # -- (b) symmetry-canonicalized step cache -----------------------------
    def _family(self, sub: FaultScenario) -> _StepFamily:
        """The step-program family of ``sub``'s touched-rank partition.
        Signature *values* reach the color refinement only through
        equality, so renaming them to partition-group indices memoizes
        one reduction plan across every window of the same pattern."""
        sigs = sub.rank_signatures()
        groups: Dict[tuple, List[int]] = {}
        for r, s in sigs.items():
            groups.setdefault(s, []).append(r)
        part = tuple(sorted(tuple(sorted(g)) for g in groups.values()))
        fam = self._families.get(part)
        if fam is None:
            from simumax_tpu.simulator.reduce import build_reduction

            h_cls = self._healthy_reduction()
            touch = {r: gi for gi, g in enumerate(part) for r in g}
            # seed every rank with its healthy class: the refinement
            # then converges from the already-stable healthy partition
            # (same fixpoint — seeds only matter through equality)
            seeds = {
                r: (h_cls[r], touch.get(r, -1))
                for r in range(len(h_cls))
            }
            fam = _StepFamily(plan=build_reduction(
                self.perf.strategy, {}, signatures=seeds,
                structure=self._structure,
            ))
            self._families[part] = fam
        return fam

    def _clamp_events(self, sub: FaultScenario, span_s: float):
        """Per-event cache signatures with the horizon clamp applied.

        With ``horizon_clamp`` on, any window that outlasts the
        nominal step span is keyed as open-ended (``"open"`` in the
        duration slot): the engine never consults fault state past the
        step's *realized* end, so two windows both covering it behave
        identically — which is what lets every interior step of a
        long-running fault (and its interval-grid wall shifts) share
        one replay. Returns ``(sigs, min_end, any_clamped)`` where
        ``min_end`` is the smallest finite original end among clamped
        events: a cached entry is valid only while its realized raw
        end stays at or below it (checked at lookup AND at store)."""
        sigs: List[tuple] = []
        min_end = math.inf
        clamped = False
        for ev in sub.events:
            if (self.options.horizon_clamp and ev.kind != "rank_death"
                    and ev.end_ms * 1e-3 >= span_s):
                clamped = True
                end_s = ev.end_ms * 1e-3
                if end_s < min_end:
                    min_end = end_s
                sigs.append((ev.kind, ev.start_ms, "open",
                             ev.multiplier, ev.dim))
            else:
                sigs.append(ev.signature())
        return sigs, min_end, clamped

    def _clamped_key(self, sub: FaultScenario, sigs: List[tuple]
                     ) -> tuple:
        """Horizon-clamped twin of ``FaultScenario.signature()``."""
        return tuple(
            sig + (ev.rank, tuple(ev.ranks) if ev.ranks else None)
            for sig, ev in zip(sigs, sub.events)
        )

    def _canonical_key(self, sub: FaultScenario, plan,
                       sigs: List[tuple]) -> tuple:
        """Serialize the *engine-level problem* — per-class fault
        timelines (horizon-clamped ``sigs``, aligned with
        ``sub.events``) plus the plan's rendezvous/neighbor structure —
        in a structure-canonical class numbering
        (``reduce.canonical_class_order``). Byte-equal keys are the
        same abstract problem up to class relabeling, which the engine
        resolves identically (the reduce-parity contract), so two
        scenarios hitting symmetric ranks at the same offsets share
        one replay. An imperfect relabeling can only cost hits, never
        correctness: the key carries the full problem."""
        from simumax_tpu.simulator.reduce import canonical_class_order

        k = plan.n_classes
        reps = plan.reps
        by_rank: Dict[int, List[tuple]] = {}
        for sig, ev in zip(sigs, sub.events):
            if ev.kind != "link_degradation":
                for r in ev.targets():
                    by_rank.setdefault(r, []).append(sig)
        rank_events = [
            tuple(sorted(by_rank.get(reps[i], ()), key=repr))
            for i in range(k)
        ]
        mkey = (id(plan), tuple(rank_events))
        order = self._canon_orders.get(mkey)
        if order is None:
            order = canonical_class_order(plan, rank_events)
            self._canon_orders[mkey] = order
        perm = [0] * k
        for new, old in enumerate(order):
            perm[old] = new
        parts = []
        for old in order:
            groups = tuple(sorted(
                (dim, tuple(sorted(perm[p] for p in g)))
                for dim, g in plan.groups[old].items()
            ))
            nbrs = tuple(sorted(
                (s, perm[p])
                for s, p in plan.neighbor_maps[old].items()
            ))
            parts.append((plan.stages[old], plan.perturbs[old],
                          len(plan.classes[old]), rank_events[old],
                          groups, nbrs))
        links = []
        for sig, ev in zip(sigs, sub.events):
            if ev.kind != "link_degradation":
                continue
            scope = None
            if ev.ranks is not None:
                # engine-level scope: the classes whose REPRESENTATIVE
                # is scoped (only reps are consulted in a reduced run)
                sset = set(ev.ranks)
                scope = tuple(sorted(
                    perm[i] for i in range(k) if reps[i] in sset
                ))
            links.append(sig + (scope,))
        return (self.granularity, tuple(parts),
                tuple(sorted(links, key=repr)))

    # -- (c) recorded-stream replay + healthy-prefix fork ------------------

    def _remap_streams(self, fam: _StepFamily) -> Optional[List[list]]:
        """Build ``fam``'s per-class request streams by rewriting a
        recorded stream of the same pipeline stage from another family.

        ``StageProcess`` output is a pure function of ``(stage,
        granularity, perturb, groups, neighbor_map, barrier)``, so a
        stream recorded under one reduction plan converts exactly into
        any other plan's stream for the same stage by rewriting the
        engine ids it carries: rendezvous groups/peers by dim, p2p
        src/dst through the pipeline-stage neighbor map, and the
        optimizer barrier to ``range(n_classes)``. The request
        vocabulary is closed (``engine.py`` docstring); an unknown
        kind or missing source aborts the remap (``None``) and the
        family records its own streams instead."""
        plan = fam.plan
        out: List[list] = []
        for i in range(plan.n_classes):
            if plan.perturbs[i] != 1.0:
                return None
            src = self._stage_sources.get(plan.stages[i])
            if src is None:
                return None
            stream, s_plan, j = src
            if s_plan.perturbs[j] != 1.0:
                return None
            mapped = self._remap_stream(stream, s_plan, plan, i)
            if mapped is None:
                return None
            out.append(mapped)
        return out

    @staticmethod
    def _remap_stream(stream: list, s_plan, plan, i: int
                      ) -> Optional[list]:
        groups = plan.groups[i]
        nmap = plan.neighbor_maps[i]
        s_stages = s_plan.stages
        barrier = list(range(plan.n_classes))
        out: list = []
        for req in stream:
            kind = req[0]
            if kind in ("compute", "advance", "advance_rel", "trace",
                        "wait_comm"):
                out.append(req)
                continue
            if kind == "collective":
                _, key, dur, name, _peers = req
                if isinstance(key, tuple):
                    tag = key[0]
                    dim = (tag.rsplit(":", 1)[1] if ":" in tag
                           else tag)
                    g = groups.get(dim)
                    if g is None:
                        return None
                    out.append((kind, (tag, tuple(g)), dur, name,
                                list(g)))
                    continue
                if key == "optimizer_barrier":
                    out.append((kind, key, dur, name, list(barrier)))
                    continue
                return None
            if kind == "async_collective":
                _, stream_name, dur, name, _peers = req
                dim = stream_name.rsplit(":", 1)[1]
                g = groups.get(dim)
                # _async_bucket degrades to a self-rendezvous when the
                # rank carries no group on the dim
                out.append((kind, stream_name, dur, name,
                            list(g) if g else [i]))
                continue
            if kind in ("send", "send_sync", "recv"):
                peer = nmap.get(s_stages[req[1]])
                if peer is None:
                    return None
                out.append((kind, peer) + req[2:])
                continue
            if kind == "sendrecv":
                _, dst, stag, sdur, src_r, rtag, name = req[:7]
                nd = ns = None
                if dst is not None:
                    nd = nmap.get(s_stages[dst])
                    if nd is None:
                        return None
                if src_r is not None:
                    ns = nmap.get(s_stages[src_r])
                    if ns is None:
                        return None
                out.append((kind, nd, stag, sdur, ns, rtag, name)
                           + req[7:])
                continue
            return None  # unknown request kind: record instead
        return out

    def _replay(self, sub: FaultScenario,
                fam: _StepFamily) -> Tuple[float, Optional[float]]:
        from simumax_tpu.simulator.engine import (
            RecordingProc,
            ReplayProc,
            SimuEngine,
        )
        from simumax_tpu.simulator.runner import build_reduced_engine

        plan = fam.plan
        model = StepFaultModel(sub, rank_map=plan.reps)
        ratio = self.healthy()["straggle_ratio"]
        if (fam.streams is None and self.options.prefix_fork
                and self._stage_sources):
            fam.streams = self._remap_streams(fam)
        if fam.streams is not None and self.options.prefix_fork:
            self.stats["replays"] += 1
            onset = min(ev.start_ms for ev in sub.events) * 1e-3
            eng = None
            if onset > 0.0:
                best = None
                for (t, snap) in fam.ladder:
                    if t <= onset and (best is None or t > best[0]):
                        best = (t, snap)
                if best is not None:
                    eng = best[1].fork()
                    self.stats["forks"] += 1
                    self._c_forks.inc()
            if eng is None:
                eng = SimuEngine(plan.n_classes, drop_events=True)
                for i in range(plan.n_classes):
                    eng.add_rank(i, ReplayProc(fam.streams[i]))
            eng._fault = model
            finished = False
            if onset > 0.0:
                # pause at the onset: every decision so far is
                # fault-model-agnostic, so the paused state joins the
                # fork ladder for later scenarios of this family
                finished = eng.run_incremental(pause_at=onset)
                if (not finished
                        and len(fam.ladder) < self.options.max_snapshots
                        and all(t != onset for t, _ in fam.ladder)):
                    snap = eng.fork()
                    snap._fault = None
                    fam.ladder.append((onset, snap))
            if not finished:
                eng.run_incremental()
            raw_end = max(eng.clock) if eng.clock else 0.0
            deaths = eng.deaths
        else:
            recorders: Dict[int, RecordingProc] = {}

            def wrap(i, gen):
                rp = RecordingProc(gen)
                recorders[i] = rp
                return rp

            self.stats["recordings"] += 1
            eng = build_reduced_engine(
                self.perf, plan, self.granularity, fault_model=model,
                wrap_proc=wrap if self.options.prefix_fork else None,
                drop_events=True,
            )
            raw_end = eng.run()
            deaths = eng.deaths
            if (self.options.prefix_fork and recorders
                    and all(r.complete for r in recorders.values())):
                # a stream truncated by a rank death must not be
                # cached — it would starve longer-lived replays
                fam.streams = [
                    recorders[i].stream for i in range(plan.n_classes)
                ]
                for i in range(plan.n_classes):
                    stage = plan.stages[i]
                    if (plan.perturbs[i] == 1.0
                            and stage not in self._stage_sources):
                        self._stage_sources[stage] = (
                            fam.streams[i], plan, i,
                        )
        if deaths:
            # mirror _simulate_step's float path exactly: the runner
            # reports deaths in ms (t * ratio * 1e3) and the exact walk
            # converts back with * 1e-3 — same associativity, same bits
            t = min(t for (_r, t) in deaths)
            td = t * ratio * 1e3 * 1e-3
            return (td, td, t)
        return (raw_end * ratio, None, raw_end)

    # -- the step entry point ----------------------------------------------
    def _step_probe(self, sub: FaultScenario, span_s: float):
        """The cache/short-circuit pipeline of one step, short of
        simulating: ``(answer, None)`` when a cache layer or the slack
        gate answers, else ``(None, miss_state)`` where ``miss_state``
        carries everything :meth:`_step_commit` needs to store the
        simulated result — ``(key, hkey, ckey, fam, min_end)``."""
        key = sub.signature()
        hit = self._cache.get(key)
        if hit is not None:
            self.stats["cache_hits"] += 1
            self._c_hits.inc()
            return hit, None
        opts = self.options
        if opts.short_circuit and self._gate(sub):
            self.stats["shortcircuits"] += 1
            self._c_gate.inc()
            out = (self.healthy()["end_time"], None)
            self._cache[key] = out
            return out, None
        sigs, min_end, clamped = self._clamp_events(sub, span_s)
        hkey = None
        if clamped:
            hkey = self._clamped_key(sub, sigs)
            got = self._clamped.get(hkey)
            if got is not None and min_end >= got[2]:
                out = (got[0], got[1])
                self.stats["clamp_hits"] += 1
                self._c_clamp.inc()
                self._cache[key] = out
                return out, None
        fam = None
        ckey = None
        if opts.canonical_cache and (
                self._canon_misses < CANON_PROBE_LIMIT
                or self.stats.get("canon_hits", 0) > 0):
            fam = self._family(sub)
            ckey = self._canonical_key(sub, fam.plan, sigs)
            got = self._canon.get(ckey)
            if got is not None and min_end >= got[2]:
                out = (got[0], got[1])
                self.stats["canon_hits"] += 1
                self._c_canon.inc()
                self._cache[key] = out
                if hkey is not None:
                    self._clamped[hkey] = got
                return out, None
            self._canon_misses += 1
        if fam is None:
            fam = self._family(sub)
        return None, (key, hkey, ckey, fam, min_end)

    def _step_commit(self, state: tuple,
                     result: Tuple[float, Optional[float], float]
                     ) -> Tuple[float, Optional[float]]:
        """Store one simulated miss into every cache layer whose
        validity guard passes — the exact tail of the pre-batched
        ``simulate_step``, shared by the scalar and batched paths."""
        key, hkey, ckey, _fam, min_end = state
        dur, death, raw_limit = result
        out = (dur, death)
        self.stats["sims"] += 1
        self._cache[key] = out
        if min_end >= raw_limit:
            # the realized end stayed inside every clamped window, so
            # the result is a faithful answer for the open-ended key
            entry = (dur, death, raw_limit)
            if hkey is not None:
                self._clamped[hkey] = entry
            if ckey is not None:
                self._canon[ckey] = entry
        return out

    def simulate_step(self, sub: FaultScenario, span_s: float
                      ) -> Tuple[float, Optional[float]]:
        """(wall duration, death time | None) of one step under the
        re-based sub-scenario ``sub`` (nominal window ``span_s``
        seconds) — the incremental twin of :func:`_simulate_step`,
        bit-identical by construction."""
        self.stats["steps"] += 1
        out, state = self._step_probe(sub, span_s)
        if out is not None:
            return out
        return self._step_commit(state, self._replay(sub, state[3]))

    # -- batched miss replay (ISSUE 17 tentpole) ---------------------------
    def simulate_step_batch(self, reqs: List[Tuple[FaultScenario, float]]
                            ) -> List[Tuple[float, Optional[float]]]:
        """Answer one lockstep round of steps together: probe every
        request through the cache pipeline, then replay the deduped
        misses — batched through the vmapped array program where the
        family lowers, scalar with a counted fallback reason where it
        doesn't. Answers are bit-identical to calling
        :meth:`simulate_step` on each request in order: the caches
        guarantee cached == computed, and within-round duplicates
        (exact, clamped, or canonical) defer to the next round where
        the freshly committed entries answer them through the same
        validity guards the serial path applies."""
        outs: List[Any] = [None] * len(reqs)
        pending = []
        for j, (sub, span_s) in enumerate(reqs):
            self.stats["steps"] += 1
            out, state = self._step_probe(sub, span_s)
            if out is not None:
                outs[j] = out
            else:
                pending.append((j, sub, span_s, state))
        while pending:
            seen: set = set()
            batch, rest = [], []
            for item in pending:
                key, hkey, ckey = item[3][0], item[3][1], item[3][2]
                dup = (key in seen
                       or (hkey is not None and hkey in seen)
                       or (ckey is not None and ckey in seen))
                if dup:
                    rest.append(item)
                    continue
                seen.add(key)
                if hkey is not None:
                    seen.add(hkey)
                if ckey is not None:
                    seen.add(ckey)
                batch.append(item)
            self._solve_misses(batch, outs)
            pending = []
            for j, sub, span_s, _old in rest:
                out, state = self._step_probe(sub, span_s)
                if out is not None:
                    outs[j] = out
                else:
                    pending.append((j, sub, span_s, state))
        return outs

    def _count_fallback(self, reason: str, n: int = 1):
        k = "fallback_" + reason
        self.stats[k] = self.stats.get(k, 0) + n
        c = self._c_fallbacks.get(reason)
        if c is None:
            c = self._registry.counter("replay_batch_fallbacks_total",
                                       reason=reason)
            self._c_fallbacks[reason] = c
        c.inc(n)

    def _lowered(self, fam: _StepFamily):
        """``fam``'s lowered array program, or the fallback-reason
        string explaining why it cannot lower. Lowering outcomes are
        memoized per family; the one retryable miss — streams not
        recorded yet — is not cached, so the family lowers on the
        round after its recording run."""
        if not self.options.prefix_fork:
            return "no_streams"
        got = self._lowerings.get(id(fam))
        if got is not None:
            return got
        if fam.streams is None and self._stage_sources:
            fam.streams = self._remap_streams(fam)
        if fam.streams is None:
            return "no_streams"
        br = _batched_replay()
        try:
            prog = br.lower_family(fam.streams, fam.plan)
        except br.LoweringError as err:
            prog = err.reason
        self._lowerings[id(fam)] = prog
        return prog

    def _solve_misses(self, batch: List[tuple], outs: List[Any]):
        """Replay one deduped round of cache misses. Lowerable
        families go through ``batched_replay.solve_batch`` in one
        vmapped call per family; everything else falls back to the
        scalar engine per scenario with a counted reason."""
        backend = self.options.replay_backend
        scalar: List[Tuple[tuple, str]] = []
        groups: Dict[int, Tuple[_StepFamily, Any, list]] = {}
        if backend == "numpy":
            scalar = [(item, "backend_numpy") for item in batch]
        elif not _batched_replay().jax_available():
            scalar = [(item, "jax_unavailable") for item in batch]
        else:
            for item in batch:
                _j, sub, _span, state = item
                fam = state[3]
                model = StepFaultModel(sub, rank_map=fam.plan.reps)
                if model._deaths:
                    scalar.append((item, "deaths"))
                    continue
                prog = self._lowered(fam)
                if isinstance(prog, str):
                    scalar.append((item, prog))
                    continue
                groups.setdefault(id(fam), (fam, prog, []))[2].append(
                    (item, model))
            if backend == "auto":
                floor = (self.options.jit_batch_min
                         or _batched_replay().JIT_BATCH_MIN)
                for gid in list(groups):
                    members = groups[gid][2]
                    if len(members) < floor:
                        scalar.extend(
                            (it, "small_batch") for it, _m in members)
                        del groups[gid]
        self._solve_groups(groups, outs)
        # scalar loop with a staleness retry: "no_streams" is the one
        # fallback a scalar replay CURES (the first sim of a stage
        # records its stream sources), so every later no_streams item
        # in the same round re-attempts lowering and rejoins a batched
        # group instead of walking the engine — one recorder per
        # stage, not one per scenario
        retry: Dict[int, Tuple[_StepFamily, Any, list]] = {}
        for item, reason in scalar:
            j, sub, _span, state = item
            if reason == "no_streams":
                fam = state[3]
                prog = self._lowered(fam)
                if not isinstance(prog, str):
                    model = StepFaultModel(sub, rank_map=fam.plan.reps)
                    retry.setdefault(id(fam), (fam, prog, []))[2].append(
                        (item, model))
                    continue
            self._count_fallback(reason)
            outs[j] = self._step_commit(state,
                                        self._replay(sub, state[3]))
        if retry and backend == "auto":
            floor = (self.options.jit_batch_min
                     or _batched_replay().JIT_BATCH_MIN)
            for gid in list(retry):
                members = retry[gid][2]
                if len(members) < floor:
                    for it, _m in members:
                        j, sub, _span, state = it
                        self._count_fallback("small_batch")
                        outs[j] = self._step_commit(
                            state, self._replay(sub, state[3]))
                    del retry[gid]
        self._solve_groups(retry, outs)

    def _solve_groups(self, groups: Dict[int, Tuple["_StepFamily",
                                                    Any, list]],
                      outs: List[Any]):
        """Solve per-family miss groups in one vmapped call each and
        commit the makespans through the scalar engine's exact
        ``(raw * ratio, None, raw)`` tail."""
        if not groups:
            return
        ratio = self.healthy()["straggle_ratio"]
        br = _batched_replay()
        for fam, prog, members in groups.values():
            raws = br.solve_batch(prog, [m for _it, m in members])
            self.stats["batched"] += len(members)
            self._c_batched.inc(len(members))
            self.stats["replays"] += len(members)
            for (item, _m), raw in zip(members, raws):
                j, _sub, _span, state = item
                raw_end = float(raw)
                outs[j] = self._step_commit(
                    state, (raw_end * ratio, None, raw_end))

    # -- (d) parallel merge-back -------------------------------------------
    def absorb_stats(self, delta: Dict[str, int]):
        """Merge a pool worker's stat deltas into this context and its
        registry counters (observe-only; results never depend on it)."""
        for k, v in delta.items():
            if v:
                self.stats[k] = self.stats.get(k, 0) + v
        for k, counter in (
            ("scenarios", self._c_scenarios),
            ("cache_hits", self._c_hits),
            ("canon_hits", self._c_canon),
            ("clamp_hits", self._c_clamp),
            ("shortcircuits", self._c_gate),
            ("forks", self._c_forks),
        ):
            if delta.get(k):
                counter.inc(delta[k])


# -- (d) process-parallel Monte-Carlo (PR-2 executor discipline) -----------

#: per-worker-process state, filled by the pool initializer
_MC_WORKER: Dict[str, Any] = {}

def _mc_context():
    import multiprocessing as _mp

    name = os.environ.get("SIMUMAX_MP_START", "")
    if not name:
        name = "fork" if "fork" in _mp.get_all_start_methods() else "spawn"
    return _mp.get_context(name)


def _mc_worker_init(env: tuple):
    (strategy, model, system, granularity, reduce, options,
     timeout) = env
    from simumax_tpu.perf import PerfLLM

    perf = PerfLLM()
    perf.configure(strategy, model, system)
    perf.run_estimate()
    ctx = ReplayContext(perf, granularity=granularity, reduce=reduce,
                        options=options)
    _MC_WORKER["ctx"] = ctx
    _MC_WORKER["timeout"] = timeout
    _MC_WORKER["shipped"] = set(ctx._canon)
    _MC_WORKER["stats"] = dict(ctx.stats)


def _mc_task(task: tuple):
    """One Monte-Carlo work item on the worker's MAIN thread (so the
    SIGALRM scenario deadline is fully effective). Ships back the
    fresh canonical-cache entries and stat deltas for merge-back."""
    kind, idx, scenario, spec, interval_list = task
    ctx: ReplayContext = _MC_WORKER["ctx"]
    timeout = _MC_WORKER["timeout"]
    if kind == "base":
        with _deadline(timeout, f"scenario[{idx}]"):
            out: Any = predict_goodput(
                ctx.perf, scenario, spec=spec,
                granularity=ctx.granularity, reduce=ctx.reduce,
                _ctx=ctx,
            ).to_dict()
    else:
        out = {}
        for k in interval_list:
            k_spec = CheckpointSpec(
                interval_steps=int(k),
                restart_overhead_s=spec.restart_overhead_s,
                write_gbps=spec.write_gbps,
                read_gbps=spec.read_gbps,
            )
            # one deadline per (scenario, interval) walk — the same
            # scope the serial path arms, so a scenario that fits the
            # per-walk budget cannot time out only under --jobs
            with _deadline(timeout, f"scenario[{idx}]@interval{k}"):
                out[int(k)] = predict_goodput(
                    ctx.perf, scenario, spec=k_spec,
                    granularity=ctx.granularity, reduce=ctx.reduce,
                    _ctx=ctx,
                ).goodput
    shipped = _MC_WORKER["shipped"]
    fresh = {k: v for k, v in ctx._canon.items() if k not in shipped}
    shipped.update(fresh)
    last = _MC_WORKER["stats"]
    delta = {k: ctx.stats[k] - last.get(k, 0) for k in ctx.stats}
    _MC_WORKER["stats"] = dict(ctx.stats)
    return idx, out, fresh, delta


def _mc_open_pool(ctx: ReplayContext, env: tuple, jobs: int):
    """One worker pool shared by every Monte-Carlo phase: workers keep
    their replay context (recorded streams, fork ladders, caches) warm
    between the base walk and the interval sweep, so the expensive
    per-worker init (estimate rebuild + healthy critical-path run)
    is paid exactly once. Workers always start with a cold canonical
    cache — caches warm in-worker during the base phase and ship fresh
    entries back; a parent-side fork-seed global would leak entries
    across concurrent analyses of different estimates, whose canonical
    keys encode only structural identity."""
    import concurrent.futures as _cf

    return _cf.ProcessPoolExecutor(
        max_workers=jobs,
        mp_context=_mc_context(),
        initializer=_mc_worker_init,
        initargs=(env,),
    )


def _mc_pool_map(pool, ctx: ReplayContext,
                 tasks: List[tuple]) -> Dict[int, Any]:
    """Fan tasks across the pool; merge canonical-cache entries and
    stats back into ``ctx``. Results are keyed by task index, so the
    caller assembles them in scenario order — serial == parallel
    bit-for-bit (cached values equal computed values by construction).
    A worker exception (including a scenario deadline) propagates."""
    results: Dict[int, Any] = {}
    futures = [pool.submit(_mc_task, t) for t in tasks]
    for fut in futures:
        idx, out, fresh, delta = fut.result()
        ctx._canon.update(fresh)
        ctx.absorb_stats(delta)
        results[idx] = out
    return results


def predict_goodput(
    perf,
    scenario: FaultScenario,
    spec: Optional[CheckpointSpec] = None,
    granularity: str = "chunk",
    reduce="auto",
    max_restarts: int = 1000,
    _cache: Optional[Dict[tuple, Tuple[float, Optional[float]]]] = None,
    incremental: bool = True,
    options: Optional[ReplayOptions] = None,
    _ctx: Optional[ReplayContext] = None,
    observer=None,
) -> GoodputReport:
    """Predict goodput of ``scenario`` over its ``horizon_steps``.

    Walks job wall-clock step by step: each step's duration comes from
    a discrete-event simulation with the scenario's events re-based
    onto the step window (steps no event touches reuse the fault-free
    step, so only perturbed steps pay for a simulation); every
    ``interval_steps`` committed steps a checkpoint write is charged; a
    rank death aborts the step, rolls uncommitted progress back to the
    last checkpoint (its wall time becomes ``restart_replay``), and
    charges restart overhead + restore read before training resumes.

    ``incremental=True`` (default) routes perturbed-step costing
    through the incremental replay engine (:class:`ReplayContext` —
    slack short-circuit, canonicalized step cache, recorded-stream
    replay with healthy-prefix forks), bit-identical to the exact path
    and ~10x+ faster on Monte-Carlo workloads. ``incremental=False``
    (or ``reduce=False``) keeps the pre-incremental exact walk.
    ``options`` tunes the individual optimizations; ``_ctx`` shares
    one replay context across calls (``analyze_faults`` does).
    ``observer`` (optional callable) receives the walk's accounting
    events — ``("step", wall_s, healthy_s, dur_s)``,
    ``("checkpoint", wall_s, write_s)`` and ``("restart",
    abort_wall_s, extra_lost_s, overhead_s, read_s)`` — the bucket
    provenance the fleet ledger attributes to causing trace events
    (``observe/fleetledger.py``). Pure notification: an observer
    cannot change a single number, so observed and unobserved walks
    are bit-identical by construction.
    """
    from simumax_tpu.observe.telemetry import get_registry, get_tracer

    ctx = _ctx
    if ctx is None and incremental and reduce is not False:
        ctx = ReplayContext(perf, granularity=granularity,
                            reduce=reduce, options=options)
    if ctx is not None and (ctx.perf is not perf
                            or ctx.granularity != granularity):
        raise ConfigError(
            "predict_goodput _ctx mismatch: the replay context was "
            f"built for granularity {ctx.granularity!r} on a "
            "different estimate",
            phase="simulate",
        )
    # validation + checkpoint-spec resolution hoist once per shared
    # context (the fleet walk re-costs a scenario thousands of times);
    # without a context both run per call, behaviorally identical
    if ctx is not None:
        ctx.validate_scenario(scenario)
    else:
        scenario.validate(perf.strategy.world_size)
    # an explicitly passed spec wins outright (a CLI flag must beat
    # the scenario's bundled default, not the other way round); the
    # scenario's "checkpoint" block only fills in when none is given
    if spec is None:
        spec = (ctx.resolve_spec(scenario) if ctx is not None
                else CheckpointSpec.from_overrides(scenario.checkpoint))
    with get_tracer().span("predict_goodput",
                           events=len(scenario.events),
                           horizon=scenario.horizon_steps,
                           incremental=ctx is not None):
        if ctx is not None:
            ctx.stats["scenarios"] += 1
            ctx._c_scenarios.inc()
            ckpt = ctx.checkpoint_model(spec)
            healthy = ctx.healthy()
        else:
            from simumax_tpu.simulator.runner import run_simulation

            get_registry().counter("faults_scenarios_total").inc()
            ckpt = CheckpointCostModel.from_perf(perf, spec)
            healthy = run_simulation(
                perf, None, granularity=granularity, world_ranks=True,
                reduce=reduce,
            )
        return _goodput_walk(perf, scenario, spec, ckpt, healthy,
                             granularity, reduce, max_restarts, _cache,
                             ctx, observer=observer)


def _goodput_walk(perf, scenario, spec, ckpt, healthy, granularity,
                  reduce, max_restarts, _cache, ctx,
                  observer=None) -> GoodputReport:
    """Drive one scenario's walk generator serially, answering each
    step request as it arrives — behaviorally identical to the
    pre-generator inline walk. The generator split exists so the
    lockstep driver (:func:`_predict_goodput_batch`) can advance many
    walks in rounds and feed whole miss batches to the batched replay
    backend."""
    cache = _cache if _cache is not None else {}
    gen = _walk_gen(scenario, spec, ckpt, healthy, max_restarts,
                    observer=observer)
    ans = None
    while True:
        try:
            sub, span = gen.send(ans)
        except StopIteration as stop:
            return stop.value
        if ctx is not None:
            ans = ctx.simulate_step(sub, span)
        else:
            ans = _simulate_step(perf, sub, cache, granularity, reduce)


def _walk_gen(scenario, spec, ckpt, healthy, max_restarts,
              observer=None):
    """The goodput walk as a coroutine: yields ``(sub, span_s)`` step
    requests, receives ``(dur, death)`` answers, and returns the
    finished :class:`GoodputReport` (via ``StopIteration.value``).
    Pure bookkeeping — every simulation happens in the driver.
    ``observer`` (see :func:`predict_goodput`) is notified of each
    accounting event; it never feeds back into the walk."""
    h = healthy["end_time"]
    horizon = scenario.horizon_steps
    interval = spec.interval_steps
    b = GoodputBuckets()
    wall = 0.0
    committed = 0
    ckpt_committed = 0
    n_ckpt = n_restart = replayed = 0
    #: (healthy_part, stall_part) of steps committed since the last
    #: checkpoint — rolled into restart_replay on a death
    uncommitted: List[Tuple[float, float]] = []
    deaths: List[Dict[str, float]] = []
    truncated = False

    def first_death_in(t0_s: float, t1_s: float) -> Optional[float]:
        """Earliest rank-death absolute time inside [t0, t1)."""
        times = [
            ev.start_ms * 1e-3 for ev in scenario.events
            if ev.kind == "rank_death"
            and t0_s <= ev.start_ms * 1e-3 < t1_s
        ]
        return min(times) if times else None

    def restart(abort_wall_s: float, extra_lost_s: float):
        """Roll uncommitted progress back to the last checkpoint and
        charge the recovery sequence. ``extra_lost_s`` is wall time of
        the aborted partial step / checkpoint write."""
        nonlocal wall, committed, n_restart, replayed, uncommitted
        deaths.append({
            "wall_time_s": abort_wall_s,
            "lost_steps": committed - ckpt_committed,
        })
        for (hp, sp) in uncommitted:
            b.useful_train -= hp
            b.fault_stall -= sp
            b.restart_replay += hp + sp
        replayed += len(uncommitted)
        b.restart_replay += extra_lost_s
        committed = ckpt_committed
        uncommitted = []
        wall = abort_wall_s + spec.restart_overhead_s + ckpt.read_s
        b.restart_overhead += spec.restart_overhead_s
        b.restore_read += ckpt.read_s
        n_restart += 1
        if observer is not None:
            observer(("restart", abort_wall_s, extra_lost_s,
                      spec.restart_overhead_s, ckpt.read_s))

    while committed < horizon:
        # fixpoint window growth: a step stretched by faults may pull
        # later events into its window
        span = h
        dur, death = h, None
        for _ in range(8):
            sub = scenario.shifted(wall * 1e3, span * 1e3)
            if sub.empty:
                dur, death = h, None
                break
            dur, death = yield (sub, span)
            if death is not None or dur <= span * (1 + 1e-12):
                break
            span = dur
        if death is None:
            if observer is not None:
                observer(("step", wall, h, dur))
            wall += dur
            b.useful_train += h
            b.fault_stall += dur - h
            uncommitted.append((h, dur - h))
            committed += 1
            if committed % interval == 0 and committed < horizon:
                # a rank death during the checkpoint write still kills
                # the job — and the interrupted write never commits
                t_d = first_death_in(wall, wall + ckpt.write_s)
                if t_d is not None:
                    restart(t_d, t_d - wall)
                    if n_restart >= max_restarts:
                        truncated = True
                        break
                    continue
                if observer is not None:
                    observer(("checkpoint", wall, ckpt.write_s))
                wall += ckpt.write_s
                b.checkpoint_write += ckpt.write_s
                n_ckpt += 1
                ckpt_committed = committed
                uncommitted = []
        else:
            # committed-but-uncheckpointed steps are lost: their wall
            # time (healthy + stall) turns into replay, plus the
            # aborted partial step
            restart(wall + death, death)
            if n_restart >= max_restarts:
                truncated = True
                break
    useful = b.useful_train
    return GoodputReport(
        goodput=(useful / wall) if wall > 0 else 1.0,
        wall_time_s=wall,
        useful_time_s=useful,
        healthy_step_s=h,
        horizon_steps=horizon,
        n_checkpoints=n_ckpt,
        n_restarts=n_restart,
        steps_replayed=replayed,
        buckets=b,
        deaths=deaths,
        checkpoint=ckpt.to_dict(),
        truncated=truncated,
    )


def _predict_goodput_batch(ctx: ReplayContext,
                           tasks: List[Tuple[FaultScenario,
                                             CheckpointSpec]],
                           max_restarts: int = 1000
                           ) -> List[GoodputReport]:
    """Lockstep twin of calling :func:`predict_goodput` serially on
    ``tasks`` with a shared context: every walk advances one step per
    round, and the round's step requests are answered together by
    :meth:`ReplayContext.simulate_step_batch`, so the batched replay
    backend sees whole miss batches instead of one miss at a time.
    Reports are bit-identical to the serial loop — every cache layer
    guarantees cached == computed, so answer order cannot change a
    number, only which request pays for the simulation."""
    from simumax_tpu.observe.telemetry import get_tracer

    healthy = ctx.healthy()
    results: List[Any] = [None] * len(tasks)
    walks = []
    with get_tracer().span("predict_goodput_batch", walks=len(tasks),
                           incremental=True):
        for scenario, spec in tasks:
            ctx.validate_scenario(scenario)
            ctx.stats["scenarios"] += 1
            ctx._c_scenarios.inc()
            ckpt = ctx.checkpoint_model(spec)
            walks.append(_walk_gen(scenario, spec, ckpt, healthy,
                                   max_restarts))
        pend: Dict[int, tuple] = {}
        for i, gen in enumerate(walks):
            try:
                pend[i] = gen.send(None)
            except StopIteration as stop:
                results[i] = stop.value
        while pend:
            order = sorted(pend)
            answers = ctx.simulate_step_batch([pend[i] for i in order])
            for i, ans in zip(order, answers):
                try:
                    pend[i] = walks[i].send(ans)
                except StopIteration as stop:
                    results[i] = stop.value
                    del pend[i]
    return results


# --------------------------------------------------------------------------
# Monte-Carlo sampling
# --------------------------------------------------------------------------


def sample_scenario(
    rng: random.Random,
    world_size: int,
    horizon_ms: float,
    *,
    horizon_steps: int = 100,
    max_events: int = 6,
    death_prob: float = 0.3,
    seed: Optional[int] = None,
) -> FaultScenario:
    """One random-but-seeded fault scenario: a mix of slowdown windows,
    preemptions, scoped/unscoped link degradations, and (with
    ``death_prob``) rank deaths, all inside ``[0, horizon_ms)``."""
    events: List[FaultEvent] = []
    n = rng.randint(0, max_events)
    for _ in range(n):
        kind = rng.choice(("slowdown", "preemption", "link_degradation"))
        start = rng.uniform(0.0, horizon_ms * 0.9)
        dur = rng.uniform(horizon_ms * 0.005, horizon_ms * 0.25)
        if kind == "slowdown":
            events.append(FaultEvent(
                "slowdown", start_ms=start, duration_ms=dur,
                rank=rng.randrange(world_size),
                multiplier=rng.uniform(1.05, 5.0),
            ))
        elif kind == "preemption":
            events.append(FaultEvent(
                "preemption", start_ms=start,
                duration_ms=rng.uniform(horizon_ms * 0.002,
                                        horizon_ms * 0.05),
                rank=rng.randrange(world_size),
            ))
        else:
            scope = None
            if rng.random() < 0.5:
                k = rng.randint(1, max(1, min(4, world_size)))
                scope = sorted(rng.sample(range(world_size), k))
            events.append(FaultEvent(
                "link_degradation", start_ms=start, duration_ms=dur,
                dim=rng.choice(("tp", "pp", "dp_cp", "*")),
                multiplier=rng.uniform(1.1, 8.0), ranks=scope,
            ))
    if rng.random() < death_prob:
        events.append(FaultEvent(
            "rank_death", start_ms=rng.uniform(0.0, horizon_ms * 0.9),
            rank=rng.randrange(world_size),
        ))
    return FaultScenario(events=events, horizon_steps=horizon_steps,
                         seed=seed)


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def analyze_faults(
    perf,
    n_scenarios: int = 32,
    seed: int = 0,
    horizon_steps: int = 50,
    spec: Optional[CheckpointSpec] = None,
    intervals: Optional[Sequence[int]] = None,
    granularity: str = "chunk",
    reduce="auto",
    max_events: int = 6,
    death_prob: float = 0.3,
    jobs: int = 0,
    incremental: bool = True,
    options: Optional[ReplayOptions] = None,
    scenario_timeout: Optional[float] = None,
    _ctx: Optional[ReplayContext] = None,
) -> Dict[str, Any]:
    """Seeded Monte-Carlo goodput analysis: sample ``n_scenarios``
    random scenarios, predict each one's goodput, and sweep checkpoint
    intervals to find the empirically optimal one (reported next to
    the Young–Daly closed form ``sqrt(2 * write_time * MTBF)``).
    Deterministic for a given seed.

    ``incremental=True`` (default) shares one :class:`ReplayContext`
    across every prediction — the grid entry equal to
    ``spec.interval_steps`` reuses the base walk outright, and the
    remaining walks hit the slack gate / canonical cache / prefix
    forks. ``jobs=N`` fans scenarios across a process pool (PR-2
    executor discipline: worker-main-thread SIGALRM deadlines via
    ``scenario_timeout``, canonical-cache merge-back); the result is
    bit-for-bit equal to the serial one. ``incremental=False`` keeps
    the pre-incremental exact path."""
    from simumax_tpu.observe.telemetry import get_tracer

    spec = spec or CheckpointSpec()
    st = perf.strategy
    jobs = max(0, int(jobs or 0))
    ctx = _ctx
    if ctx is None and incremental and reduce is not False:
        ctx = ReplayContext(perf, granularity=granularity,
                            reduce=reduce, options=options)
    if ctx is not None:
        healthy = ctx.healthy()
    else:
        from simumax_tpu.simulator.runner import run_simulation

        healthy = run_simulation(
            perf, None, granularity=granularity, world_ranks=True,
            reduce=reduce,
        )
    h = healthy["end_time"]
    # sample against the rough job wall (healthy horizon + slack so
    # late-run faults land inside the actual, stretched wall-clock)
    horizon_ms = horizon_steps * h * 1e3 * 1.25
    rng = random.Random(seed)
    scenarios = [
        sample_scenario(
            rng, st.world_size, horizon_ms, horizon_steps=horizon_steps,
            max_events=max_events, death_prob=death_prob, seed=seed,
        )
        for _ in range(n_scenarios)
    ]
    parallel = ctx is not None and jobs > 1 and len(scenarios) > 1
    # lockstep batching: advance every scenario walk in rounds so the
    # batched replay backend sees whole miss batches. Off under a
    # per-scenario deadline (SIGALRM scopes one walk, not a round) and
    # under replay_backend="numpy" (nothing to batch)
    lockstep = (ctx is not None and not parallel
                and scenario_timeout is None
                and ctx.options.replay_backend != "numpy"
                and len(scenarios) > 1)
    env = None
    if parallel:
        env = (perf.strategy, perf.model_config, perf.system,
               granularity, reduce, ctx.options, scenario_timeout)
    cache: Dict[tuple, Tuple[float, Optional[float]]] = {}
    pool = None
    try:
      # (one pool for both phases: workers keep recorded streams, fork
      # ladders and caches warm between the base walk and the sweep)
      with get_tracer().span("analyze_faults", n_scenarios=n_scenarios,
                             seed=seed, jobs=jobs,
                             incremental=ctx is not None):
        if parallel:
            pool = _mc_open_pool(ctx, env, min(jobs, len(scenarios)))
            got = _mc_pool_map(
                pool, ctx,
                [("base", i, s, spec, None)
                 for i, s in enumerate(scenarios)],
            )
            report_dicts = [got[i] for i in range(len(scenarios))]
        elif lockstep:
            report_dicts = [
                r.to_dict() for r in _predict_goodput_batch(
                    ctx, [(s, spec) for s in scenarios])
            ]
        else:
            report_dicts = []
            for i, s in enumerate(scenarios):
                with _deadline(scenario_timeout, f"scenario[{i}]"):
                    report_dicts.append(predict_goodput(
                        perf, s, spec=spec, granularity=granularity,
                        reduce=reduce, _cache=cache,
                        incremental=ctx is not None, _ctx=ctx,
                    ).to_dict())
        goodputs = sorted(r["goodput"] for r in report_dicts)
        n_interrupts = sum(r["n_restarts"] for r in report_dicts)
        total_wall = sum(r["wall_time_s"] for r in report_dicts)
        mtbf = (total_wall / n_interrupts) if n_interrupts else math.inf
        ckpt = (ctx.checkpoint_model(spec) if ctx is not None
                else CheckpointCostModel.from_perf(perf, spec))
        if math.isfinite(mtbf):
            yd_interval = max(
                1, int(round(math.sqrt(2.0 * ckpt.write_s * mtbf) / h))
            )
        else:
            yd_interval = horizon_steps
        if intervals is None:
            grid = sorted({
                max(1, horizon_steps // 16), max(1, horizon_steps // 8),
                max(1, horizon_steps // 4), max(1, horizon_steps // 2),
                horizon_steps, min(yd_interval, horizon_steps),
            })
            intervals = grid
        base_goodputs = [r["goodput"] for r in report_dicts]
        pending = [
            int(k) for k in intervals
            if not (ctx is not None and int(k) == spec.interval_steps)
        ]
        grid_vals: Dict[int, Dict[int, float]] = {}
        if parallel and pending:
            grid_vals = _mc_pool_map(
                pool, ctx,
                [("grid", i, s, spec, tuple(pending))
                 for i, s in enumerate(scenarios)],
            )
        elif pending:
            # one spec per interval, shared across scenarios (the
            # per-(scenario, interval) rebuild was pure duplication)
            k_specs = {
                k: CheckpointSpec(
                    interval_steps=int(k),
                    restart_overhead_s=spec.restart_overhead_s,
                    write_gbps=spec.write_gbps,
                    read_gbps=spec.read_gbps,
                )
                for k in pending
            }
            if lockstep:
                reports = _predict_goodput_batch(
                    ctx,
                    [(s, k_specs[k]) for s in scenarios
                     for k in pending],
                )
                for i in range(len(scenarios)):
                    grid_vals[i] = {
                        int(k): reports[i * len(pending) + p].goodput
                        for p, k in enumerate(pending)
                    }
            else:
                for i, s in enumerate(scenarios):
                    per: Dict[int, float] = {}
                    for k in pending:
                        k_spec = k_specs[k]
                        with _deadline(scenario_timeout,
                                       f"scenario[{i}]@interval{k}"):
                            per[int(k)] = predict_goodput(
                                perf, s, spec=k_spec,
                                granularity=granularity, reduce=reduce,
                                _cache=cache,
                                incremental=ctx is not None, _ctx=ctx,
                            ).goodput
                    grid_vals[i] = per
        by_interval: Dict[int, float] = {}
        for k in intervals:
            k = int(k)
            if ctx is not None and k == spec.interval_steps:
                # the base walk already costed this interval: reuse its
                # reports instead of re-walking every scenario
                vals = base_goodputs
            else:
                vals = [grid_vals[i][k] for i in range(len(scenarios))]
            by_interval[k] = sum(vals) / len(vals) if vals else 1.0
    finally:
        if pool is not None:
            # cancel_futures: a worker failure (e.g. a scenario
            # deadline) must not wait out every still-queued task —
            # only the <= jobs currently-running walks drain
            pool.shutdown(cancel_futures=True)
    best_interval = max(by_interval, key=lambda k: (by_interval[k], -k))
    return {
        "schema": "simumax-fault-analysis-v1",
        "seed": seed,
        "n_scenarios": n_scenarios,
        "horizon_steps": horizon_steps,
        "healthy_step_s": h,
        "goodput": {
            "mean": sum(goodputs) / len(goodputs) if goodputs else 1.0,
            "min": goodputs[0] if goodputs else 1.0,
            "max": goodputs[-1] if goodputs else 1.0,
            "p10": _quantile(goodputs, 0.10),
            "p50": _quantile(goodputs, 0.50),
            "p90": _quantile(goodputs, 0.90),
        },
        "restarts_total": n_interrupts,
        "mtbf_s": mtbf,
        "checkpoint": ckpt.to_dict(),
        "goodput_by_interval": by_interval,
        "best_interval_steps": best_interval,
        "young_daly_interval_steps": yd_interval,
        "reports": report_dicts,
    }


__all__ = [
    "EVENT_KINDS",
    "LINK_DIMS",
    "FaultEvent",
    "FaultScenario",
    "StepFaultModel",
    "FaultOutcome",
    "CheckpointSpec",
    "CheckpointCostModel",
    "GoodputReport",
    "ReplayOptions",
    "ReplayContext",
    "predict_goodput",
    "sample_scenario",
    "analyze_faults",
]

"""Fault injection, checkpoint/restore cost model, goodput prediction.

SimuMax predicts MFU for a *healthy* job; at pod scale a real TPU
training run also spends wall-clock on preemptions, slow hosts,
degraded links, and checkpoint/restore — the gap between MFU and
*goodput* that resilient-training systems (Bamboo, Oobleck) exist to
close. This module makes failure a first-class, simulatable input:

* :class:`FaultEvent` / :class:`FaultScenario` — a declarative,
  JSON-loadable timeline of faults: per-rank compute-slowdown windows,
  ICI/DCN link-bandwidth degradation scoped to specific collective
  groups, host preemptions (a rank frozen for a window), and rank
  deaths followed by restart-from-checkpoint.
* :class:`StepFaultModel` — the discrete-event engine's view of one
  training step: piecewise compute-rate multipliers integrated at
  event-service time, comm-time multipliers per collective dim, and
  death times. A dead rank no longer deadlocks the world: its
  collective partners resolve against the fault model
  (``SimuEngine`` consults it, see ``simulator/engine.py``) and the
  run returns a structured :class:`FaultOutcome` instead of crashing.
* :class:`CheckpointCostModel` — checkpoint write / restore read times
  derived from :class:`~simumax_tpu.core.config.SystemConfig`'s
  HBM→host→storage chain (``SystemConfig.host``) and the per-rank
  weight + optimizer-state bytes of the estimate.
* :func:`predict_goodput` — composes perturbed step simulations,
  periodic checkpoint writes, and death→restart→replay sequences into
  a wall-time decomposition (:class:`GoodputBuckets`) whose buckets
  sum to the wall time exactly; ``goodput = useful_train / wall``.
* :func:`analyze_faults` — seeded Monte-Carlo over sampled scenarios:
  goodput distribution plus the empirically optimal checkpoint
  interval (cross-checked against the Young–Daly closed form).

All scenario times are **milliseconds relative to the simulated
window** (one step for ``simulate(faults=...)``; job wall-clock for
:func:`predict_goodput`, which re-bases events per step itself).
"""

from __future__ import annotations

import json
import math
import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from simumax_tpu.core.errors import ConfigError
from simumax_tpu.core.records import GoodputBuckets

EVENT_KINDS = ("slowdown", "link_degradation", "preemption", "rank_death")

#: dims a link_degradation may target: the collective-group dims the
#: schedule issues rendezvous on, plus "pp" (p2p) and "*" (every comm op)
LINK_DIMS = ("tp", "cp", "ep", "etp", "dp_cp", "edp", "pp", "*")


# --------------------------------------------------------------------------
# Scenario schema
# --------------------------------------------------------------------------


@dataclass
class FaultEvent:
    """One timed fault. Field use per ``kind``:

    * ``slowdown`` — ``rank``'s compute takes ``multiplier``× longer
      during ``[start_ms, start_ms + duration_ms)`` (``duration_ms``
      None = until the end of the window).
    * ``preemption`` — ``rank`` is frozen (makes no progress) for
      ``duration_ms`` starting at ``start_ms``; collective partners
      stall on its late arrivals.
    * ``link_degradation`` — comm ops on ``dim`` take ``multiplier``×
      longer while active; ``ranks`` (optional) scopes it to ops whose
      rendezvous involves at least one listed rank.
    * ``rank_death`` — ``rank`` dies at ``start_ms`` and never
      returns; the job must restart from the last checkpoint
      (:func:`predict_goodput` accounts the restart).
    """

    kind: str
    start_ms: float = 0.0
    duration_ms: Optional[float] = None
    rank: Optional[int] = None
    multiplier: float = 1.0
    dim: Optional[str] = None
    ranks: Optional[List[int]] = None

    @property
    def end_ms(self) -> float:
        if self.kind == "rank_death":
            return math.inf
        if self.duration_ms is None:
            return math.inf
        return self.start_ms + self.duration_ms

    def validate(self, world_size: Optional[int] = None) -> "FaultEvent":
        def bad(msg):
            raise ConfigError(
                f"fault event {self.to_dict()}: {msg}",
                phase="simulate", fault_kind=self.kind,
            )

        if self.kind not in EVENT_KINDS:
            bad(f"unknown kind (expected one of {EVENT_KINDS})")
        if not (isinstance(self.start_ms, (int, float))
                and math.isfinite(self.start_ms) and self.start_ms >= 0):
            bad("start_ms must be a finite non-negative number")
        if self.duration_ms is not None and not (
            isinstance(self.duration_ms, (int, float))
            and math.isfinite(self.duration_ms) and self.duration_ms > 0
        ):
            bad("duration_ms must be a finite positive number")
        if self.kind in ("slowdown", "preemption", "rank_death"):
            if self.rank is None:
                bad("needs a target rank")
            if world_size is not None and not 0 <= self.rank < world_size:
                bad(f"rank {self.rank} outside world [0, {world_size})")
        if self.kind == "preemption" and self.duration_ms is None:
            bad("preemption needs a finite duration_ms")
        if self.kind in ("slowdown", "link_degradation"):
            if not (math.isfinite(self.multiplier) and self.multiplier >= 1.0):
                bad("multiplier must be finite and >= 1.0")
        if self.kind == "link_degradation":
            if self.dim not in LINK_DIMS:
                bad(f"dim {self.dim!r} not one of {LINK_DIMS}")
            if self.ranks is not None and world_size is not None:
                oob = [r for r in self.ranks
                       if not 0 <= r < world_size]
                if oob:
                    bad(f"scope ranks {oob} outside world "
                        f"[0, {world_size})")
        return self

    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {"kind": self.kind, "start_ms": self.start_ms}
        if self.duration_ms is not None:
            d["duration_ms"] = self.duration_ms
        if self.rank is not None:
            d["rank"] = self.rank
        if self.kind in ("slowdown", "link_degradation"):
            d["multiplier"] = self.multiplier
        if self.dim is not None:
            d["dim"] = self.dim
        if self.ranks is not None:
            d["ranks"] = list(self.ranks)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultEvent":
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        extra = set(d) - known
        if extra:
            raise ConfigError(
                f"fault event has unknown fields {sorted(extra)} "
                f"(known: {sorted(known)})", phase="simulate",
            )
        return cls(**d)

    def signature(self) -> tuple:
        """Hashable identity used for symmetry-reduction coloring."""
        return (self.kind, self.start_ms, self.duration_ms,
                self.multiplier, self.dim)


@dataclass
class FaultScenario:
    """A declarative fault timeline plus the job-level knobs goodput
    prediction needs (horizon length, checkpoint overrides)."""

    events: List[FaultEvent] = field(default_factory=list)
    #: job horizon for goodput prediction (training steps)
    horizon_steps: int = 100
    #: optional :class:`CheckpointSpec` field overrides
    checkpoint: Optional[Dict[str, Any]] = None
    #: provenance when sampled by :func:`sample_scenario`
    seed: Optional[int] = None

    @property
    def empty(self) -> bool:
        return not self.events

    def validate(self, world_size: Optional[int] = None) -> "FaultScenario":
        if not isinstance(self.horizon_steps, int) or self.horizon_steps < 1:
            raise ConfigError(
                f"horizon_steps must be a positive int, got "
                f"{self.horizon_steps!r}", phase="simulate",
            )
        for ev in self.events:
            ev.validate(world_size)
        return self

    # -- (de)serialization -------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        d: Dict[str, Any] = {
            "schema": "simumax-fault-scenario-v1",
            "horizon_steps": self.horizon_steps,
            "events": [e.to_dict() for e in self.events],
        }
        if self.checkpoint:
            d["checkpoint"] = dict(self.checkpoint)
        if self.seed is not None:
            d["seed"] = self.seed
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FaultScenario":
        schema = d.get("schema", "simumax-fault-scenario-v1")
        if schema != "simumax-fault-scenario-v1":
            raise ConfigError(
                f"unknown fault-scenario schema {schema!r}",
                phase="simulate",
            )
        events = [
            e if isinstance(e, FaultEvent) else FaultEvent.from_dict(e)
            for e in d.get("events", [])
        ]
        return cls(
            events=events,
            horizon_steps=int(d.get("horizon_steps", 100)),
            checkpoint=d.get("checkpoint"),
            seed=d.get("seed"),
        )

    @classmethod
    def from_json(cls, path: str) -> "FaultScenario":
        try:
            with open(path, encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError) as exc:
            raise ConfigError(
                f"cannot load fault scenario {path}: {exc}",
                phase="simulate", path=path,
            )
        return cls.from_dict(data)

    def save(self, path: str) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_dict(), f, indent=2)
        return path

    # -- step windowing / reduction support --------------------------------
    def shifted(self, offset_ms: float, span_ms: float) -> "FaultScenario":
        """The sub-scenario active inside ``[offset, offset + span)``,
        with event times re-based to the window start (clamped at 0 —
        an event already in progress is active from the window start,
        with its remaining duration)."""
        out: List[FaultEvent] = []
        for ev in self.events:
            if ev.kind == "rank_death":
                if offset_ms <= ev.start_ms < offset_ms + span_ms:
                    out.append(FaultEvent(
                        "rank_death", start_ms=ev.start_ms - offset_ms,
                        rank=ev.rank,
                    ))
                continue
            if ev.end_ms <= offset_ms or ev.start_ms >= offset_ms + span_ms:
                continue
            start = max(ev.start_ms - offset_ms, 0.0)
            dur = None
            if ev.duration_ms is not None:
                dur = ev.end_ms - offset_ms - start
            out.append(FaultEvent(
                ev.kind, start_ms=start, duration_ms=dur, rank=ev.rank,
                multiplier=ev.multiplier, dim=ev.dim,
                ranks=list(ev.ranks) if ev.ranks is not None else None,
            ))
        return FaultScenario(events=out, horizon_steps=self.horizon_steps,
                             checkpoint=self.checkpoint, seed=self.seed)

    def signature(self) -> tuple:
        """Hashable identity of the event set (step-result caching)."""
        return tuple(
            ev.signature() + (ev.rank, tuple(ev.ranks) if ev.ranks else None)
            for ev in self.events
        )

    def rank_signatures(self) -> Dict[int, tuple]:
        """Per-rank fault signature for rank-symmetry reduction: two
        ranks with different signatures must land in different classes
        (``simulator/reduce.py`` colors on this), so a fault shatters
        exactly the symmetry it breaks — globally-scoped link events
        perturb every group of a dim identically and shatter nothing."""
        sigs: Dict[int, List[tuple]] = {}
        for ev in self.events:
            targets: Sequence[int] = ()
            if ev.rank is not None:
                targets = (ev.rank,)
            elif ev.kind == "link_degradation" and ev.ranks is not None:
                targets = ev.ranks
            for r in targets:
                sigs.setdefault(r, []).append(ev.signature())
        return {r: tuple(sorted(s)) for r, s in sigs.items()}


# --------------------------------------------------------------------------
# Engine-facing fault model (one step window, times in SECONDS)
# --------------------------------------------------------------------------


def key_dim(key) -> Optional[str]:
    """Collective dim of an engine rendezvous key. Keys are either
    ``(dim, group)`` tuples (leaf collectives), strings like
    ``"grad_rs:dp_cp"`` / ``"param_ag:edp"`` (bucketed DP streams and
    their async-stream names), or ``"optimizer_barrier"``. Shared with
    the critical-path engine (``observe/critpath.py``), which blames
    exposed rendezvous time onto the same dims the fault model scales."""
    if isinstance(key, tuple):
        key = key[0]
    if not isinstance(key, str):
        return None
    return key.rsplit(":", 1)[-1] if ":" in key else key


#: backwards-compatible private alias (pre-critpath internal name)
_key_dim = key_dim


class StepFaultModel:
    """The engine's consult-at-service-time view of a scenario, scoped
    to one simulated step. All times are seconds relative to the step
    start. ``rank_map`` translates engine ranks to global ranks when
    the engine runs one representative per symmetry class."""

    def __init__(self, scenario: FaultScenario,
                 rank_map: Optional[Sequence[int]] = None):
        self.scenario = scenario
        self._map = list(rank_map) if rank_map is not None else None
        #: global rank -> [(start_s, end_s, multiplier)]; multiplier
        #: math.inf encodes a preemption freeze (progress rate 0)
        self._slow: Dict[int, List[Tuple[float, float, float]]] = {}
        #: (dim, start_s, end_s, multiplier, scope frozenset | None)
        self._links: List[Tuple[str, float, float, float,
                                Optional[frozenset]]] = []
        #: global rank -> earliest death time (s)
        self._deaths: Dict[int, float] = {}
        for ev in scenario.events:
            s = ev.start_ms * 1e-3
            e = ev.end_ms * 1e-3 if math.isfinite(ev.end_ms) else math.inf
            if ev.kind == "slowdown":
                self._slow.setdefault(ev.rank, []).append(
                    (s, e, ev.multiplier)
                )
            elif ev.kind == "preemption":
                self._slow.setdefault(ev.rank, []).append((s, e, math.inf))
            elif ev.kind == "link_degradation":
                scope = (frozenset(ev.ranks)
                         if ev.ranks is not None else None)
                self._links.append((ev.dim, s, e, ev.multiplier, scope))
            elif ev.kind == "rank_death":
                prev = self._deaths.get(ev.rank)
                self._deaths[ev.rank] = s if prev is None else min(prev, s)
        for wins in self._slow.values():
            wins.sort()

    def _g(self, engine_rank: int) -> int:
        return self._map[engine_rank] if self._map is not None \
            else engine_rank

    def death_time(self, engine_rank: int) -> Optional[float]:
        return self._deaths.get(self._g(engine_rank))

    @property
    def has_deaths(self) -> bool:
        return bool(self._deaths)

    def compute_end(self, engine_rank: int, start: float,
                    duration: float) -> float:
        """Wall end time of ``duration`` seconds of work starting at
        ``start`` under this rank's piecewise slowdown windows
        (progress rate ``1/Π multipliers`` of the active windows, 0
        while preempted)."""
        wins = self._slow.get(self._g(engine_rank))
        if not wins or duration <= 0:
            return start + duration
        edges = sorted({x for w in wins for x in w[:2]
                        if math.isfinite(x) and x > start})
        t, work = start, duration
        ei = 0
        while True:
            mult = 1.0
            for (s, e, m) in wins:
                if s <= t < e:
                    mult = math.inf if m == math.inf else mult * m
            while ei < len(edges) and edges[ei] <= t:
                ei += 1
            nxt = edges[ei] if ei < len(edges) else math.inf
            if mult == math.inf:
                # frozen: no progress until the window closes (finite
                # by validation)
                t = nxt
                continue
            need = work * mult
            if t + need <= nxt:
                return t + need
            work -= (nxt - t) / mult
            t = nxt

    def comm_scale(self, key, engine_peers: Sequence[int],
                   t: float) -> float:
        """Comm-time multiplier of one rendezvous/p2p op at service
        time ``t``: the product of active link windows matching its dim
        whose scope (if any) intersects the participating ranks."""
        if not self._links:
            return 1.0
        dim = _key_dim(key)
        m = 1.0
        for (d, s, e, mult, scope) in self._links:
            if not s <= t < e:
                continue
            if d != "*" and d != dim:
                continue
            if scope is not None and not any(
                self._g(p) in scope for p in engine_peers
            ):
                continue
            m *= mult
        return m


@dataclass
class FaultOutcome:
    """Structured result of a faulted simulation: whether the step
    completed, who died when, how much was injected."""

    applied_events: int
    completed: bool
    deaths: List[Dict[str, float]] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": "simumax-fault-outcome-v1",
            "applied_events": self.applied_events,
            "completed": self.completed,
            "deaths": list(self.deaths),
        }


# --------------------------------------------------------------------------
# Checkpoint / restore cost model
# --------------------------------------------------------------------------


@dataclass
class CheckpointSpec:
    """Checkpointing policy knobs (overridable per scenario via
    ``FaultScenario.checkpoint``)."""

    #: write a checkpoint every N committed steps
    interval_steps: int = 50
    #: failure detection + rescheduling + process restart + re-init,
    #: before the restore read begins
    restart_overhead_s: float = 120.0
    #: bandwidth overrides (GB/s per chip); None = derive from
    #: ``SystemConfig.host``
    write_gbps: Optional[float] = None
    read_gbps: Optional[float] = None

    @classmethod
    def from_overrides(cls, overrides: Optional[Dict[str, Any]],
                       base: Optional["CheckpointSpec"] = None
                       ) -> "CheckpointSpec":
        spec = base or cls()
        if not overrides:
            return spec
        known = {f for f in cls.__dataclass_fields__}  # type: ignore[attr-defined]
        extra = set(overrides) - known
        if extra:
            raise ConfigError(
                f"unknown checkpoint fields {sorted(extra)} "
                f"(known: {sorted(known)})", phase="simulate",
            )
        kw = {f: getattr(spec, f) for f in known}
        kw.update(overrides)
        out = cls(**kw)
        if out.interval_steps < 1:
            raise ConfigError(
                f"checkpoint interval_steps must be >= 1, got "
                f"{out.interval_steps}", phase="simulate",
            )
        return out


@dataclass
class CheckpointCostModel:
    """Per-rank checkpoint write / restore read times.

    The checkpointed state per rank is its weights + optimizer state
    (gradients are not checkpointed). The write streams HBM → host
    (``host.d2h_gbps``) → persistent storage / DCN
    (``host.ckpt_write_gbps``); pipelined streaming is bound by the
    slowest stage of the chain (HBM read bandwidth included for
    completeness — it never binds on real parts), plus a fixed
    commit/barrier latency. Restore is the reverse chain with the read
    bandwidths."""

    bytes_per_rank: float
    write_s: float
    read_s: float
    spec: CheckpointSpec

    @classmethod
    def from_perf(cls, perf,
                  spec: Optional[CheckpointSpec] = None
                  ) -> "CheckpointCostModel":
        spec = spec or CheckpointSpec()
        mem = perf.analysis_mem()
        nbytes = max(
            s["weight_bytes"] + s["optimizer_state_bytes"]
            for s in mem["stages"]
        )
        host = perf.system.host
        hbm = perf.system.accelerator.bandwidth["default"].gbps
        write_bw = spec.write_gbps or min(
            hbm, host.d2h_gbps, host.ckpt_write_gbps
        )
        read_bw = spec.read_gbps or min(
            hbm, host.d2h_gbps, host.ckpt_read_gbps
        )
        return cls(
            bytes_per_rank=nbytes,
            write_s=nbytes / (write_bw * 1e9) + host.latency_s,
            read_s=nbytes / (read_bw * 1e9) + host.latency_s,
            spec=spec,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "bytes_per_rank": self.bytes_per_rank,
            "write_s": self.write_s,
            "read_s": self.read_s,
            "interval_steps": self.spec.interval_steps,
            "restart_overhead_s": self.spec.restart_overhead_s,
        }


# --------------------------------------------------------------------------
# Goodput prediction
# --------------------------------------------------------------------------


@dataclass
class GoodputReport:
    """Wall-time decomposition of a scenario over ``horizon_steps``
    training steps. ``buckets`` sum to ``wall_time_s`` exactly (the
    accounting is constructive); ``goodput = useful_train / wall``."""

    goodput: float
    wall_time_s: float
    useful_time_s: float
    healthy_step_s: float
    horizon_steps: int
    n_checkpoints: int
    n_restarts: int
    steps_replayed: int
    buckets: GoodputBuckets
    deaths: List[Dict[str, float]]
    checkpoint: Dict[str, Any]
    truncated: bool = False

    def to_dict(self) -> Dict[str, Any]:
        return {
            "schema": "simumax-goodput-v1",
            "goodput": self.goodput,
            "wall_time_s": self.wall_time_s,
            "useful_time_s": self.useful_time_s,
            "healthy_step_s": self.healthy_step_s,
            "horizon_steps": self.horizon_steps,
            "n_checkpoints": self.n_checkpoints,
            "n_restarts": self.n_restarts,
            "steps_replayed": self.steps_replayed,
            "buckets": self.buckets.to_dict(),
            "deaths": list(self.deaths),
            "checkpoint": dict(self.checkpoint),
            "truncated": self.truncated,
        }


def _simulate_step(perf, sub: FaultScenario,
                   cache: Dict[tuple, Tuple[float, Optional[float]]],
                   granularity: str, reduce) -> Tuple[float, Optional[float]]:
    """(wall duration, death time | None) of one step under the
    re-based sub-scenario ``sub``; death times arrive in the same
    straggler-inflated wall base as ``end_time``."""
    from simumax_tpu.simulator.runner import run_simulation

    key = sub.signature()
    hit = cache.get(key)
    if hit is not None:
        return hit
    res = run_simulation(
        perf, None, granularity=granularity, world_ranks=True,
        reduce=reduce, faults=sub,
    )
    deaths = res["faults"]["deaths"]
    if deaths:
        t_death = min(d["time_ms"] for d in deaths) * 1e-3
        out = (t_death, t_death)
    else:
        out = (res["end_time"], None)
    cache[key] = out
    return out


def predict_goodput(
    perf,
    scenario: FaultScenario,
    spec: Optional[CheckpointSpec] = None,
    granularity: str = "chunk",
    reduce="auto",
    max_restarts: int = 1000,
    _cache: Optional[Dict[tuple, Tuple[float, Optional[float]]]] = None,
) -> GoodputReport:
    """Predict goodput of ``scenario`` over its ``horizon_steps``.

    Walks job wall-clock step by step: each step's duration comes from
    a discrete-event simulation with the scenario's events re-based
    onto the step window (steps no event touches reuse the fault-free
    step, so only perturbed steps pay for a simulation); every
    ``interval_steps`` committed steps a checkpoint write is charged; a
    rank death aborts the step, rolls uncommitted progress back to the
    last checkpoint (its wall time becomes ``restart_replay``), and
    charges restart overhead + restore read before training resumes.
    """
    scenario.validate(perf.strategy.world_size)
    from simumax_tpu.simulator.runner import run_simulation

    # an explicitly passed spec wins outright (a CLI flag must beat
    # the scenario's bundled default, not the other way round); the
    # scenario's "checkpoint" block only fills in when none is given
    if spec is None:
        spec = CheckpointSpec.from_overrides(scenario.checkpoint)
    ckpt = CheckpointCostModel.from_perf(perf, spec)
    healthy = run_simulation(
        perf, None, granularity=granularity, world_ranks=True,
        reduce=reduce,
    )
    h = healthy["end_time"]
    horizon = scenario.horizon_steps
    interval = spec.interval_steps
    cache = _cache if _cache is not None else {}
    b = GoodputBuckets()
    wall = 0.0
    committed = 0
    ckpt_committed = 0
    n_ckpt = n_restart = replayed = 0
    #: (healthy_part, stall_part) of steps committed since the last
    #: checkpoint — rolled into restart_replay on a death
    uncommitted: List[Tuple[float, float]] = []
    deaths: List[Dict[str, float]] = []
    truncated = False

    def first_death_in(t0_s: float, t1_s: float) -> Optional[float]:
        """Earliest rank-death absolute time inside [t0, t1)."""
        times = [
            ev.start_ms * 1e-3 for ev in scenario.events
            if ev.kind == "rank_death"
            and t0_s <= ev.start_ms * 1e-3 < t1_s
        ]
        return min(times) if times else None

    def restart(abort_wall_s: float, extra_lost_s: float):
        """Roll uncommitted progress back to the last checkpoint and
        charge the recovery sequence. ``extra_lost_s`` is wall time of
        the aborted partial step / checkpoint write."""
        nonlocal wall, committed, n_restart, replayed, uncommitted
        deaths.append({
            "wall_time_s": abort_wall_s,
            "lost_steps": committed - ckpt_committed,
        })
        for (hp, sp) in uncommitted:
            b.useful_train -= hp
            b.fault_stall -= sp
            b.restart_replay += hp + sp
        replayed += len(uncommitted)
        b.restart_replay += extra_lost_s
        committed = ckpt_committed
        uncommitted = []
        wall = abort_wall_s + spec.restart_overhead_s + ckpt.read_s
        b.restart_overhead += spec.restart_overhead_s
        b.restore_read += ckpt.read_s
        n_restart += 1

    while committed < horizon:
        # fixpoint window growth: a step stretched by faults may pull
        # later events into its window
        span = h
        dur, death = h, None
        for _ in range(8):
            sub = scenario.shifted(wall * 1e3, span * 1e3)
            if sub.empty:
                dur, death = h, None
                break
            dur, death = _simulate_step(
                perf, sub, cache, granularity, reduce
            )
            if death is not None or dur <= span * (1 + 1e-12):
                break
            span = dur
        if death is None:
            wall += dur
            b.useful_train += h
            b.fault_stall += dur - h
            uncommitted.append((h, dur - h))
            committed += 1
            if committed % interval == 0 and committed < horizon:
                # a rank death during the checkpoint write still kills
                # the job — and the interrupted write never commits
                t_d = first_death_in(wall, wall + ckpt.write_s)
                if t_d is not None:
                    restart(t_d, t_d - wall)
                    if n_restart >= max_restarts:
                        truncated = True
                        break
                    continue
                wall += ckpt.write_s
                b.checkpoint_write += ckpt.write_s
                n_ckpt += 1
                ckpt_committed = committed
                uncommitted = []
        else:
            # committed-but-uncheckpointed steps are lost: their wall
            # time (healthy + stall) turns into replay, plus the
            # aborted partial step
            restart(wall + death, death)
            if n_restart >= max_restarts:
                truncated = True
                break
    useful = b.useful_train
    return GoodputReport(
        goodput=(useful / wall) if wall > 0 else 1.0,
        wall_time_s=wall,
        useful_time_s=useful,
        healthy_step_s=h,
        horizon_steps=horizon,
        n_checkpoints=n_ckpt,
        n_restarts=n_restart,
        steps_replayed=replayed,
        buckets=b,
        deaths=deaths,
        checkpoint=ckpt.to_dict(),
        truncated=truncated,
    )


# --------------------------------------------------------------------------
# Monte-Carlo sampling
# --------------------------------------------------------------------------


def sample_scenario(
    rng: random.Random,
    world_size: int,
    horizon_ms: float,
    *,
    horizon_steps: int = 100,
    max_events: int = 6,
    death_prob: float = 0.3,
    seed: Optional[int] = None,
) -> FaultScenario:
    """One random-but-seeded fault scenario: a mix of slowdown windows,
    preemptions, scoped/unscoped link degradations, and (with
    ``death_prob``) rank deaths, all inside ``[0, horizon_ms)``."""
    events: List[FaultEvent] = []
    n = rng.randint(0, max_events)
    for _ in range(n):
        kind = rng.choice(("slowdown", "preemption", "link_degradation"))
        start = rng.uniform(0.0, horizon_ms * 0.9)
        dur = rng.uniform(horizon_ms * 0.005, horizon_ms * 0.25)
        if kind == "slowdown":
            events.append(FaultEvent(
                "slowdown", start_ms=start, duration_ms=dur,
                rank=rng.randrange(world_size),
                multiplier=rng.uniform(1.05, 5.0),
            ))
        elif kind == "preemption":
            events.append(FaultEvent(
                "preemption", start_ms=start,
                duration_ms=rng.uniform(horizon_ms * 0.002,
                                        horizon_ms * 0.05),
                rank=rng.randrange(world_size),
            ))
        else:
            scope = None
            if rng.random() < 0.5:
                k = rng.randint(1, max(1, min(4, world_size)))
                scope = sorted(rng.sample(range(world_size), k))
            events.append(FaultEvent(
                "link_degradation", start_ms=start, duration_ms=dur,
                dim=rng.choice(("tp", "pp", "dp_cp", "*")),
                multiplier=rng.uniform(1.1, 8.0), ranks=scope,
            ))
    if rng.random() < death_prob:
        events.append(FaultEvent(
            "rank_death", start_ms=rng.uniform(0.0, horizon_ms * 0.9),
            rank=rng.randrange(world_size),
        ))
    return FaultScenario(events=events, horizon_steps=horizon_steps,
                         seed=seed)


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return float("nan")
    idx = min(len(sorted_vals) - 1,
              max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


def analyze_faults(
    perf,
    n_scenarios: int = 32,
    seed: int = 0,
    horizon_steps: int = 50,
    spec: Optional[CheckpointSpec] = None,
    intervals: Optional[Sequence[int]] = None,
    granularity: str = "chunk",
    reduce="auto",
    max_events: int = 6,
    death_prob: float = 0.3,
) -> Dict[str, Any]:
    """Seeded Monte-Carlo goodput analysis: sample ``n_scenarios``
    random scenarios, predict each one's goodput, and sweep checkpoint
    intervals to find the empirically optimal one (reported next to
    the Young–Daly closed form ``sqrt(2 * write_time * MTBF)``).
    Deterministic for a given seed."""
    from simumax_tpu.simulator.runner import run_simulation

    spec = spec or CheckpointSpec()
    st = perf.strategy
    healthy = run_simulation(
        perf, None, granularity=granularity, world_ranks=True,
        reduce=reduce,
    )
    h = healthy["end_time"]
    # sample against the rough job wall (healthy horizon + slack so
    # late-run faults land inside the actual, stretched wall-clock)
    horizon_ms = horizon_steps * h * 1e3 * 1.25
    rng = random.Random(seed)
    scenarios = [
        sample_scenario(
            rng, st.world_size, horizon_ms, horizon_steps=horizon_steps,
            max_events=max_events, death_prob=death_prob, seed=seed,
        )
        for _ in range(n_scenarios)
    ]
    cache: Dict[tuple, Tuple[float, Optional[float]]] = {}
    reports = [
        predict_goodput(perf, s, spec=spec, granularity=granularity,
                        reduce=reduce, _cache=cache)
        for s in scenarios
    ]
    goodputs = sorted(r.goodput for r in reports)
    n_interrupts = sum(r.n_restarts for r in reports)
    total_wall = sum(r.wall_time_s for r in reports)
    mtbf = (total_wall / n_interrupts) if n_interrupts else math.inf
    ckpt = CheckpointCostModel.from_perf(perf, spec)
    if math.isfinite(mtbf):
        yd_interval = max(
            1, int(round(math.sqrt(2.0 * ckpt.write_s * mtbf) / h))
        )
    else:
        yd_interval = horizon_steps
    if intervals is None:
        grid = sorted({
            max(1, horizon_steps // 16), max(1, horizon_steps // 8),
            max(1, horizon_steps // 4), max(1, horizon_steps // 2),
            horizon_steps, min(yd_interval, horizon_steps),
        })
        intervals = grid
    by_interval: Dict[int, float] = {}
    for k in intervals:
        k_spec = CheckpointSpec(
            interval_steps=int(k),
            restart_overhead_s=spec.restart_overhead_s,
            write_gbps=spec.write_gbps, read_gbps=spec.read_gbps,
        )
        vals = [
            predict_goodput(perf, s, spec=k_spec, granularity=granularity,
                            reduce=reduce, _cache=cache).goodput
            for s in scenarios
        ]
        by_interval[int(k)] = sum(vals) / len(vals) if vals else 1.0
    best_interval = max(by_interval, key=lambda k: (by_interval[k], -k))
    return {
        "schema": "simumax-fault-analysis-v1",
        "seed": seed,
        "n_scenarios": n_scenarios,
        "horizon_steps": horizon_steps,
        "healthy_step_s": h,
        "goodput": {
            "mean": sum(goodputs) / len(goodputs) if goodputs else 1.0,
            "min": goodputs[0] if goodputs else 1.0,
            "max": goodputs[-1] if goodputs else 1.0,
            "p10": _quantile(goodputs, 0.10),
            "p50": _quantile(goodputs, 0.50),
            "p90": _quantile(goodputs, 0.90),
        },
        "restarts_total": n_interrupts,
        "mtbf_s": mtbf,
        "checkpoint": ckpt.to_dict(),
        "goodput_by_interval": by_interval,
        "best_interval_steps": best_interval,
        "young_daly_interval_steps": yd_interval,
        "reports": [r.to_dict() for r in reports],
    }


__all__ = [
    "EVENT_KINDS",
    "LINK_DIMS",
    "FaultEvent",
    "FaultScenario",
    "StepFaultModel",
    "FaultOutcome",
    "CheckpointSpec",
    "CheckpointCostModel",
    "GoodputReport",
    "predict_goodput",
    "sample_scenario",
    "analyze_faults",
]

"""Rank-local allocated-memory timeline for the event simulator.

Reference: ``simumax/core/simu_memory.py`` (``SimuMemoryTracker``: token
lifetimes with strict size checking, Chrome counter events, snapshot
records). The torch ``memory_viz`` pickle export is GPU-tooling-specific
and is replaced by a plain JSON snapshot (schema
``simumax_tpu_memory_snapshot_v1``) consumable by any plotting tool.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional


@dataclass
class MemSample:
    t: float
    bytes: float
    tag: str = ""


class SimuMemoryTracker:
    """Strict token-based alloc/free tracking (reference
    ``simu_memory.py:65-127``): every cache allocation is a token that
    must be freed exactly once with the same size."""

    def __init__(self, rank: int, static_bytes: float = 0.0):
        self.rank = rank
        self.static_bytes = static_bytes
        self.cur = static_bytes
        self.peak = static_bytes
        self.peak_time = 0.0
        self.timeline: List[MemSample] = [MemSample(0.0, static_bytes, "static")]
        self._tokens: Dict[str, List[float]] = {}
        #: running live-bytes total per token / anon-tag (kept
        #: incrementally so peak capture is not quadratic)
        self._live: Dict[str, float] = {}
        #: live set captured at the recorded peak — the per-token
        #: attribution the reference's memory-viz pickle carries
        #: (``simu_memory.py:212-556``), as plain data. Copied lazily:
        #: while the peak keeps rising only a flag flips; the O(live)
        #: copy happens once, when the plateau ends.
        self.peak_holders: Dict[str, float] = {}
        self._peak_pending = False

    def _flush_peak(self):
        self.peak_holders = {k: v for k, v in self._live.items() if v}
        self._peak_pending = False

    def alloc(self, t: float, nbytes: float, token: Optional[str] = None,
              tag: str = ""):
        if nbytes == 0:
            return
        assert nbytes > 0, f"negative alloc {nbytes}"
        if token is not None:
            self._tokens.setdefault(token, []).append(nbytes)
            key = token
        else:
            key = f"<{tag or 'anon'}>"
        self._live[key] = self._live.get(key, 0.0) + nbytes
        self.cur += nbytes
        if self.cur > self.peak:
            self.peak = self.cur
            self.peak_time = t
            self._peak_pending = True
        self.timeline.append(MemSample(t, self.cur, tag))

    def free(self, t: float, nbytes: float = 0.0,
             token: Optional[str] = None, tag: str = ""):
        if self._peak_pending:
            self._flush_peak()  # the live set still equals the peak set
        if token is not None:
            fifo = self._tokens.get(token)
            if not fifo:
                raise RuntimeError(
                    f"rank {self.rank}: free of unknown token {token!r}"
                )
            expect = fifo.pop(0)
            if nbytes and abs(expect - nbytes) > 1:
                raise RuntimeError(
                    f"rank {self.rank}: token {token!r} size mismatch: "
                    f"allocated {expect}, freeing {nbytes}"
                )
            nbytes = expect
            key = token
        else:
            key = f"<{tag or 'anon'}>"
        self._live[key] = max(self._live.get(key, 0.0) - nbytes, 0.0)
        if nbytes == 0:
            return
        self.cur -= nbytes
        if self.cur < self.static_bytes - 1:
            raise RuntimeError(
                f"rank {self.rank}: memory underflow at t={t}: "
                f"{self.cur} < static {self.static_bytes}"
            )
        self.timeline.append(MemSample(t, self.cur, tag))

    def outstanding_tokens(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self._tokens.items() if v}

    @staticmethod
    def _category(token: str) -> str:
        """Collapse a live token to its op category: drop the
        ``mb<N>:`` microbatch prefix and the ``#<id>`` uniquifier, so
        the same leaf across microbatches aggregates into one row."""
        cat = token.split(":", 1)[-1] if token.startswith("mb") else token
        return cat.split("#", 1)[0]

    def peak_by_category(self, top: int = 0) -> Dict[str, float]:
        """Who holds the memory at the recorded peak, rolled up by op
        category (plus ``<static>``); sorted descending, optionally
        truncated to the ``top`` largest with a ``<rest>`` remainder."""
        if self._peak_pending:
            self._flush_peak()
        cats: Dict[str, float] = {}
        if self.static_bytes:
            cats["<static>"] = self.static_bytes
        for token, nbytes in self.peak_holders.items():
            key = self._category(token)
            cats[key] = cats.get(key, 0.0) + nbytes
        items = sorted(cats.items(), key=lambda kv: -kv[1])
        if top and len(items) > top:
            rest = sum(v for _, v in items[top:])
            items = items[:top] + [("<rest>", rest)]
        return dict(items)

    def summary(self) -> dict:
        return {
            "rank": self.rank,
            "static_bytes": self.static_bytes,
            "peak_bytes": self.peak,
            "peak_gib": self.peak / 2**30,
            "peak_time_ms": self.peak_time * 1e3,
            "end_bytes": self.cur,
            "samples": len(self.timeline),
            "peak_by_category": self.peak_by_category(top=8),
        }

    def snapshot(self) -> dict:
        if self._peak_pending:
            self._flush_peak()
        return {
            "schema": "simumax_tpu_memory_snapshot_v1",
            "rank": self.rank,
            "static_bytes": self.static_bytes,
            "peak_by_category": self.peak_by_category(),
            "peak_holders": dict(
                sorted(self.peak_holders.items(), key=lambda kv: -kv[1])
            ),
            "timeline": [
                {"t_ms": s.t * 1e3, "bytes": s.bytes, "tag": s.tag}
                for s in self.timeline
            ],
        }

"""Rank-local allocated-memory timeline for the event simulator.

Reference: ``simumax/core/simu_memory.py`` (``SimuMemoryTracker``: token
lifetimes with strict size checking, Chrome counter events, snapshot
records, and a ``torch.cuda.memory._snapshot()``-compatible pickle for
the memory-viz web tool, ``simu_memory.py:212-556``). Both exports ship
here: a plain JSON snapshot (schema ``simumax_tpu_memory_snapshot_v1``)
for any plotting tool, and :func:`memory_viz_snapshot` producing the
torch memory-viz trace format (load the pickle at pytorch.org/memory_viz
— each simulated token appears as an alloc/free pair whose stack frame
carries the op path, so the "Active Memory Timeline" view shows
per-op attribution over virtual time).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

from simumax_tpu.core.errors import SimulationError


@dataclass(slots=True)
class MemSample:
    """Slotted: world-scale timelines hold one of these per alloc/free
    event, and the per-instance ``__dict__`` was pure overhead."""

    t: float
    bytes: float
    tag: str = ""


class SimuMemoryTracker:
    """Strict token-based alloc/free tracking (reference
    ``simu_memory.py:65-127``): every cache allocation is a token that
    must be freed exactly once with the same size."""

    def __init__(self, rank: int, static_bytes: float = 0.0,
                 record_events: bool = True, source: str = "simulated"):
        self.rank = rank
        self.static_bytes = static_bytes
        #: which predictor produced this timeline: ``"simulated"`` (the
        #: discrete-event engine) or ``"analytical"`` (the schedule
        #: replay exported by ``observe/memledger.py``) — both ship the
        #: same snapshot schema so the two predictions diff directly
        self.source = source
        #: keep the per-event alloc/free trace for the memory-viz
        #: export; runs that will never export (no save_path) disable
        #: it to skip the dead per-event work
        self.record_events = record_events
        self.cur = static_bytes
        self.peak = static_bytes
        self.peak_time = 0.0
        self.timeline: List[MemSample] = [MemSample(0.0, static_bytes, "static")]
        self._tokens: Dict[str, List[float]] = {}
        #: running live-bytes total per token / anon-tag (kept
        #: incrementally so peak capture is not quadratic)
        self._live: Dict[str, float] = {}
        #: live set captured at the recorded peak — the per-token
        #: attribution the reference's memory-viz pickle carries
        #: (``simu_memory.py:212-556``), as plain data. Copied lazily:
        #: while the peak keeps rising only a flag flips; the O(live)
        #: copy happens once, when the plateau ends.
        self.peak_holders: Dict[str, float] = {}
        self._peak_pending = False
        #: per-event trace for the memory-viz export: ("alloc"|"free",
        #: t, nbytes, key, addr). Addresses come from a virtual bump
        #: allocator so the viz tool can pair alloc/free events.
        self.events: List[tuple] = []
        self._next_addr = 1 << 20
        self._addr_fifo: Dict[str, List[tuple]] = {}
        if static_bytes and record_events:
            self.events.append(("alloc", 0.0, static_bytes, "<static>", 0))

    def _flush_peak(self):
        self.peak_holders = {k: v for k, v in self._live.items() if v}
        self._peak_pending = False

    def alloc(self, t: float, nbytes: float, token: Optional[str] = None,
              tag: str = ""):
        if nbytes == 0:
            return
        assert nbytes > 0, f"negative alloc {nbytes}"
        if token is not None:
            self._tokens.setdefault(token, []).append(nbytes)
            key = token
        else:
            key = f"<{tag or 'anon'}>"
        self._live[key] = self._live.get(key, 0.0) + nbytes
        if self.record_events:
            addr = self._next_addr
            self._next_addr += int(nbytes)
            self._addr_fifo.setdefault(key, []).append((addr, nbytes))
            self.events.append(("alloc", t, nbytes, key, addr))
        self.cur += nbytes
        if self.cur > self.peak:
            self.peak = self.cur
            self.peak_time = t
            self._peak_pending = True
        self.timeline.append(MemSample(t, self.cur, tag))

    def free(self, t: float, nbytes: float = 0.0,
             token: Optional[str] = None, tag: str = ""):
        if self._peak_pending:
            self._flush_peak()  # the live set still equals the peak set
        if token is not None:
            fifo = self._tokens.get(token)
            if not fifo:
                raise SimulationError(
                    f"rank {self.rank}: free of unknown token {token!r}"
                )
            expect = fifo.pop(0)
            if nbytes and abs(expect - nbytes) > 1:
                raise SimulationError(
                    f"rank {self.rank}: token {token!r} size mismatch: "
                    f"allocated {expect}, freeing {nbytes}"
                )
            nbytes = expect
            key = token
        else:
            key = f"<{tag or 'anon'}>"
        self._live[key] = max(self._live.get(key, 0.0) - nbytes, 0.0)
        if nbytes == 0:
            return
        if self.record_events:
            fifo = self._addr_fifo.get(key)
            addr = fifo.pop(0)[0] if fifo else 0
            self.events.append(("free", t, nbytes, key, addr))
        self.cur -= nbytes
        if self.cur < self.static_bytes - 1:
            raise SimulationError(
                f"rank {self.rank}: memory underflow at t={t}: "
                f"{self.cur} < static {self.static_bytes}"
            )
        self.timeline.append(MemSample(t, self.cur, tag))

    def outstanding_tokens(self) -> Dict[str, int]:
        return {k: len(v) for k, v in self._tokens.items() if v}

    @staticmethod
    def _category(token: str) -> str:
        """Collapse a live token to its op category: drop the
        ``mb<N>:`` microbatch prefix and the ``#<id>`` uniquifier, so
        the same leaf across microbatches aggregates into one row."""
        cat = token.split(":", 1)[-1] if token.startswith("mb") else token
        return cat.split("#", 1)[0]

    def peak_by_category(self, top: int = 0) -> Dict[str, float]:
        """Who holds the memory at the recorded peak, rolled up by op
        category (plus ``<static>``); sorted descending, optionally
        truncated to the ``top`` largest with a ``<rest>`` remainder."""
        if self._peak_pending:
            self._flush_peak()
        cats: Dict[str, float] = {}
        if self.static_bytes:
            cats["<static>"] = self.static_bytes
        for token, nbytes in self.peak_holders.items():
            key = self._category(token)
            cats[key] = cats.get(key, 0.0) + nbytes
        items = sorted(cats.items(), key=lambda kv: -kv[1])
        if top and len(items) > top:
            rest = sum(v for _, v in items[top:])
            items = items[:top] + [("<rest>", rest)]
        return dict(items)

    def summary(self) -> dict:
        return {
            "rank": self.rank,
            "source": self.source,
            "static_bytes": self.static_bytes,
            "peak_bytes": self.peak,
            "peak_gib": self.peak / 2**30,
            "peak_time_ms": self.peak_time * 1e3,
            "end_bytes": self.cur,
            "samples": len(self.timeline),
            "peak_by_category": self.peak_by_category(top=8),
        }

    def snapshot(self) -> dict:
        if self._peak_pending:
            self._flush_peak()
        return {
            "schema": "simumax_tpu_memory_snapshot_v1",
            "rank": self.rank,
            "source": self.source,
            "static_bytes": self.static_bytes,
            "peak_by_category": self.peak_by_category(),
            "peak_holders": dict(
                sorted(self.peak_holders.items(), key=lambda kv: -kv[1])
            ),
            "timeline": [
                {"t_ms": s.t * 1e3, "bytes": s.bytes, "tag": s.tag}
                for s in self.timeline
            ],
        }


def memory_viz_snapshot(tracker: SimuMemoryTracker) -> dict:
    """Convert a tracker's event trace into the
    ``torch.cuda.memory._snapshot()`` structure the PyTorch memory-viz
    web tool loads (reference parity: ``simu_memory.py:212-556``).

    Each simulated allocation becomes an ``alloc`` /``free_completed``
    pair; the op path (token category) is encoded as the top stack
    frame, phase (fwd/bwd/recompute tags come through the token text)
    as ``filename``, so the Active Memory Timeline colors by op.
    Virtual time (seconds) is exported as integer microseconds.
    """
    trace = []
    for action, t, nbytes, key, addr in tracker.events:
        cat = SimuMemoryTracker._category(key)
        trace.append({
            "action": "alloc" if action == "alloc" else "free_completed",
            "addr": int(addr),
            "size": int(nbytes),
            "stream": 0,
            "time_us": int(t * 1e6),
            "frames": [{
                "name": cat,
                "filename": key,
                "line": 0,
            }],
        })
    return {
        "segments": [],
        "device_traces": [trace],
    }


def export_memory_viz(tracker: SimuMemoryTracker, path: str) -> str:
    """Write the memory-viz pickle (open at pytorch.org/memory_viz)."""
    import pickle

    snap = memory_viz_snapshot(tracker)
    with open(path, "wb") as f:
        pickle.dump(snap, f)
    return path

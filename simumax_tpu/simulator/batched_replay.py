"""Batched scenario replay: the reduced DES lowered into a vmapped
JAX array program (ROADMAP open item 4, the raw-speed refactor behind
``replay_backend="jax"``).

PR 14's incremental replay already collapsed Monte-Carlo fault analysis
onto a small set of *step-program families* (one recorded per-class
request stream per touched-rank partition) and answered 81-98%% of
steps from caches — but every remaining miss still walked the Python
event loop of :class:`simulator.engine.SimuEngine` one request at a
time. This module compiles a family's recorded streams ONCE into a
fixed-shape array program and replays all of a Monte-Carlo round's
cache misses in a single compiled call:

* :func:`lower_family` runs a symbolic (time-free) scheduler over the
  recorded streams, mirroring the engine's rendezvous / p2p / async
  matching rules, and emits a linear op table in a dependency-valid
  service order. With no rank deaths the engine's values are
  order-independent (every op's outputs are pure functions of its
  inputs — max/+ clock algebra), so ANY valid topological order
  reproduces the scalar engine bit-for-bit; the one order-dependent
  request kind (``sendrecv``) is a justified fallback, not lowered.
* :func:`solve_batch` evaluates the op table as a ``jax.lax.scan``
  over op index — rendezvous joins as masked segment-max, compute ops
  as the exact piecewise slowdown integration of
  ``StepFaultModel.compute_end``, link degradations as an ordered
  product over the scenario's event-ordered link windows — vmapped
  over the scenario batch and jitted under ``enable_x64``.
* Compiled programs are cached by PADDED shape only (op tables are
  *arguments*, not closure constants), so every family whose padded
  dimensions agree shares one XLA executable — the PR 11 compile-cache
  discipline at family granularity.

The scalar engine remains the bit-identity oracle: batched makespans
feed the same ``(raw_end * straggle_ratio, None, raw_end)`` tail as
``ReplayContext._replay``, and ``tests/test_batched_replay.py`` pins
byte-equality of whole ``GoodputReport``/fleet reports across the
chaos grid. Scenarios that cannot lower fall back per-scenario to the
scalar engine with a counted reason (``FALLBACK_REASONS``) — never a
whole-batch downgrade.

Determinism: this module is in the SIM003 lint scope — no wall-clock,
no unsorted set iteration; the symbolic scheduler visits ranks in
index order, so the emitted op table is a pure function of the input
streams.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

# --------------------------------------------------------------------------
# Lowering vocabulary (the SIM002-style drift contract)
# --------------------------------------------------------------------------

#: op codes of the array program, scan-dispatched via ``lax.switch``
OP_NOOP = 0          # padding
OP_COMPUTE = 1       # piecewise slowdown integration (compute_end)
OP_ADVANCE_ABS = 2   # clock = max(clock, t)
OP_ADVANCE_REL = 3   # clock = max(clock, clock + delta)
OP_COLL = 4          # sync rendezvous: masked max + link scale
OP_ASYNC_POST = 5    # record poster's clock in a value slot
OP_ASYNC_FINISH = 6  # chained stream op: max(posts, chain) + scale
OP_WAIT_COMM = 7     # clock = max(clock, comm_done)
OP_SEND = 8          # publish post + scaled duration (non-blocking)
OP_SEND_SYNC = 9     # rendezvous send: max(clock, peer recv post)
OP_RECV = 10         # consume a published send

N_OP_KINDS = 11

#: engine request kind -> lowered op kind(s). Every kind the scalar
#: engine's ``_try_serve`` handles MUST appear here or in
#: ``FALLBACK_REQUEST_KINDS`` — drift is a staticcheck finding
#: (SIM008, ``tools/staticcheck/checkers/replay_drift.py``).
LOWERED_REQUEST_KINDS: Dict[str, Tuple[int, ...]] = {
    "compute": (OP_COMPUTE,),
    "advance": (OP_ADVANCE_ABS,),
    "advance_rel": (OP_ADVANCE_REL,),
    "trace": (OP_NOOP,),  # zero-advance visibility span: no state
    "collective": (OP_COLL,),
    "async_collective": (OP_ASYNC_POST, OP_ASYNC_FINISH),
    "wait_comm": (OP_WAIT_COMM,),
    "send": (OP_SEND,),
    "send_sync": (OP_SEND_SYNC,),
    "recv": (OP_RECV,),
}

#: request kinds deliberately NOT lowered, with the justification the
#: drift checker requires. A kind listed here routes the scenario to
#: the scalar engine with a counted fallback reason.
FALLBACK_REQUEST_KINDS: Dict[str, str] = {
    "sendrecv": "completion races the peer's recv consumption "
                "(_sr_done): genuinely service-order-dependent, so no "
                "single static op order reproduces the engine",
}

#: the closed per-scenario fallback-reason catalogue surfaced by
#: ``replay_batch_fallbacks_total{reason}`` and the bench JSON lines
FALLBACK_REASONS = (
    "deaths",          # rank deaths mid-step: kill/abort paths stay scalar
    "sendrecv",        # stream contains an order-dependent sendrecv
    "unknown_kind",    # stream contains a kind outside the vocabulary
    "no_streams",      # family not recorded yet (first sim records)
    "lowering_error",  # symbolic schedule wedged / inconsistent stream
    "jax_unavailable", # no jax at runtime: numpy scalar engine only
    "small_batch",     # auto backend: batch below the dispatch floor
    "backend_numpy",   # replay_backend="numpy" requested
)

#: minimum miss-batch size for ``replay_backend="auto"`` to dispatch
#: the compiled program; below it the XLA dispatch + prep overhead
#: beats the win and the scalar engine stays faster (PR 11 discipline:
#: ``search/batched.py::JIT_GROUP_MIN``, scaled to step-replay cost)
JIT_BATCH_MIN = 2


class LoweringError(Exception):
    """The family's streams cannot lower to an array program; carries
    the counted fallback ``reason``."""

    def __init__(self, reason: str, detail: str = ""):
        self.reason = reason
        super().__init__(f"{reason}: {detail}" if detail else reason)


_JAX: Optional[bool] = None


def jax_available() -> bool:
    """Whether the jax backend can be used (import guarded: the scalar
    engine remains the no-JAX path, so machines without jax keep the
    full fault model)."""
    global _JAX
    if _JAX is None:
        try:
            import jax
            import jax.numpy

            _JAX = jax.numpy is not None
        except Exception:
            _JAX = False
    return _JAX


# --------------------------------------------------------------------------
# Symbolic lowering: recorded streams -> linear op table
# --------------------------------------------------------------------------


@dataclass
class LoweredProgram:
    """The fixed-shape array program of one step-program family."""

    n_classes: int
    reps: Tuple[int, ...]            # class -> representative global rank
    kind: np.ndarray                 # int32 [L]
    rank: np.ndarray                 # int32 [L]
    dur: np.ndarray                  # float64 [L]
    aux: np.ndarray                  # int32 [L] (dst / slot / chain id)
    mask: np.ndarray                 # bool [L, K] rendezvous members
    refs: np.ndarray                 # int32 [L, G] async post slots
    peer_mask: np.ndarray            # bool [L, K] comm-scale scope peers
    op_dim_id: np.ndarray            # int32 [L], -1 = not a comm op
    dim_ids: Dict[str, int]          # collective-dim vocabulary
    n_chains: int                    # async chain slots (V2 length)

    @property
    def n_ops(self) -> int:
        return int(self.kind.shape[0])


def _key_dim_of(key) -> Optional[str]:
    from simumax_tpu.simulator.faults import key_dim

    return key_dim(key)


def lower_family(streams: Sequence[list], plan) -> LoweredProgram:
    """Lower one family's recorded per-class request streams into a
    linear op table.

    Runs a time-free mirror of the engine's matching rules (rendezvous
    seq counters, p2p send/recv seq + post windows, async chains) and
    serves requests in a deterministic lowest-ready-class order. The
    emitted order is *a* valid topological order of the step's event
    DAG; with no deaths the engine's values are order-independent, so
    the array program reproduces the ready-heap schedule bit-for-bit.

    Raises :class:`LoweringError` with a counted reason for streams
    that cannot lower (``sendrecv``, unknown kinds, or a wedged
    symbolic schedule)."""
    k_classes = plan.n_classes
    if len(streams) != k_classes:
        raise LoweringError("lowering_error",
                            f"{len(streams)} streams for {k_classes} "
                            "classes")
    idx = [0] * k_classes
    done = [len(s) == 0 for s in streams]
    coll_seq: Dict[tuple, int] = {}
    send_seq: Dict[tuple, int] = {}
    recv_seq: Dict[tuple, int] = {}
    async_seq: Dict[tuple, int] = {}
    collectives: Dict[tuple, dict] = {}
    sends: Dict[tuple, int] = {}         # skey -> publishing op slot
    recv_posted: set = set()
    async_rv: Dict[tuple, dict] = {}
    async_pending: List[set] = [set() for _ in range(k_classes)]
    chain_ids: Dict[tuple, int] = {}

    kinds: List[int] = []
    ranks: List[int] = []
    durs: List[float] = []
    auxs: List[int] = []
    masks: List[Optional[Tuple[int, ...]]] = []
    refs: List[Optional[Tuple[int, ...]]] = []
    peer_masks: List[Optional[Tuple[int, ...]]] = []
    op_dims: List[Optional[str]] = []

    def emit(op: int, rank: int = 0, dur: float = 0.0, aux: int = 0,
             mask: Optional[Tuple[int, ...]] = None,
             ref: Optional[Tuple[int, ...]] = None,
             peers: Optional[Tuple[int, ...]] = None,
             dim: Optional[str] = None) -> int:
        kinds.append(op)
        ranks.append(rank)
        durs.append(dur)
        auxs.append(aux)
        masks.append(mask)
        refs.append(ref)
        peer_masks.append(peers)
        op_dims.append(dim)
        return len(kinds) - 1

    def serve(r: int) -> bool:
        """Attempt to serve class ``r``'s next request; True when it
        progressed (the request completed and the pointer advanced)."""
        req = streams[r][idx[r]]
        kind = req[0]
        if kind == "compute":
            _, duration, _name, _lane = req
            emit(OP_COMPUTE, rank=r, dur=float(duration))
            return True
        if kind == "advance":
            emit(OP_ADVANCE_ABS, rank=r, dur=float(req[1]))
            return True
        if kind == "advance_rel":
            emit(OP_ADVANCE_REL, rank=r, dur=float(req[1]))
            return True
        if kind == "trace":
            return True  # no clock/state effect under drop_events
        if kind == "collective":
            # seq bookkeeping mirrors the engine exactly: a rank
            # arrives under its CURRENT per-(key, rank) seq, stays
            # blocked until the rendezvous completes, and increments
            # only when it consumes the completed rendezvous — a
            # blocked peer re-served after completion must land on the
            # same ckey, not the next seq slot
            _, key, duration, _name, peers = req
            seq = coll_seq.get((key, r), 0)
            pset = frozenset(peers)
            ckey = (key, pset, seq)
            rv = collectives.get(ckey)
            if rv is None:
                rv = collectives[ckey] = {
                    "arrived": set(), "consumed": set(),
                    "dur": float(duration), "done": False,
                }
            if r not in rv["arrived"]:
                if r not in pset:
                    raise LoweringError(
                        "lowering_error",
                        f"collective {key!r}#{seq}: class {r} not in "
                        f"its own peer list")
                if rv["dur"] != float(duration):
                    raise LoweringError(
                        "lowering_error",
                        f"collective {key!r}#{seq}: mismatched "
                        "durations")
                rv["arrived"].add(r)
                if rv["arrived"] == pset:
                    members = tuple(sorted(pset))
                    emit(OP_COLL, dur=rv["dur"], mask=members,
                         peers=members, dim=_key_dim_of(key))
                    rv["done"] = True
            if not rv["done"]:
                return False  # blocked until the last peer arrives
            coll_seq[(key, r)] = seq + 1
            rv["consumed"].add(r)
            if rv["consumed"] == pset:
                del collectives[ckey]
            return True
        if kind == "async_collective":
            _, stream_name, duration, _name, peers = req
            seq = async_seq.get((stream_name, r), 0)
            async_seq[(stream_name, r)] = seq + 1
            pset = frozenset(peers)
            ckey = (stream_name, pset, seq)
            rv = async_rv.get(ckey)
            if rv is None:
                rv = async_rv[ckey] = {
                    "slots": [], "arrived": set(), "dur": float(duration),
                }
            if r not in pset or rv["dur"] != float(duration):
                raise LoweringError(
                    "lowering_error",
                    f"async {stream_name!r}#{seq}: inconsistent post")
            slot = emit(OP_ASYNC_POST, rank=r)
            rv["slots"].append(slot)
            rv["arrived"].add(r)
            async_pending[r].add(ckey)
            if rv["arrived"] == pset:
                chain_key = (stream_name, pset)
                cid = chain_ids.setdefault(chain_key, len(chain_ids))
                members = tuple(sorted(pset))
                emit(OP_ASYNC_FINISH, dur=rv["dur"], aux=cid,
                     mask=members, ref=tuple(rv["slots"]),
                     peers=members, dim=_key_dim_of(stream_name))
                del async_rv[ckey]
                for p in pset:
                    async_pending[p].discard(ckey)
            return True  # poster never blocks
        if kind == "wait_comm":
            if async_pending[r]:
                return False  # some posted op still waits on peers
            emit(OP_WAIT_COMM, rank=r)
            return True
        if kind == "send":
            _, dst, tag, duration, _name, *_rest = req
            seq = send_seq.get((r, dst, tag), 0)
            send_seq[(r, dst, tag)] = seq + 1
            skey = (r, dst, tag, seq)
            if skey in sends:
                raise LoweringError("lowering_error",
                                    f"duplicate send {skey}")
            sends[skey] = emit(OP_SEND, rank=r, dur=float(duration),
                               peers=(r, dst), dim="pp")
            return True
        if kind == "send_sync":
            _, dst, tag, duration, _name, *_rest = req
            seq = send_seq.get((r, dst, tag), 0)
            skey = (r, dst, tag, seq)
            if skey not in recv_posted:
                return False  # peer not at its recv yet
            send_seq[(r, dst, tag)] = seq + 1
            sends[skey] = emit(OP_SEND_SYNC, rank=r,
                               dur=float(duration), aux=dst,
                               peers=(r, dst), dim="pp")
            return True
        if kind == "recv":
            _, src, tag, _name, *_rest = req
            seq = recv_seq.get((r, src, tag), 0)
            skey = (src, r, tag, seq)
            recv_posted.add(skey)
            slot = sends.pop(skey, None)
            if slot is None:
                return False  # sender hasn't published yet
            recv_posted.discard(skey)
            recv_seq[(r, src, tag)] = seq + 1
            emit(OP_RECV, rank=r, aux=slot)
            return True
        if kind in FALLBACK_REQUEST_KINDS:
            raise LoweringError(kind)
        raise LoweringError("unknown_kind", repr(kind))

    remaining = sum(len(s) for s in streams)
    while remaining:
        progressed = False
        for r in range(k_classes):
            if done[r]:
                continue
            while idx[r] < len(streams[r]):
                if not serve(r):
                    break
                idx[r] += 1
                remaining -= 1
                progressed = True
            if idx[r] >= len(streams[r]):
                done[r] = True
        if not progressed:
            raise LoweringError("lowering_error",
                                "symbolic schedule made no progress "
                                "(wedged rendezvous/p2p matching)")
    if collectives or async_rv:
        raise LoweringError("lowering_error",
                            "unfinished rendezvous at stream end")

    n_ops = len(kinds)
    group = max((len(rf) for rf in refs if rf), default=1)
    mask_a = np.zeros((n_ops, k_classes), dtype=bool)
    peer_a = np.zeros((n_ops, k_classes), dtype=bool)
    refs_a = np.full((n_ops, max(group, 1)), n_ops, dtype=np.int32)
    dim_ids: Dict[str, int] = {}
    dim_a = np.full(n_ops, -1, dtype=np.int32)
    for i in range(n_ops):
        if masks[i]:
            mask_a[i, list(masks[i])] = True
        if peer_masks[i]:
            peer_a[i, list(peer_masks[i])] = True
        if refs[i]:
            refs_a[i, : len(refs[i])] = refs[i]
        d = op_dims[i]
        if d is not None:
            dim_a[i] = dim_ids.setdefault(d, len(dim_ids))
    return LoweredProgram(
        n_classes=k_classes,
        reps=tuple(plan.reps),
        kind=np.asarray(kinds, dtype=np.int32),
        rank=np.asarray(ranks, dtype=np.int32),
        dur=np.asarray(durs, dtype=np.float64),
        aux=np.asarray(auxs, dtype=np.int32),
        mask=mask_a,
        refs=refs_a,
        peer_mask=peer_a,
        op_dim_id=dim_a,
        dim_ids=dim_ids,
        n_chains=max(len(chain_ids), 1),
    )


# --------------------------------------------------------------------------
# Per-scenario host prep (vectorized numpy; no JAX needed here)
# --------------------------------------------------------------------------


def _pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length() if n > 1 else 1


@dataclass
class ScenarioArrays:
    """One scenario's fault-model arrays, padded to the batch shape."""

    win_s: np.ndarray     # [K, W]
    win_e: np.ndarray     # [K, W]
    win_m: np.ndarray     # [K, W]
    edges: np.ndarray     # [K, We]
    has_slow: np.ndarray  # [K] bool
    link_s: np.ndarray    # [E]
    link_e: np.ndarray    # [E]
    link_m: np.ndarray    # [E]
    app: np.ndarray       # [L, E] bool: link applies to op


def prepare_scenario(prog: LoweredProgram, model, wp: int, wep: int,
                     ep: int) -> ScenarioArrays:
    """Lower one ``StepFaultModel`` (no deaths) against ``prog``:
    per-class slowdown windows + integration edges, and the scenario's
    event-ordered link windows with a precomputed per-op applicability
    matrix (dim match x scope intersection), so the compiled program
    never branches on host state."""
    k = prog.n_classes
    win_s = np.full((k, wp), math.inf)
    win_e = np.full((k, wp), math.inf)
    win_m = np.ones((k, wp))
    edges = np.full((k, wep), math.inf)
    has_slow = np.zeros(k, dtype=bool)
    for i in range(k):
        wins = model._slow.get(prog.reps[i])
        if not wins:
            continue
        has_slow[i] = True
        for j, (s, e, m) in enumerate(wins):
            win_s[i, j] = s
            win_e[i, j] = e
            win_m[i, j] = m
        eds = sorted({x for w in wins for x in w[:2]
                      if math.isfinite(x)})
        edges[i, : len(eds)] = eds
    links = model._links
    n_ops = prog.n_ops
    link_s = np.full(ep, math.inf)
    link_e = np.full(ep, math.inf)
    link_m = np.ones(ep)
    app = np.zeros((n_ops, ep), dtype=bool)
    is_comm = prog.op_dim_id >= 0
    for j, (d, s, e, mult, scope) in enumerate(links):
        link_s[j] = s
        link_e[j] = e
        link_m[j] = mult
        if d == "*":
            dim_ok = is_comm
        else:
            dim_ok = prog.op_dim_id == prog.dim_ids.get(d, -2)
        if scope is None:
            app[:, j] = dim_ok
        else:
            in_scope = np.fromiter(
                (prog.reps[c] in scope for c in range(k)), dtype=bool,
                count=k,
            )
            app[:, j] = dim_ok & (prog.peer_mask @ in_scope)
    return ScenarioArrays(win_s, win_e, win_m, edges, has_slow,
                          link_s, link_e, link_m, app)


# --------------------------------------------------------------------------
# Compiled program cache (keyed by padded shape ONLY — tables are
# arguments, so families sharing a bucket share one XLA executable)
# --------------------------------------------------------------------------

_PROGRAM_CACHE: Dict[tuple, Any] = {}

#: entry bound: crossing it clears the whole cache (shape churn past
#: this point means the workload isn't bucketing — start over)
_PROGRAM_CACHE_CAPACITY = 64


def compile_cache_info(registry=None) -> Dict[str, int]:
    """Observability hook: compiled-shape count + the entry bound
    (bench forensics, and the ``replay_compile_cache_*`` gauges in
    ``/metrics``). Collect-on-scrape: the gauges mirror module state
    rather than an event stream, so callers refresh them by calling
    this — the server does it per ``/metrics`` scrape against its
    own registry."""
    from simumax_tpu.observe.telemetry import get_registry

    reg = registry if registry is not None else get_registry()
    reg.gauge("replay_compile_cache_shapes").set(len(_PROGRAM_CACHE))
    reg.gauge("replay_compile_cache_capacity").set(
        _PROGRAM_CACHE_CAPACITY)
    return {"compiled_shapes": len(_PROGRAM_CACHE),
            "capacity": _PROGRAM_CACHE_CAPACITY}


def _compiled(lp: int, kp: int, gp: int, cp: int, wp: int, wep: int,
              ep: int):
    key = (lp, kp, gp, cp, wp, wep, ep)
    fn = _PROGRAM_CACHE.get(key)
    if fn is not None:
        return fn
    import jax
    import jax.numpy as jnp

    inf = jnp.inf

    def run_one(n_ops, kind_a, rank_a, dur_a, aux_a, mask_a, refs_a,
                win_s, win_e, win_m, edges, has_slow,
                link_s, link_e, link_m, app_bits):
        clock0 = jnp.zeros((kp,), dtype=jnp.float64)
        cd0 = jnp.zeros((kp,), dtype=jnp.float64)
        v0 = jnp.full((lp + 1,), 0.0, dtype=jnp.float64).at[lp].set(-inf)
        v20 = jnp.zeros((cp,), dtype=jnp.float64)

        # Body discipline (the measured 10x): NO lax.switch/cond at
        # all. An HLO Conditional materializes its operands and defeats
        # XLA:CPU fusion, costing ~2us/iteration in branch dispatch
        # alone; but every op kind here is a handful of scalar max/+
        # flops, so computing ALL kinds' candidate results and
        # combining them with scalar selects fuses into one flat loop
        # body. State updates are unconditional and inert for kinds
        # that don't own the resource (no-op writes / all-false masks).
        def body(i, carry):
            clock, cd, v, v2 = carry
            k_i = kind_a[i]
            r = rank_a[i]
            d = dur_a[i]
            a = aux_a[i]
            msk = mask_a[i]
            apb = app_bits[i]
            cr = clock[r]
            cdr = cd[r]
            a_kp = jnp.clip(a, 0, kp - 1)
            a_cp = jnp.clip(a, 0, cp - 1)
            a_lp = jnp.clip(a, 0, lp)
            ca = clock[a_kp]          # send_sync partner clock
            va = v[a_lp]              # recv: published send value
            v2a = v2[a_cp]            # async chain tail
            gmax = jnp.max(v[refs_a[i]])   # async post arrivals
            cstart = jnp.max(jnp.where(msk, clock, -inf))

            is_compute = k_i == OP_COMPUTE
            is_adv_abs = k_i == OP_ADVANCE_ABS
            is_adv_rel = k_i == OP_ADVANCE_REL
            is_coll = k_i == OP_COLL
            is_af = k_i == OP_ASYNC_FINISH
            is_wait = k_i == OP_WAIT_COMM
            is_send = k_i == OP_SEND
            is_ss = k_i == OP_SEND_SYNC
            is_recv = k_i == OP_RECV

            # one comm-scale evaluation at the kind-selected start time
            af_start = jnp.maximum(gmax, v2a)
            ss_start = jnp.maximum(cr, ca)
            t_comm = jnp.where(is_coll, cstart,
                               jnp.where(is_af, af_start,
                                         jnp.where(is_ss, ss_start,
                                                   cr)))
            # ordered product over the scenario's event-ordered link
            # windows — float multiply is order-sensitive, so the
            # engine's event order is preserved (identity factors for
            # inactive links: x * 1.0 is bit-exact x)
            scale = jnp.asarray(1.0, dtype=jnp.float64)
            for j in range(ep):
                act = ((apb >> j) & 1).astype(bool) \
                    & (link_s[j] <= t_comm) & (t_comm < link_e[j])
                scale = scale * jnp.where(act, link_m[j], 1.0)
            # abs() blocks LLVM's mul+add -> fma contraction (XLA:CPU
            # emits contractable IR, and a fused single rounding is a
            # 1-ulp drift off the engine's two-step rounding); it is a
            # bit-exact identity here since d >= 0 and scale >= 1
            dsc = jnp.abs(d * scale)
            coll_end = cstart + dsc
            af_end = af_start + dsc
            ss_end = ss_start + dsc

            # compute: exact piecewise slowdown integration, UNROLLED
            # (a nested lax.scan defeats fusion) and executed
            # unconditionally. The engine advances segment by segment
            # to the NEXT window boundary > t; since the per-class edge
            # list is sorted ascending (inf-padded), visiting edges in
            # table order with a "passed already" guard reproduces that
            # exact sequence WITHOUT a min-reduce per step — each
            # executed step sees e == min(edges > t), and the float
            # expressions are the engine's verbatim, so the walk stays
            # bit-identical. wp == 0 (no slowdown anywhere in the
            # batch) collapses the whole chain to ``res = cr + d``.
            ws, we, wm = win_s[r], win_e[r], win_m[r]
            eds = edges[r]
            trivial = (~has_slow[r]) | (d <= 0.0)
            t, work, pdone, res = cr, d, trivial, cr + d
            for s in range(wep + 1):
                e = eds[s] if s < wep else inf
                act = (~pdone) & (e > t)
                mult = jnp.asarray(1.0, dtype=jnp.float64)
                for j in range(wp):
                    win = (ws[j] <= t) & (t < we[j])
                    mult = jnp.where(win, mult * wm[j], mult)
                frozen = jnp.isinf(mult)
                # abs() = identity (work >= 0, mult >= 1): fma fence,
                # as for dsc above — `t + need` must round twice
                need = jnp.abs(work * mult)
                fits = (~frozen) & (t + need <= e)
                res = jnp.where(act & fits, t + need, res)
                pdone = pdone | (act & fits)
                work = jnp.where(act & ~(fits | frozen),
                                 work - (e - t) / mult, work)
                t = jnp.where(act & ~fits, e, t)

            new_cr = jnp.where(
                is_compute, res,
                jnp.where(is_adv_abs, jnp.maximum(cr, d),
                jnp.where(is_adv_rel, jnp.maximum(cr, cr + d),
                jnp.where(is_wait, jnp.maximum(cr, cdr),
                jnp.where(is_ss, ss_end,
                jnp.where(is_recv, jnp.maximum(cr, va), cr))))))
            vval = jnp.where(is_send, cr + dsc,
                             jnp.where(is_ss, ss_end, cr))
            v2val = jnp.where(is_af, af_end, v2a)
            grp_end = jnp.where(is_coll, coll_end, af_end)

            clock = clock.at[r].set(new_cr)
            clock = jnp.where(is_coll & msk, grp_end, clock)
            cd = jnp.where(is_af & msk, jnp.maximum(cd, grp_end), cd)
            v = v.at[i].set(vval)
            v2 = v2.at[a_cp].set(v2val)
            return (clock, cd, v, v2)

        # dynamic trip count: the padded table tail is all NOOPs, so
        # stopping at the family's REAL op count skips up to half the
        # bucket's iterations for free (n_ops is an argument, not a
        # shape, so the compile key stays the padded bucket)
        clock, _, _, _ = jax.lax.fori_loop(
            0, n_ops, body, (clock0, cd0, v0, v20))
        return jnp.max(clock)

    fn = jax.jit(jax.vmap(
        run_one,
        in_axes=(None, None, None, None, None, None, None,
                 0, 0, 0, 0, 0, 0, 0, 0, 0),
    ))
    if len(_PROGRAM_CACHE) > _PROGRAM_CACHE_CAPACITY:
        _PROGRAM_CACHE.clear()
    _PROGRAM_CACHE[key] = fn
    from simumax_tpu.observe.telemetry import get_registry

    get_registry().gauge("replay_compile_cache_shapes").set(
        len(_PROGRAM_CACHE))
    return fn


def solve_batch(prog: LoweredProgram, models: Sequence[Any]
                ) -> np.ndarray:
    """Replay ``prog`` under each scenario's fault model in ONE
    compiled vmapped call; returns the raw (pre-straggle) makespans,
    bit-identical to ``SimuEngine.run()`` on the same streams.

    Caller contract: every model has no deaths (``deaths`` fall back
    scalar), and the caller holds ``jax.experimental.enable_x64()``
    around trace AND execution."""
    n = len(models)
    k = prog.n_classes
    n_ops = prog.n_ops
    w_max = max((len(m._slow.get(rep, ()))
                 for m in models for rep in prog.reps), default=0)
    e_max = max((len(m._links) for m in models), default=0)
    # fault-array width buckets: wp drives the length of the unrolled
    # integration chain and ep the link-scale product (every op pays
    # both), so they hug the batch's real maxima — 0 is a real bucket
    # that deletes the loop at trace time (a no-slowdown batch computes
    # ``cr + d`` directly; a no-link batch gets scale == 1.0). The
    # sampler emits 1-2 windows/links per scenario, so the shape space
    # stays tiny (the shape key is the compile key — PR 11 discipline)
    wp = _pow2(w_max) if w_max else 0
    wep = 2 * wp
    ep = _pow2(e_max) if e_max else 0
    lp = _pow2(n_ops)
    kp = _pow2(k)
    gp = _pow2(prog.refs.shape[1])
    cp = _pow2(prog.n_chains)
    bp = _pow2(n)

    kind_a = np.zeros(lp, dtype=np.int32)
    kind_a[:n_ops] = prog.kind
    rank_a = np.zeros(lp, dtype=np.int32)
    rank_a[:n_ops] = prog.rank
    dur_a = np.zeros(lp, dtype=np.float64)
    dur_a[:n_ops] = prog.dur
    aux_a = np.zeros(lp, dtype=np.int32)
    aux_a[:n_ops] = prog.aux
    mask_a = np.zeros((lp, kp), dtype=bool)
    mask_a[:n_ops, :k] = prog.mask
    refs_a = np.full((lp, gp), lp, dtype=np.int32)
    refs_a[:n_ops, : prog.refs.shape[1]] = np.where(
        prog.refs >= n_ops, lp, prog.refs)

    arrs = [prepare_scenario(prog, m, wp, wep, ep) for m in models]

    # padded classes: inert windows / edges / flags; padded batch rows
    # repeat the last real scenario (results discarded past n)
    win_s = np.full((bp, kp, wp), math.inf)
    win_e = np.full((bp, kp, wp), math.inf)
    win_m = np.ones((bp, kp, wp))
    edges = np.full((bp, kp, wep), math.inf)
    has_slow = np.zeros((bp, kp), dtype=bool)
    link_s = np.full((bp, ep), math.inf)
    link_e = np.full((bp, ep), math.inf)
    link_m = np.ones((bp, ep))
    # per-op link applicability packed as a bitmask (bit j = link j):
    # one int gather per scan iteration instead of an (ep,) bool row
    shifts = np.arange(ep, dtype=np.int64)
    app_bits = np.zeros((bp, lp), dtype=np.int64)
    for b in range(bp):
        a = arrs[min(b, n - 1)]
        win_s[b, :k] = a.win_s
        win_e[b, :k] = a.win_e
        win_m[b, :k] = a.win_m
        edges[b, :k] = a.edges
        has_slow[b, :k] = a.has_slow
        link_s[b] = a.link_s
        link_e[b] = a.link_e
        link_m[b] = a.link_m
        app_bits[b, :n_ops] = (
            a.app.astype(np.int64) << shifts).sum(axis=1)

    from jax.experimental import enable_x64

    fn = _compiled(lp, kp, gp, cp, wp, wep, ep)
    # x64 held around trace AND execution: the engine oracle runs in
    # python doubles, and bit-identity is the whole contract
    with enable_x64():
        raw = fn(n_ops, kind_a, rank_a, dur_a, aux_a, mask_a, refs_a,
                 win_s, win_e, win_m, edges, has_slow,
                 link_s, link_e, link_m, app_bits)
    return np.asarray(raw)[:n]


__all__ = [
    "FALLBACK_REASONS",
    "FALLBACK_REQUEST_KINDS",
    "JIT_BATCH_MIN",
    "LOWERED_REQUEST_KINDS",
    "LoweredProgram",
    "LoweringError",
    "ScenarioArrays",
    "compile_cache_info",
    "jax_available",
    "lower_family",
    "prepare_scenario",
    "solve_batch",
]

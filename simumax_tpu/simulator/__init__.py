from simumax_tpu.simulator.faults import (  # noqa: F401
    CheckpointSpec,
    FaultEvent,
    FaultScenario,
    analyze_faults,
    predict_goodput,
)
from simumax_tpu.simulator.runner import run_simulation  # noqa: F401

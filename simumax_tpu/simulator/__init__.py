from simumax_tpu.simulator.runner import run_simulation  # noqa: F401

"""Memory-timeline plotting (optional; needs matplotlib).

The reference exports a ``torch.cuda.memory._snapshot()``-compatible
pickle for memory-viz; the TPU-native equivalent renders the
simulator's JSON snapshot directly to a PNG (per-stage allocated-HBM
step lines with the peak annotated)."""

from __future__ import annotations

from typing import List, Optional


def plot_memory_timeline(snapshots: List[dict], out_path: str,
                         hbm_gib: Optional[float] = None) -> str:
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, ax = plt.subplots(figsize=(10, 4.5))
    for snap in snapshots:
        ts = [p["t_ms"] for p in snap["timeline"]]
        bs = [p["bytes"] / 2**30 for p in snap["timeline"]]
        ax.step(ts, bs, where="post", label=f"stage {snap['rank']}")
        peak_i = max(range(len(bs)), key=lambda i: bs[i])
        ax.annotate(
            f"{bs[peak_i]:.1f} GiB",
            (ts[peak_i], bs[peak_i]),
            textcoords="offset points", xytext=(4, 4), fontsize=8,
        )
    if hbm_gib:
        ax.axhline(hbm_gib, color="red", ls="--", lw=1, label="HBM capacity")
    ax.set_xlabel("time (ms)")
    ax.set_ylabel("allocated HBM (GiB)")
    ax.legend(loc="upper right", fontsize=8)
    ax.set_title("simulated per-stage HBM timeline")
    fig.tight_layout()
    fig.savefig(out_path, dpi=120)
    plt.close(fig)
    return out_path

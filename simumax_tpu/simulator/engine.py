"""Discrete-event virtual-time engine (L5).

Reference: ``simumax/core/base_struct.py:1225-2004`` (``BarrierBackend``,
``P2PBackend``, ``SimuThread`` lanes, ``SimuSystem.simu`` heap loop,
``SimuContext`` comm state).

Redesign: the reference drives real OS threads with rendezvous locks;
here each simulated rank is a *generator coroutine* yielding typed
requests to a deterministic scheduler — no real concurrency, perfectly
reproducible, and the engine's invariants (queue ordering, deadlock
detection with a full state dump) are kept as hard errors.

Request vocabulary (yielded by rank coroutines):

* ``("compute", duration, name, lane)`` — advance this rank's lane clock
* ``("collective", key, duration, name, peers)`` — rendezvous of
  ``peers``; completes at ``max(arrival) + duration`` for everyone
* ``("send", dst, tag, duration, name, lane)`` — non-blocking post
  (async isend semantics: sender's clock does not advance)
* ``("send_sync", dst, tag, duration, name, lane)`` — blocking
  rendezvous send: waits until the matching recv is posted, then both
  sides complete at ``max(send_post, recv_post) + duration`` (used for
  unpaired warmup/cooldown sends in blocking pipelines, where the peer
  is in a recv-only phase — Megatron ``batch_isend_irecv`` semantics)
* ``("recv", src, tag, name, lane)`` — blocks until the matching send's
  data has arrived (``send_post_time + duration``)
* ``("sendrecv", dst, stag, sdur, src, rtag, name, lane)`` — one
  batched ``isend/irecv`` pair (Megatron ``batch_isend_irecv``): the
  send is PUBLISHED on the first service attempt (so rings of mutual
  sendrecvs cannot deadlock), then the rank blocks until (a) the
  inbound matching send has arrived and (b) the peer has posted the
  recv matching our send; completes at the max of both transfer ends.
  ``dst=None`` degrades to a plain blocking recv, ``src=None`` to a
  blocking rendezvous send (same semantics as ``send_sync``)
* ``("advance", t)`` — jump lane clock to at least t
* ``("trace", duration, name, lane)`` — zero-advance visibility span
  (overlapped comm shown in the trace without consuming rank time)
* ``("async_collective", stream, duration, name, peers)`` — post a
  rendezvous on a *comm stream* and continue immediately (NCCL-on-a-
  side-stream semantics): the op starts when every peer has posted and
  the stream's previous op finished, runs ``duration``, and records its
  completion in each peer's ``comm_done`` without advancing main clocks
* ``("wait_comm",)`` — block until every async collective this rank
  posted has completed, then advance the main clock to the latest
  completion (stream join)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Generator, List, Optional, Tuple

from simumax_tpu.core.errors import SimulationError


@dataclass
class TraceEvent:
    rank: int
    lane: str
    name: str
    start: float
    end: float
    kind: str = "compute"  # compute | comm | p2p | wait | marker
    flow_id: Optional[int] = None  # links send->recv arrows


@dataclass
class _Rendezvous:
    peers: frozenset
    arrivals: Dict[int, float] = field(default_factory=dict)
    duration: float = 0.0

    @property
    def complete(self) -> bool:
        return set(self.arrivals) == set(self.peers)

    @property
    def end_time(self) -> float:
        return max(self.arrivals.values()) + self.duration


class DeadlockError(SimulationError):
    """No rank can make progress and no blocked request published new
    state — the schedule itself is wedged. Carries the full per-rank
    state dump in the message and structured context for diagnostics."""


class SimuEngine:
    """Deterministic multi-rank virtual-time executor."""

    def __init__(self, num_ranks: int):
        self.num_ranks = num_ranks
        self.clock = [0.0] * num_ranks  # per-rank main lane clock
        self.events: List[TraceEvent] = []
        self._procs: List[Optional[Generator]] = [None] * num_ranks
        self._pending: List[Optional[tuple]] = [None] * num_ranks
        self._done = [False] * num_ranks
        self._collectives: Dict[tuple, _Rendezvous] = {}
        self._coll_seq: Dict[tuple, int] = {}
        self._sends: Dict[tuple, Tuple[float, float]] = {}  # (src,dst,tag) -> (post, dur)
        self._send_seq: Dict[tuple, int] = {}
        self._recv_seq: Dict[tuple, int] = {}
        self._recv_posts: Dict[tuple, float] = {}  # sync-send rendezvous
        #: sendrecv: publish time of the outbound send of an in-flight
        #: batched pair (keyed like _sends; removed on completion)
        self._sr_done: Dict[tuple, float] = {}
        #: bumped when a BLOCKED request mutates shared state (publishes
        #: a send, records a recv post): another pass may now succeed,
        #: so the run loop must not declare deadlock on this pass
        self._state_version = 0
        self._flow_ids: Dict[tuple, int] = {}
        self._next_flow = 0
        #: async comm-stream state: per-(stream,peers) chained end time,
        #: per-rank latest completion, per-rank outstanding posts
        self._async_chain: Dict[tuple, float] = {}
        self._async_seq: Dict[tuple, int] = {}
        self._async_rv: Dict[tuple, _Rendezvous] = {}
        self.comm_done = [0.0] * num_ranks
        self._async_pending: List[set] = [set() for _ in range(num_ranks)]
        self.mem_hooks: List[Callable[[int, str, float], None]] = []

    def add_rank(self, rank: int, proc: Generator):
        self._procs[rank] = proc

    # -- engine loop -------------------------------------------------------
    def run(self) -> float:
        # prime every coroutine to its first request
        for r in range(self.num_ranks):
            self._advance_rank(r, None)
        while not all(self._done):
            progressed = False
            v0 = self._state_version
            # serve ranks in clock order for determinism
            order = sorted(range(self.num_ranks), key=lambda r: self.clock[r])
            for r in order:
                if self._done[r] or self._pending[r] is None:
                    continue
                if self._try_serve(r):
                    progressed = True
            if not progressed and self._state_version == v0:
                # no rank ran AND no blocked request published new state
                # (a send publish / recv post can unblock a rank already
                # visited this pass)
                self._deadlock_dump()
        return max(self.clock)

    def _advance_rank(self, rank: int, value):
        proc = self._procs[rank]
        try:
            req = proc.send(value)
        except StopIteration:
            self._done[rank] = True
            self._pending[rank] = None
            return
        self._pending[rank] = req

    def _try_serve(self, rank: int) -> bool:
        req = self._pending[rank]
        kind = req[0]
        if kind == "compute":
            _, duration, name, lane = req
            start = self.clock[rank]
            self.clock[rank] = start + duration
            if duration > 0:
                self.events.append(
                    TraceEvent(rank, lane, name, start, self.clock[rank])
                )
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "advance":
            _, t = req
            self.clock[rank] = max(self.clock[rank], t)
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "trace":
            # zero-advance visibility span (e.g. overlapped async comm)
            _, duration, name, lane = req
            start = self.clock[rank]
            self.events.append(
                TraceEvent(rank, lane, name, start, start + duration,
                           kind="comm")
            )
            self._advance_rank(rank, start)
            return True
        if kind == "collective":
            _, key, duration, name, peers = req
            seq = self._coll_seq.get((key, rank), 0)
            ckey = (key, frozenset(peers), seq)
            rv = self._collectives.get(ckey)
            if rv is None:
                rv = self._collectives[ckey] = _Rendezvous(
                    peers=frozenset(peers), duration=duration
                )
            if rank not in rv.arrivals:
                rv.arrivals[rank] = self.clock[rank]
                if rv.duration != duration:
                    raise SimulationError(
                        f"collective {key}#{seq}: mismatched durations "
                        f"{rv.duration} vs {duration} from rank {rank}",
                        phase="simulate", rank=rank, collective=str(key),
                    )
            if not rv.complete:
                return False  # stay blocked
            start = self.clock[rank]
            end = rv.end_time
            self.events.append(
                TraceEvent(rank, "comm", name, start, end, kind="comm")
            )
            self.clock[rank] = end
            self._coll_seq[(key, rank)] = seq + 1
            self._advance_rank(rank, end)
            return True
        if kind == "async_collective":
            _, stream, duration, name, peers = req
            seq = self._async_seq.get((stream, rank), 0)
            self._async_seq[(stream, rank)] = seq + 1
            pset = frozenset(peers)
            ckey = (stream, pset, seq)
            rv = self._async_rv.get(ckey)
            if rv is None:
                rv = self._async_rv[ckey] = _Rendezvous(
                    peers=pset, duration=duration
                )
            if rv.duration != duration:
                raise SimulationError(
                    f"async collective {stream}#{seq}: mismatched durations "
                    f"{rv.duration} vs {duration} from rank {rank}",
                    phase="simulate", rank=rank, stream=str(stream),
                )
            rv.arrivals[rank] = self.clock[rank]
            self._async_pending[rank].add(ckey)
            if rv.complete:
                self._finish_async(ckey, rv, name)
            # poster never blocks: continue at the unchanged clock
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "wait_comm":
            if self._async_pending[rank]:
                return False  # some posted op is waiting on peers
            self.clock[rank] = max(self.clock[rank], self.comm_done[rank])
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "send":
            _, dst, tag, duration, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            seq = self._send_seq.get((rank, dst, tag), 0)
            self._send_seq[(rank, dst, tag)] = seq + 1
            skey = (rank, dst, tag, seq)
            if skey in self._sends:
                raise SimulationError(
                    f"duplicate send {skey}",
                    phase="simulate", rank=rank, send=str(skey),
                )
            post = self.clock[rank]
            self._sends[skey] = (post, duration)
            fid = self._next_flow
            self._next_flow += 1
            self._flow_ids[skey] = fid
            self.events.append(
                TraceEvent(rank, lane, name, post, post + duration,
                           kind="p2p", flow_id=fid)
            )
            self._advance_rank(rank, post)
            return True
        if kind == "send_sync":
            _, dst, tag, duration, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            seq = self._send_seq.get((rank, dst, tag), 0)
            skey = (rank, dst, tag, seq)
            # rendezvous: wait until the peer posts the matching recv
            recv_post = self._recv_posts.get(skey)
            if recv_post is None:
                return False  # peer not at its recv yet: stay blocked
            self._send_seq[(rank, dst, tag)] = seq + 1
            start = max(self.clock[rank], recv_post)
            end = start + duration
            # publish as a completed transfer for the recv side
            self._sends[skey] = (start, duration)
            fid = self._next_flow
            self._next_flow += 1
            self._flow_ids[skey] = fid
            self.events.append(
                TraceEvent(rank, lane, name, self.clock[rank], end,
                           kind="p2p", flow_id=fid)
            )
            self.clock[rank] = end
            self._advance_rank(rank, end)
            return True
        if kind == "recv":
            _, src, tag, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            seq = self._recv_seq.get((rank, src, tag), 0)
            skey = (src, rank, tag, seq)
            if skey not in self._recv_posts:
                # record when this recv was first posted (sync sends
                # rendezvous against it)
                self._recv_posts[skey] = self.clock[rank]
                self._state_version += 1
            if skey not in self._sends:
                return False  # sender hasn't posted yet
            post, duration = self._sends.pop(skey)
            if skey in self._sr_done:
                # the sender is a blocked send-only sendrecv: preserve
                # the rendezvous time so its completion reflects when
                # this recv actually arrived (not just its publish time)
                self._sr_done[skey] = max(
                    self._sr_done[skey], self._recv_posts.get(skey, post)
                )
            self._recv_posts.pop(skey, None)
            self._recv_seq[(rank, src, tag)] = seq + 1
            arrive = max(self.clock[rank], post + duration)
            if arrive > self.clock[rank]:
                self.events.append(
                    TraceEvent(rank, lane, f"wait_{name}", self.clock[rank],
                               arrive, kind="wait",
                               flow_id=self._flow_ids.get(skey))
                )
            self.clock[rank] = arrive
            self._advance_rank(rank, arrive)
            return True
        if kind == "sendrecv":
            _, dst, stag, sdur, src, rtag, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            post_t = self.clock[rank]
            out_key = None
            if dst is not None:
                # publish the outbound send exactly once per pending
                # request (the request is re-served while blocked)
                seq = self._send_seq.get((rank, dst, stag), 0)
                if (rank, dst, stag, seq - 1) in self._sr_done:
                    out_key = (rank, dst, stag, seq - 1)  # re-serve attempt
                else:
                    out_key = (rank, dst, stag, seq)
                if out_key not in self._sends and out_key not in self._sr_done:
                    self._send_seq[(rank, dst, stag)] = seq + 1
                    self._sends[out_key] = (post_t, sdur)
                    self._sr_done[out_key] = post_t
                    self._state_version += 1
                    fid = self._next_flow
                    self._next_flow += 1
                    self._flow_ids[out_key] = fid
                    self.events.append(
                        TraceEvent(rank, lane, f"send_{name}", post_t,
                                   post_t + sdur, kind="p2p", flow_id=fid)
                    )
                post_t = self._sr_done[out_key]
            in_key = None
            if src is not None:
                seq = self._recv_seq.get((rank, src, rtag), 0)
                in_key = (src, rank, rtag, seq)
                if in_key not in self._recv_posts:
                    self._recv_posts[in_key] = self.clock[rank]
                    self._state_version += 1
                if in_key not in self._sends:
                    return False  # inbound not posted yet
            if out_key is not None and in_key is None:
                # send-only batched call: true rendezvous — completes
                # only once the peer has posted (or consumed) the
                # matching recv. Paired calls instead complete when the
                # inbound data arrives (the outbound is eager wire
                # time): requiring the peer's recv-post for paired
                # sends would chain op-granular pairs into cycles the
                # real schedule's wider batch_isend_irecv calls (4-way
                # at 1F1B phase boundaries) do not have.
                peer_post = self._recv_posts.get(out_key)
                if peer_post is None and out_key in self._sends:
                    return False  # peer's recv not posted yet
            end = self.clock[rank]
            if in_key is not None:
                post, duration = self._sends.pop(in_key)
                if in_key in self._sr_done:
                    self._sr_done[in_key] = max(
                        self._sr_done[in_key],
                        self._recv_posts.get(in_key, post),
                    )
                self._recv_posts.pop(in_key, None)
                self._recv_seq[(rank, src, rtag)] = seq + 1
                end = max(end, post + duration)
            if out_key is not None:
                peer_post = self._recv_posts.get(out_key)
                if in_key is None and peer_post is not None:
                    send_end = max(self._sr_done[out_key], peer_post) + sdur
                else:
                    send_end = self._sr_done[out_key] + sdur
                end = max(end, send_end)
                del self._sr_done[out_key]
            if end > self.clock[rank]:
                self.events.append(
                    TraceEvent(rank, lane, f"wait_{name}", self.clock[rank],
                               end, kind="wait")
                )
            self.clock[rank] = end
            self._advance_rank(rank, end)
            return True
        raise SimulationError(
            f"unknown request {req!r}", phase="simulate", rank=rank
        )

    def _finish_async(self, ckey: tuple, rv: _Rendezvous, name: str):
        """All peers posted: schedule the op on its comm stream (starts
        after the stream's previous op and the last arrival) and record
        completion for every peer."""
        stream, pset, _seq = ckey
        chain_key = (stream, pset)
        start = max(
            max(rv.arrivals.values()), self._async_chain.get(chain_key, 0.0)
        )
        end = start + rv.duration
        self._async_chain[chain_key] = end
        for peer in pset:
            self.comm_done[peer] = max(self.comm_done[peer], end)
            self._async_pending[peer].discard(ckey)
            self.events.append(
                TraceEvent(peer, "comm", name, start, end, kind="comm")
            )
        del self._async_rv[ckey]

    # -- diagnostics (reference ``base_struct.py:1415-1474``) --------------
    def _deadlock_dump(self):
        lines = ["simulator deadlock — per-rank state:"]
        for r in range(self.num_ranks):
            state = "done" if self._done[r] else f"blocked on {self._pending[r]!r}"
            lines.append(f"  rank {r} t={self.clock[r]*1e3:.3f}ms: {state}")
        incomplete = {
            k: dict(v.arrivals)
            for k, v in self._collectives.items()
            if not v.complete
        }
        if incomplete:
            lines.append(f"  incomplete collectives: {incomplete}")
        if self._sends:
            lines.append(f"  unmatched sends: {list(self._sends)}")
        pending_async = {
            k: dict(v.arrivals) for k, v in self._async_rv.items()
        }
        if pending_async:
            lines.append(f"  incomplete async collectives: {pending_async}")
        raise DeadlockError("\n".join(lines))

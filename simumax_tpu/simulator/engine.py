"""Discrete-event virtual-time engine (L5).

Reference: ``simumax/core/base_struct.py:1225-2004`` (``BarrierBackend``,
``P2PBackend``, ``SimuThread`` lanes, ``SimuSystem.simu`` heap loop,
``SimuContext`` comm state).

Redesign: the reference drives real OS threads with rendezvous locks;
here each simulated rank is a *generator coroutine* yielding typed
requests to a deterministic scheduler — no real concurrency, perfectly
reproducible, and the engine's invariants (queue ordering, deadlock
detection with a full state dump) are kept as hard errors.

Scheduling: a ready heap keyed ``(clock, rank)`` plus wake indexes.
Each runnable rank sits in the heap; serving pops the lowest-clock rank
(ties broken by rank id — the explicit determinism contract). A rank
whose request cannot complete registers the *wake keys* it awaits
(collective rendezvous, send/recv tag, async stream join) and leaves
the heap; publishing a key re-queues exactly the ranks waiting on it.
Serving is O(log R) per event instead of the previous
sort-everything-and-rescan-all-blocked O(R log R) per pass, which is
what makes pod-size world-rank runs (1024+ ranks) tractable.
Event-driven ML-system simulators (ASTRA-sim) use the same indexed
wakeup structure. Deadlock == the heap drains while ranks remain
blocked; the dump names every blocked rank and the keys it awaits.

Request vocabulary (yielded by rank coroutines):

* ``("compute", duration, name, lane)`` — advance this rank's lane clock
* ``("collective", key, duration, name, peers)`` — rendezvous of
  ``peers``; completes at ``max(arrival) + duration`` for everyone
* ``("send", dst, tag, duration, name, lane)`` — non-blocking post
  (async isend semantics: sender's clock does not advance)
* ``("send_sync", dst, tag, duration, name, lane)`` — blocking
  rendezvous send: waits until the matching recv is posted, then both
  sides complete at ``max(send_post, recv_post) + duration`` (used for
  unpaired warmup/cooldown sends in blocking pipelines, where the peer
  is in a recv-only phase — Megatron ``batch_isend_irecv`` semantics)
* ``("recv", src, tag, name, lane)`` — blocks until the matching send's
  data has arrived (``send_post_time + duration``)
* ``("sendrecv", dst, stag, sdur, src, rtag, name, lane)`` — one
  batched ``isend/irecv`` pair (Megatron ``batch_isend_irecv``): the
  send is PUBLISHED on the first service attempt (so rings of mutual
  sendrecvs cannot deadlock), then the rank blocks until (a) the
  inbound matching send has arrived and (b) the peer has posted the
  recv matching our send; completes at the max of both transfer ends.
  ``dst=None`` degrades to a plain blocking recv, ``src=None`` to a
  blocking rendezvous send (same semantics as ``send_sync``)
* ``("advance", t)`` — jump lane clock to at least t
* ``("trace", duration, name, lane)`` — zero-advance visibility span
  (overlapped comm shown in the trace without consuming rank time)
* ``("async_collective", stream, duration, name, peers)`` — post a
  rendezvous on a *comm stream* and continue immediately (NCCL-on-a-
  side-stream semantics): the op starts when every peer has posted and
  the stream's previous op finished, runs ``duration``, and records its
  completion in each peer's ``comm_done`` without advancing main clocks
* ``("wait_comm",)`` — block until every async collective this rank
  posted has completed, then advance the main clock to the latest
  completion (stream join)

Memory: trace records are slotted objects with interned name/lane/kind
strings, and an ``event_sink`` callable (see
:class:`simumax_tpu.simulator.trace.StreamingTraceWriter`) replaces the
in-memory event list entirely so peak RSS no longer scales with total
event count. Completed rendezvous and consumed p2p bookkeeping are
deleted eagerly for the same reason.

Incremental replay (the ISSUE-14 fault-replay engine,
``simulator/faults.py``) adds three capabilities, all inert on the
default path:

* ``drop_events=True`` keeps the per-rank event *counters* but never
  constructs :class:`TraceEvent` objects — a replayed fault step only
  needs the makespan and the death log;
* :class:`RecordingProc` / :class:`ReplayProc` capture a rank
  coroutine's request stream once and replay it without re-running the
  schedule walk. ``advance`` targets are the one clock-derived request
  payload (``StageProcess`` computes ``clock + p2p_time``), so they are
  delta-encoded against the engine's last sent value and re-based at
  replay time — a recorded stream stays exact under a different fault
  timeline;
* :meth:`SimuEngine.run_incremental` with ``pause_at=T`` stops just
  before any service whose *timing decision* could observe fault state
  at or after ``T`` (a heap pop at clock >= T, a compute span crossing
  T, an async-stream op starting at or after T). Every service the
  paused prefix performed is therefore bit-identical under any fault
  model whose first onset is >= T, which makes the paused state a
  reusable fork point: :meth:`SimuEngine.fork` clones it (replay procs
  are plain index cursors), the caller attaches the scenario's fault
  model and resumes only the suffix.
"""

from __future__ import annotations

import sys
import time as _time
from dataclasses import dataclass, field
from heapq import heappop, heappush
from typing import Callable, Dict, Generator, List, Optional, Tuple

from simumax_tpu.core.errors import SimulationError


class TraceEvent:
    """One simulated span. Slotted + interned: world-rank runs emit
    millions of these, and the previous dataclass (``__dict__`` per
    instance, fresh f-string per name) dominated peak RSS."""

    __slots__ = ("rank", "lane", "name", "start", "end", "kind", "flow_id")

    def __init__(self, rank: int, lane: str, name: str, start: float,
                 end: float, kind: str = "compute",
                 flow_id: Optional[int] = None):
        self.rank = rank
        self.lane = sys.intern(lane)
        self.name = sys.intern(name)
        self.start = start
        self.end = end
        self.kind = sys.intern(kind)
        self.flow_id = flow_id

    def __repr__(self):  # keep the old dataclass debugging ergonomics
        return (
            f"TraceEvent(rank={self.rank}, lane={self.lane!r}, "
            f"name={self.name!r}, start={self.start}, end={self.end}, "
            f"kind={self.kind!r}, flow_id={self.flow_id})"
        )

    def __eq__(self, other):
        if not isinstance(other, TraceEvent):
            return NotImplemented
        return all(
            getattr(self, s) == getattr(other, s) for s in self.__slots__
        )


@dataclass
class _Rendezvous:
    peers: frozenset
    arrivals: Dict[int, float] = field(default_factory=dict)
    duration: float = 0.0
    #: completion time, computed once when the last peer arrives
    end: Optional[float] = None
    #: peers that served their completion — the rendezvous record is
    #: deleted when every live peer consumed it (bounded-memory
    #: contract). A SET, not a count: a peer that consumed and *then*
    #: died must not be double-counted against the live quota, or the
    #: record is deleted while a live straggler still needs it — the
    #: straggler then re-creates the rendezvous at the same seq and
    #: deadlocks (found by the fleet walk's death-during-optimizer
    #: suspension pattern, pinned in tests/test_fleet.py)
    consumed: "set" = field(default_factory=set)
    #: op name, retained so a deferred completion (a dead peer resolved
    #: by the fault model) can still emit a labelled trace span
    name: str = ""
    #: seconds the fault model added on top of the nominal duration
    #: (link degradation at rendezvous start) — critical-path blame
    fault_extra: float = 0.0

    @property
    def complete(self) -> bool:
        return len(self.arrivals) == len(self.peers)


class DeadlockError(SimulationError):
    """No rank can make progress and no blocked request published new
    state — the schedule itself is wedged. Carries the full per-rank
    state dump in the message and structured context for diagnostics."""


class ReplayProc:
    """A recorded request stream driven as a rank coroutine.

    Duck-types the slice of the generator protocol the engine uses
    (``send``/``close``) and — unlike a real generator — supports
    :meth:`clone`, which is what makes :meth:`SimuEngine.fork`
    possible: the whole coroutine state is an index into a shared,
    immutable stream list.

    ``("advance_rel", delta)`` entries (see :class:`RecordingProc`)
    are re-based against the engine's last sent clock value, exactly
    mirroring how ``StageProcess`` derives its ``advance`` targets from
    the value returned by the preceding ``send`` yield.
    """

    __slots__ = ("stream", "i", "last", "closed")

    def __init__(self, stream):
        self.stream = stream
        self.i = 0
        self.last = None
        self.closed = False

    def send(self, value):
        if value is not None:
            self.last = value
        if self.closed or self.i >= len(self.stream):
            raise StopIteration
        req = self.stream[self.i]
        self.i += 1
        if req[0] == "advance_rel":
            base = self.last if self.last is not None else 0.0
            return ("advance", base + req[1])
        return req

    def close(self):
        self.closed = True

    def clone(self) -> "ReplayProc":
        c = ReplayProc.__new__(ReplayProc)
        c.stream = self.stream  # shared, append-never
        c.i = self.i
        c.last = self.last
        c.closed = self.closed
        return c


class RecordingProc:
    """Wraps a live rank coroutine and records its request stream so
    later replays of the same step program skip the schedule walk
    entirely (:class:`ReplayProc`).

    The recorded stream is fault-independent: ``StageProcess`` yields
    are structural except for ``advance`` targets, which are the value
    returned by the preceding yield plus a fixed offset — those are
    delta-encoded here (``("advance_rel", delta)``) and re-based at
    replay time. ``complete`` is True only when the coroutine ran to
    ``StopIteration``; a stream truncated by a rank death must not be
    cached (it would starve longer-lived replays).
    """

    __slots__ = ("gen", "stream", "complete", "_last")

    def __init__(self, gen):
        self.gen = gen
        self.stream: list = []
        self.complete = False
        self._last = None

    def send(self, value):
        if value is not None:
            self._last = value
        try:
            req = self.gen.send(value)
        except StopIteration:
            self.complete = True
            raise
        if req[0] == "advance" and self._last is not None:
            self.stream.append(("advance_rel", req[1] - self._last))
        else:
            self.stream.append(req)
        return req

    def close(self):
        self.gen.close()


class SimuEngine:
    """Deterministic multi-rank virtual-time executor."""

    def __init__(self, num_ranks: int,
                 event_sink: Optional[Callable[[TraceEvent], None]] = None,
                 fault_model=None, dep_recorder=None,
                 event_delays: Optional[Dict[Tuple[int, int], float]] = None,
                 progress: Optional[Callable[..., None]] = None,
                 progress_every: int = 0,
                 drop_events: bool = False):
        #: optional fault-injection hook (see ``simulator/faults.py::
        #: StepFaultModel``) consulted at event-service time: piecewise
        #: compute-rate multipliers, comm-time multipliers per
        #: collective dim, and rank death times. ``None`` keeps every
        #: code path bit-identical to the fault-free engine.
        self._fault = fault_model
        #: optional event-dependency recorder (see ``observe/critpath.
        #: py::DependencySkeleton``, duck-typed so the engine never
        #: imports the observability layer): purely observational —
        #: recorder-on and recorder-off runs are bit-identical
        self._rec = dep_recorder
        #: {(rank, per-rank emit index): extra seconds} service-time
        #: perturbations — the slack-correctness test hook: delay ONE
        #: recorded event and compare makespans (``None`` = untouched)
        self._delays = event_delays or None
        #: progress heartbeat: ``progress(served=..., events=...,
        #: clock_s=..., blocked_ranks=..., elapsed_s=...)`` every
        #: ``progress_every`` served requests (0 disables; the runner
        #: wires this to the Reporter at debug level)
        self._progress = progress if progress_every > 0 else None
        self._progress_every = progress_every
        self.num_ranks = num_ranks
        self.clock = [0.0] * num_ranks  # per-rank main lane clock
        #: retained trace records (unused when ``event_sink`` streams
        #: them out instead — the bounded-memory path)
        self.events: List[TraceEvent] = []
        self._sink = event_sink
        #: counts-only mode (incremental fault replay): keep the
        #: per-rank event counters but never construct TraceEvents
        self._drop_events = drop_events
        self._primed = False
        self.num_events = 0
        #: per-rank event counts (total / comm-kind) — symmetry-reduced
        #: runs expand these by class weight for full-world accounting
        self.events_by_rank = [0] * num_ranks
        self.comm_events_by_rank = [0] * num_ranks
        self._procs: List[Optional[Generator]] = [None] * num_ranks
        self._pending: List[Optional[tuple]] = [None] * num_ranks
        self._done = [False] * num_ranks
        self._n_done = 0
        #: ready heap of (clock, rank) + membership flags; at most one
        #: live entry per rank
        self._ready: List[Tuple[float, int]] = []
        self._queued = [False] * num_ranks
        #: wake index: key -> ranks blocked on it; inverse per rank
        self._waiters: Dict[tuple, set] = {}
        self._waiting_on: List[tuple] = [()] * num_ranks
        self._collectives: Dict[tuple, _Rendezvous] = {}
        self._coll_seq: Dict[tuple, int] = {}
        self._sends: Dict[tuple, Tuple[float, float]] = {}  # (src,dst,tag) -> (post, dur)
        self._send_seq: Dict[tuple, int] = {}
        self._recv_seq: Dict[tuple, int] = {}
        self._recv_posts: Dict[tuple, float] = {}  # sync-send rendezvous
        #: sendrecv: publish time of the outbound send of an in-flight
        #: batched pair (keyed like _sends; removed on completion)
        self._sr_done: Dict[tuple, float] = {}
        #: effective outbound duration of an in-flight sendrecv, pinned
        #: at publish time — populated only under ``event_delays`` (a
        #: re-serve attempt recomputes the nominal duration and would
        #: otherwise drop the injected perturbation)
        self._sr_dur: Dict[tuple, float] = {}
        self._flow_ids: Dict[tuple, int] = {}
        self._next_flow = 0
        #: async comm-stream state: per-(stream,peers) chained end time,
        #: per-rank latest completion, per-rank outstanding posts
        self._async_chain: Dict[tuple, float] = {}
        self._async_seq: Dict[tuple, int] = {}
        self._async_rv: Dict[tuple, _Rendezvous] = {}
        self.comm_done = [0.0] * num_ranks
        self._async_pending: List[set] = [set() for _ in range(num_ranks)]
        self.mem_hooks: List[Callable[[int, str, float], None]] = []
        #: graceful-degradation state: ranks killed by the fault model,
        #: their death (virtual) times, and the kill log in kill order
        self._dead = [False] * num_ranks
        self._death_at: Dict[int, float] = {}
        self.deaths: List[Tuple[int, float]] = []
        #: per-rank fault fast paths, refreshed at every run entry (the
        #: replay engine swaps fault models between resumes): death
        #: time and whether the rank has any slowdown window — the hot
        #: serve loop indexes these instead of calling into the model
        self._death_t: List[Optional[float]] = [None] * num_ranks
        self._has_slow: List[bool] = [False] * num_ranks

    def add_rank(self, rank: int, proc: Generator):
        self._procs[rank] = proc

    # -- engine loop -------------------------------------------------------
    def run(self) -> float:
        self.run_incremental()
        return max(self.clock) if self.clock else 0.0

    def run_incremental(self, pause_at: Optional[float] = None) -> bool:
        """Run (or resume) the engine loop; returns True when every
        rank finished.

        With ``pause_at=T`` the loop stops (returning False) just
        before any service whose *timing decision* could observe fault
        state at or after virtual time ``T``: a heap pop at clock >= T,
        a compute span that would cross T, an async-stream op whose
        rendezvous would start at or after T, or a drain-time kill
        (deaths are fault state by definition). Everything the paused
        prefix served made decisions strictly before T — compute spans
        fully inside ``[0, T)``, comm durations fixed at starts < T —
        so the paused state is bit-identical under *any* fault model
        whose earliest event starts at or after T, which is what makes
        it a reusable fork point (:meth:`fork`). Resume by calling
        again with a later ``pause_at`` or None."""
        fault = self._fault
        if fault is not None:
            self._death_t = [
                fault.death_time(r) for r in range(self.num_ranks)
            ]
            self._has_slow = [
                fault.has_slow(r) for r in range(self.num_ranks)
            ]
        if not self._primed:
            self._primed = True
            # prime every coroutine to its first request (rank order:
            # every clock is 0.0, so the heap replays this tie-break)
            for r in range(self.num_ranks):
                self._advance_rank(r, None)
        ready = self._ready
        served = 0
        every = self._progress_every if self._progress is not None else 0
        t0 = _time.monotonic() if every else 0.0
        # hot-loop locals + the conditions under which the compute fast
        # path below is bit-identical to _try_serve's compute arm (no
        # recorder/delay/progress hooks to fire, no pending death)
        pending = self._pending
        clock = self.clock
        done = self._done
        queued = self._queued
        procs = self._procs
        events_by_rank = self.events_by_rank
        death_t = self._death_t
        has_slow = self._has_slow
        drop = self._drop_events
        sink = self._sink
        events = self.events
        fast_ok = (self._rec is None and self._delays is None
                   and every == 0)
        while True:
            while ready:
                if pause_at is not None and ready[0][0] >= pause_at:
                    return False
                _, r = heappop(ready)
                queued[r] = False
                if done[r] or pending[r] is None:
                    continue
                if pause_at is not None and self._crosses_pause(
                    r, pause_at
                ):
                    # push back untouched: the resume re-pops it first
                    queued[r] = True
                    heappush(ready, (clock[r], r))
                    return False
                req = pending[r]
                if (fast_ok and req[0] == "compute"
                        and (fault is None or death_t[r] is None)):
                    # inlined compute serve (the dominant request kind
                    # in a replay): same arithmetic, same emission,
                    # same advance as _try_serve — minus the call chain
                    duration = req[1]
                    start = clock[r]
                    if fault is not None and has_slow[r]:
                        end = fault.compute_end(r, start, duration)
                    else:
                        end = start + duration
                    if end > start:
                        self.num_events += 1
                        events_by_rank[r] += 1
                        if not drop:
                            ev = TraceEvent(r, req[3], req[2], start,
                                            end)
                            if sink is not None:
                                sink(ev)
                            else:
                                events.append(ev)
                    clock[r] = end
                    proc = procs[r]
                    try:
                        nreq = proc.send(end)
                    except StopIteration:
                        done[r] = True
                        self._n_done += 1
                        pending[r] = None
                        continue
                    pending[r] = nreq
                    if not queued[r]:
                        queued[r] = True
                        heappush(ready, (end, r))
                    continue
                if not self._try_serve(r):
                    self._block(r)
                elif every:
                    served += 1
                    if served % every == 0:
                        elapsed = _time.monotonic() - t0
                        self._progress(
                            served=served,
                            events=self.num_events,
                            clock_s=max(self.clock) if self.clock else 0.0,
                            blocked_ranks=sum(
                                1 for w in self._waiting_on if w
                            ),
                            elapsed_s=elapsed,
                        )
            if self._n_done >= self.num_ranks:
                return True
            # heap drained with live ranks left: nothing can wake them —
            # unless a blocked rank is scheduled to die, in which case
            # the death resolves its partners' waits (graceful
            # degradation via the fault model, not a deadlock). Kill
            # only the EARLIEST death per drain pass: resolving it may
            # unblock later-doomed ranks, which then live to finish
            # the step instead of being spuriously killed at their own
            # (possibly far-future) death time.
            doomed = []
            if self._fault is not None:
                doomed = [
                    (self._fault.death_time(r), r)
                    for r in range(self.num_ranks)
                    if not self._done[r]
                    and self._fault.death_time(r) is not None
                ]
            if not doomed:
                self._deadlock_dump()
            if pause_at is not None:
                # deaths are never earlier than the scenario onset, so
                # the kill belongs to the suffix — pause before it
                return False
            dt, r = min(doomed)
            self.clock[r] = max(self.clock[r], dt)
            self._kill(r)

    def _crosses_pause(self, rank: int, pause_at: float) -> bool:
        """Whether serving ``rank``'s pending request now could commit
        a timing decision at or after ``pause_at``. Pops are already
        gated at clock < pause_at; the residual cases are a compute
        span crossing the pause time (its duration integrates fault
        windows inside the span) and an async-stream rendezvous this
        post would complete with a start at or after the pause (its
        comm scale is sampled at that start)."""
        req = self._pending[rank]
        kind = req[0]
        if kind == "compute":
            return self.clock[rank] + req[1] > pause_at
        if kind == "async_collective":
            _, stream, _duration, _name, peers = req
            seq = self._async_seq.get((stream, rank), 0)
            pset = frozenset(peers)
            rv = self._async_rv.get((stream, pset, seq))
            arrivals = rv.arrivals if rv is not None else {}
            missing = len(pset) - len(arrivals) - (
                0 if rank in arrivals else 1
            )
            if missing == 0:  # this post completes the rendezvous
                start = max(
                    max(arrivals.values(), default=0.0),
                    self.clock[rank],
                    self._async_chain.get((stream, pset), 0.0),
                )
                return start >= pause_at
        return False

    def fork(self) -> "SimuEngine":
        """Clone the engine's full scheduling state. Only valid when
        every rank coroutine is cloneable (:class:`ReplayProc`) — live
        generators cannot be copied, which is exactly why the
        incremental fault replay records request streams first."""
        for p in self._procs:
            if p is not None and not hasattr(p, "clone"):
                raise SimulationError(
                    "engine.fork() needs cloneable rank procs "
                    "(ReplayProc); live generators cannot be forked",
                    phase="simulate",
                )

        def rv_copy(rv: _Rendezvous) -> _Rendezvous:
            return _Rendezvous(
                peers=rv.peers, arrivals=dict(rv.arrivals),
                duration=rv.duration, end=rv.end,
                consumed=set(rv.consumed),
                name=rv.name, fault_extra=rv.fault_extra,
            )

        new = SimuEngine.__new__(SimuEngine)
        new._fault = self._fault
        new._rec = None
        new._delays = None
        new._progress = None
        new._progress_every = 0
        new.num_ranks = self.num_ranks
        new.clock = list(self.clock)
        new.events = []
        new._sink = self._sink
        new._drop_events = self._drop_events
        new._primed = self._primed
        new.num_events = self.num_events
        new.events_by_rank = list(self.events_by_rank)
        new.comm_events_by_rank = list(self.comm_events_by_rank)
        new._procs = [
            p.clone() if p is not None else None for p in self._procs
        ]
        new._pending = list(self._pending)
        new._done = list(self._done)
        new._n_done = self._n_done
        new._ready = list(self._ready)
        new._queued = list(self._queued)
        new._waiters = {k: set(v) for k, v in self._waiters.items()}
        new._waiting_on = list(self._waiting_on)
        new._collectives = {
            k: rv_copy(v) for k, v in self._collectives.items()
        }
        new._coll_seq = dict(self._coll_seq)
        new._sends = dict(self._sends)
        new._send_seq = dict(self._send_seq)
        new._recv_seq = dict(self._recv_seq)
        new._recv_posts = dict(self._recv_posts)
        new._sr_done = dict(self._sr_done)
        new._sr_dur = dict(self._sr_dur)
        new._flow_ids = dict(self._flow_ids)
        new._next_flow = self._next_flow
        new._async_chain = dict(self._async_chain)
        new._async_seq = dict(self._async_seq)
        new._async_rv = {k: rv_copy(v) for k, v in self._async_rv.items()}
        new.comm_done = list(self.comm_done)
        new._async_pending = [set(s) for s in self._async_pending]
        new.mem_hooks = []
        new._dead = list(self._dead)
        new._death_at = dict(self._death_at)
        new.deaths = list(self.deaths)
        new._death_t = list(self._death_t)
        new._has_slow = list(self._has_slow)
        return new

    # -- scheduler plumbing ------------------------------------------------
    def _enqueue(self, rank: int):
        if not self._queued[rank]:
            self._queued[rank] = True
            heappush(self._ready, (self.clock[rank], rank))

    def _wake(self, rank: int):
        """Re-queue a blocked rank and drop its remaining wake
        registrations (it will re-register if it blocks again)."""
        for k in self._waiting_on[rank]:
            ws = self._waiters.get(k)
            if ws is not None:
                ws.discard(rank)
                if not ws:
                    del self._waiters[k]
        self._waiting_on[rank] = ()
        if not self._done[rank] and self._pending[rank] is not None:
            self._enqueue(rank)

    def _publish(self, key: tuple):
        """New shared state under ``key``: wake exactly the ranks
        blocked on it (the indexed replacement for the old
        rescan-every-blocked-rank ``_state_version`` pass)."""
        ws = self._waiters.get(key)
        if ws:
            for r in sorted(ws):
                self._wake(r)

    def _block(self, rank: int):
        keys = self._wait_keys(rank)
        if not keys:  # pragma: no cover - defensive: unwakeable block
            raise SimulationError(
                f"rank {rank} blocked on {self._pending[rank]!r} with no "
                f"wake key — scheduler bug",
                phase="simulate", rank=rank,
            )
        self._waiting_on[rank] = keys
        for k in keys:
            self._waiters.setdefault(k, set()).add(rank)

    def _wait_keys(self, rank: int) -> tuple:
        """The wake keys a blocked request awaits, derived from the same
        state its failed service attempt just observed (and mutated —
        first attempts post recv windows / publish sendrecv sends)."""
        req = self._pending[rank]
        kind = req[0]
        if kind == "collective":
            _, key, _duration, _name, peers = req
            seq = self._coll_seq.get((key, rank), 0)
            return (("coll", key, frozenset(peers), seq),)
        if kind == "wait_comm":
            return (("async", rank),)
        if kind == "recv":
            _, src, tag, _name, *_rest = req
            seq = self._recv_seq.get((rank, src, tag), 0)
            return (("send", (src, rank, tag, seq)),)
        if kind == "send_sync":
            _, dst, tag, _duration, _name, *_rest = req
            seq = self._send_seq.get((rank, dst, tag), 0)
            return (("recvpost", (rank, dst, tag, seq)),)
        if kind == "sendrecv":
            _, dst, stag, _sdur, src, rtag, _name, *_rest = req
            if src is not None:
                seq = self._recv_seq.get((rank, src, rtag), 0)
                return (("send", (src, rank, rtag, seq)),)
            # send-only batched call blocked on the peer's recv: wakes
            # when the peer posts the recv window OR consumes the send
            seq = self._send_seq.get((rank, dst, stag), 0)
            out_key = (rank, dst, stag, seq - 1)
            if out_key not in self._sr_done:
                out_key = (rank, dst, stag, seq)
            return (("recvpost", out_key), ("sendpop", out_key))
        raise SimulationError(  # pragma: no cover - served kinds never block
            f"unblockable request {req!r}", phase="simulate", rank=rank
        )

    def _complete_rv(self, pub_key: tuple, rv: _Rendezvous, key):
        """Fix a sync rendezvous' completion time and wake its waiters.
        Dead peers that never arrived contribute their death time as
        the arrival (the survivors resolve via the fault model); the
        duration picks up any active link-degradation multiplier at
        the rendezvous start."""
        dead_times = []
        if self._fault is not None:
            dead_times = [
                self._death_at[p] for p in rv.peers
                if p not in rv.arrivals and self._dead[p]
            ]
        start = max(list(rv.arrivals.values()) + dead_times)
        dur = rv.duration
        if self._fault is not None:
            dur *= self._fault.comm_scale(key, rv.peers, start)
            rv.fault_extra = dur - rv.duration
        rv.end = start + dur
        self._publish(pub_key)

    def _kill(self, rank: int):
        """The fault model killed ``rank`` at its current clock: close
        its coroutine, resolve every rendezvous now waiting only on the
        dead, and wake all blocked ranks so their service attempts
        re-evaluate against the updated death state."""
        t = self.clock[rank]
        self._dead[rank] = True
        self._death_at[rank] = t
        self.deaths.append((rank, t))
        if self._rec is not None:
            self._rec.on_death(rank, t)
        self._emit_ev(rank, "comp", "rank_death", t, t, kind="fault")
        proc = self._procs[rank]
        if proc is not None:
            proc.close()
        if not self._done[rank]:
            self._done[rank] = True
            self._n_done += 1
        self._pending[rank] = None
        for k in self._waiting_on[rank]:
            ws = self._waiters.get(k)
            if ws is not None:
                ws.discard(rank)
                if not ws:
                    del self._waiters[k]
        self._waiting_on[rank] = ()
        # p2p state only the dead rank could ever consume (inbound
        # sends and its posted recv windows): drop it — bounded-memory
        # contract, and senders rendezvousing against the dead rank
        # must abort via the fault model, not complete into a corpse
        for skey in [k for k in self._sends if k[1] == rank]:
            del self._sends[skey]
            self._flow_ids.pop(skey, None)
        for skey in [k for k in self._recv_posts if k[1] == rank]:
            del self._recv_posts[skey]
        # async rendezvous the dead rank never posted to: finish the
        # ones every live peer has posted, drop the ones nobody can
        for ckey, rv in list(self._async_rv.items()):
            if rank not in rv.peers or rank in rv.arrivals:
                continue
            if all(self._dead[p] for p in rv.peers):
                del self._async_rv[ckey]
                continue
            if all(p in rv.arrivals or self._dead[p] for p in rv.peers):
                self._finish_async(ckey, rv, rv.name or "async")
        self._async_pending[rank].clear()
        # wake everyone blocked: collective / p2p dead-peer resolution
        # happens inside their re-served requests
        for r in range(self.num_ranks):
            if self._waiting_on[r]:
                self._wake(r)

    def _emit_ev(self, rank: int, lane: str, name: str, start: float,
                 end: float, kind: str = "compute",
                 flow_id: Optional[int] = None):
        """Counting emit: under ``drop_events`` (incremental fault
        replay) the per-rank counters advance — they drive the
        ``event_delays`` keying and the result accounting — but no
        :class:`TraceEvent` is ever constructed."""
        self.num_events += 1
        self.events_by_rank[rank] += 1
        if kind != "compute":
            self.comm_events_by_rank[rank] += 1
        if self._drop_events:
            return
        ev = TraceEvent(rank, lane, name, start, end, kind, flow_id)
        if self._sink is not None:
            self._sink(ev)
        else:
            self.events.append(ev)

    def _delay(self, rank: int) -> float:
        """Service-time perturbation of the event this rank is about to
        emit (keyed by its per-rank emit index) — the slack-correctness
        test hook. Zero for untouched events and untouched runs."""
        if self._delays is None:
            return 0.0
        return self._delays.get((rank, self.events_by_rank[rank]), 0.0)

    def _advance_rank(self, rank: int, value):
        proc = self._procs[rank]
        try:
            req = proc.send(value)
        except StopIteration:
            self._done[rank] = True
            self._n_done += 1
            self._pending[rank] = None
            return
        self._pending[rank] = req
        self._enqueue(rank)

    def _try_serve(self, rank: int) -> bool:
        fault = self._fault
        if fault is not None and not self._dead[rank]:
            dt = self._death_t[rank]
            if dt is not None and self.clock[rank] >= dt:
                self._kill(rank)
                return True
        req = self._pending[rank]
        kind = req[0]
        if kind == "compute":
            _, duration, name, lane = req
            start = self.clock[rank]
            if fault is not None:
                end = (fault.compute_end(rank, start, duration)
                       if self._has_slow[rank] else start + duration)
                dt = self._death_t[rank]
                if dt is not None and end > dt:
                    # the rank dies mid-op: emit the truncated span,
                    # then let the kill resolve its partners
                    if dt > start:
                        if self._rec is not None:
                            self._rec.on_compute(rank, name, lane, start,
                                                 dt, 0.0)
                        self._emit_ev(rank, lane, name, start, dt)
                    self.clock[rank] = dt
                    self._kill(rank)
                    return True
            else:
                end = start + duration
            if end > start:
                # fault share of the span (slowdown stretch) for blame
                extra = end - (start + duration)
                if self._delays is not None:
                    end += self._delay(rank)
                if self._rec is not None:
                    self._rec.on_compute(rank, name, lane, start, end,
                                         extra)
                self._emit_ev(rank, lane, name, start, end)
            self.clock[rank] = end
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "advance":
            _, t = req
            if self._rec is not None and t > self.clock[rank]:
                self._rec.on_advance(rank, self.clock[rank], t)
            self.clock[rank] = max(self.clock[rank], t)
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "trace":
            # zero-advance visibility span (e.g. overlapped async comm)
            _, duration, name, lane = req
            start = self.clock[rank]
            if self._rec is not None:
                self._rec.on_trace(rank, name, start, start + duration)
            self._emit_ev(rank, lane, name, start, start + duration,
                          kind="comm")
            self._advance_rank(rank, start)
            return True
        if kind == "collective":
            _, key, duration, name, peers = req
            seq = self._coll_seq.get((key, rank), 0)
            ckey = (key, frozenset(peers), seq)
            rv = self._collectives.get(ckey)
            if rv is None:
                rv = self._collectives[ckey] = _Rendezvous(
                    peers=ckey[1], duration=duration, name=name
                )
            if rank not in rv.arrivals:
                if rank not in rv.peers:
                    # membership invariant (kept as a hard error): the
                    # len-based completion check below must never let a
                    # malformed peer list complete silently
                    raise SimulationError(
                        f"collective {key}#{seq}: rank {rank} arrived at "
                        f"a rendezvous whose peers {sorted(rv.peers)} do "
                        f"not include it",
                        phase="simulate", rank=rank, collective=str(key),
                    )
                rv.arrivals[rank] = self.clock[rank]
                if self._rec is not None:
                    self._rec.on_coll_arrive(ckey, rank)
                if rv.duration != duration:
                    raise SimulationError(
                        f"collective {key}#{seq}: mismatched durations "
                        f"{rv.duration} vs {duration} from rank {rank}",
                        phase="simulate", rank=rank, collective=str(key),
                    )
                if rv.complete:
                    self._complete_rv(("coll",) + ckey, rv, key)
            if rv.end is None and fault is not None:
                # graceful degradation: with every live peer arrived
                # and the rest dead, the survivors resolve against the
                # fault model (arrival time = the peer's death time)
                # instead of deadlocking on a rendezvous that can
                # never complete
                if all(p in rv.arrivals or self._dead[p]
                       for p in rv.peers):
                    self._complete_rv(("coll",) + ckey, rv, key)
            if rv.end is None:
                return False  # stay blocked until the last peer arrives
            start = self.clock[rank]
            end = rv.end
            if self._delays is not None:
                end += self._delay(rank)
            if self._rec is not None:
                dead = [] if fault is None else [
                    p for p in rv.peers
                    if p not in rv.arrivals and self._dead[p]
                ]
                self._rec.on_coll_serve(ckey, key, rank, name, start, end,
                                        rv.fault_extra, dead)
            self._emit_ev(rank, "comm", name, start, end, kind="comm")
            self.clock[rank] = end
            self._coll_seq[(key, rank)] = seq + 1
            rv.consumed.add(rank)
            done_rv = len(rv.consumed) >= len(rv.peers)
            if not done_rv and fault is not None and self.deaths:
                # every peer either consumed or died: a dead peer that
                # consumed BEFORE dying is already in the set, so a
                # live straggler can never be counted out (deleting
                # early would re-create the rendezvous at this seq and
                # deadlock the straggler)
                done_rv = all(
                    p in rv.consumed or self._dead[p]
                    for p in rv.peers
                )
            if done_rv:
                del self._collectives[ckey]
                if self._rec is not None:
                    self._rec.on_coll_done(ckey)
            self._advance_rank(rank, end)
            return True
        if kind == "async_collective":
            _, stream, duration, name, peers = req
            seq = self._async_seq.get((stream, rank), 0)
            self._async_seq[(stream, rank)] = seq + 1
            pset = frozenset(peers)
            ckey = (stream, pset, seq)
            rv = self._async_rv.get(ckey)
            if rv is None:
                rv = self._async_rv[ckey] = _Rendezvous(
                    peers=pset, duration=duration, name=name
                )
            if rank not in rv.peers:
                raise SimulationError(
                    f"async collective {stream}#{seq}: rank {rank} posted "
                    f"to a rendezvous whose peers {sorted(rv.peers)} do "
                    f"not include it",
                    phase="simulate", rank=rank, stream=str(stream),
                )
            if rv.duration != duration:
                raise SimulationError(
                    f"async collective {stream}#{seq}: mismatched durations "
                    f"{rv.duration} vs {duration} from rank {rank}",
                    phase="simulate", rank=rank, stream=str(stream),
                )
            rv.arrivals[rank] = self.clock[rank]
            if self._rec is not None:
                self._rec.on_async_post(ckey, rank)
            self._async_pending[rank].add(ckey)
            if rv.complete:
                self._finish_async(ckey, rv, name)
            elif fault is not None and all(
                p in rv.arrivals or self._dead[p] for p in rv.peers
            ):
                # the missing posters are dead: the live peers resolve
                # via the fault model instead of waiting forever
                self._finish_async(ckey, rv, name)
            # poster never blocks: continue at the unchanged clock
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "wait_comm":
            if self._async_pending[rank]:
                return False  # some posted op is waiting on peers
            new = max(self.clock[rank], self.comm_done[rank])
            if self._rec is not None:
                self._rec.on_wait_comm(rank, self.clock[rank], new)
            self.clock[rank] = new
            self._advance_rank(rank, self.clock[rank])
            return True
        if kind == "send":
            _, dst, tag, duration, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            seq = self._send_seq.get((rank, dst, tag), 0)
            self._send_seq[(rank, dst, tag)] = seq + 1
            skey = (rank, dst, tag, seq)
            if skey in self._sends:
                raise SimulationError(
                    f"duplicate send {skey}",
                    phase="simulate", rank=rank, send=str(skey),
                )
            post = self.clock[rank]
            extra = 0.0
            if fault is not None:
                scaled = duration * fault.comm_scale(
                    "pp", (rank, dst), post
                )
                extra = scaled - duration
                duration = scaled
            duration += self._delay(rank)
            self._sends[skey] = (post, duration)
            fid = self._next_flow
            self._next_flow += 1
            self._flow_ids[skey] = fid
            if self._rec is not None:
                self._rec.on_send(skey, rank, name, lane, post,
                                  post + duration, extra,
                                  advance_tail=False, rendezvous=False)
            self._emit_ev(rank, lane, name, post, post + duration,
                          kind="p2p", flow_id=fid)
            self._publish(("send", skey))
            self._advance_rank(rank, post)
            return True
        if kind == "send_sync":
            _, dst, tag, duration, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            seq = self._send_seq.get((rank, dst, tag), 0)
            skey = (rank, dst, tag, seq)
            # rendezvous: wait until the peer posts the matching recv
            recv_post = self._recv_posts.get(skey)
            if recv_post is None:
                if fault is not None and self._dead[dst]:
                    # peer died before posting its recv: the sender
                    # resolves via the fault model and aborts the send
                    self._send_seq[(rank, dst, tag)] = seq + 1
                    end = max(self.clock[rank], self._death_at[dst])
                    if end > self.clock[rank]:
                        if self._rec is not None:
                            self._rec.on_fault_span(
                                rank, f"abort_{name}", self.clock[rank],
                                end,
                            )
                        self._emit_ev(rank, lane, f"abort_{name}",
                                      self.clock[rank], end,
                                      kind="fault")
                    self.clock[rank] = end
                    self._advance_rank(rank, end)
                    return True
                return False  # peer not at its recv yet: stay blocked
            self._send_seq[(rank, dst, tag)] = seq + 1
            start = max(self.clock[rank], recv_post)
            extra = 0.0
            if fault is not None:
                scaled = duration * fault.comm_scale(
                    "pp", (rank, dst), start
                )
                extra = scaled - duration
                duration = scaled
            duration += self._delay(rank)
            end = start + duration
            # publish as a completed transfer for the recv side
            self._sends[skey] = (start, duration)
            fid = self._next_flow
            self._next_flow += 1
            self._flow_ids[skey] = fid
            if self._rec is not None:
                self._rec.on_send(skey, rank, name, lane,
                                  self.clock[rank], end, extra,
                                  advance_tail=True, rendezvous=True)
            self._emit_ev(rank, lane, name, self.clock[rank], end,
                          kind="p2p", flow_id=fid)
            self.clock[rank] = end
            self._publish(("send", skey))
            self._advance_rank(rank, end)
            return True
        if kind == "recv":
            _, src, tag, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            seq = self._recv_seq.get((rank, src, tag), 0)
            skey = (src, rank, tag, seq)
            if skey not in self._recv_posts:
                # record when this recv was first posted (sync sends
                # rendezvous against it)
                self._recv_posts[skey] = self.clock[rank]
                if self._rec is not None:
                    self._rec.on_recv_post(skey, rank)
                self._publish(("recvpost", skey))
            if skey not in self._sends:
                if fault is not None and self._dead[src]:
                    # sender died without posting: the receiver learns
                    # of the death via the fault model and aborts
                    self._recv_posts.pop(skey, None)
                    self._recv_seq[(rank, src, tag)] = seq + 1
                    end = max(self.clock[rank], self._death_at[src])
                    if end > self.clock[rank]:
                        if self._rec is not None:
                            self._rec.on_fault_span(
                                rank, f"abort_{name}", self.clock[rank],
                                end,
                            )
                        self._emit_ev(rank, lane, f"abort_{name}",
                                      self.clock[rank], end,
                                      kind="fault")
                    self.clock[rank] = end
                    self._advance_rank(rank, end)
                    return True
                return False  # sender hasn't posted yet
            post, duration = self._sends.pop(skey)
            if skey in self._sr_done:
                # the sender is a blocked send-only sendrecv: preserve
                # the rendezvous time so its completion reflects when
                # this recv actually arrived (not just its publish time)
                self._sr_done[skey] = max(
                    self._sr_done[skey], self._recv_posts.get(skey, post)
                )
            self._recv_posts.pop(skey, None)
            self._recv_seq[(rank, src, tag)] = seq + 1
            arrive = max(self.clock[rank], post + duration)
            emitted = arrive > self.clock[rank]
            if emitted:
                if self._delays is not None:
                    arrive += self._delay(rank)
            if self._rec is not None:
                self._rec.on_recv_serve(skey, rank, name, self.clock[rank],
                                        arrive, emitted)
            if emitted:
                self._emit_ev(rank, lane, f"wait_{name}",
                              self.clock[rank], arrive, kind="wait",
                              flow_id=self._flow_ids.get(skey))
            self._flow_ids.pop(skey, None)
            self.clock[rank] = arrive
            self._publish(("sendpop", skey))
            self._advance_rank(rank, arrive)
            return True
        if kind == "sendrecv":
            _, dst, stag, sdur, src, rtag, name, *rest = req
            lane = rest[0] if rest else "pp_fwd"
            post_t = self.clock[rank]
            sdur0 = sdur
            if fault is not None and dst is not None:
                # a blocked request re-serves at an unchanged clock, so
                # this samples the same multiplier on every attempt
                sdur = sdur * fault.comm_scale("pp", (rank, dst), post_t)
            out_key = None
            if dst is not None:
                # publish the outbound send exactly once per pending
                # request (the request is re-served while blocked)
                seq = self._send_seq.get((rank, dst, stag), 0)
                if (rank, dst, stag, seq - 1) in self._sr_done:
                    out_key = (rank, dst, stag, seq - 1)  # re-serve attempt
                else:
                    out_key = (rank, dst, stag, seq)
                if out_key not in self._sends and out_key not in self._sr_done:
                    self._send_seq[(rank, dst, stag)] = seq + 1
                    extra = sdur - sdur0
                    sdur += self._delay(rank)
                    if self._delays is not None:
                        self._sr_dur[out_key] = sdur
                    self._sends[out_key] = (post_t, sdur)
                    self._sr_done[out_key] = post_t
                    fid = self._next_flow
                    self._next_flow += 1
                    self._flow_ids[out_key] = fid
                    if self._rec is not None:
                        self._rec.on_send(out_key, rank, f"send_{name}",
                                          lane, post_t, post_t + sdur,
                                          extra, advance_tail=False,
                                          rendezvous=False)
                    self._emit_ev(rank, lane, f"send_{name}", post_t,
                                  post_t + sdur, kind="p2p",
                                  flow_id=fid)
                    self._publish(("send", out_key))
                elif self._delays is not None and out_key in self._sr_dur:
                    # re-serve attempt: keep the duration the publish
                    # actually used (incl. any injected perturbation)
                    sdur = self._sr_dur[out_key]
                post_t = self._sr_done[out_key]
            in_key = None
            if src is not None:
                seq = self._recv_seq.get((rank, src, rtag), 0)
                in_key = (src, rank, rtag, seq)
                if in_key not in self._recv_posts:
                    self._recv_posts[in_key] = self.clock[rank]
                    if self._rec is not None:
                        self._rec.on_recv_post(in_key, rank)
                    self._publish(("recvpost", in_key))
                if in_key not in self._sends:
                    if fault is not None and self._dead[src]:
                        # inbound sender died without posting: resolve
                        # both halves of the batched pair via the fault
                        # model (the outbound stays published — a live
                        # peer may still consume it)
                        self._recv_posts.pop(in_key, None)
                        self._recv_seq[(rank, src, rtag)] = seq + 1
                        if out_key is not None:
                            self._sr_done.pop(out_key, None)
                            self._sr_dur.pop(out_key, None)
                        end = max(self.clock[rank], self._death_at[src])
                        if end > self.clock[rank]:
                            if self._rec is not None:
                                self._rec.on_fault_span(
                                    rank, f"abort_{name}",
                                    self.clock[rank], end,
                                )
                            self._emit_ev(rank, lane, f"abort_{name}",
                                          self.clock[rank], end,
                                          kind="fault")
                        self.clock[rank] = end
                        self._advance_rank(rank, end)
                        return True
                    return False  # inbound not posted yet
            if out_key is not None and in_key is None:
                # send-only batched call: true rendezvous — completes
                # only once the peer has posted (or consumed) the
                # matching recv. Paired calls instead complete when the
                # inbound data arrives (the outbound is eager wire
                # time): requiring the peer's recv-post for paired
                # sends would chain op-granular pairs into cycles the
                # real schedule's wider batch_isend_irecv calls (4-way
                # at 1F1B phase boundaries) do not have.
                peer_post = self._recv_posts.get(out_key)
                if peer_post is None and out_key in self._sends:
                    if fault is not None and self._dead[dst]:
                        # peer died before posting the matching recv:
                        # the sender aborts the rendezvous
                        self._sr_done.pop(out_key, None)
                        self._sr_dur.pop(out_key, None)
                        end = max(self.clock[rank], self._death_at[dst])
                        if end > self.clock[rank]:
                            if self._rec is not None:
                                self._rec.on_fault_span(
                                    rank, f"abort_{name}",
                                    self.clock[rank], end,
                                )
                            self._emit_ev(rank, lane, f"abort_{name}",
                                          self.clock[rank], end,
                                          kind="fault")
                        self.clock[rank] = end
                        self._advance_rank(rank, end)
                        return True
                    return False  # peer's recv not posted yet
            end = self.clock[rank]
            if in_key is not None:
                post, duration = self._sends.pop(in_key)
                if in_key in self._sr_done:
                    self._sr_done[in_key] = max(
                        self._sr_done[in_key],
                        self._recv_posts.get(in_key, post),
                    )
                self._recv_posts.pop(in_key, None)
                self._flow_ids.pop(in_key, None)
                self._recv_seq[(rank, src, rtag)] = seq + 1
                self._publish(("sendpop", in_key))
                end = max(end, post + duration)
            if out_key is not None:
                peer_post = self._recv_posts.get(out_key)
                if in_key is None and peer_post is not None:
                    send_end = max(self._sr_done[out_key], peer_post) + sdur
                else:
                    send_end = self._sr_done[out_key] + sdur
                end = max(end, send_end)
                del self._sr_done[out_key]
                self._sr_dur.pop(out_key, None)
            emitted = end > self.clock[rank]
            if emitted:
                end += self._delay(rank)
            if self._rec is not None:
                self._rec.on_sendrecv_serve(
                    rank, f"wait_{name}", self.clock[rank], end,
                    in_key, out_key, emitted,
                )
            if emitted:
                self._emit_ev(rank, lane, f"wait_{name}",
                              self.clock[rank], end, kind="wait")
            self.clock[rank] = end
            self._advance_rank(rank, end)
            return True
        raise SimulationError(
            f"unknown request {req!r}", phase="simulate", rank=rank
        )

    def _finish_async(self, ckey: tuple, rv: _Rendezvous, name: str):
        """All peers posted (or the missing posters are dead): schedule
        the op on its comm stream (starts after the stream's previous
        op and the last arrival — a dead peer's death time counts as
        its arrival) and record completion for every live peer."""
        stream, pset, _seq = ckey
        chain_key = (stream, pset)
        dead_times = []
        if self._fault is not None:
            dead_times = [
                self._death_at[p] for p in pset
                if p not in rv.arrivals and self._dead[p]
            ]
        start = max(
            max(rv.arrivals.values()), self._async_chain.get(chain_key, 0.0),
            *dead_times,
        )
        dur = rv.duration
        extra = 0.0
        if self._fault is not None:
            dur *= self._fault.comm_scale(stream, pset, start)
            extra = dur - rv.duration
        end = start + dur
        self._async_chain[chain_key] = end
        for peer in pset:
            if self._fault is not None and self._dead[peer]:
                self._async_pending[peer].discard(ckey)
                continue
            pend = end + self._delay(peer)
            self.comm_done[peer] = max(self.comm_done[peer], pend)
            self._async_pending[peer].discard(ckey)
            if not self._async_pending[peer]:
                self._publish(("async", peer))
            if self._rec is not None:
                self._rec.on_async_finish_peer(ckey, chain_key, name,
                                               start, pend, peer, extra)
            self._emit_ev(peer, "comm", name, start, pend, kind="comm")
        if self._rec is not None:
            self._rec.on_async_done(ckey)
        del self._async_rv[ckey]

    # -- diagnostics (reference ``base_struct.py:1415-1474``) --------------
    def _deadlock_dump(self, max_ranks: int = 64):
        lines = ["simulator deadlock — per-rank state:"]
        shown = 0
        for r in range(self.num_ranks):
            if self._done[r] and self.num_ranks > max_ranks:
                continue  # pod-size dumps: list only the stuck ranks
            if shown >= max_ranks:
                blocked_left = sum(
                    1 for q in range(r, self.num_ranks) if not self._done[q]
                )
                lines.append(f"  ... and {blocked_left} more blocked ranks")
                break
            state = "done" if self._done[r] else f"blocked on {self._pending[r]!r}"
            lines.append(f"  rank {r} t={self.clock[r]*1e3:.3f}ms: {state}")
            shown += 1
        if self._waiters:
            keys = sorted(self._waiters, key=repr)[:max_ranks]
            lines.append("  blocked wake keys:")
            for k in keys:
                ranks = sorted(self._waiters[k])
                lines.append(f"    {k!r} <- ranks {ranks[:16]}")
        incomplete = {
            k: dict(v.arrivals)
            for k, v in self._collectives.items()
            if not v.complete
        }
        if incomplete:
            lines.append(f"  incomplete collectives: {incomplete}")
        if self._sends:
            lines.append(f"  unmatched sends: {list(self._sends)[:max_ranks]}")
        pending_async = {
            k: dict(v.arrivals) for k, v in self._async_rv.items()
        }
        if pending_async:
            lines.append(f"  incomplete async collectives: {pending_async}")
        raise DeadlockError("\n".join(lines))

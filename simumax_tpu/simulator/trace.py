"""Chrome/Perfetto trace export (L6).

Reference: ``simumax/core/generate_tracing.py`` + ``trace_export.py``.
The reference writes text log lines and re-parses them by regex; here
the engine produces structured :class:`TraceEvent` records directly, so
export is a straight conversion — pid = simulated rank (PP stage),
ordered tid lanes (comp / comm / pp_fwd / pp_bwd), flow arrows linking
p2p send -> recv-wait pairs, and per-rank memory counter tracks.
"""

from __future__ import annotations

import json
from typing import List, Optional

from simumax_tpu.simulator.engine import TraceEvent
from simumax_tpu.simulator.memory import SimuMemoryTracker

_LANE_ORDER = {"comp": 0, "comm": 1, "pp_fwd": 2, "pp_bwd": 3, "wait": 4}

_COLORS = {
    "compute": "good",
    "comm": "thread_state_runnable",
    "p2p": "thread_state_iowait",
    "wait": "terrible",
}


def to_chrome_trace(
    events: List[TraceEvent],
    trackers: Optional[List[SimuMemoryTracker]] = None,
    max_counter_samples: int = 4000,
) -> dict:
    out = []
    # a flow arrow needs both ends: a send whose recv never waited (data
    # already arrived -> no wait event) must not emit a dangling `s`
    # (Perfetto drops or mis-renders unpaired arrows)
    send_ids = {e.flow_id for e in events
                if e.kind == "p2p" and e.flow_id is not None}
    wait_ids = {e.flow_id for e in events
                if e.kind == "wait" and e.flow_id is not None}
    paired_flows = send_ids & wait_ids
    ranks = {e.rank for e in events}
    ranks.update(tr.rank for tr in trackers or [] if tr.timeline)
    for rank in sorted(ranks):
        out.append(
            {
                "ph": "M", "pid": rank, "name": "process_name",
                "args": {"name": f"stage{rank}"},
            }
        )
        for lane, idx in _LANE_ORDER.items():
            out.append(
                {
                    "ph": "M", "pid": rank, "tid": idx,
                    "name": "thread_name", "args": {"name": lane},
                }
            )
    for e in events:
        lane = e.lane if e.kind != "wait" else "wait"
        tid = _LANE_ORDER.get(lane, 5)
        out.append(
            {
                "ph": "X",
                "pid": e.rank,
                "tid": tid,
                "name": e.name,
                "ts": e.start * 1e6,
                "dur": max(e.end - e.start, 0.0) * 1e6,
                "cname": _COLORS.get(e.kind),
                "args": {"kind": e.kind},
            }
        )
        if e.flow_id in paired_flows and e.kind == "p2p":
            out.append(
                {
                    "ph": "s", "pid": e.rank, "tid": tid, "id": e.flow_id,
                    "name": "p2p", "ts": e.start * 1e6, "cat": "p2p",
                }
            )
        if e.flow_id in paired_flows and e.kind == "wait":
            out.append(
                {
                    "ph": "f", "pid": e.rank, "tid": tid, "id": e.flow_id,
                    "name": "p2p", "ts": e.end * 1e6, "cat": "p2p",
                    "bp": "e",
                }
            )
    for tr in trackers or []:
        samples = tr.timeline
        if not samples:
            continue  # nothing tracked for this rank: no counter lane
        stride = max(1, len(samples) // max_counter_samples)
        kept = list(samples[::stride])
        # never drop the peak or the final sample when downsampling: the
        # stride cut keeps the first of every stride window, so both the
        # peak and the step-end tail sample can otherwise vanish
        peak_sample = max(samples, key=lambda s: s.bytes)
        for extra in (peak_sample, samples[-1]):
            if extra not in kept:
                kept.append(extra)
        kept.sort(key=lambda s: s.t)
        for s in kept:
            out.append(
                {
                    "ph": "C",
                    "pid": tr.rank,
                    "name": "hbm_bytes",
                    "ts": s.t * 1e6,
                    "args": {"allocated": s.bytes},
                }
            )
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, events, trackers=None):
    with open(path, "w") as f:
        json.dump(to_chrome_trace(events, trackers), f)
    return path

"""Chrome/Perfetto trace export (L6).

Reference: ``simumax/core/generate_tracing.py`` + ``trace_export.py``.
The reference writes text log lines and re-parses them by regex; here
the engine produces structured :class:`TraceEvent` records directly, so
export is a straight conversion — pid = simulated rank (PP stage),
ordered tid lanes (comp / comm / pp_fwd / pp_bwd), flow arrows linking
p2p send -> recv-wait pairs, and per-rank memory counter tracks.

Two writers share the conversion helpers:

* :func:`to_chrome_trace` / :func:`write_chrome_trace` — batch: convert
  a retained event list in one pass (small runs, post-hoc tooling).
* :class:`StreamingTraceWriter` — incremental: plugs into the engine as
  its ``event_sink`` and flushes JSON to disk as events are emitted, so
  peak RSS no longer scales with total event count (the pod-size
  world-rank contract). Flow arrows are paired on the fly: a p2p send
  parks a tiny stub until (unless) its recv-wait streams past.
"""

from __future__ import annotations

import json
from typing import List, Optional

from simumax_tpu.simulator.engine import TraceEvent
from simumax_tpu.simulator.memory import SimuMemoryTracker

_LANE_ORDER = {"comp": 0, "comm": 1, "pp_fwd": 2, "pp_bwd": 3, "wait": 4}

_COLORS = {
    "compute": "good",
    "comm": "thread_state_runnable",
    "p2p": "thread_state_iowait",
    "wait": "terrible",
}


def _meta_dicts(rank: int) -> List[dict]:
    """Process/thread naming metadata for one simulated rank."""
    out = [
        {
            "ph": "M", "pid": rank, "name": "process_name",
            "args": {"name": f"stage{rank}"},
        }
    ]
    for lane, idx in _LANE_ORDER.items():
        out.append(
            {
                "ph": "M", "pid": rank, "tid": idx,
                "name": "thread_name", "args": {"name": lane},
            }
        )
    return out


def _event_tid(e: TraceEvent) -> int:
    lane = e.lane if e.kind != "wait" else "wait"
    return _LANE_ORDER.get(lane, 5)


def _x_dict(e: TraceEvent) -> dict:
    return {
        "ph": "X",
        "pid": e.rank,
        "tid": _event_tid(e),
        "name": e.name,
        "ts": e.start * 1e6,
        "dur": max(e.end - e.start, 0.0) * 1e6,
        "cname": _COLORS.get(e.kind),
        "args": {"kind": e.kind},
    }


def _flow_start_dict(flow_id: int, pid: int, tid: int, ts_us: float) -> dict:
    return {
        "ph": "s", "pid": pid, "tid": tid, "id": flow_id,
        "name": "p2p", "ts": ts_us, "cat": "p2p",
    }


def _flow_end_dict(e: TraceEvent) -> dict:
    return {
        "ph": "f", "pid": e.rank, "tid": _event_tid(e), "id": e.flow_id,
        "name": "p2p", "ts": e.end * 1e6, "cat": "p2p",
        "bp": "e",
    }


def _counter_dicts(tr: SimuMemoryTracker,
                   max_counter_samples: int) -> List[dict]:
    samples = tr.timeline
    if not samples:
        return []  # nothing tracked for this rank: no counter lane
    stride = max(1, len(samples) // max_counter_samples)
    kept = list(samples[::stride])
    # never drop the peak or the final sample when downsampling: the
    # stride cut keeps the first of every stride window, so both the
    # peak and the step-end tail sample can otherwise vanish
    peak_sample = max(samples, key=lambda s: s.bytes)
    for extra in (peak_sample, samples[-1]):
        if extra not in kept:
            kept.append(extra)
    kept.sort(key=lambda s: s.t)
    return [
        {
            "ph": "C",
            "pid": tr.rank,
            "name": "hbm_bytes",
            "ts": s.t * 1e6,
            "args": {"allocated": s.bytes},
        }
        for s in kept
    ]


def to_chrome_trace(
    events: List[TraceEvent],
    trackers: Optional[List[SimuMemoryTracker]] = None,
    max_counter_samples: int = 4000,
    annotations: Optional[dict] = None,
) -> dict:
    """``annotations`` maps ``(rank, per-rank emission index) ->
    (slack_seconds, on_critical_path)`` (the critical-path post-pass,
    ``observe/critpath.py``): matching X events gain ``slack_us`` /
    ``on_critical_path`` args. The events list is in engine emission
    order, so the per-rank index is reconstructed while converting."""
    out = []
    # a flow arrow needs both ends: a send whose recv never waited (data
    # already arrived -> no wait event) must not emit a dangling `s`
    # (Perfetto drops or mis-renders unpaired arrows)
    send_ids = {e.flow_id for e in events
                if e.kind == "p2p" and e.flow_id is not None}
    wait_ids = {e.flow_id for e in events
                if e.kind == "wait" and e.flow_id is not None}
    paired_flows = send_ids & wait_ids
    ranks = {e.rank for e in events}
    ranks.update(tr.rank for tr in trackers or [] if tr.timeline)
    for rank in sorted(ranks):
        out.extend(_meta_dicts(rank))
    emit_idx: dict = {}
    for e in events:
        d = _x_dict(e)
        if annotations is not None:
            idx = emit_idx.get(e.rank, 0)
            emit_idx[e.rank] = idx + 1
            ann = annotations.get((e.rank, idx))
            if ann is not None:
                slack, on_path = ann
                d["args"]["on_critical_path"] = bool(on_path)
                if slack == float("inf"):
                    d["args"]["slack_us"] = None
                else:
                    d["args"]["slack_us"] = round(slack * 1e6, 3)
        out.append(d)
        if e.flow_id in paired_flows and e.kind == "p2p":
            out.append(
                _flow_start_dict(e.flow_id, e.rank, _event_tid(e),
                                 e.start * 1e6)
            )
        if e.flow_id in paired_flows and e.kind == "wait":
            out.append(_flow_end_dict(e))
    for tr in trackers or []:
        out.extend(_counter_dicts(tr, max_counter_samples))
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def write_chrome_trace(path: str, events, trackers=None, annotations=None):
    with open(path, "w") as f:
        json.dump(to_chrome_trace(events, trackers,
                                  annotations=annotations), f)
    return path


class StreamingTraceWriter:
    """Incremental Chrome-trace writer, used as the engine's
    ``event_sink``: events are serialized and flushed to ``path`` as
    they are emitted instead of being retained in memory.

    Matches :func:`to_chrome_trace` output semantics: rank metadata is
    emitted lazily on a rank's first event, and flow arrows are emitted
    only for *paired* send/wait flows — a send's arrow stub (a 4-tuple,
    not the JSON dict) is parked until its recv-wait streams past; the
    engine serves every matching recv after its send, so the wait always
    arrives later in emission order. Call :meth:`close` (optionally with
    memory trackers for counter tracks) to finalize the JSON; the writer
    is also a context manager."""

    def __init__(self, path: str, flush_every: int = 5000,
                 max_counter_samples: int = 4000):
        self.path = path
        self.num_events = 0
        self._flush_every = flush_every
        self._max_counter_samples = max_counter_samples
        self._f = open(path, "w")
        self._f.write('{"traceEvents": [')
        self._first = True
        self._buf: List[str] = []
        self._ranks_seen = set()
        #: flow_id -> (pid, tid, ts_us) send stub awaiting its wait
        self._open_flows = {}
        self._closed = False

    def __call__(self, e: TraceEvent):
        self.num_events += 1
        if e.rank not in self._ranks_seen:
            self._ranks_seen.add(e.rank)
            for d in _meta_dicts(e.rank):
                self._push(d)
        self._push(_x_dict(e))
        if e.flow_id is not None:
            if e.kind == "p2p":
                self._open_flows[e.flow_id] = (
                    e.rank, _event_tid(e), e.start * 1e6
                )
            elif e.kind == "wait":
                stub = self._open_flows.pop(e.flow_id, None)
                if stub is not None:
                    self._push(_flow_start_dict(e.flow_id, *stub))
                    self._push(_flow_end_dict(e))

    def _push(self, d: dict):
        self._buf.append(json.dumps(d))
        if len(self._buf) >= self._flush_every:
            self._drain()

    def _drain(self):
        if not self._buf:
            return
        chunk = ", ".join(self._buf)
        self._f.write(chunk if self._first else ", " + chunk)
        self._first = False
        self._buf.clear()

    def close(self, trackers: Optional[List[SimuMemoryTracker]] = None):
        if self._closed:
            return self.path
        for tr in trackers or []:
            if not tr.timeline:
                continue
            if tr.rank not in self._ranks_seen:
                self._ranks_seen.add(tr.rank)
                for d in _meta_dicts(tr.rank):
                    self._push(d)
            for d in _counter_dicts(tr, self._max_counter_samples):
                self._push(d)
        self._drain()
        self._f.write('], "displayTimeUnit": "ms"}')
        self._f.close()
        self._closed = True
        return self.path

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

"""Multi-head Latent Attention (MLA) analytical ops (L3).

Reference: ``simumax/core/transformer/dense_module.py``
(``MLACoreAttention:1606-1805``, ``MLAAttention:2569-2887``).

Structure (DeepSeek-V2/V3): optionally low-rank q path
(``q_down -> q_norm -> q_up``), low-rank kv path
(``kv_down -> kv_norm -> kv_up``) plus a shared RoPE key branch; the
score dot uses ``qk_head_dim + qk_pos_emb_head_dim`` while values use
``v_head_dim``. Down-projections are replicated (no TP comm, rows stay
seq-sharded); up-projections are column-parallel with the usual SP
gathers. The RoPE key branch is gathered explicitly (SeqAllGather) since
it bypasses the column-parallel kv_up.
"""

from __future__ import annotations

from simumax_tpu.core.module import MetaModule
from simumax_tpu.core.tensor import TensorSpec
from simumax_tpu.models.dense import (
    ContextParallelA2A,
    CoreAttention,
    KVAllGather,
    LayerNorm,
    LinearCol,
    LinearRow,
    RotaryEmbedding,
    SeqAllGather,
    _st,
)


class MLAAttention(MetaModule):
    def __init__(self, ctx, name="mla_attention", quantized=False):
        super().__init__(ctx, name)
        m, st = ctx.model, ctx.strategy
        self.qk_dim = m.qk_head_dim + m.qk_pos_emb_head_dim
        q_out = m.head_num * self.qk_dim
        if m.q_lora_rank:
            self.q_down = LinearCol(ctx, m.hidden_size, m.q_lora_rank,
                                    "q_down", replicated=True)
            self.q_norm = LayerNorm(ctx, hidden=m.q_lora_rank, name="q_norm")
            self.q_up = LinearCol(ctx, m.q_lora_rank, q_out, "q_up",
                                  quantized=quantized)
        else:
            self.q_proj = LinearCol(ctx, m.hidden_size, q_out, "q_proj",
                                    quantized=quantized)
        self.kv_down = LinearCol(
            ctx, m.hidden_size, m.kv_lora_rank + m.qk_pos_emb_head_dim,
            "kv_down", replicated=True,
        )
        self.kv_norm = LayerNorm(ctx, hidden=m.kv_lora_rank, name="kv_norm")
        self.kv_up = LinearCol(
            ctx,
            m.kv_lora_rank,
            m.head_num * (m.qk_head_dim + m.v_head_dim),
            "kv_up",
            quantized=quantized,
        )
        # ledger tags: keep the low-rank latent path distinguishable from
        # generic GEMMs in `explain` output — the mla_up_proj recompute
        # knob targets exactly the "mla_up_proj" rows, and the down/up
        # split is the first thing a DeepSeek-shape misprediction triage
        # looks at (docs/observability.md)
        for mod in ([self.q_up] if m.q_lora_rank else []) + [self.kv_up]:
            mod.op_category = "mla_up_proj"
        for mod in ([self.q_down] if m.q_lora_rank else []) + [self.kv_down]:
            mod.op_category = "mla_down_proj"
        if st.enable_sequence_parallel and st.tp_size > 1:
            self.rope_gather = SeqAllGather(ctx, "tp", "rope_k_gather")
        self.rope = RotaryEmbedding(ctx, name="rope")
        if st.cp_size > 1 and st.cp_comm_type == "a2a":
            self.cp_q = ContextParallelA2A(ctx, "scatter_heads", "cp_a2a_q")
            self.cp_k = ContextParallelA2A(ctx, "scatter_heads", "cp_a2a_k")
            self.cp_v = ContextParallelA2A(ctx, "scatter_heads", "cp_a2a_v")
            self.cp_o = ContextParallelA2A(ctx, "gather_seq", "cp_a2a_o")
        elif st.cp_size > 1 and st.cp_comm_type == "all_gather":
            self.kv_gather_k = KVAllGather(ctx, name="kv_allgather_k")
            self.kv_gather_v = KVAllGather(ctx, name="kv_allgather_v")
        self.core = CoreAttention(ctx, name="mla_core_attention")
        self.out_proj = LinearRow(
            ctx, m.head_num * m.v_head_dim, m.hidden_size, "out_proj",
            quantized=quantized,
        )
        self.norms = [self.kv_norm] + (
            [self.q_norm] if m.q_lora_rank else []
        )

    def _post_forward(self):
        from simumax_tpu.models.dense import bound_async_cp_overlap

        bound_async_cp_overlap(self)

    def forward(self, x: TensorSpec) -> TensorSpec:
        st, m = _st(self.ctx), self.ctx.model
        tp = st.tp_size
        hl = m.head_num // tp

        if m.q_lora_rank:
            q = self.q_down(x)
            q = self.q_norm(q)
            q = self.q_up(q)
        else:
            q = self.q_proj(x)
        b, s, _ = q.shape
        q = q.with_shape(b, s, hl, self.qk_dim)

        kv = self.kv_down(x)
        kv_c = kv.with_shape(kv.shape[0], kv.shape[1], m.kv_lora_rank)
        k_rope = kv.with_shape(kv.shape[0], kv.shape[1], m.qk_pos_emb_head_dim)
        kv_c = self.kv_norm(kv_c)
        kv_up = self.kv_up(kv_c)  # [b, s, hl*(qk_nope + v)]
        if hasattr(self, "rope_gather"):
            k_rope = self.rope_gather(k_rope)
        # k = concat(k_nope, broadcast k_rope): [b, s, hl, qk_dim]
        k = kv_up.with_shape(b, s, hl, self.qk_dim)
        v = kv_up.with_shape(b, s, hl, m.v_head_dim)
        q, k = self.rope(q, k)
        if st.cp_size > 1 and st.cp_comm_type == "a2a":
            q = self.cp_q(q)
            k = self.cp_k(k)
            v = self.cp_v(v)
        elif st.cp_size > 1 and st.cp_comm_type == "all_gather":
            k = self.kv_gather_k(k)
            v = self.kv_gather_v(v)
        o = self.core(q, k, v)
        if st.cp_size > 1 and st.cp_comm_type == "a2a":
            o = self.cp_o(o)
        b2, s2, hl2, dv = o.shape
        return self.out_proj(o.with_shape(b2, s2, hl2 * dv))

"""Dense analytical ops (L3).

Reference: ``simumax/core/transformer/dense_module.py`` (Embedding:18,
LinearCol:195, LinearRow:511, LayerNorm:784, CoreAttention:1061,
RotaryEmbedding:1806, Swiglu/Gelu:1874, ParallelCE:2097, Attention:2454,
MLP:2888).

Shape conventions (all sizes are **per-device, per-microbatch**):

* ``s_cp``  = seq_len / cp — the sequence shard attention-external ops see
  under context parallelism;
* ``s_sp``  = s_cp / tp when Megatron sequence-parallel is on — the shard
  between TP regions;
* TP collectives ride the ``tp`` CommPath (innermost ICI axis), CP a2a the
  ``cp`` path, etc. Collective ``size_bytes`` is always the *full logical
  tensor* being communicated (matching ``SystemConfig.compute_net_op_time``
  semantics).
"""

from __future__ import annotations

from typing import Dict, List

from simumax_tpu.core.config import _require
from simumax_tpu.core.module import BuildContext, GemmBase, LeafModule, MetaModule
from simumax_tpu.core.records import ActivationInfo, CollectiveCall
from simumax_tpu.core.tensor import TensorSpec


def _st(ctx: BuildContext):
    return ctx.strategy


# --------------------------------------------------------------------------
# Shape-only "function" ops (reference ``transformer/function.py``)
# --------------------------------------------------------------------------


class AddFunction(LeafModule):
    """Residual add: memory-bound, no cache (bwd is fan-out passthrough)."""

    op_category = "elementwise"

    def forward_spec(self, a: TensorSpec, b: TensorSpec) -> TensorSpec:
        assert a.shape == b.shape, (a.shape, b.shape)
        return a.with_shape(*a.shape)

    def op_accessed(self) -> Dict[str, float]:
        n = self.outputs[0].bytes
        return {"fwd": 3 * n}


class SplitFunction(LeafModule):
    """Split last dim into parts; zero-cost shape op."""

    op_category = "elementwise"

    def __init__(self, ctx, sizes, name=""):
        super().__init__(ctx, name)
        self.sizes = sizes

    def forward_spec(self, x: TensorSpec):
        assert sum(self.sizes) == x.shape[-1]
        return tuple(x.with_shape(*x.shape[:-1], sz) for sz in self.sizes)


class ConcatFunction(LeafModule):
    op_category = "elementwise"

    def __init__(self, ctx, dim=-1, name=""):
        super().__init__(ctx, name)
        self.dim = dim

    def forward_spec(self, *xs: TensorSpec):
        base = list(xs[0].shape)
        base[self.dim] = sum(x.shape[self.dim] for x in xs)
        return xs[0].with_shape(*base)

    def op_accessed(self) -> Dict[str, float]:
        n = self.outputs[0].bytes
        return {"fwd": 2 * n, "bwd_act": 2 * n}


# --------------------------------------------------------------------------
# Embedding
# --------------------------------------------------------------------------


class Embedding(LeafModule):
    """TP-sharded vocab embedding (reference ``dense_module.py:18-193``):
    fwd TP all-reduce (or SP reduce-scatter); bwd-W all-gather under SP;
    ZeRO-1 state sharding."""

    op_category = "embedding"

    def __init__(self, ctx, name="embedding"):
        super().__init__(ctx, name)
        st = _st(ctx)
        self.vocab = ctx.model.padded_vocab_size
        self.hidden = ctx.model.hidden_size
        self.numel = self.vocab * self.hidden // st.tp_size

    def forward_spec(self, ids: TensorSpec) -> TensorSpec:
        st = _st(self.ctx)
        b, s = ids.shape
        if st.enable_sequence_parallel:
            s = s // st.tp_size
        return TensorSpec((b, s, self.hidden), st.dtype)

    def op_accessed(self) -> Dict[str, float]:
        out = self.outputs[0]
        full = out.bytes * (_st(self.ctx).tp_size if _st(self.ctx).enable_sequence_parallel else 1)
        # lookup write + bwd scatter-add read/write of fp32 grad
        return {"fwd": 2 * full, "bwd_w": 2 * full + self.inputs[0].bytes}

    def activation_info(self) -> ActivationInfo:
        fsdp = _fsdp_temp(self, self.numel)
        return ActivationInfo(
            cache_bytes=self.inputs[0].numel() * 4,  # ids
            fwd_temp_bytes=fsdp,
            bwd_temp_bytes=fsdp + _zero_grad_temp(self, self.numel),
        )

    def extra_param_info(self):
        return self.make_param_info(self.numel)

    def collectives(self) -> List[CollectiveCall]:
        st = _st(self.ctx)
        calls = _fsdp_calls(self, self.numel)
        if st.tp_size == 1:
            return calls
        out = self.outputs[0]
        full = out.bytes * (st.tp_size if st.enable_sequence_parallel else 1)
        if st.enable_sequence_parallel:
            calls.append(CollectiveCall("fwd", "reduce_scatter", "tp", full, "post"))
            calls.append(CollectiveCall("bwd_w", "all_gather", "tp", full, "pre"))
        else:
            calls.append(CollectiveCall("fwd", "all_reduce", "tp", full, "post"))
        return calls


# --------------------------------------------------------------------------
# Norms
# --------------------------------------------------------------------------


class LayerNorm(LeafModule):
    """RMS/LayerNorm (reference ``dense_module.py:784-995``): memory-bound,
    caches its input; weight is dense state."""

    op_category = "norm"

    def __init__(self, ctx, hidden=None, name="norm"):
        super().__init__(ctx, name)
        self.hidden = hidden or ctx.model.hidden_size

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        return x

    def op_flops(self) -> Dict[str, float]:
        n = self.inputs[0].numel()
        return {"fwd": 4 * n, "bwd_act": 8 * n}

    def op_accessed(self) -> Dict[str, float]:
        nb = self.inputs[0].bytes
        fused = _st(self.ctx).use_fused_norm
        return {
            "fwd": (2 if fused else 3) * nb,
            "bwd_act": (3 if fused else 4) * nb,
            "bwd_w": nb,  # weight-grad reduction pass
        }

    def activation_info(self) -> ActivationInfo:
        nb = self.inputs[0].bytes
        rows = self.inputs[0].numel() / self.hidden
        return ActivationInfo(cache_bytes=nb + rows * 4)  # input + rstd

    def extra_param_info(self):
        return self.make_param_info(self.hidden)


# --------------------------------------------------------------------------
# Linear layers
# --------------------------------------------------------------------------


def _fsdp_calls(leaf, numel, is_moe=False):
    """ZeRO-3/FSDP per-layer weight collectives: all-gather the shard
    before fwd and again before the wgrad, reduce-scatter the grads
    right after bwd (per microbatch)."""
    st = leaf.ctx.strategy
    if st.zero_state < 3 or numel <= 0:
        return []
    dim = "edp" if is_moe else "dp_cp"
    group = st.edp_size if is_moe else st.dp_size * st.cp_size
    if group <= 1:
        return []
    w_bytes = numel * st.element_size
    g_bytes = numel * st.grad_element_size
    # FSDP prefetches gathers under compute; the excess beyond the
    # block's compute budget is re-exposed by LLMBlock._post_forward
    return [
        CollectiveCall("fwd", "all_gather", dim, w_bytes, "pre",
                       exposed=False),
        CollectiveCall("bwd_act", "all_gather", dim, w_bytes, "pre",
                       exposed=False),
        CollectiveCall("bwd_w", "reduce_scatter", dim, g_bytes, "post",
                       exposed=False),
    ]


def _fsdp_temp(leaf, numel, is_moe=False):
    """Transient full (gathered) weight bytes while the op runs."""
    st = leaf.ctx.strategy
    if st.zero_state < 3 or numel <= 0:
        return 0.0
    group = st.edp_size if is_moe else st.dp_size * st.cp_size
    if group <= 1:
        return 0.0
    return numel * st.element_size * (1 - 1 / group)


def _zero_grad_temp(leaf, numel, is_moe=False):
    """ZeRO>=2: the full-size layer gradient exists between the wgrad
    and its reduce-scatter; only the shard survives."""
    st = leaf.ctx.strategy
    if st.zero_state < 2 or numel <= 0:
        return 0.0
    group = st.edp_size if is_moe else st.dp_size * st.cp_size
    if group <= 1:
        return 0.0
    return numel * st.grad_element_size * (1 - 1 / group)


class LinearCol(GemmBase):
    """Column-parallel linear (reference ``dense_module.py:195-509``).

    Under SP: fwd all-gather of the seq-sharded input, bwd-act
    reduce-scatter of the input grad, bwd-w re-all-gather of the input for
    the wgrad GEMM. Without SP (tp>1): bwd-act all-reduce.
    """

    def __init__(self, ctx, in_features, out_features, name="linear_col",
                 quantized=False, skip_comm=False, replicated=False,
                 count_params=True):
        super().__init__(ctx, name, quantized=quantized)
        st = _st(ctx)
        self.in_features = in_features
        self.out_features = out_features
        # replicated: weight duplicated on every TP rank, rows stay
        # seq-sharded, no collectives (MLA down-projections)
        self.replicated = replicated
        self.out_local = out_features // (1 if replicated else st.tp_size)
        self.numel = in_features * self.out_local
        self.skip_comm = skip_comm or replicated
        # tied-weight layers (lm_head sharing the embedding) compute but
        # do not own parameters
        self.count_params = count_params

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        st = _st(self.ctx)
        b, s, k = x.shape
        assert k == self.in_features, (k, self.in_features, self.name)
        if st.enable_sequence_parallel and st.tp_size > 1 and not self.skip_comm:
            s = s * st.tp_size  # gathered inside the TP region
        return TensorSpec((b, s, self.out_local), st.dtype)

    def gemm_mnk(self, phase: str):
        out = self.outputs[0]
        m = out.shape[0] * out.shape[1]
        k, n = self.in_features, self.out_local
        if phase == "fwd":
            return (1, m, k, n)
        if phase == "bwd_act":
            return (1, m, n, k)
        return (1, k, m, n)

    def op_flops(self) -> Dict[str, float]:
        _, m, k, n = self.gemm_mnk("fwd")
        f = 2.0 * m * k * n
        return {"fwd": f, "bwd_act": f, "bwd_w": f}

    def op_accessed(self) -> Dict[str, float]:
        st = _st(self.ctx)
        e = st.element_size
        _, m, k, n = self.gemm_mnk("fwd")
        io = (m * k + k * n + m * n) * e
        wgrad_extra = k * n * (st.grad_element_size - e)  # fp32 accum out
        return {
            "fwd": io + self.quant_cast_bytes("fwd"),
            "bwd_act": io + self.quant_cast_bytes("bwd_act"),
            "bwd_w": io + wgrad_extra + self.quant_cast_bytes("bwd_w"),
        }

    def activation_info(self) -> ActivationInfo:
        st = _st(self.ctx)
        # cache the *pre-gather* input under SP (re-gathered for wgrad)
        cached = self.inputs[0].bytes
        temp = 0.0
        if st.enable_sequence_parallel and st.tp_size > 1 and not self.skip_comm:
            temp = cached * st.tp_size  # gathered copy live during compute
        n = self.numel if self.count_params else 0
        fsdp = _fsdp_temp(self, n)
        return ActivationInfo(
            cache_bytes=cached,
            fwd_temp_bytes=temp + fsdp,
            bwd_temp_bytes=temp + fsdp + _zero_grad_temp(self, n),
        )

    def extra_param_info(self):
        if not self.count_params:
            return self.make_param_info(0)
        return self.make_param_info(self.numel)

    def collectives(self) -> List[CollectiveCall]:
        st = _st(self.ctx)
        calls = _fsdp_calls(self, self.numel if self.count_params else 0)
        if st.tp_size == 1 or self.skip_comm:
            return calls
        _, m, k, _ = self.gemm_mnk("fwd")
        full_in = m * k * st.element_size
        if st.enable_sequence_parallel:
            return calls + [
                CollectiveCall("fwd", "all_gather", "tp", full_in, "pre"),
                CollectiveCall("bwd_act", "reduce_scatter", "tp", full_in, "post"),
                CollectiveCall("bwd_w", "all_gather", "tp", full_in, "pre"),
            ]
        return calls + [
            CollectiveCall("bwd_act", "all_reduce", "tp", full_in, "post")
        ]


class LinearRow(GemmBase):
    """Row-parallel linear (reference ``dense_module.py:511-783``):
    fwd reduce-scatter (SP) / all-reduce (TP); bwd-act all-gather under SP.
    """

    def __init__(self, ctx, in_features, out_features, name="linear_row",
                 quantized=False, skip_comm=False):
        super().__init__(ctx, name, quantized=quantized)
        st = _st(ctx)
        self.in_features = in_features
        self.out_features = out_features
        self.in_local = in_features // st.tp_size
        self.numel = self.in_local * out_features
        self.skip_comm = skip_comm

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        st = _st(self.ctx)
        b, s, k = x.shape
        assert k == self.in_local, (k, self.in_local, self.name)
        if st.enable_sequence_parallel and st.tp_size > 1 and not self.skip_comm:
            s = s // st.tp_size  # scattered back to seq shards
        return TensorSpec((b, s, self.out_features), st.dtype)

    def gemm_mnk(self, phase: str):
        x = self.inputs[0]
        m = x.shape[0] * x.shape[1]
        k, n = self.in_local, self.out_features
        if phase == "fwd":
            return (1, m, k, n)
        if phase == "bwd_act":
            return (1, m, n, k)
        return (1, k, m, n)

    def op_flops(self) -> Dict[str, float]:
        _, m, k, n = self.gemm_mnk("fwd")
        f = 2.0 * m * k * n
        return {"fwd": f, "bwd_act": f, "bwd_w": f}

    def op_accessed(self) -> Dict[str, float]:
        st = _st(self.ctx)
        e = st.element_size
        _, m, k, n = self.gemm_mnk("fwd")
        io = (m * k + k * n + m * n) * e
        wgrad_extra = k * n * (st.grad_element_size - e)  # fp32 accum out
        return {
            "fwd": io + self.quant_cast_bytes("fwd"),
            "bwd_act": io + self.quant_cast_bytes("bwd_act"),
            "bwd_w": io + wgrad_extra + self.quant_cast_bytes("bwd_w"),
        }

    def activation_info(self) -> ActivationInfo:
        fsdp = _fsdp_temp(self, self.numel)
        return ActivationInfo(
            cache_bytes=self.inputs[0].bytes,
            fwd_temp_bytes=fsdp,
            bwd_temp_bytes=fsdp + _zero_grad_temp(self, self.numel),
        )

    def extra_param_info(self):
        return self.make_param_info(self.numel)

    def collectives(self) -> List[CollectiveCall]:
        st = _st(self.ctx)
        calls = _fsdp_calls(self, self.numel)
        if st.tp_size == 1 or self.skip_comm:
            return calls
        _, m, _, n = self.gemm_mnk("fwd")
        full_out = m * n * st.element_size
        if st.enable_sequence_parallel:
            return calls + [
                CollectiveCall("fwd", "reduce_scatter", "tp", full_out, "post"),
                CollectiveCall("bwd_act", "all_gather", "tp", full_out, "pre"),
            ]
        return calls + [
            CollectiveCall("fwd", "all_reduce", "tp", full_out, "post")
        ]


# --------------------------------------------------------------------------
# Attention core
# --------------------------------------------------------------------------


class RotaryEmbedding(LeafModule):
    """RoPE application to q,k: memory-bound (reference
    ``dense_module.py:1806-1873``)."""

    op_category = "rope"

    def forward_spec(self, q: TensorSpec, k: TensorSpec):
        return q, k

    def op_accessed(self) -> Dict[str, float]:
        nb = sum(t.bytes for t in self.inputs)
        return {"fwd": 2 * nb, "bwd_act": 2 * nb}


class CoreAttention(LeafModule):
    """Scaled-dot-product attention cost model (reference
    ``dense_module.py:1061-1604``): flash vs math paths, causal sparsity,
    GQA; CP handled by the enclosing :class:`Attention` via
    :class:`ContextParallelA2A` / KV all-gather (ring) wrappers.

    Inputs q,k,v are per-device: ``[b, sq, hl, d]`` / ``[b, skv, kvl, d]``.
    """

    op_category = "attention"

    def __init__(self, ctx, head_dim_v=None, name="core_attention"):
        super().__init__(ctx, name)
        self.head_dim_v = head_dim_v

    def forward_spec(self, q: TensorSpec, k: TensorSpec, v: TensorSpec):
        b, sq, hl, d = q.shape
        dv = v.shape[-1]
        return TensorSpec((b, sq, hl, dv), q.dtype)

    def _dims(self):
        q, k, v = self.inputs
        b, sq, hl, d = q.shape
        skv = k.shape[1]
        dv = v.shape[-1]
        return b, sq, skv, hl, d, dv

    def _causal(self) -> bool:
        return bool(self.ctx.model.use_causal_attention)

    def op_flops(self) -> Dict[str, float]:
        st = _st(self.ctx)
        b, sq, skv, hl, d, dv = self._dims()
        # causal masking skips this fraction; full attention skips none
        sparse = st.attention_sparse_ratio if self._causal() else 0.0
        qk = 2.0 * b * hl * sq * skv * d
        pv = 2.0 * b * hl * sq * skv * dv
        fwd = (qk + pv) * (1.0 - sparse)
        bwd = 2.5 * fwd if st.use_flash_sdp else 2.0 * fwd
        return {"fwd": fwd, "bwd_act": bwd}

    def op_accessed(self) -> Dict[str, float]:
        st = _st(self.ctx)
        b, sq, skv, hl, d, dv = self._dims()
        e = st.element_size
        kvl = self.inputs[1].shape[2]
        qo = b * sq * hl * (d + dv) * e
        kv = b * skv * kvl * (d + dv) * e
        lse = b * hl * sq * 4
        if st.use_flash_sdp:
            return {"fwd": qo + kv + lse, "bwd_act": 2 * (qo + kv) + lse}
        # math path materializes the fp32 score/probs matrices (XLA
        # computes softmax in fp32 — see docs/memory_validation.md)
        score = b * hl * sq * skv * 4.0
        return {"fwd": qo + kv + 2 * score, "bwd_act": 2 * (qo + kv) + 4 * score}

    @staticmethod
    def render_sdp_shape_key(b, sq, skv, hn, kv_hn, hd, hd_v, causal,
                             flash, dtype, backend="xla") -> str:
        """Canonical sdp efficiency-table key — static single source
        shared with the batched sweep kernel (``search/batched.py``)."""
        prefix = "" if backend == "xla" else f"backend={backend}, "
        return (
            f"{prefix}b={b}, sq={sq}, skv={skv}, hn={hn}, kv_hn={kv_hn}, "
            f"hd={hd}, hd_v={hd_v}, causal={causal}, "
            f"flash={flash}, dtype={dtype}"
        )

    def comp_key(self, phase):
        st = _st(self.ctx)
        b, sq, skv, hl, d, dv = self._dims()
        kvl = self.inputs[1].shape[2]
        key = self.render_sdp_shape_key(
            b, sq, skv, hl, kvl, d, dv, self._causal(),
            st.use_flash_sdp, st.dtype, backend=st.sdp_backend,
        )
        return ("sdp_fwd" if phase == "fwd" else "sdp_bwd", key)

    def activation_info(self) -> ActivationInfo:
        st = _st(self.ctx)
        b, sq, skv, hl, d, dv = self._dims()
        e = st.element_size
        kvl = self.inputs[1].shape[2]
        lse = b * hl * sq * 4
        if st.use_flash_sdp:
            # flash caches q,k,v,o,lse
            cache = (
                b * sq * hl * d * e
                + b * skv * kvl * (d + dv) * e
                + b * sq * hl * dv * e
                + lse
            )
            return ActivationInfo(cache_bytes=cache)
        # math (XLA composite) path: softmax runs in fp32; the fp32
        # probs are cached for the backward (the pre-softmax scores fuse
        # into the probs buffer). The backward ADDITIONALLY materializes
        # dP = dO @ V^T — a matmul output in the model dtype — while the
        # cached probs are still live; the fp32 dS chain then fuses into
        # the dq/dk/dv matmul operand reads, so exactly one extra score
        # matrix is transient (anchored against TPU
        # compiled.memory_analysis() across seq/layers/remat,
        # docs/memory_validation.md: omitting it underpredicted the
        # 8192-seq remat case by 18%)
        probs_f32 = b * hl * sq * skv * 4.0
        cache = (
            b * sq * hl * d * e
            + b * skv * kvl * (d + dv) * e
            + probs_f32
        )
        return ActivationInfo(
            cache_bytes=cache,
            bwd_temp_bytes=b * hl * sq * skv * e,
        )

    def bw_key(self, phase):
        return "default"


class ContextParallelA2A(LeafModule):
    """One Ulysses-style CP all-to-all stage: re-shard ``[b, s/cp, H, d]``
    (seq-sharded) <-> ``[b, s, H/cp, d]`` (head-sharded) over the cp axis
    (reference ``_get_cp_a2a_stage_specs`` dense_module.py:1158-1186).

    ``direction='scatter_heads'`` gathers sequence / scatters heads (the
    pre-attention direction); 'gather_seq' is the inverse. The backward of
    each is the opposite a2a with the same volume, so fwd/bwd sizes match.
    """

    op_category = "comm"

    def __init__(self, ctx, direction="scatter_heads", name="cp_a2a"):
        super().__init__(ctx, name)
        self.direction = direction

    def _replication(self, h: int) -> int:
        """GQA with fewer (kv) heads than cp ranks: real Ulysses
        replicates the heads to ``cp`` before the a2a so every rank owns
        one; the a2a then moves the replicated volume. (Without this the
        k/v shard would round to zero heads and the KV cache/comm would
        be modeled as free.)"""
        cp = _st(self.ctx).cp_size
        if self.direction != "scatter_heads":
            return 1
        if h >= cp:
            _require(
                h % cp == 0,
                f"{h} local (kv) heads not divisible by cp_size {cp}",
            )
            return 1
        _require(
            cp % h == 0,
            f"cp_size {cp} not a multiple of the {h} local kv heads",
        )
        return cp // h

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        cp = _st(self.ctx).cp_size
        b, s, h, d = x.shape
        if self.direction == "scatter_heads":
            r = self._replication(h)
            return x.with_shape(b, s * cp, (h * r) // cp, d)
        return x.with_shape(b, s // cp, h * cp, d)

    def collectives(self) -> List[CollectiveCall]:
        st = _st(self.ctx)
        if st.cp_size == 1:
            return []
        # full logical tensor = per-chip shard * cp (net-op contract);
        # kv-head replication inflates the moved volume accordingly
        r = self._replication(self.inputs[0].shape[2])
        nbytes = self.inputs[0].bytes * r * st.cp_size
        exposed = st.cp_a2a_mode == "sync_cp"
        return [
            CollectiveCall("fwd", "all2all", "cp", nbytes, "pre", exposed=exposed),
            CollectiveCall("bwd_act", "all2all", "cp", nbytes, "post", exposed=exposed),
        ]

    def activation_info(self) -> ActivationInfo:
        # the re-sharded copy is a transient; source freed after a2a
        r = self._replication(self.inputs[0].shape[2])
        return ActivationInfo(fwd_temp_bytes=self.inputs[0].bytes * r)




# --------------------------------------------------------------------------
# Activations / losses
# --------------------------------------------------------------------------


class Dropout(LeafModule):
    """Hidden dropout: memory-bound elementwise with a cached 1-byte
    mask per element for the backward. (The reference warns and ignores
    ``enable_dropout`` — config.py:678-681; modeled fully here:
    embedding-output + both residual-branch sites, the standard
    Megatron recipe.)"""

    op_category = "elementwise"

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        return x

    def op_accessed(self) -> Dict[str, float]:
        nb = self.inputs[0].bytes
        mask = self.inputs[0].numel()
        return {"fwd": 2 * nb + mask, "bwd_act": 2 * nb + mask}

    def activation_info(self) -> ActivationInfo:
        return ActivationInfo(cache_bytes=self.inputs[0].numel())  # mask


class SeqAllGather(LeafModule):
    """Gather a seq-sharded tensor over a parallel dim (fwd all-gather,
    bwd-act reduce-scatter) — used for e.g. the MLA RoPE branch whose
    producer is a replicated linear outside the column-parallel gather."""

    op_category = "comm"

    def __init__(self, ctx, dim="tp", name="seq_allgather"):
        super().__init__(ctx, name)
        self.dim = dim

    def _group(self) -> int:
        return getattr(_st(self.ctx), f"{self.dim}_size")

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        g = self._group()
        return x.with_shape(x.shape[0], x.shape[1] * g, *x.shape[2:])

    def collectives(self) -> List[CollectiveCall]:
        if self._group() == 1:
            return []
        full = self.outputs[0].bytes
        return [
            CollectiveCall("fwd", "all_gather", self.dim, full, "pre"),
            CollectiveCall("bwd_act", "reduce_scatter", self.dim, full, "post"),
        ]

    def activation_info(self) -> ActivationInfo:
        return ActivationInfo(fwd_temp_bytes=self.outputs[0].bytes)


class KVAllGather(SeqAllGather):
    """CP ``all_gather`` (ring-attention family) KV gather: fwd all-gather
    of k or v over cp, bwd reduce-scatter of its grad. The reference only
    costs the net time and raises on flops (``dense_module.py:1521-1524``);
    here it is a complete op. The gathered copy also stays live through
    the attention backward (re-gathered), unlike the plain SeqAllGather."""

    def __init__(self, ctx, name="kv_allgather"):
        super().__init__(ctx, dim="cp", name=name)

    def activation_info(self) -> ActivationInfo:
        full = self.outputs[0].bytes
        return ActivationInfo(fwd_temp_bytes=full, bwd_temp_bytes=full)


class Swiglu(LeafModule):
    """SwiGLU activation (reference ``dense_module.py:1874-2096``):
    memory-bound; input is the concatenated ``[.., 2*f]`` projection.
    ``weighted`` fuses the router-prob multiply into the activation
    (reference ``is_weighted_silu``, the ``dispatch_probs`` MoE path):
    one extra per-token fp32 prob is read each phase and cached for the
    backward's dL/dprob term."""

    op_category = "activation"

    def __init__(self, ctx, name="swiglu", weighted: bool = False):
        super().__init__(ctx, name)
        self.weighted = weighted

    def _probs_bytes(self) -> float:
        if not self.weighted:
            return 0.0
        b, s, _ = self.outputs[0].shape
        return b * s * 4.0  # one fp32 prob per routed token copy

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        return x.split_dim(-1, 2)

    def op_accessed(self) -> Dict[str, float]:
        i, o = self.inputs[0].bytes, self.outputs[0].bytes
        p = self._probs_bytes()
        return {"fwd": i + o + p, "bwd_act": 2 * i + o + p}

    def activation_info(self) -> ActivationInfo:
        return ActivationInfo(
            cache_bytes=self.inputs[0].bytes + self._probs_bytes()
        )


class Gelu(LeafModule):
    op_category = "activation"

    def forward_spec(self, x: TensorSpec) -> TensorSpec:
        return x

    def op_accessed(self) -> Dict[str, float]:
        n = self.inputs[0].bytes
        return {"fwd": 2 * n, "bwd_act": 3 * n}

    def activation_info(self) -> ActivationInfo:
        return ActivationInfo(cache_bytes=self.inputs[0].bytes)


class ParallelCE(LeafModule):
    """Vocab-parallel cross-entropy (reference ``dense_module.py:2097-2363``):
    three TP all-reduces of ``[b, s]`` fp32 scalars (max, predicted logit,
    sum-exp); the fused variant batches two into one collective and keeps
    only the bf16 logits cached."""

    op_category = "loss"

    def forward_spec(self, logits: TensorSpec) -> TensorSpec:
        b, s, v = logits.shape
        return TensorSpec((b, s), "fp32")

    def op_accessed(self) -> Dict[str, float]:
        st = _st(self.ctx)
        lg = self.inputs[0].bytes
        # Under jit, XLA fuses the log-softmax + target-gather: the fp32
        # log-probs are never materialized (the elementwise x - lse
        # fuses into the gather), so both variants stream the bf16
        # logits — fwd two reduction passes, bwd read-logits +
        # write-dlogits (anchored: the fp32-probs model overpredicted
        # the CE-peaked rows of docs/memory_validation.md by ~10%).
        return {"fwd": 2 * lg, "bwd_act": 2 * lg}

    def bw_key(self, phase):
        return "ce_fusion" if _st(self.ctx).use_fused_ce else "ce"

    def activation_info(self) -> ActivationInfo:
        st = _st(self.ctx)
        b, s, _ = self.inputs[0].shape
        # bf16 logits + the fp32 log-sum-exp row vector; no fp32 probs
        # materialization on the XLA path (see op_accessed)
        return ActivationInfo(
            cache_bytes=self.inputs[0].bytes + b * s * 4.0
        )

    def collectives(self) -> List[CollectiveCall]:
        st = _st(self.ctx)
        if st.tp_size == 1:
            return []
        b, s, _ = self.inputs[0].shape
        scalar = b * s * 4.0
        ncalls = 2 if st.use_fused_ce else 3
        return [
            CollectiveCall("fwd", "all_reduce", "tp", scalar, "post")
            for _ in range(ncalls)
        ]


# --------------------------------------------------------------------------
# Composites
# --------------------------------------------------------------------------


def bound_async_cp_overlap(attention: MetaModule):
    """Async CP can hide its a2a only under the attention-core compute;
    the excess goes back onto the critical path (shared by Attention and
    MLAAttention _post_forward hooks)."""
    st = _st(attention.ctx)
    if not (st.cp_size > 1 and st.cp_comm_type == "a2a"
            and st.cp_a2a_mode == "async_cp"):
        return
    cp_leaves = [
        c for c in attention.children() if isinstance(c, ContextParallelA2A)
    ]
    for phase in ("fwd", "bwd_act"):
        budget = attention.core.cost_info.compute.get(phase)
        attention.expose_unhidden(cp_leaves, phase, budget)


class Attention(MetaModule):
    """GQA/MHA attention (reference ``dense_module.py:2454-2568``):
    LinearCol(qkv) -> split -> RoPE -> [CP re-shard] -> CoreAttention ->
    [CP re-shard back] -> LinearRow(out)."""

    def __init__(self, ctx, name="attention", quantized=False):
        super().__init__(ctx, name)
        m, st = ctx.model, ctx.strategy
        self.hd = m.head_size
        self.q_out = m.head_num * m.head_size
        self.kv_out = m.kv_head_num * m.head_size
        self.qkv_proj = LinearCol(
            ctx, m.hidden_size, self.q_out + 2 * self.kv_out, "qkv_proj",
            quantized=quantized,
        )
        self.rope = RotaryEmbedding(ctx, name="rope")
        if st.cp_size > 1 and st.cp_comm_type == "a2a":
            self.cp_q = ContextParallelA2A(ctx, "scatter_heads", "cp_a2a_q")
            self.cp_k = ContextParallelA2A(ctx, "scatter_heads", "cp_a2a_k")
            self.cp_v = ContextParallelA2A(ctx, "scatter_heads", "cp_a2a_v")
            self.cp_o = ContextParallelA2A(ctx, "gather_seq", "cp_a2a_o")
        elif st.cp_size > 1 and st.cp_comm_type == "all_gather":
            self.kv_gather_k = KVAllGather(ctx, name="kv_allgather_k")
            self.kv_gather_v = KVAllGather(ctx, name="kv_allgather_v")
        self.core = CoreAttention(ctx, name="core_attention")
        self.out_proj = LinearRow(
            ctx, self.q_out, m.hidden_size, "out_proj", quantized=quantized
        )

    def _post_forward(self):
        bound_async_cp_overlap(self)

    def forward(self, x: TensorSpec) -> TensorSpec:
        st = _st(self.ctx)
        m = self.ctx.model
        qkv = self.qkv_proj(x)
        b, s, _ = qkv.shape
        tp = st.tp_size
        hl = m.head_num // tp
        kvl = max(m.kv_head_num // tp, 1)
        q = qkv.with_shape(b, s, hl, self.hd)
        k = qkv.with_shape(b, s, kvl, self.hd)
        v = qkv.with_shape(b, s, kvl, self.hd)
        q, k = self.rope(q, k)
        if st.cp_size > 1 and st.cp_comm_type == "a2a":
            q = self.cp_q(q)
            k = self.cp_k(k)
            v = self.cp_v(v)
        elif st.cp_size > 1 and st.cp_comm_type == "all_gather":
            k = self.kv_gather_k(k)
            v = self.kv_gather_v(v)
        o = self.core(q, k, v)
        if st.cp_size > 1 and st.cp_comm_type == "a2a":
            o = self.cp_o(o)
        b2, s2, hl2, dv = o.shape
        return self.out_proj(o.with_shape(b2, s2, hl2 * dv))


class MLP(MetaModule):
    """Dense MLP (reference ``dense_module.py:2888-2988``)."""

    def __init__(self, ctx, ffn=None, name="mlp", quantized=False,
                 tp_override=None):
        super().__init__(ctx, name)
        m = ctx.model
        self.ffn = ffn or m.intermediate_size
        fan = 2 * self.ffn if m.use_swiglu else self.ffn
        self.up_proj = LinearCol(ctx, m.hidden_size, fan, "up_proj",
                                 quantized=quantized)
        self.act = Swiglu(ctx, name="swiglu") if m.use_swiglu else Gelu(ctx, name="gelu")
        self.down_proj = LinearRow(ctx, self.ffn, m.hidden_size, "down_proj",
                                   quantized=quantized)

    def forward(self, x: TensorSpec) -> TensorSpec:
        return self.down_proj(self.act(self.up_proj(x)))

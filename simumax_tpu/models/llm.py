"""LLM block / per-stage model chunk (L3 top).

Reference: ``simumax/core/transformer/language_model.py`` (``LLMBlock:98``,
``LLMModel:210``, activation replay ``compute_activations:355-467``,
``PeakPoint:12``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from simumax_tpu.core.module import BuildContext, MetaModule
from simumax_tpu.core.tensor import TensorSpec
from simumax_tpu.models.dense import (
    AddFunction,
    Attention,
    Dropout,
    Embedding,
    LayerNorm,
    LinearCol,
    MLP,
    ParallelCE,
)


@dataclass
class PeakPoint:
    path: str = ""
    stage: str = ""
    bytes: float = 0.0


class LLMBlock(MetaModule):
    """One transformer layer (reference ``language_model.py:98-207``):
    input norm -> attention -> residual -> pre-MLP norm -> MLP/ExpertMLP ->
    residual, with per-layer recompute wiring."""

    def __init__(self, ctx: BuildContext, layer_idx: int, idx_in_stage: int,
                 name=""):
        super().__init__(ctx, name or f"layer{layer_idx}")
        self.layer_idx = layer_idx
        m, st = ctx.model, ctx.strategy
        quantized = st.fp8
        self.input_norm = LayerNorm(ctx, name="input_norm")
        if m.attention_type == "mla":
            try:
                from simumax_tpu.models.mla import MLAAttention
            except ImportError as e:  # pragma: no cover
                raise NotImplementedError(
                    "MLA attention is not available in this build"
                ) from e

            self.attention = MLAAttention(ctx, quantized=quantized)
        else:
            self.attention = Attention(ctx, quantized=quantized)
        if ctx.strategy.enable_dropout:
            self.attn_dropout = Dropout(ctx, name="attn_dropout")
        self.add_attn = AddFunction(ctx, name="residual_attn")
        self.pre_mlp_norm = LayerNorm(ctx, name="pre_mlp_norm")
        self.is_moe_layer = (
            m.model_type == "moe" and layer_idx >= m.dense_layers
        )
        if self.is_moe_layer:
            from simumax_tpu.models.moe import ExpertMLP

            self.mlp = ExpertMLP(ctx, quantized=quantized)
        else:
            self.mlp = MLP(ctx, quantized=quantized)
        if ctx.strategy.enable_dropout:
            self.mlp_dropout = Dropout(ctx, name="mlp_dropout")
        self.add_mlp = AddFunction(ctx, name="residual_mlp")
        self._wire_recompute(idx_in_stage)

    def _wire_recompute(self, idx_in_stage: int):
        rc = self.ctx.strategy.recompute
        if not rc.enabled or not rc.layer_recomputes(idx_in_stage):
            return
        if rc.granularity == "full_block":
            self.mark_recompute()
            return
        # selective
        # megatron tail modules force the tail model on exactly their
        # own segments (reference use_variance_tail_model, per-module);
        # None -> the segment follows the global recompute_variance flag
        def tail(module_name):
            return True if module_name in rc.tail_modules else None

        if rc.sdp_recompute:
            core = getattr(self.attention, "core", None)
            if core is not None:
                core.mark_recompute()
        if rc.attn_recompute:
            self.attention.mark_recompute()
        if rc.attn_norm_recompute:
            self.input_norm.mark_recompute(variance=tail("layernorm"))
            # MLA internal rms norms (reference mla_rms_recompute)
            for norm in getattr(self.attention, "norms", []):
                norm.mark_recompute(variance=tail("layernorm"))
        if rc.mla_up_proj_recompute:
            # MLA up-projections only (megatron_recompute_modules
            # "mla_up_proj"): the latent caches stay, the big q/kv
            # expansions replay
            for name in ("q_up", "kv_up"):
                mod = getattr(self.attention, name, None)
                if mod is not None:
                    mod.mark_recompute(variance=tail("mla_up_proj"))
        if rc.mlp_recompute:
            self.mlp.mark_recompute()
        if rc.mlp_norm_recompute:
            self.pre_mlp_norm.mark_recompute(variance=tail("layernorm"))
        if rc.moe_act_recompute and self.is_moe_layer:
            # expert activation only (megatron_recompute_modules
            # "moe_act"); skipped when the whole mlp is already marked
            if not self.mlp.recompute:
                self.mlp.act.mark_recompute(variance=tail("moe_act"))

    def _post_forward(self):
        st = self.ctx.strategy
        if st.zero_state >= 3:
            # FSDP gathers/reduce-scatters hide under the block's own
            # compute; only the excess lands on the critical path. The
            # compute already granted to async-CP a2a hiding is not
            # available twice.
            leaves = self.called_leaves()
            for phase in ("fwd", "bwd_act", "bwd_w"):
                compute = sum(
                    l.cost_info.compute.get(phase) for l in leaves
                )
                cp_hidden = sum(
                    c.time - c.exposed_time
                    for l in leaves
                    for c in l.collective_calls
                    if c.dim == "cp" and c.phase == phase
                )
                budget = max(compute - cp_hidden, 0.0)
                self.expose_unhidden(leaves, phase, budget,
                                     dims=("dp_cp", "edp"))
            # leaf mutations must propagate through the intermediate
            # composites (attention/mlp) before this block aggregates
            for c in self.children():
                c.reaggregate()

    def forward(self, x: TensorSpec) -> TensorSpec:
        h = self.input_norm(x)
        h = self.attention(h)
        if self.ctx.strategy.enable_dropout:
            h = self.attn_dropout(h)
        x = self.add_attn(x, h)
        h = self.pre_mlp_norm(x)
        h = self.mlp(h)
        if self.ctx.strategy.enable_dropout:
            h = self.mlp_dropout(h)
        return self.add_mlp(x, h)


class LLMModel(MetaModule):
    """One PP-stage model chunk (reference ``language_model.py:210-607``):
    optional Embedding (preprocess), N LLMBlocks, optional final norm +
    LM head + ParallelCE (postprocess)."""

    def __init__(
        self,
        ctx: BuildContext,
        layer_num: int,
        layer_offset: int = 0,
        preprocess: bool = True,
        postprocess: bool = True,
        stage_idx: int = 0,
        chunk_idx: int = 0,
        name: str = "",
    ):
        super().__init__(ctx, name or f"stage{stage_idx}")
        self.layer_num = layer_num
        self.layer_offset = layer_offset
        self.preprocess = preprocess
        self.postprocess = postprocess
        self.stage_idx = stage_idx
        self.chunk_idx = chunk_idx
        m = ctx.model
        if preprocess:
            self.embedding = Embedding(ctx)
            if ctx.strategy.enable_dropout:
                self.embedding_dropout = Dropout(ctx, name="embedding_dropout")
        self.blocks: List[LLMBlock] = []
        for i in range(layer_num):
            blk = LLMBlock(ctx, layer_offset + i, i)
            self.add_child(f"layer{layer_offset + i}", blk)
            self.blocks.append(blk)
        if postprocess:
            self.final_norm = LayerNorm(ctx, name="final_norm")
            # a tied lm_head owns no extra params only when the
            # embedding lives in the same chunk; at pp>1 the last stage
            # holds a physical replica of the tied weight (Megatron)
            self.lm_head = LinearCol(
                ctx, m.hidden_size, m.padded_vocab_size, "lm_head",
                count_params=m.untie_embeddings or not preprocess,
            )
            self.ce = ParallelCE(ctx, name="parallel_ce")
        self.peak_point: Optional[PeakPoint] = None

    # -- symbolic run ------------------------------------------------------
    def input_spec(self) -> TensorSpec:
        st = self.ctx.strategy
        b, s = st.micro_batch_size, st.seq_len
        s_cp = s // st.cp_size
        if self.preprocess:
            return TensorSpec((b, s_cp), "int32")
        s_sp = s_cp // st.tp_size if st.enable_sequence_parallel else s_cp
        return TensorSpec((b, s_sp, self.ctx.model.hidden_size), st.dtype)

    def forward(self, x: TensorSpec) -> TensorSpec:
        if self.preprocess:
            x = self.embedding(x)
            if self.ctx.strategy.enable_dropout:
                x = self.embedding_dropout(x)
        # layer dedup: blocks with identical construction signature and
        # input shape produce identical profiles — evaluate one
        # representative, adopt for the rest (search-loop scalability;
        # disabled under graph capture, which needs every real edge, and
        # under the per-path debug probe, which records per-layer rows)
        dedup = (
            self.ctx.layer_dedup
            and self.ctx.graph is None
            and not self.ctx.debug.enabled
        )
        reps = {}
        for blk in self.blocks:
            if not dedup:
                x = blk(x)
                continue
            sig = (
                blk.is_moe_layer,
                self._block_recompute_sig(blk),
                x.shape,
                x.dtype,
            )
            rep = reps.get(sig)
            if rep is not None:
                x = blk.adopt_call_from(rep, x)
            else:
                x = blk(x)
                reps[sig] = blk
        if self.postprocess:
            x = self.final_norm(x)
            x = self.lm_head(x)
            x = self.ce(x)
        return x

    @staticmethod
    def _block_recompute_sig(blk: LLMBlock) -> tuple:
        """Recompute wiring fingerprint: which leaves are checkpointed
        and how (layer_recomputes(idx) makes leading layers differ)."""
        return tuple(
            (l.in_recompute, l.recompute_status.name, l.variance_tail)
            for l in blk.leaves()
        )

    def run(self) -> TensorSpec:
        return self(self.input_spec())

    # -- p2p message size --------------------------------------------------
    def boundary_bytes(self) -> float:
        """Bytes of the hidden-state tensor crossing a PP boundary
        (reference ``core/utils.py:203-212``)."""
        st = self.ctx.strategy
        s_cp = st.seq_len // st.cp_size
        s_sp = s_cp // st.tp_size if st.enable_sequence_parallel else s_cp
        return (
            st.micro_batch_size
            * s_sp
            * self.ctx.model.hidden_size
            * st.element_size
        )

    # -- activation replay (reference ``language_model.py:355-467``) -------
    def activation_events(self):
        """The activation-replay walk as an event stream — the single
        source for both :meth:`compute_activations` (scalar fold to the
        peak) and the memory ledger's peak live-set materialization
        (``observe/memledger.py``), so the two can never diverge.

        Yields tuples:

        * ``("alloc", leaf, kind, bytes)`` / ``("free", leaf, kind,
          bytes)`` — the live set grows/shrinks by ``bytes``; ``kind``
          is ``act_cache`` (fwd-to-bwd activation cache) or
          ``recompute_cache`` (raw cache re-materialized during a
          checkpointed segment's replay);
        * ``("probe", leaf, stage, extras)`` — a candidate peak at the
          current live set plus the transient ``extras``: an ordered
          tuple of ``(kind, bytes)`` terms (``fwd_temp`` /
          ``bwd_temp`` / ``grad_flight`` / the negative
          ``saved_input_reuse`` adjustment of a segment replay), summed
          onto ``live`` left-to-right so the fold reproduces the
          historical float-op order bit-for-bit.
        """
        leaves = self.called_leaves()
        # ---- forward walk
        for leaf in leaves:
            yield ("alloc", leaf, "act_cache", leaf.act_info.cache_bytes)
            yield ("probe", leaf, "fwd",
                   (("fwd_temp", leaf.raw_act_info.fwd_temp_bytes),))

        # ---- backward walk with recompute replay. Segments need not be
        # contiguous in the call order (e.g. sdp-only inside a
        # checkpointed attention), so consumed leaves are tracked in a set.
        done = set()
        i = len(leaves) - 1
        while i >= 0:
            leaf = leaves[i]
            if id(leaf) in done:
                i -= 1
                continue
            seg = getattr(leaf, "recompute_segment", None)
            if leaf.in_recompute and seg is not None:
                seg_leaves = [
                    l
                    for l in leaves
                    if getattr(l, "recompute_segment", None) is seg
                ]
                # replay fwd: raw caches come alive again; the saved segment
                # input (FIRST leaf's effective cache) is reused, not
                # re-allocated, and is freed with FIRST's raw cache below.
                # A variance-tail leaf is not replayed, so its raw cache
                # never re-materialises; if the tail IS the first leaf
                # (single-leaf segment) the saved input must stay live
                # until that leaf's backward consumes it.
                saved = seg_leaves[0].act_info.cache_bytes
                tail_is_first = seg_leaves[0].variance_tail
                for sl in seg_leaves:
                    if sl.variance_tail:
                        continue
                    yield ("alloc", sl, "recompute_cache",
                           sl.raw_act_info.cache_bytes)
                    yield ("probe", sl, "recompute",
                           (("saved_input_reuse", -saved),
                            ("fwd_temp", sl.raw_act_info.fwd_temp_bytes)))
                if not tail_is_first:
                    yield ("free", seg_leaves[0], "act_cache", saved)
                # consume raw caches in reverse as bwd proceeds
                for sl in reversed(seg_leaves):
                    yield ("probe", sl, "bwd",
                           (("bwd_temp", sl.raw_act_info.bwd_temp_bytes),
                            ("grad_flight",
                             sl.raw_act_info.grad_flight_bytes)))
                    if sl.variance_tail:
                        if sl is seg_leaves[0]:
                            yield ("free", sl, "act_cache", saved)
                    else:
                        yield ("free", sl, "recompute_cache",
                               sl.raw_act_info.cache_bytes)
                    done.add(id(sl))
                i -= 1
                continue
            yield ("probe", leaf, "bwd",
                   (("bwd_temp", leaf.raw_act_info.bwd_temp_bytes),
                    ("grad_flight", leaf.raw_act_info.grad_flight_bytes)))
            yield ("free", leaf, "act_cache", leaf.act_info.cache_bytes)
            done.add(id(leaf))
            i -= 1

    def compute_activations(self) -> PeakPoint:
        """Fold :meth:`activation_events`, tracking the live activation
        set; returns the peak.

        Conservation invariant: the live set must return to ~0 after the
        backward walk (reference ``language_model.py:462-465``).
        """
        live = 0.0
        peak = PeakPoint()
        for ev in self.activation_events():
            op = ev[0]
            if op == "alloc":
                live += ev[3]
            elif op == "free":
                live -= ev[3]
            else:  # probe
                cand = live
                for _, extra in ev[3]:
                    cand += extra
                if cand > peak.bytes:
                    peak = PeakPoint(ev[1].path_name(), ev[2], cand)

        assert abs(live) < 1024, (
            f"activation conservation violated: {live} bytes left live"
        )
        self.peak_point = peak
        return peak

    # -- tables ------------------------------------------------------------
    def op_table(self) -> List[dict]:
        """Per-leaf cost/memory rows (reference ``language_model.py:514``)."""
        rows = []
        for leaf in self.called_leaves():
            rows.append(
                {
                    "path": leaf.path_name(),
                    "fwd_ms": leaf.cost_info.fwd_time * 1e3,
                    "bwd_ms": leaf.cost_info.bwd_time * 1e3,
                    "net_ms": leaf.cost_info.total_net_exposed * 1e3,
                    "fwd_gflops": leaf.compute_info.fwd_flops / 1e9,
                    "cache_mib": leaf.act_info.cache_bytes / 2**20,
                    "weight_mib": (
                        leaf.param_info.weight_bytes
                        + leaf.param_info.moe_weight_bytes
                    )
                    / 2**20,
                }
            )
        return rows
